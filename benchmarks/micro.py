"""Microbenchmarks: allreduce bandwidth + point-to-point latency.

The reference publishes no microbenchmarks (BASELINE.md: `published: {}`);
these fill that gap with the two north-star metrics from BASELINE.json:

- **allreduce bus bandwidth** (GB/s per device) over a size sweep — on a
  TPU slice this measures ICI; algorithmic bytes per device for a ring
  allreduce are ``2 * (n-1)/n * size`` (the standard bus-bandwidth
  convention, so numbers are comparable across device counts);
- **sendrecv ring latency** (µs per hop) — the halo-exchange primitive.

plus the butterfly-vs-ring allreduce sweep that measures the payload-aware
algorithm layer's crossover (``MPI4JAX_TPU_COLLECTIVE_ALGO``,
ops/_algos.py; the measured table lives in docs/microbenchmarks.md).

Usage:  python benchmarks/micro.py [--json] [--save]

Timing protocol: each measurement chains ``iters`` collectives inside one
jitted program (so dispatch overhead amortizes), syncs via a host fetch
(remote-attached devices do not honor block_until_ready), and reports the
best of 3 trials.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import mpi4jax_tpu as mpx  # noqa: E402


def _time_program(fn, args, trials=3):
    """Best-of-N wall time of ``fn(*args)`` with host-fetch sync."""
    def sync(out):
        # single-element fetch with no reshape: plain indexing slices one
        # element off the leading shard (ravel() would dispatch a full
        # device reshape of the global array inside the timed window)
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf[(0,) * leaf.ndim])

    sync(fn(*args))  # compile + drain queue
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_allreduce(comm, sizes_mb, iters=20):
    n = comm.Get_size()
    rows = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * 1e6 / 4))

        @mpx.spmd(comm=comm)
        def prog(x):
            def body(_, v):
                s, _tok = mpx.allreduce(v, op=mpx.SUM)
                return mpx.varying(s * (1.0 / n))  # keep values bounded

            return jax.lax.fori_loop(0, iters, body, x)

        x = jnp.ones((n, nelem), jnp.float32)
        t = _time_program(prog, (x,)) / iters
        # ring-allreduce bus bandwidth per device
        bus_bytes = 2 * (n - 1) / n * nelem * 4
        rows.append({
            "size_mb": round(nelem * 4 / 1e6, 3),
            "time_us": round(t * 1e6, 1),
            "bus_gb_s": round(bus_bytes / t / 1e9, 2) if n > 1 else None,
        })
    return rows


def bench_sendrecv_ring(comm, sizes_kb, iters=50):
    n = comm.Get_size()
    rows = []
    for kb in sizes_kb:
        nelem = max(1, int(kb * 1e3 / 4))

        @mpx.spmd(comm=comm)
        def prog(x):
            def body(_, v):
                r, _tok = mpx.sendrecv(v, v, dest=mpx.shift(1))
                return r

            return jax.lax.fori_loop(0, iters, body, x)

        x = jnp.ones((n, nelem), jnp.float32)
        t = _time_program(prog, (x,)) / iters
        rows.append({
            "size_kb": round(nelem * 4 / 1e3, 2),
            "hop_us": round(t * 1e6, 2),
            "link_gb_s": round(nelem * 4 / t / 1e9, 2) if n > 1 else None,
        })
    return rows


def bench_prod_and_split(comm, sizes_mb, iters=20):
    """The log-depth butterfly family: PROD allreduce (no native HLO
    collective) on the whole comm and on an even/odd color split — the
    lowerings tests/test_scale.py gates at 64 devices, timed here."""
    n = comm.Get_size()
    split = comm.Split([r % 2 for r in range(n)]) if n > 1 else None
    rows = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * 1e6 / 4))

        @mpx.spmd(comm=comm)
        def prog(x):
            def body(_, v):
                s, _tok = mpx.allreduce(v, op=mpx.PROD)
                return mpx.varying(jnp.clip(s, 0.5, 2.0))  # keep bounded

            return jax.lax.fori_loop(0, iters, body, x)

        x = jnp.ones((n, nelem), jnp.float32)
        t_whole = _time_program(prog, (x,)) / iters

        t_split = None
        if split is not None:

            @mpx.spmd(comm=comm)
            def prog_split(x):
                def body(_, v):
                    s, _tok = mpx.allreduce(v, op=mpx.PROD, comm=split)
                    return mpx.varying(jnp.clip(s, 0.5, 2.0))

                return jax.lax.fori_loop(0, iters, body, x)

            t_split = _time_program(prog_split, (x,)) / iters
        rows.append({
            "size_mb": round(nelem * 4 / 1e6, 3),
            "prod_us": round(t_whole * 1e6, 1),
            "prod_split_us": (
                round(t_split * 1e6, 1) if t_split is not None else None
            ),
        })
    return rows


def bench_allreduce_algos(comm, sizes_mb, iters=20):
    """Forced butterfly vs forced ring for the SAME PROD allreduce over a
    size sweep — the measured crossover table of docs/microbenchmarks.md.
    PROD has no native HLO collective, so the two forced settings time the
    CollectivePermute algorithm layer itself (``MPI4JAX_TPU_COLLECTIVE_ALGO``
    is folded into the program cache keys, so each setting retraces)."""
    n = comm.Get_size()
    rows = []
    saved = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    try:
        for mb in sizes_mb:
            nelem = max(1, int(mb * 1e6 / 4))
            row = {"size_mb": round(nelem * 4 / 1e6, 3)}
            for algo in ("butterfly", "ring"):
                os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = algo

                @mpx.spmd(comm=comm)
                def prog(x):
                    def body(_, v):
                        s, _tok = mpx.allreduce(v, op=mpx.PROD)
                        return mpx.varying(jnp.clip(s, 0.5, 2.0))

                    return jax.lax.fori_loop(0, iters, body, x)

                x = jnp.ones((n, nelem), jnp.float32)
                t = _time_program(prog, (x,)) / iters
                row[f"{algo}_us"] = round(t * 1e6, 1)
            # on 1 device both settings lower to the identity — no crossover
            row["ring_speedup"] = (
                round(row["butterfly_us"] / row["ring_us"], 2) if n > 1
                else None
            )
            rows.append(row)
    finally:
        # restore (not just drop) the user's global algorithm setting
        if saved is None:
            os.environ.pop("MPI4JAX_TPU_COLLECTIVE_ALGO", None)
        else:
            os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = saved
    return rows


def bench_hierarchy(comm, sizes_mb=(1, 4), topologies=("2x4", "4x2"),
                    iters=10):
    """The hierarchy sweep (``--hierarchy-sweep``): flat ring vs the
    forced two-level lowering for the SAME PROD allreduce over a payload
    x topology grid (docs/topology.md).  Each topology is faked via
    ``MPI4JAX_TPU_TOPOLOGY`` (the same knob the CI topology lane uses on
    the 8-device CPU mesh); the spec is stamped into every row so saved
    captures say which host partition produced which number.  Both knobs
    fold into the program cache keys, so every cell compiles its own
    program."""
    from mpi4jax_tpu.utils.config import parse_topology_spec

    n = comm.Get_size()
    rows = []
    saved_algo = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    saved_topo = os.environ.get("MPI4JAX_TPU_TOPOLOGY")
    try:
        for topo in topologies:
            counts = parse_topology_spec(topo)
            if sum(counts) != n:
                print(f"hierarchy sweep: skipping topology {topo} "
                      f"(covers {sum(counts)} ranks, mesh has {n})",
                      file=sys.stderr)
                continue
            os.environ["MPI4JAX_TPU_TOPOLOGY"] = topo
            for mb in sizes_mb:
                nelem = max(1, int(mb * 1e6 / 4))
                row = {"size_mb": round(nelem * 4 / 1e6, 3),
                       "topology": topo}
                for label, algo in (("flat", "ring"), ("hier", "hier")):
                    os.environ["MPI4JAX_TPU_COLLECTIVE_ALGO"] = algo

                    @mpx.spmd(comm=comm)
                    def prog(x):
                        def body(_, v):
                            s, _tok = mpx.allreduce(v, op=mpx.PROD)
                            return mpx.varying(jnp.clip(s, 0.5, 2.0))

                        return jax.lax.fori_loop(0, iters, body, x)

                    x = jnp.ones((n, nelem), jnp.float32)
                    t = _time_program(prog, (x,)) / iters
                    row[f"{label}_us"] = round(t * 1e6, 1)
                row["hier_speedup"] = (
                    round(row["flat_us"] / row["hier_us"], 2) if n > 1
                    else None
                )
                rows.append(row)
    finally:
        for key, val in (("MPI4JAX_TPU_COLLECTIVE_ALGO", saved_algo),
                         ("MPI4JAX_TPU_TOPOLOGY", saved_topo)):
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    return rows


def bench_alltoall(comm, sizes_mb=(0.25, 1), topologies=(None,), iters=10,
                   compute_dim=64):
    """The alltoall sweep (``--alltoall-sweep``): flat single-exchange
    vs the forced two-level hierarchical lowering vs the chunked async
    start/wait split (with synthetic compute in the gap), over a
    payload x topology grid (docs/moe.md) — the MoE dispatch/combine
    primitive's three execution shapes.

    A ``None`` topology entry measures under the ambient (derived)
    topology; spec strings are faked via ``MPI4JAX_TPU_TOPOLOGY`` like
    the hierarchy sweep.  Each row also carries the MODELED per-rank
    DCN byte and message columns from the pinned byte models
    (``ops/_hierarchy``): the hierarchical exchange ships the same
    bytes in ``1/r`` the DCN messages (``dcn_msg_reduction``), which is
    the latency/message-rate lever the crossover measures."""
    from mpi4jax_tpu.ops import _hierarchy
    from mpi4jax_tpu.utils.config import parse_topology_spec

    n = comm.Get_size()
    rows = []
    saved = {k: os.environ.get(k) for k in
             ("MPI4JAX_TPU_COLLECTIVE_ALGO",
              "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES",
              "MPI4JAX_TPU_TOPOLOGY")}
    try:
        for topo in topologies:
            counts = parse_topology_spec(topo) if topo else None
            if counts is not None and sum(counts) != n:
                print(f"alltoall sweep: skipping topology {topo} "
                      f"(covers {sum(counts)} ranks, mesh has {n})",
                      file=sys.stderr)
                continue
            if topo:
                os.environ["MPI4JAX_TPU_TOPOLOGY"] = topo
            else:
                os.environ.pop("MPI4JAX_TPU_TOPOLOGY", None)
            for mb in sizes_mb:
                per = max(1, int(mb * 1e6 / 4 / n))
                nbytes = n * per * 4
                row = {"size_mb": round(nbytes / 1e6, 4),
                       "topology": topo or "derived"}

                def timed(env, fn):
                    for k, v in env.items():
                        os.environ[k] = str(v)
                    try:
                        x = jnp.ones((n, n, per), jnp.float32)
                        w = jnp.full((n, compute_dim, compute_dim), 0.01,
                                     jnp.float32)
                        return _time_program(fn(), (x, w)) / iters
                    finally:
                        for k in env:
                            os.environ.pop(k, None)

                def sync_prog():
                    @mpx.spmd(comm=comm)
                    def prog(x, w):
                        def body(_, carry):
                            v, m = carry
                            r, _tok = mpx.alltoall(v)
                            m = jnp.tanh(m @ m)
                            return (mpx.varying(r), m)

                        return jax.lax.fori_loop(0, iters, body, (x, w))

                    return prog

                def async_prog():
                    @mpx.spmd(comm=comm)
                    def prog(x, w):
                        def body(_, carry):
                            v, m = carry
                            h, _tok = mpx.alltoall_start(v)
                            m = jnp.tanh(m @ m)  # overlaps the exchange
                            r, _tok = mpx.alltoall_wait(h)
                            return (mpx.varying(r), m)

                        return jax.lax.fori_loop(0, iters, body, (x, w))

                    return prog

                huge = 1 << 60  # flat: the crossover can never trip
                row["flat_us"] = round(timed(
                    {"MPI4JAX_TPU_COLLECTIVE_ALGO": "auto",
                     "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES": huge},
                    sync_prog) * 1e6, 1)
                row["hier_us"] = round(timed(
                    {"MPI4JAX_TPU_COLLECTIVE_ALGO": "hier"},
                    sync_prog) * 1e6, 1)
                row["async_us"] = round(timed(
                    {"MPI4JAX_TPU_COLLECTIVE_ALGO": "auto",
                     "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES": huge},
                    async_prog) * 1e6, 1)
                row["hier_speedup"] = (
                    round(row["flat_us"] / row["hier_us"], 2)
                    if n > 1 and row["hier_us"] else None
                )
                if counts is not None and len(set(counts)) == 1:
                    h, r = len(counts), counts[0]
                    row["dcn_bytes_flat"] = _hierarchy.flat_link_bytes(
                        "alltoall", "native", nbytes, n, h)[1]
                    row["dcn_bytes_hier"] = _hierarchy.hier_link_bytes(
                        "alltoall", nbytes, h, r)[1]
                    mf, mh = _hierarchy.alltoall_dcn_messages(h, r)
                    row["dcn_msgs_flat"] = mf
                    row["dcn_msgs_hier"] = mh
                    row["dcn_msg_reduction"] = r
                rows.append(row)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return rows


def bench_fusion(comm, counts=(8, 32), size_kb=64, iters=1):
    """The collective-fusion sweep (``--fusion-sweep``): N small allreduces
    per program, fused (``MPI4JAX_TPU_FUSION=auto``, issue-then-consume
    idiom) vs unfused, reporting per-op wall µs — per-call dispatch plus
    per-collective latency, the two costs bucketing removes
    (docs/overlap.md).  The fusion mode is folded into the program cache
    keys, so each setting compiles its own program."""
    n = comm.Get_size()
    nelem = max(1, int(size_kb * 1e3 / 4))
    rows = []
    saved = os.environ.get("MPI4JAX_TPU_FUSION")
    try:
        for count in counts:
            row = {"count": count, "size_kb": round(nelem * 4 / 1e3, 2)}
            for label, mode in (("unfused", "off"), ("fused", "auto")):
                os.environ["MPI4JAX_TPU_FUSION"] = mode

                @mpx.spmd(comm=comm)
                def prog(xs):
                    # the fusion idiom: issue the whole batch, then
                    # consume — under auto the first use flushes one
                    # fused flat-buffer collective per dtype bucket
                    red = [mpx.allreduce(x, op=mpx.SUM)[0] for x in xs]
                    return [mpx.varying(r * (1.0 / n)) for r in red]

                xs = tuple(
                    jnp.full((n, nelem), float(i % 5 + 1), jnp.float32)
                    for i in range(count)
                )
                t = _time_program(prog, (xs,))
                row[f"{label}_us_per_op"] = round(t / count * 1e6, 2)
            row["fused_speedup"] = round(
                row["unfused_us_per_op"] / row["fused_us_per_op"], 2
            )
            rows.append(row)
    finally:
        if saved is None:
            os.environ.pop("MPI4JAX_TPU_FUSION", None)
        else:
            os.environ["MPI4JAX_TPU_FUSION"] = saved
    return rows


def bench_overlap(comm, sizes_mb=(1, 4), iters=10, compute_dim=128):
    """The async-overlap sweep (``--overlap-sweep``): chunked
    ``allreduce_start``/``_wait`` with independent synthetic compute
    issued in the gap, vs the monolithic allreduce followed by the same
    compute.  Measures how much of the collective the scheduler hides
    behind the matmul chain (``MPI4JAX_TPU_OVERLAP_CHUNKS`` chunks;
    docs/overlap.md)."""
    n = comm.Get_size()
    rows = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * 1e6 / 4))

        @mpx.spmd(comm=comm)
        def mono(x, w):
            def body(_, carry):
                v, m = carry
                s, _tok = mpx.allreduce(v, op=mpx.SUM)
                m = jnp.tanh(m @ m)
                return (mpx.varying(s * (1.0 / n)), m)

            return jax.lax.fori_loop(0, iters, body, (x, w))

        @mpx.spmd(comm=comm)
        def ovl(x, w):
            def body(_, carry):
                v, m = carry
                h, _tok = mpx.allreduce_start(v, op=mpx.SUM)
                m = jnp.tanh(m @ m)  # independent: overlaps the phases
                s, _tok = mpx.allreduce_wait(h)
                return (mpx.varying(s * (1.0 / n)), m)

            return jax.lax.fori_loop(0, iters, body, (x, w))

        x = jnp.ones((n, nelem), jnp.float32)
        w = jnp.full((n, compute_dim, compute_dim), 0.01, jnp.float32)
        from mpi4jax_tpu.utils.config import overlap_chunks

        t_mono = _time_program(mono, (x, w)) / iters
        t_ovl = _time_program(ovl, (x, w)) / iters
        rows.append({
            "size_mb": round(nelem * 4 / 1e6, 3),
            "chunks": overlap_chunks(),
            "monolithic_us": round(t_mono * 1e6, 1),
            "overlap_us": round(t_ovl * 1e6, 1),
            "overlap_speedup": round(t_mono / t_ovl, 2),
        })
    return rows


def bench_compression(comm, sizes_mb=(0.25, 1, 4), topology="2x4",
                      iters=3):
    """The wire-codec sweep (``--compression-sweep``): one row per
    {off, bf16, fp8} x payload cell, carrying

    - the LOGICAL vs WIRE DCN bytes of the hierarchical allreduce
      (the pinned PR-6 byte model x the codec byte math — exactly what
      the telemetry logical/wire split records);
    - the MODELED DCN-leg time through the alpha-beta cost model with
      the codec priced in (``collective_cost(codec=...)``);
    - the MEASURED round-trip max relative error of the codec on
      synthetic gradient-scale data — the autotuner's
      codec-vs-error-budget input (docs/compression.md).

    The timing columns are modeled, not wall-clock: a single-host CI
    mesh has no DCN, and the codec's win is a byte-count fact the cost
    model prices — the convergence harness (BENCH_compress.json)
    carries the measured accuracy half."""
    from mpi4jax_tpu.analysis import costmodel
    from mpi4jax_tpu.compress import roundtrip, wire_bytes
    from mpi4jax_tpu.ops import _hierarchy
    from mpi4jax_tpu.utils.config import parse_topology_spec

    counts = parse_topology_spec(topology)
    h, r = len(counts), counts[0]
    k = h * r
    model = costmodel.load_model()
    rows = []
    for mb in sizes_mb:
        n_elems = max(1, int(mb * 1e6 / 4))
        nbytes = n_elems * 4
        logical = _hierarchy.hier_link_bytes("allreduce", nbytes, h, r)[1]
        for codec in ("off", "bf16", "fp8"):
            c = None if codec == "off" else codec
            cost_c = costmodel.collective_cost(
                "allreduce", "hier", nbytes, k, hosts=h, hier=(h, r),
                codec=c)
            if c is None:
                err = 0.0
            else:
                err = 0.0
                for i in range(iters):
                    x = jax.random.normal(
                        jax.random.PRNGKey(i), (n_elems,),
                        jnp.float32) * 0.02
                    y = roundtrip(x, c)
                    denom = max(float(jnp.max(jnp.abs(x))), 1e-30)
                    err = max(err,
                              float(jnp.max(jnp.abs(y - x))) / denom)
            rows.append({
                "size_mb": round(nbytes / 1e6, 4),
                "codec": codec,
                "topology": topology,
                "logical_dcn_bytes": int(logical),
                "wire_dcn_bytes": int(wire_bytes(int(logical), c)),
                "modeled_dcn_us": round(model.link_time_us(
                    "dcn", cost_c.dcn.rounds, cost_c.dcn.nbytes), 2),
                "rel_err": round(err, 8),
            })
    return rows


def bench_dispatch(comm, sizes_kb=(0.004, 4, 64), iters=100):
    """The dispatch sweep (``--dispatch-sweep``): per-CALL overhead of
    the three execution surfaces for the SAME one-allreduce program —

    - **eager**: ``mpx.allreduce`` outside any region (the one-op
      compiled-program cache; per call: flag-stamp check + interned key
      probe + cached jit call);
    - **spmd**: an ``mpx.spmd``-decorated program (per call: statics
      normalization, program-cache key build + probe, then the jit
      call);
    - **pinned**: ``mpx.compile`` (per call: one stamp validation, then
      the compiled executable — no key work at all; docs/aot.md).

    At the smallest payload the device op is noise and the numbers are
    pure host dispatch — the gap ``mpx.compile`` exists to close.  Each
    loop is timed whole (N calls then one sync), so per-call numbers
    amortize the device queue the way a real hot loop does.
    """
    n = comm.Get_size()
    rows = []
    for kb in sizes_kb:
        nelem = max(1, int(kb * 1e3 / 4))
        x = jnp.ones((n, nelem), jnp.float32)

        def eager_call(v):
            return mpx.allreduce(v, op=mpx.SUM)[0]

        @mpx.spmd(comm=comm)
        def prog(v):
            return mpx.varying(mpx.allreduce(v, op=mpx.SUM)[0])

        def per_rank(v):
            return mpx.varying(mpx.allreduce(v, op=mpx.SUM)[0])

        pinned = mpx.compile(per_rank, x, comm=comm)

        def time_per_call(fn):
            fn(x)
            jax.block_until_ready(fn(x))  # compile + drain
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = fn(x)
                jax.block_until_ready(out)
                best = min(best, (time.perf_counter() - t0) / iters)
            return best

        rows.append({
            "size_kb": round(nelem * 4 / 1e3, 3),
            "eager_us": round(time_per_call(eager_call) * 1e6, 2),
            "spmd_us": round(time_per_call(prog) * 1e6, 2),
            "pinned_us": round(time_per_call(pinned) * 1e6, 2),
        })
        rows[-1]["pinned_vs_spmd"] = round(
            rows[-1]["spmd_us"] / rows[-1]["pinned_us"], 2
        ) if rows[-1]["pinned_us"] else None
    return rows


def bench_dispatch_unroll(comm, unrolls=(1, 8, 64), size_kb=0.004,
                          iters=50):
    """The megastep amortization sweep (``--dispatch-sweep``'s unroll
    axis): the SAME one-allreduce step pinned at ``unroll=N`` for each N
    (``mpx.compile(fn, ..., unroll=N)`` — one host dispatch executes N
    device-resident steps, docs/aot.md "Megastep execution"), timed per
    megastep call.

    Per-step **host** cost is separated from per-step device cost with a
    two-point fit: per-call wall is ``wall(N) = D + N * d`` (D = fixed
    host dispatch per call, d = on-chip per-step time), so ``d`` falls
    out of the difference between the two largest unrolls — the dispatch
    term cancels — and each row's ``per_step_host_us = wall(N)/N - d``
    is an independent measurement.  The 1/N amortization claim is then
    checkable from the saved artifact: host cost at unroll=64 should be
    ~1/64 of unroll=1 (CI asserts < 1/8).
    """
    n = comm.Get_size()
    nelem = max(1, int(size_kb * 1e3 / 4))
    x = jnp.ones((n, nelem), jnp.float32)
    unrolls = sorted(set(int(u) for u in unrolls))

    def per_rank(v):
        return mpx.varying(mpx.allreduce(v, op=mpx.SUM)[0] * (1.0 / n))

    walls = {}
    fast_paths = {}
    for u in unrolls:
        pinned = mpx.compile(per_rank, x, comm=comm, unroll=u)
        fast_paths[u] = pinned.fast_path
        pinned(x)
        jax.block_until_ready(pinned(x))  # compile + drain
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = pinned(x)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        walls[u] = best

    # on-chip per-step estimate d from the two largest unrolls (the
    # host dispatch term cancels in the difference); one unroll = no fit
    if len(unrolls) >= 2:
        hi, lo = unrolls[-1], unrolls[-2]
        d = max(0.0, (walls[hi] - walls[lo]) / (hi - lo))
    else:
        d = 0.0
    rows = []
    for u in unrolls:
        wall = walls[u]
        rows.append({
            "unroll": u,
            "megastep_us": round(wall * 1e6, 2),
            "per_step_us": round(wall / u * 1e6, 3),
            "per_step_host_us": round(max(0.0, wall / u - d) * 1e6, 3),
            "fast_path": fast_paths[u],
        })
    return {
        "size_kb": round(nelem * 4 / 1e3, 3),
        "onchip_per_step_us": round(d * 1e6, 3),
        "rows": rows,
    }


def bench_health_overhead(comm, sizes_kb=(0.004, 4, 64), iters=200):
    """The health-plane overhead sweep (``--health-overhead``): per-call
    dispatch cost of the SAME eager one-allreduce program under four
    telemetry configurations — off, counters, counters + the armed
    flight-recorder ring (``MPI4JAX_TPU_HEALTH=on``), and full events —
    across payload sizes (docs/observability.md "Runtime health").

    The acceptance bar the sweep documents: ``counters_ring_us`` within
    10% of ``counters_us`` (``ring_overhead_ratio <= 1.10``) — the ring
    spill is one dict build + one list store riding the counter commit
    the counters tier already pays, with no new io_callbacks.  At the
    smallest payload the device op is noise and the columns are pure
    host dispatch, the worst case for relative overhead."""
    n = comm.Get_size()
    modes = (("off", "off", "off"),
             ("counters", "counters", "off"),
             ("counters_ring", "counters", "on"),
             ("events", "events", "on"))
    rows = []
    saved = {k: os.environ.get(k) for k in
             ("MPI4JAX_TPU_HEALTH", "MPI4JAX_TPU_FLIGHT_RING")}
    try:
        for kb in sizes_kb:
            nelem = max(1, int(kb * 1e3 / 4))
            x = jnp.ones((n, nelem), jnp.float32)
            row = {"size_kb": round(nelem * 4 / 1e3, 3)}

            def eager_call(v):
                return mpx.allreduce(v, op=mpx.SUM)[0]

            for label, tmode, hmode in modes:
                os.environ["MPI4JAX_TPU_HEALTH"] = hmode
                mpx.telemetry.reset()
                mpx.set_telemetry_mode(tmode)
                eager_call(x)
                jax.block_until_ready(eager_call(x))  # compile + drain
                best = float("inf")
                for _ in range(5):
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        out = eager_call(x)
                    jax.block_until_ready(out)
                    best = min(best, (time.perf_counter() - t0) / iters)
                row[f"{label}_us"] = round(best * 1e6, 3)
            row["ring_overhead_ratio"] = (
                round(row["counters_ring_us"] / row["counters_us"], 3)
                if row["counters_us"] else None
            )
            rows.append(row)
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return rows


# saved-sweep schema version: bumped when the --save payload shape
# changes, so the autotune fitter (mpi4jax_tpu/autotune/) can reject
# captures it does not understand instead of misreading them
MICRO_SCHEMA = "mpx-micro-bench/1"


def provenance_block(platform, n_devices):
    """The self-description every ``--save`` capture carries: jax/jaxlib
    versions, the topology the rows were measured under, and a content
    stamp of the whole declared-flag surface — so a saved sweep is a
    self-describing input to the autotune fitter (no more guessing what
    configuration produced which number).  One implementation serves
    every emitted artifact: this delegates to the canonical
    ``mpi4jax_tpu.autotune.runner.provenance_block``."""
    from mpi4jax_tpu.autotune.runner import provenance_block as _pb

    return _pb(platform, n_devices)


def fit_alpha_beta(points):
    """Least-squares fit of the alpha-beta line ``t_us = alpha_us +
    bytes / (gb_per_s * 1e3)`` over ``points`` = [(bytes, us), ...].
    Returns ``(alpha_us, gb_per_s)``, clamped into the cost-model
    schema's valid ranges (a tiny sweep can fit a negative intercept or
    a non-positive slope; the emitted file must still load verbatim)."""
    xs = np.asarray([p[0] for p in points], dtype=float)
    ys = np.asarray([p[1] for p in points], dtype=float)
    if len(points) >= 2 and float(np.ptp(xs)) > 0:
        slope, intercept = np.polyfit(xs, ys, 1)
    else:  # single size: all latency, analytic bandwidth
        slope, intercept = 0.0, float(ys.mean()) if len(points) else 0.0
    alpha_us = max(float(intercept), 0.001)
    # slope is us/byte; 1 GB/s == 1000 bytes/us
    gb_per_s = (1.0 / (float(slope) * 1e3)) if slope > 0 else 1e4
    gb_per_s = min(max(gb_per_s, 0.001), 1e4)
    return alpha_us, gb_per_s


def measured_ring_crossover(algo_rows):
    """The payload (bytes) where the measured ring first beats the
    measured butterfly, linearly interpolated between the straddling
    sweep points — the measured twin of
    ``MPI4JAX_TPU_RING_CROSSOVER_BYTES`` the MPX109/111/113 advisories
    cite when a tuning file is loaded.  ``None`` when the ring never
    wins in the sweep (or the sweep ran on one device — marked by a
    ``None`` speedup).  The interpolation itself is the canonical
    ``autotune.fit.measured_crossover`` (one copy of the math)."""
    from mpi4jax_tpu.autotune.fit import measured_crossover

    if any(row.get("ring_speedup") is None for row in algo_rows):
        return None  # 1-device sweep: no crossover is meaningful
    return measured_crossover(algo_rows, "size_mb", "butterfly_us",
                              "ring_us")


def build_cost_model(platform, n_devices, sendrecv_rows, algo_rows):
    """The ``--cost-calibrate`` payload: a complete ``mpx-tuning/1``
    file — the SUPERSET schema (mpi4jax_tpu/autotune/schema.py) that
    both ``MPI4JAX_TPU_COST_MODEL`` (the cost model keeps accepting
    plain ``mpx-cost-model/1`` files too — documented alias, no
    breaking change) and the ``MPI4JAX_TPU_TUNING`` config layer load
    verbatim: one calibration capture feeds the selector and the cost
    model alike (docs/autotune.md).

    ICI alpha/beta are fit by least squares over the sendrecv ring
    latency sweep (one hop = one alpha + payload/bandwidth — exactly
    the model's p2p term); the DCN class is scaled from the ICI fit by
    the documented analytic ratios (the virtual CPU mesh has no real
    DCN to measure; a multi-host capture overwrites it by hand or via a
    future ``mpx.autotune()``).  The measured ring crossover is
    interpolated from the forced butterfly-vs-ring sweep.
    """
    from mpi4jax_tpu.analysis import costmodel

    pts = [(r["size_kb"] * 1e3, r["hop_us"]) for r in sendrecv_rows]
    alpha_us, gb_per_s = fit_alpha_beta(pts)
    defaults = costmodel.DEFAULT_PARAMS
    dcn_alpha_ratio = (defaults["links"]["dcn"]["alpha_us"]
                       / defaults["links"]["ici"]["alpha_us"])
    dcn_bw_ratio = (defaults["links"]["dcn"]["gb_per_s"]
                    / defaults["links"]["ici"]["gb_per_s"])
    payload = {
        "schema": costmodel.TUNING_SCHEMA,
        "source": (f"benchmarks/micro.py --cost-calibrate ({platform}, "
                   f"{n_devices} devices; dcn scaled from the ici fit "
                   "by the analytic ratios)"),
        "links": {
            "ici": {"alpha_us": round(alpha_us, 4),
                    "gb_per_s": round(gb_per_s, 4)},
            "dcn": {"alpha_us": round(alpha_us * dcn_alpha_ratio, 4),
                    "gb_per_s": round(max(gb_per_s * dcn_bw_ratio,
                                          0.001), 4)},
        },
        "gamma_gb_per_s": defaults["gamma_gb_per_s"],
        "compute_gb_per_s": defaults["compute_gb_per_s"],
        "dispatch_us": defaults["dispatch_us"],
    }
    crossover = measured_ring_crossover(algo_rows)
    if crossover is not None:
        payload["measured"] = {"ring_crossover_bytes": crossover}
        # the superset's tuned section: the config layer serves this
        # value to resolve_algo when the file loads as MPI4JAX_TPU_TUNING
        payload["tuned"] = {"ring_crossover_bytes": crossover}
    payload["provenance"] = provenance_block(platform, n_devices)
    # the emitted file must load verbatim through BOTH consumers —
    # validate against the superset schema (which delegates the
    # cost-model section to the cost model's own rules) before anyone
    # saves it
    from mpi4jax_tpu.autotune.schema import validate_tuning_dict

    validate_tuning_dict(payload)
    return payload


def save_cost_model(payload, outdir=None):
    """Write a ``--cost-calibrate`` tuning file to
    ``benchmarks/results/`` (dated like ``save_results``), returning
    the path — the file ``MPI4JAX_TPU_COST_MODEL`` points at."""
    import datetime
    import re

    if outdir is None:
        outdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "results")
    os.makedirs(outdir, exist_ok=True)
    stamp = datetime.date.today().strftime("%Y%m%d")
    m = re.search(r"\((\w+), (\d+) devices", payload.get("source", ""))
    tag = f"{m.group(1)}_{m.group(2)}dev" if m else "unknown"
    path = os.path.join(outdir, f"cost_model_{tag}_{stamp}.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def save_results(payload, outdir=None):
    """Write one sweep payload to ``benchmarks/results/`` (the ``--save``
    flag): ``micro_{platform}_{n}dev_{YYYYMMDD}.json``, returning the path
    (dated so committed captures are never silently clobbered)."""
    import datetime

    if outdir is None:
        outdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "results")
    os.makedirs(outdir, exist_ok=True)
    stamp = datetime.date.today().strftime("%Y%m%d")
    path = os.path.join(
        outdir,
        f"micro_{payload['platform']}_{payload['n_devices']}dev_{stamp}.json",
    )
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument("--save", action="store_true",
                   help="write the sweep to benchmarks/results/")
    p.add_argument("--telemetry", action="store_true",
                   help="run under MPI4JAX_TPU_TELEMETRY=counters and embed "
                        "a per-section counter snapshot (algorithm "
                        "selections, bytes, cache stats) in the payload, so "
                        "saved BENCH files carry which algorithm actually "
                        "ran for each sweep (docs/observability.md)")
    p.add_argument("--sizes-mb", type=float, nargs="+",
                   default=[0.004, 0.25, 1, 4, 16, 64])
    p.add_argument("--sizes-kb", type=float, nargs="+",
                   default=[0.004, 4, 64, 1024])
    p.add_argument("--fusion-sweep", action="store_true",
                   help="also run the collective-fusion sweep (N small "
                        "allreduces fused vs unfused, per-op dispatch µs; "
                        "docs/overlap.md)")
    p.add_argument("--fusion-counts", type=int, nargs="+", default=[8, 32],
                   help="allreduce counts for --fusion-sweep")
    p.add_argument("--fusion-size-kb", type=float, default=64,
                   help="per-allreduce payload for --fusion-sweep (KiB)")
    p.add_argument("--overlap-sweep", action="store_true",
                   help="also run the async-overlap sweep (chunked "
                        "start/wait vs monolithic allreduce with "
                        "synthetic compute in the gap)")
    p.add_argument("--overlap-sizes-mb", type=float, nargs="+",
                   default=[1, 4],
                   help="payload sizes for --overlap-sweep (MB)")
    p.add_argument("--hierarchy-sweep", action="store_true",
                   help="also run the hierarchical-collective sweep "
                        "(flat ring vs the forced two-level ICI/DCN "
                        "lowering over a payload x topology grid; each "
                        "topology faked via MPI4JAX_TPU_TOPOLOGY and "
                        "stamped into the saved rows; docs/topology.md)")
    p.add_argument("--hierarchy-topologies", nargs="+",
                   default=["2x4", "4x2"],
                   help="MPI4JAX_TPU_TOPOLOGY specs for "
                        "--hierarchy-sweep (must cover the mesh size; "
                        "non-matching specs are skipped with a note)")
    p.add_argument("--hierarchy-sizes-mb", type=float, nargs="+",
                   default=[1, 4],
                   help="payload sizes for --hierarchy-sweep (MB)")
    p.add_argument("--alltoall-sweep", action="store_true",
                   help="also run the alltoall sweep (flat single-"
                        "exchange vs the forced two-level ICI/DCN "
                        "lowering vs the chunked async start/wait "
                        "split, over a payload x topology grid with "
                        "the modeled DCN byte/message columns; "
                        "docs/moe.md)")
    p.add_argument("--alltoall-topologies", nargs="+",
                   default=["2x4", "4x2"],
                   help="MPI4JAX_TPU_TOPOLOGY specs for "
                        "--alltoall-sweep (non-matching specs are "
                        "skipped with a note)")
    p.add_argument("--alltoall-sizes-mb", type=float, nargs="+",
                   default=[0.25, 1],
                   help="payload sizes for --alltoall-sweep (MB)")
    p.add_argument("--compression-sweep", action="store_true",
                   help="also run the wire-codec sweep (logical vs wire "
                        "DCN bytes, modeled DCN-leg time, and measured "
                        "round-trip error for {off,bf16,fp8} over a "
                        "payload grid; docs/compression.md)")
    p.add_argument("--compression-sizes-mb", type=float, nargs="+",
                   default=[0.25, 1, 4],
                   help="payload sizes for --compression-sweep (MB)")
    p.add_argument("--compression-topology", default="2x4",
                   help="modeled MPI4JAX_TPU_TOPOLOGY spec for "
                        "--compression-sweep's DCN-leg byte math")
    p.add_argument("--dispatch-sweep", action="store_true",
                   help="also run the dispatch sweep (per-call overhead "
                        "of eager vs spmd vs mpx.compile-pinned for the "
                        "same one-allreduce program across payload "
                        "sizes; docs/aot.md)")
    p.add_argument("--dispatch-sizes-kb", type=float, nargs="+",
                   default=[0.004, 4, 64],
                   help="payload sizes for --dispatch-sweep (KiB)")
    p.add_argument("--dispatch-iters", type=int, default=100,
                   help="calls per timed loop for --dispatch-sweep")
    p.add_argument("--dispatch-unrolls", type=int, nargs="+",
                   default=[1, 8, 64],
                   help="megastep trip counts for --dispatch-sweep's "
                        "unroll axis (mpx.compile(fn, ..., unroll=N): "
                        "per-step host cost amortizes ~1/N; "
                        "docs/aot.md 'Megastep execution')")
    p.add_argument("--health-overhead", action="store_true",
                   help="also run the health-plane overhead sweep "
                        "(per-call dispatch cost under off / counters / "
                        "counters+flight-ring / events across payloads; "
                        "the counters+ring column must stay within 10% "
                        "of counters-only — docs/observability.md "
                        "'Runtime health')")
    p.add_argument("--health-sizes-kb", type=float, nargs="+",
                   default=[0.004, 4, 64],
                   help="payload sizes for --health-overhead (KiB)")
    p.add_argument("--health-iters", type=int, default=200,
                   help="calls per timed loop for --health-overhead")
    p.add_argument("--cost-calibrate", action="store_true",
                   help="fit the static cost model's alpha/beta per "
                        "link class (least squares over the sendrecv "
                        "latency sweep) plus the measured ring "
                        "crossover, and emit an mpx-cost-model/1 "
                        "tuning file that MPI4JAX_TPU_COST_MODEL loads "
                        "verbatim (with --save: written to "
                        "benchmarks/results/cost_model_*.json; "
                        "docs/analysis.md 'Cost model')")
    args = p.parse_args()

    devices = jax.devices()
    mesh = mpx.make_world_mesh(devices=devices)
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    n = comm.Get_size()

    telemetry_sections = {}

    def _section(name, fn, *fn_args):
        """Run one sweep; under --telemetry, bracket it with a counter
        reset/snapshot so each section's snapshot attributes ITS traffic
        (algo selections per op, bytes, cache churn) and nothing else's.
        cache_stats are process-cumulative (reset only by clear_caches),
        so the section embeds the DELTA over the sweep."""
        if not args.telemetry:
            return fn(*fn_args)
        mpx.telemetry.reset()
        cache_before = mpx.cache_stats()
        rows = fn(*fn_args)
        cache_after = mpx.cache_stats()
        snap = mpx.telemetry.snapshot()
        telemetry_sections[name] = {
            "ops": snap["ops"],
            "meters": snap["meters"],
            "cache_stats": {
                k: (cache_after[k] - cache_before[k]
                    if k in ("hits", "misses", "evictions")
                    else cache_after[k])
                for k in cache_after
            },
        }
        return rows

    if args.telemetry:
        mpx.set_telemetry_mode("counters")

    ar = _section("allreduce", bench_allreduce, comm, args.sizes_mb)
    pp = _section("sendrecv_ring", bench_sendrecv_ring, comm, args.sizes_kb)
    pr = _section("prod_butterfly", bench_prod_and_split, comm,
                  args.sizes_mb[:4])
    al = _section("allreduce_algos", bench_allreduce_algos, comm,
                  args.sizes_mb)
    fu = (_section("fusion", bench_fusion, comm, tuple(args.fusion_counts),
                   args.fusion_size_kb)
          if args.fusion_sweep else None)
    ov = (_section("overlap", bench_overlap, comm,
                   tuple(args.overlap_sizes_mb))
          if args.overlap_sweep else None)
    hs = (_section("hierarchy", bench_hierarchy, comm,
                   tuple(args.hierarchy_sizes_mb),
                   tuple(args.hierarchy_topologies))
          if args.hierarchy_sweep else None)
    a2a = (_section("alltoall", bench_alltoall, comm,
                    tuple(args.alltoall_sizes_mb),
                    tuple(args.alltoall_topologies))
           if args.alltoall_sweep else None)
    cp = (_section("compression", bench_compression, comm,
                   tuple(args.compression_sizes_mb),
                   args.compression_topology)
          if args.compression_sweep else None)
    ds = (_section("dispatch", bench_dispatch, comm,
                   tuple(args.dispatch_sizes_kb), args.dispatch_iters)
          if args.dispatch_sweep else None)
    du = (_section("dispatch_unroll", bench_dispatch_unroll, comm,
                   tuple(args.dispatch_unrolls),
                   min(args.dispatch_sizes_kb), args.dispatch_iters)
          if args.dispatch_sweep else None)
    # NOT under _section: the sweep manages its own telemetry modes
    ho = (bench_health_overhead(comm, tuple(args.health_sizes_kb),
                                args.health_iters)
          if args.health_overhead else None)

    payload = {
        "schema": MICRO_SCHEMA,
        "platform": devices[0].platform,
        "n_devices": n,
        # self-description (jax/jaxlib, topology, config stamp): saved
        # sweeps are fitter inputs, so they must say what produced them
        "provenance": provenance_block(devices[0].platform, n),
        # honesty marker (docs/microbenchmarks.md): with a single
        # device there is no interconnect to measure, and dispatch/
        # attach overhead can dominate the timings — never read 1-device
        # numbers as link bandwidth or latency
        "environment": (
            f"{n}-device {devices[0].platform}"
            + ("; no interconnect to measure — timings may be "
               "dispatch/attach-dominated (docs/microbenchmarks.md)"
               if n == 1 else "")
        ),
        "allreduce": ar,
        "sendrecv_ring": pp,
        "prod_butterfly": pr,
        "allreduce_algos": al,
    }
    if fu is not None:
        payload["fusion"] = fu
    if ov is not None:
        payload["overlap"] = ov
    if hs is not None:
        payload["hierarchy"] = hs
        payload["hierarchy_topologies"] = list(args.hierarchy_topologies)
    if a2a is not None:
        payload["alltoall"] = a2a
        payload["alltoall_topologies"] = list(args.alltoall_topologies)
    if cp is not None:
        payload["compression"] = cp
        payload["compression_topology"] = args.compression_topology
    if ds is not None:
        payload["dispatch"] = ds
        # the AOT/persistent-cache counters are the sweep's provenance:
        # whether the pinned column was served from disk or compiled
        # (one cache_stats() call — it walks the disk tier when enabled)
        cstats = mpx.cache_stats()
        payload["dispatch_cache_stats"] = {
            k: cstats[k] for k in ("aot", "disk_cache")
        }
    if du is not None:
        payload["dispatch_unroll"] = du
    if ho is not None:
        payload["health_overhead"] = ho
    if args.cost_calibrate:
        cm = build_cost_model(devices[0].platform, n, pp, al)
        payload["cost_model"] = cm
        if args.save:
            path = save_cost_model(cm)
            print(f"saved cost model: {path}", file=sys.stderr)
    if args.telemetry:
        payload["telemetry"] = telemetry_sections
        mpx.set_telemetry_mode(None)
    if args.save:
        path = save_results(payload)
        print(f"saved: {path}", file=sys.stderr)
    if args.json:
        print(json.dumps(payload))
        return

    print(f"platform={devices[0].platform} n_devices={n}")
    print("\nallreduce (SUM, f32)          time/op      bus bandwidth/device")
    for r in ar:
        bw = f"{r['bus_gb_s']} GB/s" if r["bus_gb_s"] is not None else "n/a (1 device)"
        print(f"  {r['size_mb']:>10.3f} MB   {r['time_us']:>10.1f} us   {bw}")
    print("\nsendrecv ring (shift(1))      time/hop     link bandwidth")
    for r in pp:
        bw = (f"{r['link_gb_s']} GB/s" if r["link_gb_s"] is not None
              else "n/a (1 device)")
        print(f"  {r['size_kb']:>10.2f} KB   {r['hop_us']:>10.2f} us   {bw}")
    print("\nPROD butterfly (log-depth)    whole comm   even/odd split")
    for r in pr:
        sp = (f"{r['prod_split_us']:>10.1f} us"
              if r["prod_split_us"] is not None else "n/a (1 device)")
        print(f"  {r['size_mb']:>10.3f} MB   {r['prod_us']:>10.1f} us   {sp}")
    print("\nPROD algo crossover           butterfly    ring         ring speedup")
    for r in al:
        sp = (f"{r['ring_speedup']:>6.2f}x"
              if r["ring_speedup"] is not None else "n/a (1 device)")
        print(f"  {r['size_mb']:>10.3f} MB   {r['butterfly_us']:>10.1f} us"
              f"   {r['ring_us']:>10.1f} us   {sp}")
    if fu is not None:
        print("\nfusion sweep (SUM, f32)       unfused      fused        speedup")
        for r in fu:
            print(f"  {r['count']:>4} x {r['size_kb']:>7.1f} KB"
                  f"   {r['unfused_us_per_op']:>8.2f} us"
                  f"   {r['fused_us_per_op']:>8.2f} us"
                  f"   {r['fused_speedup']:>6.2f}x")
    if ov is not None:
        print("\noverlap sweep (SUM, f32)      monolithic   start/wait   speedup")
        for r in ov:
            print(f"  {r['size_mb']:>10.3f} MB   {r['monolithic_us']:>8.1f} us"
                  f"   {r['overlap_us']:>8.1f} us"
                  f"   {r['overlap_speedup']:>6.2f}x")
    if hs is not None:
        print("\nhierarchy sweep (PROD, f32)   topology   flat ring"
              "    two-level    hier speedup")
        for r in hs:
            sp = (f"{r['hier_speedup']:>6.2f}x"
                  if r["hier_speedup"] is not None else "n/a (1 device)")
            print(f"  {r['size_mb']:>10.3f} MB   {r['topology']:>8}"
                  f"   {r['flat_us']:>8.1f} us   {r['hier_us']:>8.1f} us"
                  f"   {sp}")
    if a2a is not None:
        print("\nalltoall sweep (f32)          topology   flat"
              "         two-level    async        hier speedup")
        for r in a2a:
            sp = (f"{r['hier_speedup']:>6.2f}x"
                  if r["hier_speedup"] is not None else "n/a (1 device)")
            print(f"  {r['size_mb']:>10.4f} MB   {r['topology']:>8}"
                  f"   {r['flat_us']:>8.1f} us   {r['hier_us']:>8.1f} us"
                  f"   {r['async_us']:>8.1f} us   {sp}")
    if cp is not None:
        print("\ncompression sweep (f32)       codec  logical DCN"
              "   wire DCN     modeled      max rel err")
        for r in cp:
            print(f"  {r['size_mb']:>10.4f} MB   {r['codec']:>4}"
                  f"   {r['logical_dcn_bytes']:>10}   {r['wire_dcn_bytes']:>10}"
                  f"   {r['modeled_dcn_us']:>8.2f} us   {r['rel_err']:.2e}")
    if ds is not None:
        print("\ndispatch sweep (SUM, f32)     eager        spmd"
              "         pinned       pinned vs spmd")
        for r in ds:
            sp = (f"{r['pinned_vs_spmd']:>6.2f}x"
                  if r["pinned_vs_spmd"] is not None else "-")
            print(f"  {r['size_kb']:>10.3f} KB   {r['eager_us']:>8.2f} us"
                  f"   {r['spmd_us']:>8.2f} us   {r['pinned_us']:>8.2f} us"
                  f"   {sp}")
    if ho is not None:
        print("\nhealth overhead (eager SUM)   off          counters"
              "     +ring        events       ring/counters")
        for r in ho:
            ratio = (f"{r['ring_overhead_ratio']:>6.3f}x"
                     if r["ring_overhead_ratio"] is not None else "-")
            print(f"  {r['size_kb']:>10.3f} KB   {r['off_us']:>8.2f} us"
                  f"   {r['counters_us']:>8.2f} us"
                  f"   {r['counters_ring_us']:>8.2f} us"
                  f"   {r['events_us']:>8.2f} us   {ratio}")
    if du is not None:
        print(f"\nmegastep unroll sweep ({du['size_kb']} KB; on-chip "
              f"~{du['onchip_per_step_us']} us/step)"
              "\n  unroll   megastep/call   per step     host/step")
        for r in du["rows"]:
            print(f"  {r['unroll']:>6}   {r['megastep_us']:>10.2f} us"
                  f"   {r['per_step_us']:>8.3f} us"
                  f"   {r['per_step_host_us']:>8.3f} us")
    if args.cost_calibrate:
        cm = payload["cost_model"]
        ici = cm["links"]["ici"]
        print(f"\ncost model fit (ici): alpha {ici['alpha_us']} us, "
              f"{ici['gb_per_s']} GB/s"
              + (f"; measured ring crossover "
                 f"{cm['measured']['ring_crossover_bytes']} B"
                 if "measured" in cm else ""))


if __name__ == "__main__":
    main()
