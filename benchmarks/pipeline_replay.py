"""Cost-model replay of the pipeline schedules -> BENCH_pipeline.json.

The committed acceptance artifact of the ``mpx.pipeline`` PR
(docs/pipeline.md): prices one forward round of every expressible
schedule — plus the naive ladder it replaces — over the acceptance grid
the PR names, 8 stages x {4, 8, 16} microbatches at a 1 MiB boundary
activation, with the analytic cost model's documented defaults
(``analysis/costmodel.py``; no accelerator, fully reproducible).

Each grid row records the modeled wall clock, the modeled bubble time
(wall minus the ``M*c`` a perfectly full pipe would take), the phase
split the schedule compiler emits (``parallel/pipeline.py``), and the
activation-stash bound.  The headline orderings the PR's acceptance
criteria name, asserted at capture time so a stale artifact can never
claim them silently:

- ``1f1b < gpipe < ladder`` on modeled bubble time at every microbatch
  count (async overlap hides the wire; microbatching kills the
  serialized fill);
- the 1F1B activation stash stays at ``min(S, M)`` while GPipe's grows
  with ``M`` — the PipeDream-flush memory win;
- ``schedule='auto'``'s argmin agrees with the per-row minimum.

The artifact rides the CI perf ratchet (``benchmarks/regress.py``
against the committed baseline) and is regenerated + byte-diffed in the
pipeline lane (.github/workflows/test.yml), so any drift in the model
or the formulas must recapture it.

Run:  python benchmarks/pipeline_replay.py [--out BENCH_pipeline.json]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX — or none.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_pipeline_replay"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "analysis.costmodel", "parallel.pipeline"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


SCHEMA = "mpx-pipeline-replay/1"

STAGES = 8
MICROBATCH_GRID = (4, 8, 16)
PAYLOAD_MB = 1
VIRTUAL = 2  # the interleaved rows' chunks-per-rank


def grid_row(cm, pl, model, schedule, m, payload, c):
    virtual = VIRTUAL if schedule == "interleaved" else 1
    wall = cm.pipeline_wall_us(schedule, STAGES, m, payload, c, model,
                               virtual=virtual)
    frac = cm.pipeline_bubble_fraction(schedule, STAGES, m, payload, c,
                                       model, virtual=virtual)
    row = {
        "op": schedule,
        "count": m,
        "size_mb": PAYLOAD_MB,
        "wall_us": round(wall, 2),
        "bubble_us": round(wall * frac, 2),
        "bubble_fraction_x1000": int(round(frac * 1000)),
    }
    if schedule != "ladder":
        plan = pl.compile_phases(schedule, STAGES, m, virtual)
        row.update(
            warmup_ticks=plan.warmup,
            steady_ticks=plan.steady,
            cooldown_ticks=plan.cooldown,
            max_stash=plan.max_stash,
        )
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "BENCH_pipeline.json"))
    args = ap.parse_args()
    root = _load()
    cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
    pl = sys.modules[f"{_ISO_NAME}.parallel.pipeline"]

    model = cm.CostModel()  # the documented analytic defaults
    payload = PAYLOAD_MB << 20
    # the compiler's roofline floor for the per-microbatch stage
    # compute: a stage at minimum streams its boundary activation in
    # and out (parallel/pipeline.py PipelineProgram.plan)
    c = model.compute_us(2 * payload)

    grid = []
    auto_picks = []
    for m in MICROBATCH_GRID:
        rows = {s: grid_row(cm, pl, model, s, m, payload, c)
                for s in cm.PIPELINE_SCHEDULES}
        grid.extend(rows[s] for s in cm.PIPELINE_SCHEDULES)
        # the cross-shape argmin over ALL grid rows: explicit
        # candidates, because best_schedule's defaults only price what
        # one program shape can express (flat -> gpipe/1f1b, chunked ->
        # interleaved alone) while this artifact compares across shapes
        best, times = cm.best_schedule(
            STAGES, m, payload, c, model, virtual=VIRTUAL,
            candidates=("gpipe", "1f1b", "interleaved"))
        auto_picks.append({
            "count": m,
            "pick": best,
            "pick_wall_us": round(times[best], 2),
        })
        # the acceptance orderings, at capture time
        assert rows["1f1b"]["bubble_us"] < rows["gpipe"]["bubble_us"] \
            < rows["ladder"]["bubble_us"], rows
        assert rows["1f1b"]["wall_us"] < rows["gpipe"]["wall_us"] \
            < rows["ladder"]["wall_us"], rows
        assert rows["1f1b"]["max_stash"] == min(STAGES, m), rows
        assert rows["gpipe"]["max_stash"] == m, rows
        assert best == min(
            (s for s in times), key=lambda s: (times[s], s)), (best, times)

    payload_out = {
        "schema": SCHEMA,
        "stages": STAGES,
        "payload_mb": PAYLOAD_MB,
        "stage_compute_us": round(c, 3),
        "grid": grid,
        "auto": auto_picks,
        "cost_model": cm.CostModel().to_json(),
        "provenance": {
            "kind": "cost-model replay (no accelerator; the measured "
                    "bubble fraction comes from the eager phase "
                    "driver's pipeline.* meters in telemetry.report() "
                    "— docs/pipeline.md 'Measured bubbles')",
            "recipe": "python benchmarks/pipeline_replay.py",
            "microbatch_grid": list(MICROBATCH_GRID),
            "interleaved_virtual": VIRTUAL,
        },
    }
    with open(args.out, "w") as f:
        json.dump(payload_out, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}: {len(grid)} grid row(s), auto picks "
          f"{[(r['count'], r['pick']) for r in auto_picks]}")
    del root


if __name__ == "__main__":
    main()
