"""Perf-regression ratchet: diff a fresh sweep against a committed
baseline (ROADMAP item 5, first slice).

Compares the MODELED/measured time columns (``*_us`` leaves by default)
of a ``--current`` JSON payload — a ``benchmarks/micro.py --save``
capture, a ``BENCH_*`` replay, anything with the same shape — against a
``--baseline`` at identical paths, and exits nonzero when any column
regressed by more than ``--threshold`` (default 10%).  Paths present on
only one side are reported but never fail the run: sweeps grow new
rows, and a ratchet that blocks additions teaches people to stop
measuring.

Positions are identity, not order: rows inside a list are keyed by
their discriminating columns (size/topology/codec/count/...) when
present, falling back to the list index, so inserting a payload point
mid-grid does not misalign every later comparison.

Run:  python benchmarks/regress.py --current new.json \
          --baseline BENCH_alltoall.json [--threshold 0.10]

Exit codes: 0 clean, 1 regression over threshold, 2 usage/IO error —
the analysis CLI's contract.  Wired into the microbench CI smoke lane
(.github/workflows/test.yml) over the committed replay artifacts.
"""

import argparse
import json
import sys

# a list row's identity, built from whichever of these it carries (in
# this order) — the discriminating axes every sweep in this repo uses
ID_KEYS = ("op", "codec", "topology", "size_mb", "size_kb", "count",
           "chunks", "unroll", "experts", "step")


def _row_key(row, index):
    if isinstance(row, dict):
        ident = tuple((k, row[k]) for k in ID_KEYS if k in row)
        if ident:
            return ident
    return index


def collect(node, suffix, path=()):
    """Flatten ``node`` to ``{path: value}`` over numeric leaves whose
    final key ends with ``suffix``."""
    out = {}
    if isinstance(node, dict):
        for k, v in node.items():
            if isinstance(v, (dict, list)):
                out.update(collect(v, suffix, path + (k,)))
            elif (isinstance(v, (int, float)) and not isinstance(v, bool)
                    and k.endswith(suffix)):
                out[path + (k,)] = float(v)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            out.update(collect(v, suffix, path + (_row_key(v, i),)))
    return out


def compare(current, baseline, suffix="_us", threshold=0.10):
    """Returns ``(regressions, improvements, only_current,
    only_baseline)``; a regression is ``current > baseline * (1 +
    threshold)`` with baseline > 0."""
    cur = collect(current, suffix)
    base = collect(baseline, suffix)
    regressions, improvements = [], []
    for path in sorted(set(cur) & set(base), key=str):
        c, b = cur[path], base[path]
        if b <= 0:
            continue
        ratio = c / b
        if ratio > 1.0 + threshold:
            regressions.append((path, b, c, ratio))
        elif ratio < 1.0 - threshold:
            improvements.append((path, b, c, ratio))
    return (regressions, improvements,
            sorted(set(cur) - set(base), key=str),
            sorted(set(base) - set(cur), key=str))


def _fmt(path):
    return "/".join(str(p) for p in path)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--current", required=True,
                    help="fresh sweep payload (micro.py --save / replay)")
    ap.add_argument("--baseline", required=True,
                    help="committed baseline (BENCH_*.json)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="allowed fractional slowdown (default 0.10)")
    ap.add_argument("--suffix", default="_us",
                    help="leaf-key suffix to compare (default _us)")
    args = ap.parse_args(argv)
    if args.threshold < 0:
        print(f"regress: --threshold must be >= 0, got {args.threshold}",
              file=sys.stderr)
        return 2
    try:
        with open(args.current) as f:
            current = json.load(f)
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    reg, imp, only_cur, only_base = compare(
        current, baseline, suffix=args.suffix, threshold=args.threshold)
    for path, b, c, ratio in reg:
        print(f"REGRESSION {_fmt(path)}: {b:g} -> {c:g} "
              f"({(ratio - 1) * 100:.1f}% slower)")
    for path, b, c, ratio in imp:
        print(f"improved   {_fmt(path)}: {b:g} -> {c:g} "
              f"({(1 - ratio) * 100:.1f}% faster)")
    if only_cur:
        print(f"new (unchecked): {len(only_cur)} column(s), e.g. "
              f"{_fmt(only_cur[0])}")
    if only_base:
        print(f"missing from current: {len(only_base)} column(s), e.g. "
              f"{_fmt(only_base[0])}")
    checked = len(collect(baseline, args.suffix))
    print(f"regress: {len(reg)} regression(s) over "
          f"{args.threshold:.0%} across {checked} baseline column(s)")
    return 1 if reg else 0


if __name__ == "__main__":
    sys.exit(main())
