"""Cost-model replay of the serving benchmark -> BENCH_serving.json.

Regenerates the committed serving acceptance artifact (docs/serving.md
"Capture protocol") by executing the recipe embedded in the artifact's
own ``provenance.reproduce`` field: the REAL continuous/static
schedulers (serving/scheduler.py) over the pinned Poisson trace, every
device dispatch priced by the static communication cost model
(analysis/costmodel.py) on a virtual clock — deterministic, no
accelerator, no jax.

The CI microbench smoke lane runs this back-to-back with
``benchmarks/regress.py --suffix _ms`` against the committed
``BENCH_serving.json``, so a change that shifts the modeled serving
latencies (p50/p99/TTFT at the p99 SLO) or the continuous-vs-static
speedup trips the ratchet the same way the alltoall replay does
(.github/workflows/test.yml).

Run:  python benchmarks/serving_replay.py [--out BENCH_serving.json]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_serving_replay"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "serving"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "analysis.costmodel", "serving.buckets",
                "serving.kvcache", "serving.metrics", "serving.scheduler",
                "serving.model", "serving.engine", "serving.sim"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root

# the committed capture's exact knobs (BENCH_serving.json
# provenance.reproduce — keep the three blocks in sync)
MODEL = {"heads": 24, "head_dim": 64, "ffn": 6144, "max_len": 160,
         "max_prompt": 16, "max_batch": 8, "unroll": 8,
         "slo_p99_ms": 1000.0, "seed": 7}
TRACE = {"n_requests": 384, "rate_rps": 8000.0, "seed": 7,
         "prompt_len": (4, 16), "max_new": (8, 24), "long_frac": 0.25,
         "long_new": (96, 128), "vocab": 64}
CHIPS = 8


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "BENCH_serving.json"))
    args = ap.parse_args()
    root = _load()
    eng = sys.modules[f"{_ISO_NAME}.serving.engine"]
    sched = sys.modules[f"{_ISO_NAME}.serving.scheduler"]
    sim = sys.modules[f"{_ISO_NAME}.serving.sim"]

    cfg = eng.ServingConfig(**MODEL)
    t = dict(TRACE)
    trace = sched.poisson_trace(
        t.pop("n_requests"), t.pop("rate_rps"), **t)
    trace_meta = {
        **{k: list(v) if isinstance(v, tuple) else v
           for k, v in TRACE.items()},
        "span_s": round(trace[-1].arrival_s, 4),
        "tokens_budgeted": sum(r.max_new_tokens for r in trace),
    }
    reproduce = (
        "from mpi4jax_tpu.serving import ServingConfig, poisson_trace; "
        "from mpi4jax_tpu.serving.sim import replay_bench; "
        f"cfg = ServingConfig(**{MODEL}); "
        f"trace = poisson_trace({TRACE['n_requests']}, "
        f"{TRACE['rate_rps']}, seed={TRACE['seed']}, "
        f"prompt_len={TRACE['prompt_len']}, max_new={TRACE['max_new']}, "
        f"long_frac={TRACE['long_frac']}, long_new={TRACE['long_new']}, "
        f"vocab={TRACE['vocab']}); "
        f"replay_bench(cfg, trace, k={CHIPS}, trace_meta={{}})"
    )
    payload, cont, stat = sim.replay_bench(
        cfg, trace, k=CHIPS, trace_meta=trace_meta,
        environment=(
            "simulated: cost-model-driven replay of the shipped "
            "scheduler over an 8-chip tensor-parallel group "
            "(analysis/costmodel.py analytic defaults; no accelerator "
            "in this container) — capture protocol and the "
            "measured-lane recipe in docs/serving.md; the CI serving "
            "lane runs the real engine on the 8-device CPU mesh and "
            "uploads its measured payload"))
    payload["provenance"] = {
        "cost_model": "analytic defaults (analysis/costmodel."
                      "DEFAULT_PARAMS)",
        "generator": "mpi4jax_tpu.serving.sim.replay_bench",
        "reproduce": reproduce,
    }
    # the acceptance invariants, asserted at capture time so a stale
    # artifact can never claim them silently
    assert cont["failed"] == 0 and stat["failed"] == 0, (cont, stat)
    assert payload["speedup_tokens_per_s"] > 1.0, payload
    assert cont["p99_ms"] <= cfg.slo_p99_ms, cont
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}: continuous p99 {cont['p99_ms']} ms vs "
          f"static {stat['p99_ms']} ms at the {cfg.slo_p99_ms} ms SLO, "
          f"speedup {payload['speedup_tokens_per_s']}x tokens/s/chip")
    del root


if __name__ == "__main__":
    main()
