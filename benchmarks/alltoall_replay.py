"""Cost-model replay of the alltoall fast path -> BENCH_alltoall.json.

The committed acceptance artifact of the expert-parallel MoE PR
(docs/moe.md): prices the three alltoall execution shapes — flat
single-exchange, two-level hierarchical, chunked async — and the MoE
step (dispatch -> per-expert MLP -> combine) with the combine either
synchronous or overlapped against the next capacity chunk's compute,
using the static cost model (``analysis/costmodel.py``) exactly the way
``BENCH_serving.json`` was captured: dispatches priced by the model, no
accelerator required, fully reproducible from the recipe embedded in
the payload.

The two headline numbers the PR's acceptance criteria name:

- ``dcn_msg_reduction``: the hierarchical exchange's DCN message count
  is ``1/r`` of flat on every ``h x r`` topology (host-aggregated
  contiguous blocks — ``ops/_hierarchy.alltoall_dcn_messages``), with
  the modeled DCN byte/round split alongside;
- ``overlap_speedup``: the overlapped MoE step beats the synchronous
  variant in the cost-model replay (the combine rides
  ``alltoall_start`` while the next capacity chunk's MLP runs).

Run:  python benchmarks/alltoall_replay.py [--out BENCH_alltoall.json]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_a2a_replay"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops", "parallel", "analysis"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._algos", "ops._hierarchy",
                "analysis.costmodel"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


SCHEMA = "mpx-alltoall-replay/1"

# the replayed grid: 8 ranks (the CI mesh) under the two uniform
# 2-host/4-host partitions the lockstep suite pins
TOPOLOGIES = ((2, 4), (4, 2))
SIZES_MB = (0.25, 1.0, 4.0)

# the replayed MoE step (examples/moe_training.py shapes, scaled up to
# a perf-relevant payload): tokens per rank x model dim x ff dim
MOE = {"tokens": 4096, "d": 1024, "d_ff": 4096, "capacity_factor": 1.25}


def replay_sweep(cm, hier_mod, overlap_chunks):
    model = cm.CostModel()
    rows = []
    for h, r in TOPOLOGIES:
        k = h * r
        for mb in SIZES_MB:
            nbytes = int(mb * 1e6)
            flat = cm.collective_cost("alltoall", "native", nbytes, k,
                                      hosts=h)
            hier = cm.collective_cost("alltoall", "hier", nbytes, k,
                                      hosts=h, hier=(h, r))
            # the chunked async split's standalone price: same bytes,
            # C-1 pipeline-fill rounds per link (cm.chunked_async_cost
            # — the win is what the gap's compute hides, priced by the
            # moe_step replay below)
            split = cm.chunked_async_cost(hier, overlap_chunks)
            msgs_flat, msgs_hier = hier_mod.alltoall_dcn_messages(h, r)
            rows.append({
                "size_mb": mb,
                "topology": f"{h}x{r}",
                "flat_us": round(model.time_us(flat), 2),
                "hier_us": round(model.time_us(hier), 2),
                "async_chunks": overlap_chunks,
                "async_us": round(model.time_us(split), 2),
                "dcn_bytes_flat": flat.dcn.nbytes,
                "dcn_bytes_hier": hier.dcn.nbytes,
                "dcn_rounds_flat": flat.dcn.rounds,
                "dcn_rounds_hier": hier.dcn.rounds,
                "dcn_msgs_flat": msgs_flat,
                "dcn_msgs_hier": msgs_hier,
                # the acceptance ratio: hier ships the SAME permutation
                # in 1/r the DCN messages (host-aggregated contiguous
                # blocks), so the per-message model is 1/r of flat
                "dcn_msg_reduction": r,
                "hier_speedup": round(
                    model.time_us(flat) / max(model.time_us(hier), 1e-9),
                    3),
            })
    return rows


def replay_moe_step(cm, h, r, chunks):
    """Price one MoE step: dispatch alltoall + per-expert MLP + combine
    alltoall, synchronous vs overlapped.  The overlap pipeline: chunk
    1's MLP runs exposed, chunks 2..C overlap the previous chunk's
    in-flight combine (alltoall_start), and only the LAST chunk's
    combine is exposed — the cost-model form of parallel/moe.py."""
    model = cm.CostModel()
    k = h * r
    cap = -(-int(MOE["tokens"] * MOE["capacity_factor"]) // k)
    bucket_bytes = k * cap * MOE["d"] * 4  # one rank's (k, cap, d) f32
    exchange = cm.collective_cost(
        "alltoall", "hier", bucket_bytes, k, hosts=h, hier=(h, r))
    t_exchange = model.time_us(exchange)
    # roofline MLP time over the k*cap received tokens: reads+writes of
    # the (tokens, d) @ (d, d_ff) @ (d_ff, d) chain
    mlp_traffic = k * cap * (2 * MOE["d"] + 2 * MOE["d_ff"]) * 4
    t_mlp = model.compute_us(mlp_traffic)

    t_sync = t_exchange + t_mlp + t_exchange  # dispatch + MLP + combine

    per_chunk = cm.collective_cost(
        "alltoall", "hier", -(-bucket_bytes // chunks), k, hosts=h,
        hier=(h, r))
    t_chunk_comb = model.time_us(per_chunk)
    t_chunk_mlp = t_mlp / chunks
    t_overlap = (t_exchange + t_chunk_mlp
                 + (chunks - 1) * max(t_chunk_mlp, t_chunk_comb)
                 + t_chunk_comb)
    return {
        "topology": f"{h}x{r}",
        "experts": k,
        "capacity": cap,
        "capacity_chunks": chunks,
        "bucket_mb": round(bucket_bytes / 1e6, 3),
        "dispatch_us": round(t_exchange, 2),
        "mlp_us": round(t_mlp, 2),
        "combine_sync_us": round(t_exchange, 2),
        "sync_step_us": round(t_sync, 2),
        "overlap_step_us": round(t_overlap, 2),
        "overlap_speedup": round(t_sync / max(t_overlap, 1e-9), 3),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "BENCH_alltoall.json"))
    args = ap.parse_args()
    root = _load()
    cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
    hier_mod = sys.modules[f"{_ISO_NAME}.ops._hierarchy"]
    config = sys.modules[f"{_ISO_NAME}.utils.config"]

    chunks = config.moe_capacity_chunks()
    payload = {
        "schema": SCHEMA,
        "sweep": replay_sweep(cm, hier_mod, config.overlap_chunks()),
        "moe_step": [replay_moe_step(cm, h, r, chunks)
                     for h, r in TOPOLOGIES],
        "cost_model": cm.CostModel().to_json(),
        "provenance": {
            "kind": "cost-model replay (no accelerator; the measured "
                    "lane is benchmarks/micro.py --alltoall-sweep on "
                    "real hardware — capture protocol in docs/moe.md)",
            "recipe": "python benchmarks/alltoall_replay.py",
            "topologies": [f"{h}x{r}" for h, r in TOPOLOGIES],
            "sizes_mb": list(SIZES_MB),
            "moe": dict(MOE, capacity_chunks=chunks),
        },
    }
    # the acceptance invariants, asserted at capture time so a stale
    # artifact can never claim them silently
    for row in payload["sweep"]:
        assert row["dcn_msgs_flat"] == row["dcn_msgs_hier"] * \
            row["dcn_msg_reduction"], row
    for row in payload["moe_step"]:
        assert row["overlap_speedup"] > 1.0, row
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}: "
          f"{len(payload['sweep'])} sweep row(s), "
          f"moe overlap speedup "
          f"{[r['overlap_speedup'] for r in payload['moe_step']]}")
    del root


if __name__ == "__main__":
    main()
