"""Chaos-drill matrix for the elastic control plane -> BENCH_elastic.json.

The committed acceptance artifact of the pod-scale control-plane PR
(docs/resilience.md "Chaos drills"): runs every kill pattern of
``resilience/drill.py`` — single rank, host row, coordinator, cascading
double fault — over 8/16/64 simulated ranks, asserts the agreement and
restore invariants inline, and records the analytic cost numbers the
acceptance criteria name:

- coordinator-mediated agreement stays O(k): at most ``k`` report
  connections per round at every world size (vs the gossip fallback's
  O(k²), recorded alongside for the ratio);
- restore stays ~flat per survivor: repair bytes per surviving rank do
  not grow with k for a fixed committed state;
- the host-row kill restores bit-identically under the striped
  placement at 2x4 AND 4x2, and is asserted UNRECOVERABLE under the old
  neighbor placement on the same matrices (the negative control).

Everything is deterministic (pure simulation, no clocks, no sockets), so
CI regenerates the artifact and diffs it byte-for-byte against the
committed copy.

Run:  python benchmarks/elastic_drill.py [--save | --out PATH]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_elastic_drill"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "resilience.faultinject",
                "resilience.retry", "resilience.watchdog",
                "resilience.elastic", "resilience.drill"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


SCHEMA = "mpx-elastic-drill/1"

KS = (8, 16, 64)

# the host-row acceptance matrices: 2 hosts x 4 ranks and 4 hosts x 2
# ranks — the two 8-rank shapes the striped-placement goldens pin
HOST_ROW_TOPOLOGIES = ((4, 4), (2, 2, 2, 2))


def per_k_summary(matrix):
    """One row per world size: the headline cost numbers."""
    rows = []
    for k in KS:
        entries = [m for m in matrix if m["k"] == k]
        # the O(k) claim is judged on live-coordinator rounds (a dead
        # coordinator degrades to gossip by design, priced separately)
        live = [m["agreement"]["coordinator_connections"]
                for m in entries if m["pattern"] != "coordinator"]
        single = next(m for m in entries if m["pattern"] == "single")
        rows.append({
            "k": k,
            "coordinator_connections_max": max(live),
            "gossip_connections":
                single["agreement"]["gossip_connections"],
            "connection_ratio": round(
                single["agreement"]["gossip_connections"]
                / max(1, max(live)), 1),
            "repair_bytes_per_survivor_single":
                single["restore"]["repair_bytes_per_survivor"],
            "repair_bytes_per_survivor_host_row":
                next(m for m in entries if m["pattern"] == "host-row")
                ["restore"]["repair_bytes_per_survivor"],
        })
    return rows


def host_row_proof(drill):
    """The stripe-vs-neighbor acceptance drills at 2x4 and 4x2: stripe
    restores (asserted inside run_drill, with the neighbor negative
    control asserted unrecoverable on the same kill)."""
    out = []
    for counts in HOST_ROW_TOPOLOGIES:
        k = sum(counts)
        m = drill.run_drill("host-row", k, counts=counts)
        assert m["recovered"] and m.get("neighbor_unrecoverable"), m
        out.append({
            "topology": "x".join(
                [str(len(counts)), str(counts[0])]
                if len(set(counts)) == 1 else map(str, counts)),
            "k": k,
            "killed": m["killed"],
            "stripe_recovered": True,
            "neighbor_unrecoverable": True,
            "repair_bytes": m["restore"]["repair_bytes"],
        })
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write the payload to PATH")
    ap.add_argument("--save", action="store_true",
                    help="write the committed artifact "
                         "(BENCH_elastic.json at the repo root)")
    args = ap.parse_args()
    root = _load()
    drill = sys.modules[f"{_ISO_NAME}.resilience.drill"]

    matrix = drill.drill_matrix(ks=KS)
    summary = per_k_summary(matrix)
    # the O(k) acceptance assertion, at capture time: a stale artifact
    # can never claim the budget silently
    for row in summary:
        assert row["coordinator_connections_max"] <= row["k"], row
    payload = {
        "schema": SCHEMA,
        "per_k": summary,
        "host_row_proof": host_row_proof(drill),
        "matrix": matrix,
        "provenance": {
            "kind": "deterministic simulated-rank chaos drills (pure "
                    "protocol models; the 2-process TCP lane is the CI "
                    "faults/elastic steps — protocol in "
                    "docs/resilience.md)",
            "recipe": "python benchmarks/elastic_drill.py --save",
            "ks": list(KS),
            "patterns": list(drill.PATTERNS),
            "redundancy": 1,
        },
    }
    out = args.out or (str(REPO / "BENCH_elastic.json") if args.save
                       else None)
    text = json.dumps(payload, indent=2) + "\n"
    if out:
        with open(out, "w") as f:
            f.write(text)
        print(f"wrote {out}")
    for row in summary:
        print(f"k={row['k']:>3}: coordinator {row['coordinator_connections_max']:>3} "
              f"conns vs gossip {row['gossip_connections']:>5} "
              f"({row['connection_ratio']}x), repair/survivor "
              f"{row['repair_bytes_per_survivor_single']}B")
    del root


if __name__ == "__main__":
    main()
