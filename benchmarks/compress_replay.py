"""Wire-compression replay -> BENCH_compress.json.

The committed acceptance artifact of the wire-compressed-collectives PR
(docs/compression.md), captured the way ``BENCH_alltoall.json`` and
``BENCH_serving.json`` were: deterministic, no accelerator required,
fully reproducible from the recipe embedded in the payload.  Two parts:

- **wire sweep** — the cost model prices the hierarchical allreduce's
  DCN leg per codec ({off, bf16, fp8} x payload x topology); logical vs
  wire bytes come from the same ``ops/_codec.wire_bytes`` the telemetry
  counters use.  The acceptance ratio asserted at capture: bf16 and fp8
  each cut DCN wire bytes by >= 2x (bf16 exactly 2x, fp8 ~3.9x).

- **convergence harness** — a pure-NumPy error-feedback SGD replay of
  the data-parallel training loop: per-rank noisy gradients of a
  separable quadratic, compensated (``comp = g + residual``), pushed
  through bit-exact NumPy mirrors of the bf16/fp8 codecs
  (``ops/_compress.py``), residual updated to the quantization error,
  quantized gradients mean-reduced.  Elementwise arithmetic only — no
  BLAS — so the curves are byte-stable across machines.  Asserted at
  capture: each compressed loss curve tracks the exact one within the
  stated tolerance, and the error-feedback telescoping invariant holds
  per rank — ``sum_t q_t == sum_t g_t - residual_final`` — the residual
  CARRIES every bit the codec dropped instead of losing it, the
  property that keeps biased codecs convergent.  A naked-fp8 curve (no
  residual) rides along for reference; with per-chunk scaled e4m3 its
  floor matches in this noise regime, which is exactly why the knob
  defaults off and the harness pins tolerances rather than miracles.

The measured lane is CI's ``compress`` job, which runs the real
``examples/data_parallel_training.py`` under ``MPI4JAX_TPU_COMPRESS``
on an 8-device host mesh and asserts the same parity on live traced
curves; this replay is the committed, hardware-free record.

Run:  python benchmarks/compress_replay.py [--out BENCH_compress.json]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_compress_replay"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops", "analysis"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._codec", "analysis.costmodel"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


SCHEMA = "mpx-compress-replay/1"

# the replayed grid: 8 ranks (the CI mesh) under the two uniform
# 2-host/4-host partitions the lockstep suite pins
TOPOLOGIES = ((2, 4), (4, 2))
SIZES_MB = (0.25, 1.0, 4.0)
CODECS = ("off", "bf16", "fp8")

# the EF-SGD convergence replay: k ranks each holding a noisy gradient
# of the same separable quadratic sum((w - w*)^2) / 2 — the elementwise
# skeleton of examples/data_parallel_training.py's loss
CONV = {"ranks": 8, "dim": 4096, "steps": 300, "record_every": 10,
        "lr": 0.1, "noise": 0.05, "seed": 0}
# capture-time parity tolerance per codec: max over recorded steps of
# |loss_codec - loss_exact| / max(loss_exact, 1e-12), after one
# record_every warmup.  bf16 keeps fp32's exponent (~2^-8 relative
# mantissa error); fp8 leans on the error-feedback residual
PARITY_TOL = {"bf16": 2e-2, "fp8": 1e-1}


# ---------------------------------------------------------------------
# NumPy codec mirrors — bit-level twins of ops/_compress.py's traced
# encode/decode, kept elementwise so the replay is machine-stable
# ---------------------------------------------------------------------

def np_bf16_roundtrip(x):
    """fp32 -> bf16 (round-to-nearest-even on the upper 16 bits) ->
    fp32, as XLA's convert does."""
    b = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32)
    rounded = (b + np.uint32(0x7FFF) + ((b >> np.uint32(16))
                                        & np.uint32(1)))
    return (rounded & np.uint32(0xFFFF0000)).view(np.float32)


def np_fp8_e4m3(x):
    """Round ``x`` (already scaled into +-448) to float8_e4m3fn's grid:
    3 mantissa bits, exponents 2^-6..2^8, saturating at +-448."""
    x = np.asarray(x, dtype=np.float32)
    ax = np.abs(x)
    nz = ax > 0
    e = np.floor(np.log2(ax, out=np.zeros_like(ax), where=nz))
    e = np.clip(e, -6.0, 8.0)
    step = np.exp2(e - 3.0)
    q = np.round(x / np.where(nz, step, 1.0)) * step
    return np.clip(q, -448.0, 448.0) * nz.astype(np.float32)


def np_fp8_roundtrip(x, chunk):
    """Per-chunk max-abs-scaled fp8 quantize/dequantize — the NumPy
    mirror of ops/_compress.roundtrip for codec='fp8'."""
    flat = np.asarray(x, dtype=np.float32).ravel()
    pad = (-len(flat)) % chunk
    padded = np.concatenate([flat, np.zeros(pad, np.float32)])
    rows = padded.reshape(-1, chunk)
    scale = np.abs(rows).max(axis=1, keepdims=True) / 448.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    deq = np_fp8_e4m3(rows / scale) * scale
    return deq.ravel()[:len(flat)].reshape(np.shape(x))


def _roundtrip(codec, chunk):
    if codec == "bf16":
        return np_bf16_roundtrip
    if codec == "fp8":
        return lambda x: np_fp8_roundtrip(x, chunk)
    return lambda x: x


# ---------------------------------------------------------------------
# part 1: the cost-model wire sweep
# ---------------------------------------------------------------------

def replay_wire_sweep(cm, codec_mod):
    model = cm.CostModel()
    rows = []
    for h, r in TOPOLOGIES:
        k = h * r
        for mb in SIZES_MB:
            nbytes = int(mb * 1e6)
            exact = cm.collective_cost("allreduce", "hier", nbytes, k,
                                       hosts=h, hier=(h, r))
            for codec in CODECS:
                c = None if codec == "off" else codec
                cost = cm.collective_cost("allreduce", "hier", nbytes,
                                          k, hosts=h, hier=(h, r),
                                          codec=c)
                logical = exact.dcn.nbytes
                wire = codec_mod.wire_bytes(logical, c)
                # the model prices exactly the wire bytes the telemetry
                # counters report — one byte-truth source (_codec)
                assert cost.dcn.nbytes == wire, (codec, cost.dcn.nbytes,
                                                 wire)
                rows.append({
                    "size_mb": mb,
                    "topology": f"{h}x{r}",
                    "codec": codec,
                    "logical_dcn_bytes": logical,
                    "wire_dcn_bytes": wire,
                    "wire_reduction": round(logical / wire, 3),
                    "dcn_rounds": cost.dcn.rounds,
                    "modeled_dcn_us": round(
                        model.link_time_us("dcn", cost.dcn.rounds,
                                           cost.dcn.nbytes), 2),
                    "modeled_total_us": round(model.time_us(cost), 2),
                })
    return rows


# ---------------------------------------------------------------------
# part 2: the EF-SGD convergence replay
# ---------------------------------------------------------------------

def replay_convergence():
    k, d = CONV["ranks"], CONV["dim"]
    rng = np.random.RandomState(CONV["seed"])
    w_star = rng.standard_normal(d).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    # one noise tape shared by every codec run: the curves differ only
    # by the codec, never by the draw
    noise = rng.standard_normal(
        (CONV["steps"], k, d)).astype(np.float32) * CONV["noise"]

    from importlib import import_module
    chunk = import_module(f"{_ISO_NAME}.ops._codec").FP8_CHUNK

    def run(codec, error_feedback=True):
        w = w0.copy()
        residual = np.zeros((k, d), np.float32)
        rt = _roundtrip(codec, chunk)
        # float64 tapes for the telescoping check: sum_t q_t must equal
        # sum_t g_t - residual_final (EF drops nothing, it defers)
        g_sum = np.zeros((k, d), np.float64)
        q_sum = np.zeros((k, d), np.float64)
        losses = []
        for t in range(CONV["steps"]):
            if t % CONV["record_every"] == 0:
                losses.append(float(0.5 * np.mean((w - w_star) ** 2)))
            grad = (w - w_star)[None, :] + noise[t]      # per-rank
            comp = grad + (residual if error_feedback else 0.0)
            q = np.stack([rt(comp[i]) for i in range(k)])
            if error_feedback:
                residual = comp - q
            g_sum += grad
            q_sum += q
            w = w - CONV["lr"] * q.mean(axis=0)          # allreduce AVG
        losses.append(float(0.5 * np.mean((w - w_star) ** 2)))
        if error_feedback:
            gap = np.abs(q_sum + residual - g_sum).max()
            assert gap < 1e-2, (codec, float(gap))
        return losses

    curves = {c: run(c) for c in CODECS}
    curves["fp8_no_ef"] = run("fp8", error_feedback=False)

    exact = np.array(curves["off"])
    parity = {}
    for codec, tol in PARITY_TOL.items():
        gap = np.abs(np.array(curves[codec]) - exact)[1:]
        rel = gap / np.maximum(exact[1:], 1e-12)
        parity[codec] = {"max_rel_gap": round(float(rel.max()), 6),
                         "tolerance": tol}
    return {
        **CONV,
        "curves": {c: [round(v, 8) for v in ls]
                   for c, ls in curves.items()},
        "parity": parity,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "BENCH_compress.json"))
    args = ap.parse_args()
    root = _load()
    cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
    codec_mod = sys.modules[f"{_ISO_NAME}.ops._codec"]

    payload = {
        "schema": SCHEMA,
        "wire_sweep": replay_wire_sweep(cm, codec_mod),
        "convergence": replay_convergence(),
        "cost_model": cm.CostModel().to_json(),
        "provenance": {
            "kind": "cost-model wire sweep + pure-NumPy EF-SGD replay "
                    "(no accelerator; the measured lane is CI's "
                    "compress job running "
                    "examples/data_parallel_training.py under "
                    "MPI4JAX_TPU_COMPRESS on an 8-device host mesh — "
                    "capture protocol in docs/compression.md)",
            "recipe": "python benchmarks/compress_replay.py",
            "topologies": [f"{h}x{r}" for h, r in TOPOLOGIES],
            "sizes_mb": list(SIZES_MB),
            "codecs": list(CODECS),
        },
    }
    # the acceptance invariants, asserted at capture time so a stale
    # artifact can never claim them silently
    for row in payload["wire_sweep"]:
        if row["codec"] != "off":
            assert row["wire_reduction"] >= 2.0, row
            assert row["modeled_dcn_us"] < next(
                r["modeled_dcn_us"] for r in payload["wire_sweep"]
                if r["codec"] == "off"
                and r["size_mb"] == row["size_mb"]
                and r["topology"] == row["topology"]), row
    conv = payload["convergence"]
    for codec, p in conv["parity"].items():
        assert p["max_rel_gap"] <= p["tolerance"], (codec, p)
    for codec in ("off", "bf16", "fp8"):
        ls = conv["curves"][codec]
        assert ls[-1] < ls[0] * 1e-2, (codec, ls[0], ls[-1])
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    reductions = sorted({r["wire_reduction"]
                         for r in payload["wire_sweep"]
                         if r["codec"] != "off"})
    print(f"wrote {args.out}: "
          f"{len(payload['wire_sweep'])} wire row(s) "
          f"(reductions {reductions}), parity "
          f"{ {c: p['max_rel_gap'] for c, p in conv['parity'].items()} }")
    del root


if __name__ == "__main__":
    main()
