"""Deterministic health-plane replay -> BENCH_health.json.

The wall-clock half of the health-overhead story lives in
``benchmarks/micro.py --health-overhead`` (measured dispatch cost per
telemetry configuration; the counters+ring column must stay within 10%
of counters-only).  Wall clocks do not replay deterministically, so the
RATCHET rides this script instead: it drives a fixed synthetic workload
through the REAL telemetry stack (journal begin/end brackets, incident
instants, the flight-recorder ring) under each configuration and
records the **record volume** each one produces — journal records,
ring pushes, ring overwrites, meter bumps, per-dispatch record cost.

That is the invariant behind the "cheap enough for counters mode"
claim: the ring adds ZERO journal records and exactly the spilled
begin/end/instant pushes, with no new io_callbacks.  A change that
starts emitting extra records per dispatch (the overhead class the 10%
bound guards against) shifts these counts and trips
``benchmarks/regress.py --suffix _records`` against the committed
``BENCH_health.json`` in the CI microbench smoke lane — and the replay
is byte-diffed, so ANY drift in the volume model must recapture the
artifact (.github/workflows/test.yml).

Run:  python benchmarks/health_replay.py [--out BENCH_health.json]

Loads the library under an isolated package name (the tests' loader
pattern), so it runs under any installed JAX — or none.
"""

import argparse
import importlib
import json
import pathlib
import sys
import types

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_health_replay"


def _load():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "telemetry"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "telemetry.hist", "telemetry.health",
                "telemetry.core", "telemetry.journal", "telemetry.merge"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


# the fixed workload every configuration replays: RANKS local ranks,
# STEPS iterations of OPS_PER_STEP bracketed collectives each, one
# incident every INCIDENT_EVERY completed brackets — sized so the
# events-tier journal stays under its cap while the small test ring
# (RING_CAP) overwrites, exercising both bounded-buffer paths
RANKS = 2
STEPS = 40
OPS_PER_STEP = 4
INCIDENT_EVERY = 16
RING_CAP = 64

CONFIGS = (
    ("counters", "counters", "off"),
    ("counters_ring", "counters", "on"),
    ("events", "events", "off"),
    ("events_ring", "events", "on"),
)

SCHEMA = "mpx-health-replay/1"


class _Arr:
    """Shape of what ``core.open_op`` reads off a dispatch operand."""

    class _DT:
        itemsize = 4

        def __str__(self):
            return "float32"

    def __init__(self, size):
        self.size = size
        self.dtype = self._DT()


class _Comm:
    uid = 0
    axes = ("x",)


def replay(core, journal, health, config, mode, hmode):
    import os

    os.environ["MPI4JAX_TPU_HEALTH"] = hmode
    os.environ["MPI4JAX_TPU_FLIGHT_RING"] = str(RING_CAP)
    os.environ.pop("MPI4JAX_TPU_TELEMETRY_DIR", None)
    core.set_telemetry_mode(mode)
    core.reset()
    comm, arrays = _Comm(), [_Arr(1024)]
    events = core.events_on()
    completed = 0
    for step in range(STEPS):
        for op in range(OPS_PER_STEP):
            call_id = f"c{op}"
            # counters-tier feed: a committed dispatch record per rank
            for rank in range(RANKS):
                rec = core.open_op("allreduce", comm, arrays)
                core.annotate(algo="native")
                if events:
                    journal.begin(call_id, rank,
                                  {"op": "allreduce", "comm_uid": 0,
                                   "bytes": 4096, "dtype": "float32"})
                core.close_op(rec)
            if events:
                for rank in range(RANKS):
                    journal.end(call_id, rank, {"algo": "native"})
                    completed += 1
                    if completed % INCIDENT_EVERY == 0:
                        journal.instant("drill", rank,
                                        {"detail": "replay"})
    dispatches = STEPS * OPS_PER_STEP * RANKS
    snap = core.snapshot(include_events=False)
    ring = health.flight_snapshot()
    row = {
        "mode": mode,
        "health": hmode,
        "dispatch_records": dispatches,
        "journal_records": len(journal.snapshot_events()),
        "journal_dropped_records": journal.dropped_records(),
        "ring_capacity_records": ring["capacity"],
        "ring_pushed_records": ring["total"],
        "ring_dropped_records": ring["dropped"],
        "meter_bump_records": sum(snap.get("meters", {}).values()),
    }
    # the per-dispatch cost model the ratchet actually guards: how many
    # bounded-buffer writes one collective execution costs in this
    # configuration (x1000 to survive rounding as an integer)
    row["ring_pushes_per_dispatch_x1000_records"] = (
        ring["total"] * 1000 // dispatches)
    core.set_telemetry_mode(None)
    core.reset()
    return row


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=str(REPO / "BENCH_health.json"))
    args = p.parse_args(argv)
    iso = _load()
    core = sys.modules[f"{_ISO_NAME}.telemetry.core"]
    journal = sys.modules[f"{_ISO_NAME}.telemetry.journal"]
    health = sys.modules[f"{_ISO_NAME}.telemetry.health"]
    rows = [replay(core, journal, health, label, mode, hmode)
            for label, mode, hmode in CONFIGS]
    payload = {
        "schema": SCHEMA,
        "workload": {
            "ranks": RANKS, "steps": STEPS,
            "ops_per_step": OPS_PER_STEP,
            "incident_every": INCIDENT_EVERY, "ring_capacity": RING_CAP,
        },
        "configs": rows,
        "reproduce": "python benchmarks/health_replay.py",
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
