"""Benchmark driver — prints ONE JSON line.

Workload: the reference's published benchmark (BASELINE.md) — the
shallow-water solver at 10x linear scale (3600 x 1800 interior), 0.1
simulated days, timed after warm-up compile, exactly the reference's
protocol (ref docs/shallow-water.rst:44-55).

Metric: steps/sec/chip.  ``vs_baseline`` compares wall time against the
reference's best published single-device result (Tesla P100, 6.28 s for
the same workload, ref docs/shallow-water.rst:81-83): values > 1 mean
faster than the reference's GPU.
"""

import argparse
import json
import os
import sys

import jax


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--unroll", type=int, default=0,
        help="megastep trip count: run the solve as pinned megastep "
             "dispatches of N device-resident steps each instead of one "
             "whole-run program (mpx.compile(fn, ..., unroll=N); "
             "docs/aot.md 'Megastep execution').  0 (default) keeps the "
             "whole-run program.")
    args = parser.parse_args()

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples")
    )
    from shallow_water import DAY_IN_SECONDS, Config, pick_process_grid, solve_fused

    devices = jax.devices()
    nproc_y, nproc_x = pick_process_grid(len(devices))
    cfg = Config(nproc_y=nproc_y, nproc_x=nproc_x, nx=3600, ny=1800)
    t1 = 0.1 * DAY_IN_SECONDS

    # fast="auto": single-device runs use the fused whole-step Pallas
    # kernel (model_step_pallas); multi-device meshes use the carried-
    # frame wide-halo kernel (model_step_pallas_wide: widen once, 4
    # margin-band messages per pair of steps), falling back to the
    # split-phase kernels (model_step_pallas_halo) only below its
    # 16-cell minimum local interior.
    # pinned=True: the timed calls execute an mpx.compile-pinned
    # artifact (docs/aot.md) — zero per-call key work, which is what
    # closes the dispatch_overhead_s gap BENCH_r05 measured at 0.063 s;
    # solve_fused falls back to the spmd program if pinning is
    # unavailable, and the "pinned" field below records which ran.
    import mpi4jax_tpu as mpx

    info1, info5 = {}, {}
    wall, n_steps = solve_fused(cfg, t1, devices=devices, fast="auto",
                                pinned=True, unroll=args.unroll,
                                info=info1)

    # second, 5x-longer run: the slope between the two cancels the fixed
    # per-dispatch overhead (on a remote-attached chip the round-trip can
    # reach ~0.1 s, a fifth of the short run's wall), giving the true
    # on-chip per-step time — see docs/shallow_water.md "Roofline"
    wall5, n_steps5 = solve_fused(cfg, 5 * t1, devices=devices,
                                  fast="auto", pinned=True,
                                  unroll=args.unroll, info=info5)
    per_step = (wall5 - wall) / (n_steps5 - n_steps)
    aot_stats = mpx.cache_stats()["aot"]

    steps_per_sec_per_chip = n_steps / wall / len(devices)
    ref_gpu_wall = 6.28  # Tesla P100, 1 process (BASELINE.md)
    # achieved HBM bandwidth, state-traffic model: each step must at least
    # read and write the six (ny_l, nx_l) f32 state fields — a *lower
    # bound* on real traffic (intermediates add more), so this understates
    # utilization; v5e peak is ~819 GB/s (measured 826 GB/s streaming on
    # this chip)
    field_bytes = cfg.nproc * cfg.ny_local * cfg.nx_local * 4
    gbps = 12 * field_bytes * n_steps / wall / 1e9 / len(devices)
    print(
        json.dumps(
            {
                "metric": "shallow-water steps/sec/chip (3600x1800, 0.1 days)",
                "value": round(steps_per_sec_per_chip, 2),
                "unit": "steps/s/chip",
                "vs_baseline": round(ref_gpu_wall / wall, 3),
                "state_traffic_gb_per_s": round(gbps, 1),
                "wall_s": round(wall, 3),
                # did the timed loops run the AOT-pinned artifact?
                # Each successful solve_fused pins exactly once, so
                # BOTH runs pinned iff pins >= 2 — a first-run pin with
                # a second-run fallback must not claim a pinned number
                "pinned": aot_stats["pins"] >= 2,
                "pinned_calls": aot_stats["calls"],
                # the megastep trip count BOTH timed runs actually
                # executed with (0 = whole-run program; a megastep
                # compile failure falls back and must not claim the
                # configuration it did not run — same honesty rule as
                # "pinned" above; docs/aot.md "Megastep execution")
                "unroll": (info1.get("unroll", 0)
                           if info1.get("unroll") == info5.get("unroll")
                           else 0),
                **(
                    {
                        "onchip_steps_per_s_per_chip": round(
                            1 / per_step / len(devices), 2
                        ),
                        "dispatch_overhead_s": round(
                            wall - n_steps * per_step, 3
                        ),
                    }
                    if per_step > 0
                    else {}
                ),
                # honesty marker for readers without docs context: only
                # observable facts about THIS run, plus the standing caveat
                # that vs_baseline compares cross-era hardware (v5e-class
                # chip vs 2016 P100); single-device runs add that no
                # interconnect was measured (this repo's published numbers
                # came from a remote-attached chip — docs/microbenchmarks.md)
                "environment": (
                    f"{len(devices)}-device {devices[0].platform}"
                    + ("; no interconnect measured"
                       if len(devices) == 1 else "")
                    + "; vs_baseline is cross-era hardware "
                    "(see docs/microbenchmarks.md)"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
