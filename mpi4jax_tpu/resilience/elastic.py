"""Elastic communicators: survive rank loss and keep training.

PR 1 gave the resilience layer *detection* — watchdog, fault injection,
numeric guards — but a dead rank still killed the whole job: every
survivor either hung in its next collective or was killed loudly by its
watchdog.  This module is the *recovery* half, shaped after MPI's
User-Level Failure Mitigation (ULFM: revoke → shrink → agree) and
Elastic Horovod (resume from replicated in-memory state, not disk):

1. **Failure commit** — a watchdog expiry (claimed via
   ``resilience.set_on_timeout``) or a peer-death error raises
   :class:`RankFailure` carrying the *suspected* global ranks.  The
   survivors then agree on the failed set: a gossip round over
   still-healthy links (:func:`gossip_agreement` is the pure model the
   tests pin; :func:`exchange_suspects` is the TCP runtime form), so
   every survivor commits the SAME set even when each observed a
   different symptom.
2. **Revoke + shrink** — the current *communication epoch* is revoked:
   :func:`advance_epoch` bumps a monotonic counter that is folded into
   every compiled-program cache key (via ``resilience.runtime
   .cache_token``), so every executable traced against the old world
   becomes unreachable and re-traces at the new size; in-flight watchdog
   entries are drained and the eager program cache cleared.  The mesh
   and every registered comm are rebuilt as "all minus failed"
   (``parallel/mesh.shrink_world_mesh``, ``Comm.shrink``) with survivor
   ranks compacted (:func:`compact_rank_map`).
3. **Resume** — :class:`ShardStore` keeps an in-memory, sharded copy of
   registered state (the natural shard unit ``reduce_scatter`` produces:
   rank ``r`` owns flat-byte shard ``r``) with **k-redundant neighbor
   replication**: shard ``s`` is replicated on ranks ``s, s+1, ...,
   s+redundancy (mod k)``, so ANY ``redundancy`` simultaneous rank
   losses leave at least one live copy of every shard
   (:func:`recoverable`).  :func:`run` wraps the training loop: on
   ``RankFailure`` it commits the failure, shrinks, restores the last
   committed state (reassembled from surviving replicas — one SUM
   allreduce over the *new* comm in multi-process mode), and continues
   on ``k − f`` ranks from the last committed step.

Pure by construction below the jax line: epoch arithmetic, the
ownership/replication maps, the agreement model, and the byte-packing
helpers import no jax, so ``tests/test_elastic_pure.py`` exercises them
under any JAX via the isolated loader.  Everything that traces or moves
bytes imports jax lazily.

Protocol, redundancy math, and the drill recipe: docs/resilience.md
("Elastic recovery").
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from ..utils import config

__all__ = [
    "RankFailure",
    "ShardStore",
    "run",
    "current_epoch",
    "advance_epoch",
    "elastic_cache_token",
    "compact_rank_map",
    "shrink_groups",
    "replica_ranks",
    "shards_held_by",
    "recoverable",
    "reconstruction_plan",
    "shard_bounds",
    "gossip_agreement",
    "majority_survives",
    "reassemble_from_stores",
    "revoke_epoch",
    "exchange_suspects",
    "classify_failure",
    "take_pending_failure",
    "pack_leaves",
    "unpack_leaves",
]


class RankFailure(RuntimeError):
    """One or more ranks are suspected dead/stalled.

    ``suspects`` are GLOBAL ranks (row-major over the comm's full axes —
    the same rank space ``MPI4JAX_TPU_FAULT_SPEC`` addresses).  An empty
    suspect set means "something died but this rank cannot name it" (a
    generic distributed-runtime error): the agreement round resolves it
    from link health.
    """

    def __init__(self, suspects: Iterable[int] = (), detail: str = ""):
        self.suspects: FrozenSet[int] = frozenset(int(r) for r in suspects)
        self.detail = detail
        names = sorted(self.suspects) if self.suspects else "unknown"
        super().__init__(
            f"rank failure suspected (ranks {names})"
            + (f": {detail}" if detail else "")
        )


# ---------------------------------------------------------------------------
# communication epochs
# ---------------------------------------------------------------------------
#
# The epoch is the revocation mechanism: every compiled-program cache key
# folds it in (resilience.runtime.cache_token -> ops/_base._dynamic_state
# -> both the eager and the spmd program caches), so advancing it makes
# every executable traced against the old world unreachable — the moral
# equivalent of ULFM's MPI_Comm_revoke, enforced at the cache layer
# instead of in the transport.  Comms stamp the epoch they were built in
# (parallel/comm.py); a collective dispatched on a comm whose epoch is
# behind the current one is flagged MPX126 by the trace-time verifier.

_epoch_lock = threading.Lock()
_epoch = 0


def current_epoch() -> int:
    """The current communication epoch (0 until the first revocation)."""
    return _epoch


def advance_epoch() -> int:
    """Revoke the current epoch: bump the counter and invalidate every
    stamp-memoized configuration consumer (the program caches fold the
    epoch in via ``resilience.cache_token``, so every old-world
    executable re-traces).  Returns the new epoch."""
    global _epoch
    with _epoch_lock:
        _epoch += 1
        new = _epoch
    config.bump_config_epoch()
    return new


def _reset_epoch_for_tests() -> None:
    global _epoch
    with _epoch_lock:
        _epoch = 0
    config.bump_config_epoch()


def elastic_cache_token() -> int:
    """The epoch, as folded into every compiled-program cache key.  With
    elastic never engaged this is the constant 0 and the keys (and HLO)
    are identical to a build without the elastic layer."""
    return _epoch


# ---------------------------------------------------------------------------
# shard ownership + k-redundant neighbor replication (pure)
# ---------------------------------------------------------------------------


def shard_bounds(nbytes: int, k: int) -> Tuple[int, int]:
    """``(shard_size, padded_size)`` splitting ``nbytes`` into ``k`` equal
    byte shards (the last shard is zero-padded) — the same equal-chunk
    padding rule the ring reduce_scatter uses for non-divisible
    payloads."""
    if k < 1:
        raise ValueError(f"need at least one rank, got k={k}")
    shard = -(-nbytes // k) if nbytes else 0  # ceil div; 0 stays 0
    return shard, shard * k


def replica_ranks(shard: int, k: int, redundancy: int) -> Tuple[int, ...]:
    """Ranks holding a copy of ``shard``: the owner (rank == shard id)
    plus its ``redundancy`` right neighbors, mod k — so every shard has
    ``redundancy + 1`` copies on distinct ranks and ANY ``redundancy``
    simultaneous failures leave a live copy."""
    if not 0 <= shard < k:
        raise ValueError(f"shard {shard} out of range for k={k}")
    if redundancy < 0:
        raise ValueError(f"redundancy must be >= 0, got {redundancy}")
    r = min(redundancy, k - 1)  # more copies than ranks is just "everyone"
    return tuple((shard + j) % k for j in range(r + 1))


def shards_held_by(rank: int, k: int, redundancy: int) -> Tuple[int, ...]:
    """Inverse of :func:`replica_ranks`: the shards rank ``rank`` stores —
    its own plus its ``redundancy`` left neighbors', mod k."""
    if not 0 <= rank < k:
        raise ValueError(f"rank {rank} out of range for k={k}")
    r = min(max(redundancy, 0), k - 1)
    return tuple(sorted((rank - j) % k for j in range(r + 1)))


def recoverable(failed: Iterable[int], k: int, redundancy: int) -> bool:
    """True iff every shard still has at least one surviving copy after
    losing ``failed`` — i.e. no shard's whole replica set died."""
    dead = frozenset(failed)
    return all(
        any(r not in dead for r in replica_ranks(s, k, redundancy))
        for s in range(k)
    )


def reconstruction_plan(
    failed: Iterable[int], k: int, redundancy: int
) -> Dict[int, int]:
    """``{shard: provider}`` naming, for EVERY shard, the lowest-numbered
    surviving rank holding a copy — the deterministic choice every
    survivor computes independently (no coordination needed), so the
    restore exchange has exactly one contributor per shard.  Raises
    ``RankFailure`` when a shard lost all its copies (more simultaneous
    failures than the redundancy budget)."""
    dead = frozenset(failed)
    plan = {}
    for s in range(k):
        live = [r for r in replica_ranks(s, k, redundancy) if r not in dead]
        if not live:
            raise RankFailure(
                dead,
                f"shard {s} unrecoverable: all {redundancy + 1} replica "
                f"ranks {replica_ranks(s, k, redundancy)} failed "
                f"(redundancy={redundancy} tolerates at most {redundancy} "
                "simultaneous failures)",
            )
        plan[s] = min(live)
    return plan


# ---------------------------------------------------------------------------
# rank compaction + group shrink (pure)
# ---------------------------------------------------------------------------


def compact_rank_map(world: int, failed: Iterable[int]) -> Dict[int, int]:
    """``{old_global_rank: new_global_rank}`` for the survivors, compacted
    in ascending old-rank order (survivor i becomes new rank i) — the
    rank renumbering ULFM's ``MPI_Comm_shrink`` specifies."""
    dead = frozenset(failed)
    bad = [r for r in dead if not 0 <= r < world]
    if bad:
        raise ValueError(f"failed ranks {sorted(bad)} out of range for "
                         f"world {world}")
    if len(dead) >= world:
        raise RankFailure(dead, "no survivors: every rank failed")
    survivors = [r for r in range(world) if r not in dead]
    return {old: new for new, old in enumerate(survivors)}


def shrink_groups(groups, failed: Iterable[int], world: int):
    """Rebuild a color-split comm's group tables as "all minus failed":
    drop the failed ranks, renumber survivors via :func:`compact_rank_map`
    (preserving each group's order), drop groups that lost every member.
    Returns the new group tuple in the new (compacted) rank space."""
    rmap = compact_rank_map(world, failed)
    out = []
    for members in groups:
        kept = tuple(rmap[r] for r in members if r in rmap)
        if kept:
            out.append(kept)
    return tuple(out)


# ---------------------------------------------------------------------------
# failure agreement (pure model + TCP runtime form)
# ---------------------------------------------------------------------------


def gossip_agreement(
    suspects: Dict[int, Iterable[int]],
    links,
) -> Dict[int, FrozenSet[int]]:
    """The agreement round, as a pure fixpoint over a link matrix.

    ``suspects[r]`` is rank r's locally-observed suspect set;
    ``links[i][j]`` is True when the i↔j link is healthy (symmetric;
    the diagonal is ignored).  Each round every rank unions the suspect
    sets of the peers it can reach over healthy links, and additionally
    suspects any peer it has NO healthy link to; rounds repeat to
    fixpoint (≤ world rounds — each round only grows sets).

    Within one connected component of the healthy-survivor subgraph the
    result is identical on every member — the agreement property the
    runtime form inherits.  Disconnected components can disagree; that is
    the split-brain case :func:`majority_survives` arbitrates.
    """
    world = len(links)
    # every rank computes (a dead rank's output is simply ignored by its
    # peers — they have no healthy link to read it over)
    agreed = {r: set(map(int, suspects.get(r, ()))) for r in range(world)}
    changed = True
    rounds = 0
    while changed and rounds <= world + 1:
        changed = False
        rounds += 1
        snapshot = {r: frozenset(s) for r, s in agreed.items()}
        for r in range(world):
            mine = agreed[r]
            before = len(mine)
            for p in range(world):
                if p == r:
                    continue
                healthy = links[r][p] and links[p][r]
                if not healthy:
                    mine.add(p)          # unreachable peer => suspect
                elif p not in mine:
                    mine |= snapshot[p]  # gossip over the healthy link
            if len(mine) != before:
                changed = True
    return {r: frozenset(s) for r, s in agreed.items()}


def majority_survives(agreed_failed: Iterable[int], world: int) -> bool:
    """Split-brain guard: a survivor partition keeps running only when it
    holds a strict majority of the original world (otherwise two halves
    of a partitioned job would both shrink and train divergent models).
    """
    survivors = world - len(frozenset(agreed_failed))
    return survivors * 2 > world


def _recv_all(conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def exchange_suspects(
    my_rank: int,
    world: int,
    suspects: Iterable[int],
    host: str,
    port_base: int,
    *,
    rounds: int = 2,
    timeout: float = 20.0,
) -> FrozenSet[int]:
    """The runtime agreement: gossip suspect sets over TCP among the
    survivors (rank r listens on ``port_base + r``).

    Two rounds by default: round 1 unions everyone's locally-observed
    suspects (a peer that cannot be reached joins the set), round 2
    propagates the unions so survivors that observed different symptoms
    converge — the TCP realization of :func:`gossip_agreement` on a
    connected survivor component.  Small-world only (the drill scale);
    pod-scale deployments would run this over the coordinator.
    """
    agreed = set(int(r) for r in suspects)
    agreed.discard(my_rank)

    inbox: List[FrozenSet[int]] = []
    heard: set = set()   # peers we have EVIDENCE are alive (they sent to us)
    lock = threading.Lock()
    stop = threading.Event()

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port_base + my_rank))
    srv.listen(world)
    srv.settimeout(0.2)

    def _serve():
        try:
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with conn:
                    try:
                        conn.settimeout(timeout)
                        header = _recv_all(conn, 8)
                        if len(header) < 8:
                            continue
                        n = int.from_bytes(header, "big")
                        payload = json.loads(_recv_all(conn, n).decode())
                        with lock:
                            heard.add(int(payload["from"]))
                            inbox.append(frozenset(
                                int(r) for r in payload["suspects"]))
                    except (OSError, ValueError, KeyError, TypeError):
                        continue
        finally:
            srv.close()

    def _send_with_patience(peer: int, msg: bytes) -> bool:
        """Deliver to a peer, retrying refusals until ``timeout``: the
        survivors reach the agreement phase at different times (failure
        detection is not synchronized), so an instant connection-refused
        from a healthy-but-late peer must not get it declared dead.  A
        peer that stays unreachable for the whole window — and never sent
        us anything either — is suspected."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                with socket.create_connection(
                    (host, port_base + peer),
                    timeout=max(0.1, deadline - time.monotonic()),
                ) as c:
                    c.sendall(len(msg).to_bytes(8, "big") + msg)
                return True
            except OSError:
                with lock:
                    if peer in heard:
                        # alive but done serving (it finished its rounds
                        # before us): not a failure, just asymmetry
                        return True
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.1)

    server = threading.Thread(target=_serve, daemon=True)
    server.start()
    try:
        for rnd in range(max(1, rounds)):
            # never gossip ourselves as a suspect (we are demonstrably
            # alive and sending) — but KEEP my_rank in the returned set
            # when peers put it there: a rank its peers declared failed
            # must see itself in the result and abort (docs/resilience.md
            # step 1), not silently strip the verdict
            msg = json.dumps(
                {"from": my_rank,
                 "suspects": sorted(agreed - {my_rank})}).encode()
            for peer in range(world):
                if peer == my_rank or peer in agreed:
                    continue
                if not _send_with_patience(peer, msg):
                    agreed.add(peer)  # unreachable survivor => suspect
            # let the peers' sends for this round land before folding
            # (their rounds are not synchronized with ours)
            if rnd == max(1, rounds) - 1:
                time.sleep(0.5)
            with lock:
                got, inbox[:] = list(inbox), []
            for s in got:
                agreed |= set(s)
    finally:
        # linger: keep answering slow peers so OUR early exit does not get
        # us suspected (the server thread closes the socket after stop);
        # daemon so a finished worker's interpreter never waits on it
        linger = threading.Timer(timeout, stop.set)
        linger.daemon = True
        linger.start()
    return frozenset(agreed)


# ---------------------------------------------------------------------------
# watchdog claim: expiry -> pending RankFailure instead of process death
# ---------------------------------------------------------------------------

_pending_lock = threading.Lock()
_pending_failure: Optional[RankFailure] = None


def _post_failure(rf: RankFailure) -> None:
    global _pending_failure
    with _pending_lock:
        if _pending_failure is None:
            _pending_failure = rf


def take_pending_failure() -> Optional[RankFailure]:
    """Pop the failure posted by the claimed watchdog handler (or a peer
    death notification), if any."""
    global _pending_failure
    with _pending_lock:
        rf, _pending_failure = _pending_failure, None
    return rf


def _claimed_on_timeout(entries, expired) -> None:
    """The elastic watchdog handler (installed by :func:`run` via
    ``resilience.set_on_timeout``): instead of killing the process, post
    a pending :class:`RankFailure` (suspects unknown — this rank only
    knows its own collective stalled; the agreement round names the dead)
    and try to break the main thread out of the stalled collective.

    The expiry was already journalled as a telemetry incident by the
    monitor before this handler ran (resilience/watchdog.py).
    """
    _meter("elastic.watchdog_claims")
    _post_failure(RankFailure(
        (),
        f"watchdog expiry: {expired['opname']} exceeded "
        f"{expired['timeout']:g}s (call {expired['call_id']})",
    ))
    _abort_inflight()


def _abort_inflight() -> None:
    """Best-effort unblock of a main thread stalled inside a collective
    whose peers are dead: tear down the distributed client (pending
    collectives then fail with a runtime error the recovery loop
    classifies), and interrupt the main thread for the host-side blocks
    (an injected ``hang`` sleeps in ``time.sleep``, which
    ``interrupt_main`` does break)."""
    try:
        import jax

        jax.distributed.shutdown()
    except Exception:
        pass
    try:
        import _thread

        _thread.interrupt_main()
    except Exception:
        pass


_FAILURE_MARKERS = (
    "deadline", "heartbeat", "connection", "unavailable", "shut down",
    "shutdown", "peer", "socket closed", "cancelled", "aborted",
    "barrier timed out", "preempt",
)


def classify_failure(exc: BaseException) -> Optional[RankFailure]:
    """Map an exception escaping the training step to a
    :class:`RankFailure`, or ``None`` when it is an ordinary error that
    must propagate.  Three sources:

    - an explicit :class:`RankFailure` (simulated drills, peer-death
      notifications) passes through;
    - a pending failure posted by the claimed watchdog handler adopts
      the interrupting exception (``KeyboardInterrupt`` from
      ``interrupt_main``, or the runtime error the distributed teardown
      provoked);
    - a distributed-runtime death rattle (connection/heartbeat/shutdown
      wording) with no pending claim becomes an unknown-suspect failure.
    """
    if isinstance(exc, RankFailure):
        pending = take_pending_failure()
        if pending is not None and pending.suspects - exc.suspects:
            return RankFailure(exc.suspects | pending.suspects, exc.detail)
        return exc
    pending = take_pending_failure()
    if pending is not None:
        return pending
    if isinstance(exc, (RuntimeError, OSError)):
        text = str(exc).lower()
        if any(m in text for m in _FAILURE_MARKERS):
            return RankFailure((), f"{type(exc).__name__}: {exc}")
    return None


# ---------------------------------------------------------------------------
# state packing (pure: numpy only)
# ---------------------------------------------------------------------------


def _flatten_state(state):
    """``(leaves, treedef)`` — jax.tree when importable, else a minimal
    deterministic flattener over dict/list/tuple nests (sorted dict keys,
    jax's rule) so the pure tests run without jax.  ``treedef`` is only
    ever passed back to the matching unflattener."""
    try:
        import jax

        leaves, treedef = jax.tree.flatten(state)
        return leaves, ("jax", treedef)
    except ImportError:
        pass

    leaves = []

    def build(node):
        if isinstance(node, dict):
            return ("d", tuple(sorted(node)),
                    tuple(build(node[k]) for k in sorted(node)))
        if isinstance(node, (list, tuple)):
            kind = "l" if isinstance(node, list) else "t"
            return (kind, len(node), tuple(build(v) for v in node))
        leaves.append(node)
        return ("*",)

    return leaves, ("pure", build(state))


def _unflatten_state(treedef, leaves):
    kind, spec = treedef
    if kind == "jax":
        import jax

        return jax.tree.unflatten(spec, leaves)
    it = iter(leaves)

    def rebuild(node):
        tag = node[0]
        if tag == "*":
            return next(it)
        if tag == "d":
            _, keys, subs = node
            return {k: rebuild(s) for k, s in zip(keys, subs)}
        _, _, subs = node
        vals = [rebuild(s) for s in subs]
        return vals if tag == "l" else tuple(vals)

    return rebuild(spec)


def pack_leaves(leaves):
    """``(buffer, meta)``: concatenate the leaves' raw bytes into one
    uint8 vector (the flat unit the byte shards slice), recording
    ``(shape, dtype, nbytes)`` per leaf for :func:`unpack_leaves`."""
    import numpy as np

    arrays = [np.asarray(a) for a in leaves]
    meta = [(a.shape, a.dtype.str, a.nbytes) for a in arrays]
    if arrays:
        # tobytes (C order) rather than a uint8 view: views reject 0-d
        # arrays (scalar leaves — loss scales, step counters) and
        # non-contiguous layouts; the copy is once per commit
        buf = np.concatenate(
            [np.frombuffer(a.tobytes(), np.uint8) for a in arrays])
    else:
        buf = np.zeros((0,), np.uint8)
    return buf, meta


def unpack_leaves(buf, meta):
    import numpy as np

    out = []
    off = 0
    for shape, dtype, nbytes in meta:
        chunk = np.asarray(buf[off:off + nbytes], np.uint8)
        out.append(chunk.view(np.dtype(dtype)).reshape(shape))
        off += nbytes
    return out


# ---------------------------------------------------------------------------
# telemetry glue (guarded: the package is optional under isolated loaders)
# ---------------------------------------------------------------------------


def _meter(name: str) -> None:
    try:
        from ..telemetry import core as _tcore
    except ImportError:
        return
    _tcore.meter(name)


def _incident(meter: str, name: str, rank: int, detail: str) -> None:
    try:
        from ..telemetry import journal
    except ImportError:
        return
    journal.incident(meter, name, rank, detail)


# ---------------------------------------------------------------------------
# ShardStore
# ---------------------------------------------------------------------------


class ShardStore:
    """In-memory sharded checkpoint of registered state with k-redundant
    neighbor replication.

    Each committed state pytree is flattened to one flat byte buffer,
    split into ``k`` equal byte shards (``shard s`` owned by rank ``s`` —
    the unit a ``reduce_scatter`` naturally produces), and this process
    stores the shards of its *local* ranks plus each local rank's
    ``redundancy`` left neighbors (:func:`shards_held_by`): every shard
    lives on ``redundancy + 1`` distinct ranks, so any ``redundancy``
    simultaneous rank losses are recoverable.  Memory cost per rank is
    ``(redundancy + 1)/k`` of the state size — for the default
    ``redundancy=1`` on 8 ranks, a quarter of a full on-disk checkpoint,
    restored at memory speed.

    Single-controller processes driving multiple ranks (the virtual
    multi-device mesh, or multi-host with several devices per process)
    hold the union of their local ranks' shards; a 1-process-per-rank
    deployment holds exactly ``redundancy + 1`` shards.

    ``comm`` may be ``None`` (the default world comm resolves lazily).
    ``rank`` pins the store to ONE global rank — the per-rank simulation
    handle the pure tests (and the protocol docs) use; default derives
    local ranks from the comm's mesh process layout.
    """

    def __init__(self, comm=None, *, redundancy: Optional[int] = None,
                 rank: Optional[int] = None, bootstrap: Optional[dict] = None):
        self.redundancy = (config.elastic_redundancy()
                           if redundancy is None else int(redundancy))
        if self.redundancy < 0:
            raise ValueError(
                f"redundancy must be >= 0, got {self.redundancy}")
        self._comm = comm
        self._rank = rank
        # multi-process recovery parameters (coordinator host/ports for
        # re-bootstrap + agreement); single-process runs need none
        self.bootstrap = dict(bootstrap or {})
        self._committed: Optional[dict] = None
        self._lock = threading.Lock()

    # -- world plumbing ----------------------------------------------------

    @property
    def comm(self):
        if self._comm is None:
            from ..parallel.region import get_default_comm

            self._comm = get_default_comm()
        return self._comm

    def world_size(self) -> int:
        return int(self.comm.world_size())

    def local_ranks(self) -> Tuple[int, ...]:
        """Global ranks whose devices THIS process owns (all of them on a
        single-controller virtual mesh), or the pinned ``rank``."""
        if self._rank is not None:
            return (self._rank,)
        comm = self.comm
        if comm.mesh is None:
            return tuple(range(self.world_size()))
        import jax

        me = jax.process_index()
        devices = list(comm.mesh.devices.flat)
        return tuple(
            r for r, d in enumerate(devices)
            if getattr(d, "process_index", 0) == me
        )

    def held_shards(self, k: Optional[int] = None) -> Tuple[int, ...]:
        """Shards this process stores on commit: the union of
        :func:`shards_held_by` over its local ranks."""
        k = self.world_size() if k is None else k
        held = set()
        for r in self.local_ranks():
            if r < k:
                held.update(shards_held_by(r, k, self.redundancy))
        return tuple(sorted(held))

    # -- commit ------------------------------------------------------------

    def commit(self, step: int, state) -> None:
        """Commit ``state`` as of (completed) ``step``: flatten, slice this
        process's shards, and atomically replace the previous commit.
        ``state`` must be the replicated (every-rank-identical) training
        state — the data-parallel contract; the commit itself moves no
        bytes over the network."""
        import numpy as np

        leaves, treedef = _flatten_state(state)
        host_leaves = [np.asarray(a) for a in leaves]
        buf, meta = pack_leaves(host_leaves)
        k = self.world_size()
        shard, padded = shard_bounds(buf.nbytes, k)
        if padded > buf.nbytes:
            buf = np.concatenate(
                [buf, np.zeros(padded - buf.nbytes, np.uint8)])
        shards = {
            s: bytes(buf[s * shard:(s + 1) * shard])
            for s in self.held_shards(k)
        }
        record = {
            "step": int(step),
            "epoch": current_epoch(),
            "k": k,
            "shard": shard,
            "nbytes": int(len(meta) and sum(m[2] for m in meta)),
            "meta": meta,
            "treedef": treedef,
            "shards": shards,
        }
        with self._lock:
            self._committed = record
        _meter("elastic.commits")

    @property
    def committed_step(self) -> Optional[int]:
        with self._lock:
            return self._committed["step"] if self._committed else None

    # -- restore -----------------------------------------------------------

    def _require_commit(self) -> dict:
        with self._lock:
            rec = self._committed
        if rec is None:
            raise RuntimeError(
                "ShardStore.restore: nothing committed yet — commit an "
                "initial state before entering the elastic loop so step-0 "
                "failures are recoverable"
            )
        return rec

    def restore(self, failed: Iterable[int] = ()):
        """Reassemble the last committed state after losing ``failed``
        (old-world global ranks) and return ``(step, state)``.

        When this process holds every needed shard (single-controller
        meshes always do), reassembly is local.  Otherwise each surviving
        process contributes the shards :func:`reconstruction_plan` makes
        it the provider of, and ONE ``SUM`` allreduce over the *current*
        (post-shrink) comm reassembles the full buffer on every rank —
        the exchange runs over the new world, never the revoked one.
        """
        import numpy as np

        rec = self._require_commit()
        dead = frozenset(failed)
        k, shard = rec["k"], rec["shard"]
        plan = reconstruction_plan(dead, k, self.redundancy)
        have = set(rec["shards"])
        need_remote = any(s not in have for s in range(k))

        if not need_remote:
            buf = np.concatenate(
                [np.frombuffer(rec["shards"][s], np.uint8)
                 for s in range(k)]
            ) if shard else np.zeros((0,), np.uint8)
        else:
            buf = self._exchange_shards(rec, plan)

        total = sum(m[2] for m in rec["meta"])
        leaves = unpack_leaves(buf[:total], rec["meta"])
        state = _unflatten_state(rec["treedef"], leaves)
        _meter("elastic.restores")
        return rec["step"], state

    def _exchange_shards(self, rec: dict, plan: Dict[int, int]):
        """One SUM allreduce over the current (post-shrink) comm moves
        every old-world shard from its designated provider to every rank:
        each provider process places its shards in the flat contribution,
        everyone else zeros — exactly one contributor per shard
        (``plan``), so SUM is placement, and a uint8 sum cannot wrap."""
        import numpy as np

        from ..ops import SUM, allreduce

        comm = self.comm
        k, shard = rec["k"], rec["shard"]
        locals_ = set(
            r for r in self.local_ranks() if r < int(comm.world_size())
        )
        # providers are named in OLD ranks; this process provides the
        # shards whose provider it held before the shrink
        provided = {
            s for s, provider in plan.items()
            if s in rec["shards"] and self._provides(provider, rec)
        }
        contrib = np.zeros((k * shard,), np.uint8)
        for s in provided:
            contrib[s * shard:(s + 1) * shard] = np.frombuffer(
                rec["shards"][s], np.uint8)
        size = int(comm.world_size())
        glob = np.zeros((size, k * shard), np.uint8)
        for r in locals_:
            glob[r] = contrib
        out, _ = allreduce(glob, op=SUM, comm=comm)
        return np.asarray(out)[0]

    def _provides(self, old_provider: int, rec: dict) -> bool:
        """Whether THIS process is the provider: it is the process that
        holds ``old_provider``'s rank now.  After a shrink the old->new
        rank map recorded on the commit translates; with no shrink (a
        plain restore) old ranks ARE current ranks — either way, exactly
        one process answers True per provider, preserving the
        one-contributor-per-shard invariant of the SUM exchange."""
        rank_map = rec.get("rank_map")
        current = (old_provider if rank_map is None
                   else rank_map.get(old_provider))
        return current is not None and current in set(self.local_ranks())

    # -- failure handling entry points used by run() -----------------------

    def apply_shrink(self, failed: Iterable[int]) -> Dict[int, int]:
        """Rebuild the mesh and this store's comm as "all minus failed"
        and record the old->new rank map on the last commit (the restore
        exchange resolves providers through it).  Single-controller path:
        the surviving devices of the bound mesh form the new world.
        Returns the rank map."""
        from ..parallel.mesh import set_default_mesh, shrink_world_mesh
        from ..parallel import region as _region

        dead = frozenset(failed)
        comm = self.comm
        if comm.mesh is None:
            raise RuntimeError("elastic shrink needs a comm bound to a mesh")
        world = int(comm.world_size())
        rank_map = compact_rank_map(world, dead)
        new_mesh = shrink_world_mesh(comm.mesh, dead)
        self._comm = comm.shrink(dead, mesh=new_mesh)
        set_default_mesh(new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                self._committed["rank_map"] = dict(rank_map)
        if self._rank is not None and self._rank in rank_map:
            self._rank = rank_map[self._rank]
        return rank_map

    def rebootstrap(self, failed: Iterable[int]) -> Dict[int, int]:
        """Multi-process shrink: tear down the old distributed world and
        re-initialize jax.distributed over the survivors (compacted
        process ids; the lowest surviving old rank hosts the new
        coordinator on ``port_base + epoch`` — a fresh port per epoch so
        TIME_WAIT sockets from the revoked world cannot collide).
        Requires ``bootstrap`` = {"host", "port_base", "process_id",
        "num_processes"} (one device per process).  Returns the old->new
        rank map."""
        import jax

        from ..parallel.mesh import make_world_mesh, set_default_mesh
        from ..parallel import mesh as _mesh_mod, region as _region
        from .retry import retry_with_backoff

        bs = self.bootstrap
        for key in ("host", "port_base", "process_id", "num_processes"):
            if key not in bs:
                raise RuntimeError(
                    "elastic rebootstrap needs ShardStore(bootstrap="
                    "{'host', 'port_base', 'process_id', 'num_processes'})"
                    f"; missing {key!r}"
                )
        dead = frozenset(failed)
        world = int(bs["num_processes"])
        rank_map = compact_rank_map(world, dead)
        me_old = int(bs["process_id"])
        if me_old in dead or me_old not in rank_map:
            raise RankFailure(dead, "this rank was declared failed")
        me_new = rank_map[me_old]
        new_world = len(rank_map)
        coord = f"{bs['host']}:{int(bs['port_base']) + current_epoch()}"

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        # drop compiled backends/devices of the revoked world before the
        # new one initializes (API name varies across jax versions)
        for clear in ("clear_backends",):
            fn = getattr(jax, clear, None)
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass

        retry_with_backoff(
            lambda: jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=new_world,
                process_id=me_new,
            ),
            what=f"elastic re-bootstrap (epoch {current_epoch()}, "
                 f"coordinator {coord})",
            deadline=config.bootstrap_deadline(),
            max_attempts=config.bootstrap_max_attempts() or None,
        )
        _mesh_mod._distributed_initialized = True
        bs["process_id"] = me_new
        bs["num_processes"] = new_world

        # preserve the old world's axis name: Comm.shrink validates the
        # new mesh along the COMM's axes, and the elastic contract is a
        # 1-D mesh (apply_shrink's shrink_world_mesh keeps the name too)
        old_mesh = self.comm.mesh
        old_axes = (tuple(old_mesh.axis_names)
                    if old_mesh is not None else None)
        if old_axes is not None and len(old_axes) == 1:
            new_mesh = make_world_mesh((new_world,), old_axes)
        else:
            new_mesh = make_world_mesh()
        set_default_mesh(new_mesh)
        self._comm = self.comm.shrink(dead, mesh=new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                self._committed["rank_map"] = dict(rank_map)
        if self._rank is not None:
            self._rank = rank_map.get(self._rank, self._rank)
        return rank_map

    def multiprocess(self) -> bool:
        return bool(self.bootstrap)


def reassemble_from_stores(stores: Dict[int, "ShardStore"],
                           failed: Iterable[int] = ()):
    """Pure simulation of the restore exchange: given per-rank stores
    (``{old_rank: rank-pinned ShardStore}``), reassemble ``(step, state)``
    from the SURVIVING stores only — byte-for-byte what the one-allreduce
    runtime exchange produces.  The protocol model the pure tests (and
    docs/resilience.md's redundancy math) pin: kill any ``redundancy``
    stores and the state must still come back bit-identical."""
    import numpy as np

    dead = frozenset(failed)
    survivors = {r: s for r, s in stores.items() if r not in dead}
    if not survivors:
        raise RankFailure(dead, "no surviving stores")
    rec = next(iter(survivors.values()))._require_commit()
    k, shard = rec["k"], rec["shard"]
    redundancy = next(iter(survivors.values())).redundancy
    plan = reconstruction_plan(dead, k, redundancy)
    buf = np.zeros((k * shard,), np.uint8)
    for s, provider in plan.items():
        prec = survivors[provider]._require_commit()
        buf[s * shard:(s + 1) * shard] = np.frombuffer(
            prec["shards"][s], np.uint8)
    total = sum(m[2] for m in rec["meta"])
    leaves = unpack_leaves(buf[:total], rec["meta"])
    return rec["step"], _unflatten_state(rec["treedef"], leaves)


# ---------------------------------------------------------------------------
# revoke: make the old world unreachable
# ---------------------------------------------------------------------------


def revoke_epoch(failed: Iterable[int], *, rank: int = 0,
                 world: Optional[int] = None) -> int:
    """Revoke the current comm epoch after the failed set is agreed:

    - advance the epoch (every compiled-program cache key folds it in,
      so old-world executables re-trace rather than replay);
    - drain the watchdog's in-flight registry (arms from collectives of
      the revoked world must not kill the recovered job);
    - drop the eager compiled-program cache (entries pin revoked meshes);
    - journal exactly one ``epoch_change`` telemetry incident.

    Returns the new epoch.
    """
    from . import watchdog as _wd

    new_epoch = advance_epoch()
    _wd.drain_registry()
    # drop the eager program cache (entries pin revoked meshes) — via
    # sys.modules so the isolated pure-test loader, which never loads the
    # ops stack, does not pull it in here
    import sys

    ops = sys.modules.get(__package__.rsplit(".", 1)[0] + ".ops")
    if ops is not None:
        ops.clear_caches()
    dead = sorted(frozenset(failed))
    _incident(
        "elastic.epoch_changes", "epoch_change", rank,
        f"epoch {new_epoch - 1} -> {new_epoch}: shrank out rank(s) "
        f"{dead}" + (f" of {world}" if world else ""),
    )
    return new_epoch


# ---------------------------------------------------------------------------
# the elastic training loop
# ---------------------------------------------------------------------------


def run(step_fn, state, store: ShardStore, *, steps: int,
        start_step: int = 0, commit_every: int = 1,
        claim_watchdog: bool = True):
    """Run ``state = step_fn(state, step, comm)`` for ``steps`` steps,
    surviving rank loss: on a :class:`RankFailure` (raised by the step,
    posted by the claimed watchdog, or classified from a distributed
    death rattle) the loop commits the failure with the surviving peers,
    revokes the epoch, shrinks the world, restores the last committed
    state, and continues on ``k - f`` ranks from the committed step.

    ``step_fn`` takes the CURRENT comm — after a shrink it is a new
    (smaller, new-epoch) comm and the step re-traces at the new size.
    ``commit_every`` bounds the recovery replay window; the initial
    state is committed before step ``start_step`` so a first-step
    failure is recoverable.  ``claim_watchdog=True`` installs the
    elastic expiry handler (``resilience.set_on_timeout``) for the
    duration of the loop, so an expiry becomes a recovery instead of a
    process kill — the detection path a hung (not dead) peer needs.
    """
    from . import watchdog as _wd

    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    if commit_every < 1:
        raise ValueError(f"commit_every must be >= 1, got {commit_every}")

    claimed = False
    prev_handler = prev_fallback = None
    if claim_watchdog:
        # save whatever was installed (a user handler counts too) and
        # restore IT on exit, not the stock default
        prev_handler = _wd._registry.on_timeout
        prev_fallback = _wd._force_fallback
        _wd.set_on_timeout(_claimed_on_timeout)
        # the native C++ monitor kills on expiry and cannot hand the
        # expiry to a Python handler: route arms through the claimable
        # Python-fallback registry for the duration of the loop
        _wd.force_python_fallback(True)
        claimed = True
    try:
        if store.committed_step is None:
            store.commit(start_step, state)
        step = start_step
        while step < steps:
            try:
                state = step_fn(state, step, store.comm)
                _block_on(state)
                step += 1
                if (step - start_step) % commit_every == 0 or step == steps:
                    store.commit(step, state)
            except BaseException as exc:  # noqa: B036 - KeyboardInterrupt too
                rf = classify_failure(exc)
                if rf is None:
                    raise
                step, state = _recover(rf, store)
        return state
    finally:
        if claimed:
            _wd.set_on_timeout(prev_handler)
            _wd.force_python_fallback(prev_fallback)


def _block_on(state) -> None:
    """Force the step's device work to complete INSIDE the try: a peer
    death must surface here (as an error or a watchdog expiry), not at an
    uninstrumented later use."""
    try:
        import jax

        jax.block_until_ready(state)
    except ImportError:
        pass


def _recover(rf: RankFailure, store: ShardStore):
    """The shrink-and-resume sequence: agree -> revoke -> shrink ->
    restore.  Returns ``(committed_step, state)``."""
    _meter("elastic.failures_detected")
    comm = store.comm
    world = int(store.bootstrap.get("num_processes") or comm.world_size())

    if store.multiprocess():
        bs = store.bootstrap
        my_rank = int(bs["process_id"])
        failed = exchange_suspects(
            my_rank, world, rf.suspects, bs["host"],
            int(bs.get("agree_port_base",
                       int(bs["port_base"]) + 1000)) + 17 * current_epoch(),
            timeout=float(bs.get("agree_timeout", 20.0)),
        )
        if my_rank in failed:
            raise RankFailure(failed, "this rank was declared failed by "
                                      "its peers") from rf
    else:
        my_rank = 0
        failed = frozenset(rf.suspects)
    _meter("elastic.agreements")

    if not failed:
        raise RankFailure(
            (), "failure agreement produced an empty failed set: the "
                "suspects were not confirmed and no peer is unreachable — "
                "refusing to shrink a healthy world"
        ) from rf
    if not majority_survives(failed, world):
        raise RankFailure(
            failed,
            f"only {world - len(failed)} of {world} ranks survive — below "
            "the majority threshold (split-brain guard): aborting instead "
            "of training a divergent minority partition",
        ) from rf
    # raises RankFailure when a shard lost its whole replica set
    reconstruction_plan(failed, world, store.redundancy)

    revoke_epoch(failed, rank=my_rank, world=world)
    if store.multiprocess():
        store.rebootstrap(failed)
    else:
        store.apply_shrink(failed)
    step, state = store.restore(failed)
    _meter("elastic.resumes")
    return step, state
