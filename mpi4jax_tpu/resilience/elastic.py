"""Elastic communicators: survive rank loss and keep training.

PR 1 gave the resilience layer *detection* — watchdog, fault injection,
numeric guards — but a dead rank still killed the whole job: every
survivor either hung in its next collective or was killed loudly by its
watchdog.  This module is the *recovery* half, shaped after MPI's
User-Level Failure Mitigation (ULFM: revoke → shrink → agree) and
Elastic Horovod (resume from replicated in-memory state, not disk):

1. **Failure commit** — a watchdog expiry (claimed via
   ``resilience.set_on_timeout``) or a peer-death error raises
   :class:`RankFailure` carrying the *suspected* global ranks.  The
   survivors then agree on the failed set.  The default route is
   coordinator-mediated (O(k) connections: survivors report local
   suspect sets to rank 0, which unions and rebroadcasts —
   :func:`coordinator_agreement` is the pure model,
   :func:`coordinator_exchange_suspects` the TCP runtime form); when
   the coordinator itself is a suspect (or
   ``MPI4JAX_TPU_ELASTIC_AGREEMENT=gossip``), agreement degrades to a
   gossip round over still-healthy links (:func:`gossip_agreement` /
   :func:`exchange_suspects`).  The gossip fixpoint stays the arbiter —
   the coordinator verdict provably equals it on every drill matrix —
   so every survivor commits the SAME set even when each observed a
   different symptom.
2. **Revoke + shrink** — the current *communication epoch* is revoked:
   :func:`advance_epoch` bumps a monotonic counter that is folded into
   every compiled-program cache key (via ``resilience.runtime
   .cache_token``), so every executable traced against the old world
   becomes unreachable and re-traces at the new size; in-flight watchdog
   entries are drained and the eager program cache cleared.  The mesh
   and every registered comm are rebuilt as "all minus failed"
   (``parallel/mesh.shrink_world_mesh``, ``Comm.shrink``) with survivor
   ranks compacted (:func:`compact_rank_map`).
3. **Resume** — :class:`ShardStore` keeps an in-memory, sharded copy of
   registered state (the natural shard unit ``reduce_scatter`` produces:
   rank ``r`` owns flat-byte shard ``r``) with **topology-aware striped
   replication** (:func:`stripe_placement`, the default): every replica
   of shard ``s`` lands on a *different host* than its owner (and than
   each other, while hosts allow), so losing a whole host still leaves
   ≥1 live copy of every shard whenever ``redundancy ≥ 1`` and ``hosts
   ≥ 2``.  Without topology information (or under
   ``MPI4JAX_TPU_ELASTIC_PLACEMENT=neighbor``) placement degrades to
   the classic neighbor ring (:func:`neighbor_placement`: shard ``s``
   on ranks ``s, s+1, ..., s+redundancy (mod k)``), which tolerates any
   ``redundancy`` simultaneous *rank* losses (:func:`recoverable`) but
   not a host-row loss.  The table in force is recorded on each commit
   record, and restores follow the RECORDED table.  :func:`run` wraps
   the training loop: on
   ``RankFailure`` it commits the failure, shrinks, restores the last
   committed state (reassembled from surviving replicas — one SUM
   allreduce over the *new* comm in multi-process mode), and continues
   on ``k − f`` ranks from the last committed step.

Pure by construction below the jax line: epoch arithmetic, the
ownership/replication maps, the agreement model, and the byte-packing
helpers import no jax, so ``tests/test_elastic_pure.py`` exercises them
under any JAX via the isolated loader.  Everything that traces or moves
bytes imports jax lazily.

Protocol, redundancy math, and the drill recipe: docs/resilience.md
("Elastic recovery").
"""

from __future__ import annotations

import json
import socket
import threading
import time
import warnings
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from ..utils import config

__all__ = [
    "RankFailure",
    "ShardStore",
    "run",
    "join_and_run",
    "current_epoch",
    "advance_epoch",
    "epoch_history",
    "elastic_cache_token",
    "compact_rank_map",
    "shrink_groups",
    "expand_fail_unit",
    "shrunken_shape",
    "replica_ranks",
    "shards_held_by",
    "neighbor_placement",
    "stripe_placement",
    "placement_shards_held_by",
    "placement_recoverable",
    "plan_from_placement",
    "recoverable",
    "reconstruction_plan",
    "shard_bounds",
    "gossip_agreement",
    "coordinator_agreement",
    "majority_survives",
    "reassemble_from_stores",
    "revoke_epoch",
    "exchange_suspects",
    "coordinator_exchange_suspects",
    "negotiate_failed",
    "classify_failure",
    "take_pending_failure",
    "request_drain",
    "take_pending_drain",
    "install_preemption_handler",
    "post_simulated_join",
    "request_join",
    "coordinator_port",
    "join_port",
    "control_port",
    "agree_port",
    "mark_comm_draining",
    "comm_drained",
    "pack_leaves",
    "unpack_leaves",
]


class RankFailure(RuntimeError):
    """One or more ranks are suspected dead/stalled.

    ``suspects`` are GLOBAL ranks (row-major over the comm's full axes —
    the same rank space ``MPI4JAX_TPU_FAULT_SPEC`` addresses).  An empty
    suspect set means "something died but this rank cannot name it" (a
    generic distributed-runtime error): the agreement round resolves it
    from link health.
    """

    def __init__(self, suspects: Iterable[int] = (), detail: str = ""):
        self.suspects: FrozenSet[int] = frozenset(int(r) for r in suspects)
        self.detail = detail
        names = sorted(self.suspects) if self.suspects else "unknown"
        super().__init__(
            f"rank failure suspected (ranks {names})"
            + (f": {detail}" if detail else "")
        )


# ---------------------------------------------------------------------------
# communication epochs
# ---------------------------------------------------------------------------
#
# The epoch is the revocation mechanism: every compiled-program cache key
# folds it in (resilience.runtime.cache_token -> ops/_base._dynamic_state
# -> both the eager and the spmd program caches), so advancing it makes
# every executable traced against the old world unreachable — the moral
# equivalent of ULFM's MPI_Comm_revoke, enforced at the cache layer
# instead of in the transport.  Comms stamp the epoch they were built in
# (parallel/comm.py); a collective dispatched on a comm whose epoch is
# behind the current one is flagged MPX126 by the trace-time verifier.

_epoch_lock = threading.Lock()
_epoch = 0
# one record per epoch advance: {"epoch", "world", "cause", "detail"} —
# the audit trail telemetry.report() renders for churn runs ("epoch,
# world size, cause"), kept host-side so it survives every re-trace
_epoch_history: List[dict] = []


def current_epoch() -> int:
    """The current communication epoch (0 until the first revocation)."""
    return _epoch


def advance_epoch(*, world: Optional[int] = None, cause: str = "revoke",
                  detail: str = "") -> int:
    """Revoke the current epoch: bump the counter and invalidate every
    stamp-memoized configuration consumer (the program caches fold the
    epoch in via ``resilience.cache_token``, so every old-world
    executable re-traces).  ``world``/``cause``/``detail`` describe the
    boundary for :func:`epoch_history` — an epoch now carries a world
    *delta*, not just removals: ``cause`` is ``"failure"``, ``"drain"``,
    or ``"join"`` for elastic boundaries (``"revoke"`` for bare
    revocations).  Returns the new epoch."""
    global _epoch
    with _epoch_lock:
        _epoch += 1
        new = _epoch
        _epoch_history.append({
            "epoch": new,
            "world": world,
            "cause": cause,
            "detail": detail,
        })
    config.bump_config_epoch()
    return new


def epoch_history() -> List[dict]:
    """One record per epoch advance (epoch, post-boundary world size,
    cause, detail) — the audit trail of a churning run, rendered by
    ``telemetry.report()`` and embedded in telemetry snapshots."""
    with _epoch_lock:
        return [dict(r) for r in _epoch_history]


def _set_epoch(n: int) -> None:
    """Adopt an externally-agreed epoch (a joiner admitted into epoch
    ``n`` must trace under the same cache keys as the world it joins).
    Never moves backwards."""
    global _epoch
    with _epoch_lock:
        if n < _epoch:
            raise ValueError(
                f"cannot move the epoch backwards ({_epoch} -> {n})")
        if n > _epoch:
            _epoch = n
            _epoch_history.append({
                "epoch": n, "world": None, "cause": "adopt", "detail": "",
            })
    config.bump_config_epoch()


def _reset_epoch_for_tests() -> None:
    global _epoch
    with _epoch_lock:
        _epoch = 0
        del _epoch_history[:]
    with _drain_lock:
        _pending_drain.clear()
        _peer_drain.clear()
        _draining_comms.clear()
        _drained_comms.clear()
    with _join_lock:
        del _pending_joins[:]
    config.bump_config_epoch()


def elastic_cache_token():
    """The elastic contribution to every compiled-program cache key: the
    epoch plus the declared elastic knobs (grow, fail unit, drain grace,
    port span).  With elastic never engaged and every knob at its
    default this is the constant 0 — byte-identical keys (and HLO) to a
    build without the elastic layer, the PR 1-8 contract."""
    grow = config.elastic_grow()
    unit = config.elastic_fail_unit()
    grace = config.drain_grace_s()
    span = config.elastic_port_span()
    if (not grow and unit == "rank"
            and grace == config.DEFAULT_DRAIN_GRACE_S
            and span == config.DEFAULT_ELASTIC_PORT_SPAN):
        return _epoch
    return (_epoch, grow, unit, grace, span)


# ---------------------------------------------------------------------------
# shard ownership + replica placement (pure)
#
# Two placement policies share one table shape — ``table[s]`` is the tuple
# of ranks holding shard s, owner first:
#
#   neighbor  (replica_ranks / neighbor_placement): shard s on ranks
#             s..s+redundancy mod k.  Host-blind: a whole-host loss kills
#             a contiguous rank block PLUS the neighbors holding its
#             replicas, so a host-row kill can erase every copy of a
#             shard even within the redundancy budget.
#   stripe    (stripe_placement): topology-aware — every replica lands on
#             a DIFFERENT host than the owner (and than each other, while
#             hosts allow), so any single-host loss leaves every shard a
#             live copy whenever redundancy >= 1 and hosts >= 2.
#
# The stripe is the default (MPI4JAX_TPU_ELASTIC_PLACEMENT); with no
# topology it degrades to exactly the neighbor table, so single-host
# (and topology-less test) deployments see identical placement to the
# pre-stripe builds.  benchmarks/elastic_drill.py drills the difference.
# ---------------------------------------------------------------------------


def shard_bounds(nbytes: int, k: int) -> Tuple[int, int]:
    """``(shard_size, padded_size)`` splitting ``nbytes`` into ``k`` equal
    byte shards (the last shard is zero-padded) — the same equal-chunk
    padding rule the ring reduce_scatter uses for non-divisible
    payloads."""
    if k < 1:
        raise ValueError(f"need at least one rank, got k={k}")
    shard = -(-nbytes // k) if nbytes else 0  # ceil div; 0 stays 0
    return shard, shard * k


def replica_ranks(shard: int, k: int, redundancy: int) -> Tuple[int, ...]:
    """Ranks holding a copy of ``shard``: the owner (rank == shard id)
    plus its ``redundancy`` right neighbors, mod k — so every shard has
    ``redundancy + 1`` copies on distinct ranks and ANY ``redundancy``
    simultaneous failures leave a live copy."""
    if not 0 <= shard < k:
        raise ValueError(f"shard {shard} out of range for k={k}")
    if redundancy < 0:
        raise ValueError(f"redundancy must be >= 0, got {redundancy}")
    r = min(redundancy, k - 1)  # more copies than ranks is just "everyone"
    return tuple((shard + j) % k for j in range(r + 1))


def shards_held_by(rank: int, k: int, redundancy: int) -> Tuple[int, ...]:
    """Inverse of :func:`replica_ranks`: the shards rank ``rank`` stores —
    its own plus its ``redundancy`` left neighbors', mod k."""
    if not 0 <= rank < k:
        raise ValueError(f"rank {rank} out of range for k={k}")
    r = min(max(redundancy, 0), k - 1)
    return tuple(sorted((rank - j) % k for j in range(r + 1)))


def neighbor_placement(k: int, redundancy: int) -> Tuple[Tuple[int, ...], ...]:
    """The full neighbor placement table: ``table[s] == replica_ranks(s)``.
    Kept reachable (``MPI4JAX_TPU_ELASTIC_PLACEMENT=neighbor``) as the
    drill harness's negative control — the placement a host-row kill
    provably defeats (benchmarks/elastic_drill.py)."""
    return tuple(replica_ranks(s, k, redundancy) for s in range(k))


def _host_of_rank(topology, k: int) -> Optional[Tuple[int, ...]]:
    """Normalize ``topology`` to a length-``k`` host-id tuple, or ``None``.

    Accepts an object with ``host_of_rank`` (parallel/topology.Topology),
    a per-host rank-count sequence (``(4, 4)``), or a spec string in the
    ``MPI4JAX_TPU_TOPOLOGY`` grammar (``'2x4'`` / ``'3,5'``).  A topology
    that does not cover exactly ``k`` ranks resolves to ``None`` (the
    caller falls back to the topology-less table): placement silently
    guessing host boundaries would void the stripe guarantee."""
    if topology is None:
        return None
    hor = getattr(topology, "host_of_rank", None)
    if hor is None:
        counts = (config.parse_topology_spec(topology)
                  if isinstance(topology, str)
                  else tuple(int(c) for c in topology))
        if counts is None:
            return None
        if any(c < 1 for c in counts):
            raise ValueError(
                f"topology host counts must be positive, got {counts}")
        hor = tuple(h for h, c in enumerate(counts) for _ in range(c))
    else:
        hor = tuple(int(h) for h in hor)
    return hor if len(hor) == k else None


def stripe_placement(k: int, redundancy: int,
                     topology=None) -> Tuple[Tuple[int, ...], ...]:
    """Topology-aware replica placement: ``table[s]`` is the tuple of
    ranks holding shard ``s``, owner (rank ``s``) first.

    Candidate replicas are ordered one rank per host, hosts in
    increasing (wrapping) distance from the owner's host, the owner's
    own host strictly last; within a host the candidate local index
    wraps from the owner's local index, keeping per-host shard load
    balanced.  Consequences the tests pin:

    - every replica lands on a DIFFERENT host than the owner, and than
      the other replicas, while hosts allow (``redundancy < hosts``);
    - any SINGLE-host loss leaves every shard >= 1 live copy whenever
      ``redundancy >= 1`` and ``hosts >= 2`` (the first replica is
      always off-host) — the property neighbor placement lacks;
    - with no topology (or one host) the table degrades to exactly
      :func:`neighbor_placement`;
    - ``redundancy >= hosts`` forces replica co-location on hosts: the
      placement warns once and wraps gracefully (copies still land on
      distinct ranks while ``k`` allows — the extra copies buy rank-loss
      budget, not host-loss budget).
    """
    if k < 1:
        raise ValueError(f"need at least one rank, got k={k}")
    if redundancy < 0:
        raise ValueError(f"redundancy must be >= 0, got {redundancy}")
    r = min(redundancy, k - 1)
    hor = _host_of_rank(topology, k)
    if hor is None:
        return neighbor_placement(k, redundancy)
    members: Dict[int, List[int]] = {}
    for rank, h in enumerate(hor):
        members.setdefault(h, []).append(rank)
    order = sorted(members)
    hosts = len(order)
    hidx = {h: i for i, h in enumerate(order)}
    lidx = {}
    for ranks in members.values():
        for i, rank in enumerate(ranks):
            lidx[rank] = i
    if hosts > 1 and r >= hosts:
        warnings.warn(
            f"stripe_placement: redundancy {redundancy} >= hosts {hosts}: "
            "replica copies must co-locate on hosts (a single-host loss "
            "stays recoverable; the extra copies only add rank-loss "
            "budget) — wrapping the stripe around the hosts",
            RuntimeWarning, stacklevel=2)
    table = []
    for s in range(k):
        h = hidx[hor[s]]
        l = lidx[s]
        cands = []
        for c in range(k):
            if c == s:
                continue
            ch = hidx[hor[c]]
            d = (ch - h) % hosts
            q = (lidx[c] - l) % len(members[order[ch]])
            # one rank per host per wrap q, hosts in distance order,
            # the owner's own host (d == 0) strictly after every other
            cands.append(((1, q, 0) if d == 0 else (0, q, d), c))
        cands.sort()
        table.append((s,) + tuple(c for _, c in cands[:r]))
    return tuple(table)


def placement_shards_held_by(rank: int, placement) -> Tuple[int, ...]:
    """Inverse of a placement table: the shards ``rank`` holds."""
    return tuple(sorted(s for s, holders in enumerate(placement)
                        if rank in holders))


def placement_recoverable(failed: Iterable[int], placement) -> bool:
    """True iff every shard keeps >= 1 surviving copy under ``placement``
    after losing ``failed``."""
    dead = frozenset(failed)
    return all(any(r not in dead for r in holders)
               for holders in placement)


def plan_from_placement(failed: Iterable[int], placement) -> Dict[int, int]:
    """``{shard: provider}`` over an arbitrary placement table: for EVERY
    shard, the lowest-numbered surviving holder — the deterministic
    choice every survivor computes independently from the same committed
    table, so the restore exchange has exactly one contributor per
    shard.  Raises ``RankFailure`` when a shard lost every copy."""
    dead = frozenset(failed)
    plan = {}
    for s, holders in enumerate(placement):
        live = [r for r in holders if r not in dead]
        if not live:
            raise RankFailure(
                dead,
                f"shard {s} unrecoverable: all {len(holders)} replica "
                f"ranks {tuple(holders)} failed (the placement tolerates "
                f"at most {len(holders) - 1} simultaneous losses of a "
                "shard's holders)",
            )
        plan[s] = min(live)
    return plan


def recoverable(failed: Iterable[int], k: int, redundancy: int,
                placement=None) -> bool:
    """True iff every shard still has at least one surviving copy after
    losing ``failed`` — i.e. no shard's whole replica set died.
    ``placement`` defaults to the neighbor table (back-compat); pass a
    :func:`stripe_placement` table to judge the striped layout."""
    table = (neighbor_placement(k, redundancy)
             if placement is None else placement)
    return placement_recoverable(failed, table)


def reconstruction_plan(
    failed: Iterable[int], k: int, redundancy: int, placement=None
) -> Dict[int, int]:
    """``{shard: provider}`` naming, for EVERY shard, the lowest-numbered
    surviving rank holding a copy (:func:`plan_from_placement`).  Raises
    ``RankFailure`` when a shard lost all its copies (more simultaneous
    failures than the placement tolerates).  ``placement`` defaults to
    the neighbor table; the runtime passes the table recorded on the
    commit, so restore always follows the placement the bytes actually
    landed under."""
    table = (neighbor_placement(k, redundancy)
             if placement is None else placement)
    if len(table) != k:
        raise ValueError(
            f"placement table covers {len(table)} shards, expected {k}")
    return plan_from_placement(failed, table)


# ---------------------------------------------------------------------------
# rank compaction + group shrink (pure)
# ---------------------------------------------------------------------------


def compact_rank_map(world: int, failed: Iterable[int]) -> Dict[int, int]:
    """``{old_global_rank: new_global_rank}`` for the survivors, compacted
    in ascending old-rank order (survivor i becomes new rank i) — the
    rank renumbering ULFM's ``MPI_Comm_shrink`` specifies."""
    dead = frozenset(failed)
    bad = [r for r in dead if not 0 <= r < world]
    if bad:
        raise ValueError(f"failed ranks {sorted(bad)} out of range for "
                         f"world {world}")
    if len(dead) >= world:
        raise RankFailure(dead, "no survivors: every rank failed")
    survivors = [r for r in range(world) if r not in dead]
    return {old: new for new, old in enumerate(survivors)}


def shrink_groups(groups, failed: Iterable[int], world: int):
    """Rebuild a color-split comm's group tables as "all minus failed":
    drop the failed ranks, renumber survivors via :func:`compact_rank_map`
    (preserving each group's order), drop groups that lost every member.
    Returns the new group tuple in the new (compacted) rank space.

    Generalizes unchanged to the 2-D renumbering: a Cartesian row/column
    shrink passes the *expanded* failed set (:func:`expand_fail_unit`),
    and because whole rows/columns are removed the row-major compaction
    IS the new grid's row-major numbering."""
    rmap = compact_rank_map(world, failed)
    out = []
    for members in groups:
        kept = tuple(rmap[r] for r in members if r in rmap)
        if kept:
            out.append(kept)
    return tuple(out)


def expand_fail_unit(failed: Iterable[int], shape, fail_unit: str):
    """Expand a failed-rank set to the declared shrink granularity.

    ``shape`` is the mesh's dimension tuple (row-major rank order);
    ``fail_unit`` is ``"rank"`` / ``"row"`` / ``"col"``
    (``MPI4JAX_TPU_ELASTIC_FAIL_UNIT``).  ``"row"`` returns every rank
    sharing a first-axis index with a failed rank, ``"col"`` every rank
    sharing a second-axis index — the whole-grid-line removal that keeps
    a Cartesian mesh rectangular.  On a 1-D mesh a row is a rank, so
    every unit degrades to ``"rank"``.  Pure (no jax): the renumbering
    tests drive it directly."""
    shape = tuple(int(n) for n in shape)
    world = 1
    for n in shape:
        world *= n
    failed = frozenset(int(r) for r in failed)
    bad = [r for r in failed if not 0 <= r < world]
    if bad:
        raise ValueError(
            f"failed ranks {sorted(bad)} out of range for world {world}")
    if fail_unit not in ("rank", "row", "col"):
        raise ValueError(
            f"fail_unit must be 'rank', 'row', or 'col', got {fail_unit!r}")
    if fail_unit == "rank" or len(shape) == 1 or not failed:
        return failed
    if len(shape) != 2:
        raise ValueError(
            f"fail_unit={fail_unit!r} supports 1-D and 2-D meshes, got "
            f"shape {shape}"
        )
    rows, cols = shape
    if fail_unit == "row":
        dead_rows = {r // cols for r in failed}
        return frozenset(
            i * cols + j for i in dead_rows for j in range(cols))
    dead_cols = {r % cols for r in failed}
    return frozenset(
        i * cols + j for i in range(rows) for j in dead_cols)


def shrunken_shape(shape, expanded_failed: Iterable[int], fail_unit: str):
    """The mesh shape after removing ``expanded_failed`` (an
    :func:`expand_fail_unit` result) at ``fail_unit`` granularity —
    whole rows/columns drop off the matching dimension; rank-unit
    removal flattens a 1-D shape."""
    shape = tuple(int(n) for n in shape)
    dead = frozenset(int(r) for r in expanded_failed)
    if len(shape) == 1 or fail_unit == "rank":
        world = 1
        for n in shape:
            world *= n
        return (world - len(dead),)
    rows, cols = shape
    if fail_unit == "row":
        dead_rows = {r // cols for r in dead}
        return (rows - len(dead_rows), cols)
    dead_cols = {r % cols for r in dead}
    return (rows, cols - len(dead_cols))


# ---------------------------------------------------------------------------
# per-epoch rendezvous ports (pure math)
# ---------------------------------------------------------------------------
#
# Every elastic rendezvous derives its port from the epoch so revoked-world
# sockets can never collide with the recovered world's — but the naive
# ``port_base + epoch`` walks out of the ephemeral range after enough
# churn.  All port derivation therefore wraps within a declared window of
# ``span`` ports (``MPI4JAX_TPU_ELASTIC_PORT_SPAN``):
#
#   [port_base,          port_base +   span)   jax.distributed coordinator
#   [port_base +   span, port_base + 2*span)   join listener (rank 0)
#   [port_base + 2*span, port_base + 4*span)   per-rank control listeners
#                                              (two alternating epoch banks,
#                                              so consecutive epochs never
#                                              contend for a port)
#   [port_base + 4*span, port_base + 5*span)   agreement listener (rank 0):
#                                              the coordinator-mediated
#                                              suspect-report star
#
# A wrap collision (epoch e vs e+span) lands on a socket the revoked world
# closed span epochs ago; the residual TIME_WAIT case is absorbed by the
# bootstrap retry policy that already wraps every bind/connect.


def wrapped_epoch(epoch: int, span: Optional[int] = None) -> int:
    """``epoch % span`` with the span from the declared flag."""
    span = config.elastic_port_span() if span is None else int(span)
    if span < 1:
        raise ValueError(f"port span must be >= 1, got {span}")
    return int(epoch) % span


def coordinator_port(port_base: int, epoch: int,
                     span: Optional[int] = None) -> int:
    """The jax.distributed coordinator port for ``epoch`` — what a
    replacement process contacts (``port_base + epoch``, wrapped within
    the declared window)."""
    return int(port_base) + wrapped_epoch(epoch, span)


def join_port(port_base: int, epoch: int, span: Optional[int] = None) -> int:
    """The coordinator's join-listener port for ``epoch`` (its own
    span-wide bank above the coordinator window, so a joiner can scan
    the whole window without ever poking a jax.distributed socket)."""
    span = config.elastic_port_span() if span is None else int(span)
    return int(port_base) + span + wrapped_epoch(epoch, span)


def control_port(port_base: int, rank: int, epoch: int,
                 span: Optional[int] = None) -> int:
    """Rank ``rank``'s control-listener port in ``epoch`` (drain notices
    and acks).  Two alternating epoch banks: epoch e and e+1 use
    disjoint ports, so a process rebinding after a shrink can never race
    the previous world's listener for the same port."""
    span = config.elastic_port_span() if span is None else int(span)
    if not 0 <= int(rank) < span:
        raise ValueError(
            f"control_port: rank {rank} outside the span window {span} "
            "(raise MPI4JAX_TPU_ELASTIC_PORT_SPAN above the world size)")
    bank = int(epoch) % 2
    return int(port_base) + 2 * span + bank * span + int(rank)


def agree_port(port_base: int, epoch: int, span: Optional[int] = None) -> int:
    """The coordinator's agreement-listener port for ``epoch`` — where
    survivors report their suspect sets in the coordinator-mediated
    agreement (its own span-wide bank above the control windows, so a
    report can never poke a jax.distributed or control socket)."""
    span = config.elastic_port_span() if span is None else int(span)
    return int(port_base) + 4 * span + wrapped_epoch(epoch, span)


# ---------------------------------------------------------------------------
# failure agreement (pure model + TCP runtime form)
# ---------------------------------------------------------------------------


def gossip_agreement(
    suspects: Dict[int, Iterable[int]],
    links,
) -> Dict[int, FrozenSet[int]]:
    """The agreement round, as a pure fixpoint over a link matrix.

    ``suspects[r]`` is rank r's locally-observed suspect set;
    ``links[i][j]`` is True when the i↔j link is healthy (symmetric;
    the diagonal is ignored).  Each round every rank unions the suspect
    sets of the peers it can reach over healthy links, and additionally
    suspects any peer it has NO healthy link to; rounds repeat to
    fixpoint (≤ world rounds — each round only grows sets).

    Within one connected component of the healthy-survivor subgraph the
    result is identical on every member — the agreement property the
    runtime form inherits.  Disconnected components can disagree; that is
    the split-brain case :func:`majority_survives` arbitrates.

    Gossip is read over EVERY healthy link, including from a peer already
    in the reader's suspect set — matching the runtime form, whose inbox
    unions every message that lands regardless of the reader's current
    suspicion.  (An earlier revision skipped suspected peers' gossip,
    which made convergence order-dependent: a rank could hearsay-suspect
    a live peer mid-round and then permanently miss a suspect known only
    to that peer — the "something died but unnamed" case under a
    partitioned link matrix, where the only name-carrier may itself be
    partition-suspected.)  Suspect values outside ``range(world)`` raise
    ``ValueError`` — a stale-numbering suspect silently joining the
    fixpoint would poison every survivor's verdict.
    """
    world = len(links)
    for r, named in suspects.items():
        bad = sorted(int(p) for p in named if not 0 <= int(p) < world)
        if bad:
            raise ValueError(
                f"gossip_agreement: rank {r} names suspects {bad} outside "
                f"the world of {world} ranks (stale numbering?)")
    # every rank computes (a dead rank's output is simply ignored by its
    # peers — they have no healthy link to read it over)
    agreed = {r: set(map(int, suspects.get(r, ()))) for r in range(world)}
    changed = True
    rounds = 0
    while changed and rounds <= world + 1:
        changed = False
        rounds += 1
        snapshot = {r: frozenset(s) for r, s in agreed.items()}
        for r in range(world):
            mine = agreed[r]
            before = len(mine)
            for p in range(world):
                if p == r:
                    continue
                healthy = links[r][p] and links[p][r]
                if not healthy:
                    mine.add(p)          # unreachable peer => suspect
                else:
                    mine |= snapshot[p]  # gossip over the healthy link
            if len(mine) != before:
                changed = True
    return {r: frozenset(s) for r, s in agreed.items()}


def coordinator_agreement(
    suspects: Dict[int, Iterable[int]],
    links,
    coordinator: int = 0,
) -> Dict[int, FrozenSet[int]]:
    """Pure model of the coordinator-mediated agreement round — the O(k)
    star that replaces the O(k²) all-pairs gossip at pod scale.

    Every rank's effective REPORT is its local suspect set plus every
    peer it has no healthy link to (the same link-derived suspicion
    :func:`gossip_agreement` applies).  Ranks with a healthy link to
    ``coordinator`` that do not locally name it a suspect form the star:
    each sends one report, the coordinator unions them (its own
    included), adds every rank that never reported, and rebroadcasts one
    verdict — 2 messages over k-1 connections.  Ranks outside the star
    degrade to peer gossip among themselves; the star ranks are parked
    at the coordinator and answer no gossip, so the degraded matrix
    masks them out (an isolated degraded rank therefore suspects
    everyone and aborts on the majority guard — conservative, never
    split-brained).

    Arbiter property (pinned by the tests): whenever the coordinator has
    a healthy link to every live rank, the star verdict equals
    :func:`gossip_agreement`'s fixpoint on the same inputs — the star is
    a 1-hop spanning tree of the survivor component and both compute the
    component-wide union.  A dead (or universally-suspected) coordinator
    degrades EVERY survivor, and the result is exactly the gossip
    fixpoint — so the pure gossip model stays the arbiter the runtime
    transport must converge to in every case.
    """
    world = len(links)

    def healthy(a: int, b: int) -> bool:
        return bool(links[a][b] and links[b][a])

    local = {r: set(map(int, suspects.get(r, ()))) for r in range(world)}
    reports = {
        r: local[r] | {p for p in range(world)
                       if p != r and not healthy(r, p)}
        for r in range(world)
    }
    star = [r for r in range(world)
            if r == coordinator
            or (healthy(r, coordinator) and coordinator not in local[r])]
    verdict: set = set()
    for r in star:
        verdict |= reports[r]
    # a rank that never reports is suspected (it is either dead — already
    # in the coordinator's own link-derived report — or degraded to
    # gossip the star cannot hear)
    verdict |= set(range(world)) - set(star)
    out = {r: frozenset(verdict) for r in star}
    rest = [r for r in range(world) if r not in star]
    if rest:
        keep = set(rest)
        masked = [[bool(links[i][j]) and i in keep and j in keep
                   for j in range(world)] for i in range(world)]
        fallen = gossip_agreement(suspects, masked)
        for r in rest:
            out[r] = fallen[r]
    return out


def majority_survives(agreed_failed: Iterable[int], world: int) -> bool:
    """Split-brain guard: a survivor partition keeps running only when it
    holds a strict majority of the original world (otherwise two halves
    of a partitioned job would both shrink and train divergent models).
    """
    survivors = world - len(frozenset(agreed_failed))
    return survivors * 2 > world


def _recv_all(conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def exchange_suspects(
    my_rank: int,
    world: int,
    suspects: Iterable[int],
    host: str,
    port_base: int,
    *,
    rounds: int = 2,
    timeout: float = 20.0,
) -> FrozenSet[int]:
    """The runtime agreement: gossip suspect sets over TCP among the
    survivors (rank r listens on ``port_base + r``).

    Two rounds by default: round 1 unions everyone's locally-observed
    suspects (a peer that cannot be reached joins the set), round 2
    propagates the unions so survivors that observed different symptoms
    converge — the TCP realization of :func:`gossip_agreement` on a
    connected survivor component.  Small-world only (the drill scale);
    pod-scale deployments would run this over the coordinator.
    """
    agreed = set(int(r) for r in suspects)
    agreed.discard(my_rank)

    inbox: List[FrozenSet[int]] = []
    heard: set = set()   # peers we have EVIDENCE are alive (they sent to us)
    lock = threading.Lock()
    stop = threading.Event()

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port_base + my_rank))
    srv.listen(world)
    srv.settimeout(0.2)

    def _serve():
        try:
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with conn:
                    try:
                        conn.settimeout(timeout)
                        header = _recv_all(conn, 8)
                        if len(header) < 8:
                            continue
                        n = int.from_bytes(header, "big")
                        payload = json.loads(_recv_all(conn, n).decode())
                        with lock:
                            heard.add(int(payload["from"]))
                            inbox.append(frozenset(
                                int(r) for r in payload["suspects"]))
                    except (OSError, ValueError, KeyError, TypeError):
                        continue
        finally:
            srv.close()

    def _send_with_patience(peer: int, msg: bytes) -> bool:
        """Deliver to a peer, retrying refusals until ``timeout``: the
        survivors reach the agreement phase at different times (failure
        detection is not synchronized), so an instant connection-refused
        from a healthy-but-late peer must not get it declared dead.  A
        peer that stays unreachable for the whole window — and never sent
        us anything either — is suspected."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                with socket.create_connection(
                    (host, port_base + peer),
                    timeout=max(0.1, deadline - time.monotonic()),
                ) as c:
                    c.sendall(len(msg).to_bytes(8, "big") + msg)
                return True
            except OSError:
                with lock:
                    if peer in heard:
                        # alive but done serving (it finished its rounds
                        # before us): not a failure, just asymmetry
                        return True
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.1)

    server = threading.Thread(target=_serve, daemon=True)
    server.start()
    try:
        for rnd in range(max(1, rounds)):
            # never gossip ourselves as a suspect (we are demonstrably
            # alive and sending) — but KEEP my_rank in the returned set
            # when peers put it there: a rank its peers declared failed
            # must see itself in the result and abort (docs/resilience.md
            # step 1), not silently strip the verdict
            msg = json.dumps(
                {"from": my_rank,
                 "suspects": sorted(agreed - {my_rank})}).encode()
            for peer in range(world):
                if peer == my_rank or peer in agreed:
                    continue
                if not _send_with_patience(peer, msg):
                    agreed.add(peer)  # unreachable survivor => suspect
            # let the peers' sends for this round land before folding
            # (their rounds are not synchronized with ours)
            if rnd == max(1, rounds) - 1:
                time.sleep(0.5)
            with lock:
                got, inbox[:] = list(inbox), []
            for s in got:
                agreed |= set(s)
    finally:
        # linger: keep answering slow peers so OUR early exit does not get
        # us suspected (the server thread closes the socket after stop);
        # daemon so a finished worker's interpreter never waits on it
        linger = threading.Timer(timeout, stop.set)
        linger.daemon = True
        linger.start()
    return frozenset(agreed)


def coordinator_exchange_suspects(
    my_rank: int,
    world: int,
    suspects: Iterable[int],
    host: str,
    port: int,
    *,
    coordinator: int = 0,
    timeout: float = 20.0,
) -> FrozenSet[int]:
    """Runtime form of :func:`coordinator_agreement`'s star: O(k)
    connections instead of the all-pairs gossip's O(k²).

    The coordinator (rank ``coordinator`` of the CURRENT world, normally
    0) binds ``port`` (:func:`agree_port`), collects one suspect report
    per survivor, unions them with its own, adds every rank that never
    reported within ``timeout``, and answers each parked connection with
    the verdict — one connection per non-coordinator survivor, the
    verdict riding the report's socket back.  Reporters dial with
    full-jitter backoff (:mod:`.retry` — the reconnection-stampede cure:
    k-1 survivors hit one listener at once) until the report lands or
    ``timeout`` elapses.

    Raises ``RuntimeError``/``OSError`` when the round cannot complete
    (coordinator unreachable, bind lost, malformed verdict) — the caller
    (:func:`negotiate_failed`) degrades to :func:`exchange_suspects`
    peer gossip, the documented fallback when the coordinator itself is
    the casualty.  Like the gossip form, ``my_rank`` is never gossiped
    by itself but is KEPT in the returned verdict when peers put it
    there — a rank its peers declared failed must see the verdict and
    abort, not silently strip it.
    """
    mine = set(int(r) for r in suspects)
    mine.discard(my_rank)

    if my_rank != coordinator:
        from .retry import retry_with_backoff

        deadline = time.monotonic() + timeout

        def _report():
            budget = max(0.1, deadline - time.monotonic())
            with socket.create_connection((host, port),
                                          timeout=budget) as c:
                # once connected the coordinator is known alive; the
                # verdict waits on ITS collection window, which may have
                # opened up to a full window after ours — grant the recv
                # that extra patience so detection skew between survivors
                # costs latency, never a spurious fallback
                c.settimeout(max(0.1, deadline - time.monotonic())
                             + timeout)
                _send_json(c, {"from": my_rank,
                               "suspects": sorted(mine)})
                reply = _recv_json(c)
                return frozenset(
                    int(r) for r in reply["verdict"]) | mine

        return retry_with_backoff(
            _report,
            what=f"suspect report to agreement coordinator "
                 f"{host}:{port}",
            deadline=timeout,
            base_delay=0.05,
            max_delay=1.0,
        )

    # --- coordinator side: collect, union, rebroadcast ---
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(world)
    srv.settimeout(0.2)
    reports: Dict[int, FrozenSet[int]] = {my_rank: frozenset(mine)}
    parked = []
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            union = set().union(*reports.values())
            # stop waiting once every rank not already suspected (by
            # anyone) has reported; suspected ranks cost no deadline
            if not (set(range(world)) - set(reports) - union):
                break
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            try:
                conn.settimeout(max(0.1, deadline - time.monotonic()))
                payload = _recv_json(conn)
                sender = int(payload["from"])
                reports[sender] = frozenset(
                    int(r) for r in payload.get("suspects", ()))
                parked.append((sender, conn))
            except (OSError, ValueError, KeyError, TypeError):
                conn.close()
        # NOT discarding my_rank: if a report named the (serving)
        # coordinator, every reporter gets a verdict containing it, so
        # the coordinator must judge itself by the same verdict —
        # stripping it locally would hand the survivors divergent sets
        verdict = set().union(*reports.values())
        verdict |= set(range(world)) - set(reports)  # non-reporters
        _meter("elastic.agreement_reports", len(parked))
        for _, conn in parked:
            try:
                _send_json(conn, {"verdict": sorted(verdict)})
            except OSError:
                pass
            finally:
                conn.close()
    finally:
        srv.close()
    return frozenset(verdict)


def negotiate_failed(
    my_rank: int,
    world: int,
    suspects: Iterable[int],
    host: str,
    *,
    agree_port_no: int,
    gossip_port_base: int,
    timeout: float = 20.0,
    mode: Optional[str] = None,
    coordinator: int = 0,
) -> FrozenSet[int]:
    """The runtime agreement entry ``_recover`` uses: coordinator star
    first (O(k) connections), degradation to all-pairs peer gossip when
    the coordinator is locally a suspect, unreachable, or the declared
    mode (``MPI4JAX_TPU_ELASTIC_AGREEMENT``) forces gossip.

    The coordinator phase gets at most HALF the agreement window: a
    survivor that needed the fallback still reaches the gossip ports
    well inside its peers' full-window send patience, so a dead
    coordinator costs latency, never a spurious suspicion."""
    mine = set(int(r) for r in suspects)
    mode = config.elastic_agreement() if mode is None else mode
    if mode == "coordinator" and coordinator not in mine:
        try:
            return coordinator_exchange_suspects(
                my_rank, world, mine, host, agree_port_no,
                coordinator=coordinator,
                timeout=max(0.2, timeout / 2.0),
            )
        except (OSError, RuntimeError):
            _meter("elastic.agreement_fallbacks")
    elif mode == "coordinator":
        _meter("elastic.agreement_fallbacks")
    return exchange_suspects(
        my_rank, world, mine, host, gossip_port_base, timeout=timeout)


# ---------------------------------------------------------------------------
# watchdog claim: expiry -> pending RankFailure instead of process death
# ---------------------------------------------------------------------------

_pending_lock = threading.Lock()
_pending_failure: Optional[RankFailure] = None


def _post_failure(rf: RankFailure) -> None:
    global _pending_failure
    with _pending_lock:
        if _pending_failure is None:
            _pending_failure = rf


def take_pending_failure() -> Optional[RankFailure]:
    """Pop the failure posted by the claimed watchdog handler (or a peer
    death notification), if any."""
    global _pending_failure
    with _pending_lock:
        rf, _pending_failure = _pending_failure, None
    return rf


# ---------------------------------------------------------------------------
# graceful drain: announced departures instead of detected deaths
# ---------------------------------------------------------------------------
#
# A preemption notice (SIGTERM, the scheduler's eviction warning, or the
# ``preempt`` fault verb) marks this rank as *leaving*.  The elastic loop
# picks the mark up at its next step boundary, forces an early
# ``store.commit``, notifies every peer (with acks, so nobody can race
# past the leave boundary), and executes a PLANNED shrink: no watchdog
# expiry, no gossip agreement round, exactly one ``drain`` incident —
# an announced eviction costs one commit interval instead of a detection
# timeout.

_drain_lock = threading.Lock()
_pending_drain: dict = {}       # this process wants to leave (or a
#                                 simulated rank does): {"rank", "grace"}
_peer_drain: dict = {}          # a peer announced its departure:
#                                 {"rank", "boundary"}
_draining_comms: Dict[int, int] = {}   # comm uid -> scheduled leave boundary
_drained_comms: Dict[int, int] = {}    # comm uid -> passed leave boundary


def request_drain(grace: Optional[float] = None, *,
                  rank: Optional[int] = None) -> None:
    """Mark a rank as *leaving* (idempotent).  ``rank=None`` means the
    calling process (the SIGTERM / ``preempt`` path); a concrete rank is
    the single-controller simulation form.  The elastic loop executes
    the drain at its next step boundary; ``grace`` bounds the peer-ack
    wait (default ``MPI4JAX_TPU_DRAIN_GRACE_S``)."""
    with _drain_lock:
        if not _pending_drain:
            _pending_drain.update({
                "rank": None if rank is None else int(rank),
                "grace": grace,
            })


def take_pending_drain() -> Optional[dict]:
    """Pop the pending drain request, if any."""
    with _drain_lock:
        if not _pending_drain:
            return None
        out = dict(_pending_drain)
        _pending_drain.clear()
    return out


def _post_peer_drain(rank: int, boundary: int) -> None:
    with _drain_lock:
        if not _peer_drain:
            _peer_drain.update({"rank": int(rank),
                                "boundary": int(boundary)})


def peek_peer_drain() -> Optional[dict]:
    with _drain_lock:
        return dict(_peer_drain) if _peer_drain else None


def take_peer_drain() -> Optional[dict]:
    with _drain_lock:
        if not _peer_drain:
            return None
        out = dict(_peer_drain)
        _peer_drain.clear()
    return out


def install_preemption_handler(grace: Optional[float] = None, *,
                               signum=None):
    """Install a SIGTERM handler that posts a drain request (the
    graceful-preemption entry: schedulers announce evictions with
    SIGTERM minutes before the kill).  Returns the previous handler (pass
    it to ``signal.signal`` to restore), or ``None`` when handlers
    cannot be installed here (non-main thread / unsupported platform) —
    the elastic loop degrades to the failure path in that case."""
    import signal as _signal

    signum = _signal.SIGTERM if signum is None else signum

    def _on_term(_signo, _frame):
        _meter("elastic.preempt_notices")
        request_drain(grace)

    try:
        return _signal.signal(signum, _on_term)
    except (ValueError, OSError):   # not the main thread, or no signals
        return None


def mark_comm_draining(comm_or_uid, boundary: int) -> None:
    """Record that ``comm``'s world has a scheduled leave boundary.
    Collectives remain legal through the boundary; once
    :func:`seal_drained_comm` runs the comm is *drained* and any further
    collective on it is flagged MPX127 by the verifier."""
    uid = getattr(comm_or_uid, "uid", comm_or_uid)
    with _drain_lock:
        _draining_comms[int(uid)] = int(boundary)


def seal_drained_comm(comm_or_uid) -> None:
    """The leave boundary passed: collectives on this comm are now
    errors (MPX127) — its world executed its planned shrink."""
    uid = int(getattr(comm_or_uid, "uid", comm_or_uid))
    with _drain_lock:
        boundary = _draining_comms.pop(uid, 0)
        _drained_comms[uid] = boundary


def comm_draining(comm_or_uid) -> Optional[int]:
    """The scheduled leave boundary of a draining comm, or ``None``."""
    uid = int(getattr(comm_or_uid, "uid", comm_or_uid))
    with _drain_lock:
        return _draining_comms.get(uid)


def comm_drained(comm_or_uid) -> bool:
    """True once the comm's leave boundary passed (MPX127 territory)."""
    uid = int(getattr(comm_or_uid, "uid", comm_or_uid))
    with _drain_lock:
        return uid in _drained_comms


# ---------------------------------------------------------------------------
# grow: admit replacement ranks at an epoch boundary
# ---------------------------------------------------------------------------
#
# A replacement process contacts the CURRENT coordinator (the join
# listener derived from ``port_base`` + the wrapped epoch — it scans the
# declared window, since it cannot know how many epochs passed while it
# was being scheduled), parks on the open connection, and is admitted at
# the survivors' next commit boundary: the world advances one epoch with
# a positive delta, every process re-bootstraps at world+J, and the
# committed state is streamed to the joiner through the one-allreduce
# restore path's cold-join branch (the joiner contributes zeros and
# receives everything).

_join_lock = threading.Lock()
_pending_joins: List[dict] = []   # [{"conn": socket|None, "info": dict}]


def post_simulated_join(count: int = 1) -> None:
    """Queue ``count`` simulated joiners (single-controller drills: the
    replacement "process" is a device the mesh shrank away, re-admitted
    by ``ShardStore.apply_grow``)."""
    with _join_lock:
        for _ in range(int(count)):
            _pending_joins.append({"conn": None, "info": {"simulated": True}})


def pending_join_count() -> int:
    with _join_lock:
        return len(_pending_joins)


def _take_pending_joins() -> List[dict]:
    with _join_lock:
        out, _pending_joins[:] = list(_pending_joins), []
    return out


class _JoinServer:
    """The coordinator's join listener (rank 0 only, one epoch at a
    time): accepts ``{"kind": "join"}`` hellos, parks each connection in
    the pending-join queue, and answers a scanning joiner's probe so it
    can find the live epoch without guessing."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, self.port))
        self._srv.listen(8)
        self._srv.settimeout(0.2)
        self._thread = threading.Thread(
            target=self._serve, name="mpi4jax_tpu-join", daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                try:
                    conn.settimeout(10.0)
                    header = _recv_all(conn, 8)
                    if len(header) < 8:
                        conn.close()
                        continue
                    n = int.from_bytes(header, "big")
                    payload = json.loads(_recv_all(conn, n).decode())
                    if payload.get("kind") != "join":
                        conn.close()
                        continue
                    # park the connection: the admit message goes out at
                    # the next commit boundary (run loop, rank 0)
                    conn.settimeout(None)
                    with _join_lock:
                        _pending_joins.append(
                            {"conn": conn, "info": payload})
                except (OSError, ValueError, KeyError):
                    conn.close()
        finally:
            self._srv.close()

    def stop(self):
        self._stop.set()


def _send_json(conn, payload: dict) -> None:
    msg = json.dumps(payload).encode()
    conn.sendall(len(msg).to_bytes(8, "big") + msg)


def _recv_json(conn) -> dict:
    header = _recv_all(conn, 8)
    if len(header) < 8:
        raise OSError("connection closed before header")
    n = int.from_bytes(header, "big")
    return json.loads(_recv_all(conn, n).decode())


def request_join(host: str, port_base: int, *, timeout: float = 300.0,
                 scan_interval: float = 0.5) -> dict:
    """The replacement process's half of the join protocol: scan the
    declared port window for the live epoch's join listener, send a join
    hello, and block until the coordinator admits us at a commit
    boundary.  Returns the admit message ({"epoch", "process_id",
    "num_processes", "step", "commit", "mesh_shape", "axes"}).  Raises
    ``RuntimeError`` when no coordinator answers within ``timeout``."""
    span = config.elastic_port_span()
    deadline = time.monotonic() + timeout
    hello = {"kind": "join", "host": socket.gethostname()}
    while time.monotonic() < deadline:
        for e in range(span):
            port = join_port(port_base, e, span)
            try:
                conn = socket.create_connection((host, port), timeout=0.3)
            except OSError:
                continue
            try:
                conn.settimeout(max(1.0, deadline - time.monotonic()))
                _send_json(conn, hello)
                admit = _recv_json(conn)     # parks until the boundary
                if admit.get("kind") == "admit":
                    return admit
            except OSError:
                pass
            finally:
                conn.close()
        time.sleep(scan_interval)
    raise RuntimeError(
        f"request_join: no coordinator admitted us within {timeout:g}s "
        f"(scanned ports {join_port(port_base, 0, span)}.."
        f"{join_port(port_base, span - 1, span)}; is the running world's "
        "MPI4JAX_TPU_ELASTIC_GROW on?)"
    )


# ---------------------------------------------------------------------------
# control plane: drain notices between peers
# ---------------------------------------------------------------------------


class _ControlServer:
    """Per-rank control listener (one epoch at a time): receives drain
    notices from a departing peer, posts them for the run loop, and acks
    immediately — the ack is what lets the leaver prove every peer knows
    the leave boundary BEFORE anyone steps toward it."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, self.port))
        self._srv.listen(8)
        self._srv.settimeout(0.2)
        self._thread = threading.Thread(
            target=self._serve, name="mpi4jax_tpu-control", daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with conn:
                    try:
                        conn.settimeout(5.0)
                        payload = _recv_json(conn)
                        if payload.get("kind") == "drain":
                            _post_peer_drain(payload["rank"],
                                             payload["boundary"])
                            _send_json(conn, {"kind": "ack"})
                    except (OSError, ValueError, KeyError):
                        continue
        finally:
            self._srv.close()

    def stop(self):
        self._stop.set()


def notify_drain(host: str, port_base: int, my_rank: int, world: int,
                 boundary: int, *, epoch: Optional[int] = None,
                 grace: Optional[float] = None) -> List[int]:
    """Send the drain notice to every peer's control port and collect
    acks (bounded by ``grace``).  Returns the ranks that did NOT ack —
    they may be dead, which the ordinary failure path will discover; the
    drain proceeds regardless (an eviction deadline does not wait)."""
    epoch = current_epoch() if epoch is None else epoch
    grace = config.drain_grace_s() if grace is None else float(grace)
    notice = {"kind": "drain", "rank": int(my_rank),
              "boundary": int(boundary)}
    unacked = []
    deadline = time.monotonic() + grace
    for peer in range(world):
        if peer == my_rank:
            continue
        acked = False
        try:
            port = control_port(port_base, peer, epoch)
        except ValueError:
            # a rank beyond the declared span has no control listener
            # (raise MPI4JAX_TPU_ELASTIC_PORT_SPAN above the world
            # size): report it unacked, never crash the drain path
            unacked.append(peer)
            continue
        while time.monotonic() < deadline and not acked:
            try:
                with socket.create_connection(
                    (host, port),
                    timeout=max(0.1, deadline - time.monotonic()),
                ) as c:
                    c.settimeout(max(0.1, deadline - time.monotonic()))
                    _send_json(c, notice)
                    acked = _recv_json(c).get("kind") == "ack"
            except OSError:
                time.sleep(0.05)
        if not acked:
            unacked.append(peer)
    return unacked


def _claimed_on_timeout(entries, expired) -> None:
    """The elastic watchdog handler (installed by :func:`run` via
    ``resilience.set_on_timeout``): instead of killing the process, post
    a pending :class:`RankFailure` (suspects unknown — this rank only
    knows its own collective stalled; the agreement round names the dead)
    and try to break the main thread out of the stalled collective.

    The expiry was already journalled as a telemetry incident by the
    monitor before this handler ran (resilience/watchdog.py).
    """
    _meter("elastic.watchdog_claims")
    _post_failure(RankFailure(
        (),
        f"watchdog expiry: {expired['opname']} exceeded "
        f"{expired['timeout']:g}s (call {expired['call_id']})",
    ))
    _abort_inflight()


def _abort_inflight() -> None:
    """Best-effort unblock of a main thread stalled inside a collective
    whose peers are dead: tear down the distributed client (pending
    collectives then fail with a runtime error the recovery loop
    classifies), and interrupt the main thread for the host-side blocks
    (an injected ``hang`` sleeps in ``time.sleep``, which
    ``interrupt_main`` does break)."""
    try:
        import jax

        jax.distributed.shutdown()
    except Exception:
        pass
    try:
        import _thread

        _thread.interrupt_main()
    except Exception:
        pass


_FAILURE_MARKERS = (
    "deadline", "heartbeat", "connection", "unavailable", "shut down",
    "shutdown", "peer", "socket closed", "cancelled", "aborted",
    "barrier timed out", "preempt",
)


def classify_failure(exc: BaseException) -> Optional[RankFailure]:
    """Map an exception escaping the training step to a
    :class:`RankFailure`, or ``None`` when it is an ordinary error that
    must propagate.  Three sources:

    - an explicit :class:`RankFailure` (simulated drills, peer-death
      notifications) passes through;
    - a pending failure posted by the claimed watchdog handler adopts
      the interrupting exception (``KeyboardInterrupt`` from
      ``interrupt_main``, or the runtime error the distributed teardown
      provoked);
    - a distributed-runtime death rattle (connection/heartbeat/shutdown
      wording) with no pending claim becomes an unknown-suspect failure.
    """
    if isinstance(exc, RankFailure):
        pending = take_pending_failure()
        if pending is not None and pending.suspects - exc.suspects:
            return RankFailure(exc.suspects | pending.suspects, exc.detail)
        return exc
    pending = take_pending_failure()
    if pending is not None:
        return pending
    if isinstance(exc, (RuntimeError, OSError)):
        text = str(exc).lower()
        if any(m in text for m in _FAILURE_MARKERS):
            return RankFailure((), f"{type(exc).__name__}: {exc}")
    return None


# ---------------------------------------------------------------------------
# state packing (pure: numpy only)
# ---------------------------------------------------------------------------


def _pure_spec(state):
    """``(spec, leaves)`` from the minimal deterministic flattener over
    dict/list/tuple nests (sorted dict keys, jax's rule).  The spec is
    JSON-able nested tuples — the structural description the join
    protocol ships to a cold joiner, which has the committed bytes but
    never saw the state object."""
    leaves = []

    def build(node):
        if isinstance(node, dict):
            return ("d", tuple(sorted(node)),
                    tuple(build(node[k]) for k in sorted(node)))
        if isinstance(node, (list, tuple)):
            kind = "l" if isinstance(node, list) else "t"
            return (kind, len(node), tuple(build(v) for v in node))
        leaves.append(node)
        return ("*",)

    return build(state), leaves


def _spec_from_json(obj):
    """Rebuild a :func:`_pure_spec` spec from its JSON round trip (JSON
    turns every tuple into a list)."""
    if isinstance(obj, list):
        return tuple(_spec_from_json(v) for v in obj)
    return obj


def _flatten_state(state):
    """``(leaves, treedef)`` — jax.tree when importable, else the pure
    flattener (sorted dict keys, jax's rule) so the pure tests run
    without jax.  ``treedef`` is only ever passed back to the matching
    unflattener."""
    try:
        import jax

        leaves, treedef = jax.tree.flatten(state)
        return leaves, ("jax", treedef)
    except ImportError:
        pass

    spec, leaves = _pure_spec(state)
    return leaves, ("pure", spec)


def _unflatten_state(treedef, leaves):
    kind, spec = treedef
    if kind == "jax":
        import jax

        return jax.tree.unflatten(spec, leaves)
    it = iter(leaves)

    def rebuild(node):
        tag = node[0]
        if tag == "*":
            return next(it)
        if tag == "d":
            _, keys, subs = node
            return {k: rebuild(s) for k, s in zip(keys, subs)}
        _, _, subs = node
        vals = [rebuild(s) for s in subs]
        return vals if tag == "l" else tuple(vals)

    return rebuild(spec)


def pack_leaves(leaves):
    """``(buffer, meta)``: concatenate the leaves' raw bytes into one
    uint8 vector (the flat unit the byte shards slice), recording
    ``(shape, dtype, nbytes)`` per leaf for :func:`unpack_leaves`."""
    import numpy as np

    arrays = [np.asarray(a) for a in leaves]
    meta = [(a.shape, a.dtype.str, a.nbytes) for a in arrays]
    if arrays:
        # tobytes (C order) rather than a uint8 view: views reject 0-d
        # arrays (scalar leaves — loss scales, step counters) and
        # non-contiguous layouts; the copy is once per commit
        buf = np.concatenate(
            [np.frombuffer(a.tobytes(), np.uint8) for a in arrays])
    else:
        buf = np.zeros((0,), np.uint8)
    return buf, meta


def unpack_leaves(buf, meta):
    import numpy as np

    out = []
    off = 0
    for shape, dtype, nbytes in meta:
        chunk = np.asarray(buf[off:off + nbytes], np.uint8)
        out.append(chunk.view(np.dtype(dtype)).reshape(shape))
        off += nbytes
    return out


# ---------------------------------------------------------------------------
# telemetry glue (guarded: the package is optional under isolated loaders)
# ---------------------------------------------------------------------------


def _meter(name: str, n: int = 1) -> None:
    try:
        from ..telemetry import core as _tcore
    except ImportError:
        return
    _tcore.meter(name, n)


def _incident(meter: str, name: str, rank: int, detail: str) -> None:
    try:
        from ..telemetry import journal
    except ImportError:
        return
    journal.incident(meter, name, rank, detail)


def _health_boundary(store, step: int, committed: bool) -> None:
    """Health-detector tick at a run-loop step boundary
    (telemetry/health.py): local slowdown check + the cross-rank digest
    exchange over the store's mesh-bound comm.  A RankFailure raised by
    the suspect handoff (MPI4JAX_TPU_HEALTH_SUSPECTS) must PROPAGATE —
    it is how a persistent straggler enters the classify -> agree ->
    shrink path; anything else from the observer is swallowed."""
    try:
        from ..telemetry import health as _health
    except ImportError:
        return
    try:
        _health.on_boundary(step, comm=store.comm, committed=committed)
    except RankFailure:
        raise
    except Exception:
        _meter("health.boundary_errors")


def _health_failure(rf) -> None:
    """Postmortem bundle the moment an exception classifies as a rank
    failure, before recovery mutates any state (telemetry/health.py)."""
    try:
        from ..telemetry import health as _health
    except ImportError:
        return
    try:
        _health.on_failure_classified(rf)
    except Exception:
        pass


def _health_rank_failed(failed, rf) -> None:
    """Symmetric post-agreement verdict: every survivor journals one
    ``health`` incident naming each agreed-failed rank
    (telemetry/health.py)."""
    try:
        from ..telemetry import health as _health
    except ImportError:
        return
    try:
        _health.on_rank_failed(failed, getattr(rf, "detail", "") or "")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# ShardStore
# ---------------------------------------------------------------------------


class ShardStore:
    """In-memory sharded checkpoint of registered state with k-redundant,
    topology-striped replication.

    Each committed state pytree is flattened to one flat byte buffer,
    split into ``k`` equal byte shards (``shard s`` owned by rank ``s`` —
    the unit a ``reduce_scatter`` naturally produces), and this process
    stores the shards its local ranks hold under the commit's placement
    table: every shard lives on ``redundancy + 1`` distinct ranks, so
    any ``redundancy`` simultaneous rank losses are recoverable.  Memory
    cost per rank is ``(redundancy + 1)/k`` of the state size — for the
    default ``redundancy=1`` on 8 ranks, a quarter of a full on-disk
    checkpoint, restored at memory speed.

    Placement is the topology-aware stripe by default
    (:func:`stripe_placement` — replicas land on a different HOST than
    their owner, so a whole-host loss stays recoverable with
    ``redundancy >= 1``); ``MPI4JAX_TPU_ELASTIC_PLACEMENT=neighbor`` (or
    ``placement='neighbor'``) restores the host-blind ring-neighbor
    table.  The table in force is recorded ON the commit, and restore
    follows the recorded table — never the current flags — so the bytes
    are always found where they actually landed.

    Single-controller processes driving multiple ranks (the virtual
    multi-device mesh, or multi-host with several devices per process)
    hold the union of their local ranks' shards; a 1-process-per-rank
    deployment holds exactly ``redundancy + 1`` shards.

    ``comm`` may be ``None`` (the default world comm resolves lazily).
    ``rank`` pins the store to ONE global rank — the per-rank simulation
    handle the pure tests, the chaos drills (resilience/drill.py), and
    the protocol docs use; default derives local ranks from the comm's
    mesh process layout.  ``topology`` overrides host-map discovery for
    placement: a per-host count tuple, a spec string (``'2x4'``), or a
    ``parallel.topology.Topology``; default consults the declared
    ``MPI4JAX_TPU_TOPOLOGY`` spec, then the comm's derived topology.
    """

    def __init__(self, comm=None, *, redundancy: Optional[int] = None,
                 rank: Optional[int] = None, bootstrap: Optional[dict] = None,
                 topology=None, placement: Optional[str] = None):
        self.redundancy = (config.elastic_redundancy()
                           if redundancy is None else int(redundancy))
        if self.redundancy < 0:
            raise ValueError(
                f"redundancy must be >= 0, got {self.redundancy}")
        if placement is not None and placement not in ("stripe", "neighbor"):
            raise ValueError(
                f"placement must be 'stripe' or 'neighbor', got "
                f"{placement!r}")
        self._topology = topology
        self._placement_mode = placement
        self._comm = comm
        self._rank = rank
        # multi-process recovery parameters (coordinator host/ports for
        # re-bootstrap + agreement); single-process runs need none
        self.bootstrap = dict(bootstrap or {})
        self._committed: Optional[dict] = None
        self._lock = threading.Lock()
        # set by the elastic loop when THIS rank is shrunk out by a
        # planned drain (the announcer, or a row-mate on a Cartesian
        # drain): run() then returned the last committed state early
        self.drained = False

    # -- world plumbing ----------------------------------------------------

    @property
    def comm(self):
        if self._comm is None:
            from ..parallel.region import get_default_comm

            self._comm = get_default_comm()
        return self._comm

    def world_size(self) -> int:
        return int(self.comm.world_size())

    def local_ranks(self) -> Tuple[int, ...]:
        """Global ranks whose devices THIS process owns (all of them on a
        single-controller virtual mesh), or the pinned ``rank``."""
        if self._rank is not None:
            return (self._rank,)
        comm = self.comm
        if comm.mesh is None:
            return tuple(range(self.world_size()))
        import jax

        me = jax.process_index()
        devices = list(comm.mesh.devices.flat)
        return tuple(
            r for r, d in enumerate(devices)
            if getattr(d, "process_index", 0) == me
        )

    def placement_mode(self) -> str:
        """``'stripe'`` or ``'neighbor'`` — the constructor override,
        else the declared ``MPI4JAX_TPU_ELASTIC_PLACEMENT`` flag."""
        return self._placement_mode or config.elastic_placement()

    def _topology_for(self, k: int):
        """Host map consulted for placement at world size ``k``: the
        explicit ``topology`` argument, else the declared
        ``MPI4JAX_TPU_TOPOLOGY`` spec when it covers ``k`` ranks, else
        the comm's derived topology, else ``None`` (single host — the
        stripe degrades to the neighbor table)."""
        if self._topology is not None:
            return self._topology
        spec = config.topology_spec()
        if spec:
            counts = config.parse_topology_spec(spec)
            if counts is not None and sum(counts) == k:
                return counts
            return None
        try:
            from ..parallel.topology import derive_world_topology

            topo = derive_world_topology(self.comm)
        except Exception:
            return None
        if topo is not None and len(topo.host_of_rank) == k:
            return topo
        return None

    def placement_table(self, k: Optional[int] = None
                        ) -> Tuple[Tuple[int, ...], ...]:
        """The replica placement table the next commit lands under."""
        k = self.world_size() if k is None else int(k)
        if self.placement_mode() == "neighbor":
            return neighbor_placement(k, self.redundancy)
        return stripe_placement(k, self.redundancy, self._topology_for(k))

    def held_shards(self, k: Optional[int] = None,
                    placement=None) -> Tuple[int, ...]:
        """Shards this process stores on commit: the union of
        :func:`placement_shards_held_by` over its local ranks."""
        k = self.world_size() if k is None else k
        table = self.placement_table(k) if placement is None else placement
        held = set()
        for r in self.local_ranks():
            if r < k:
                held.update(placement_shards_held_by(r, table))
        return tuple(sorted(held))

    # -- commit ------------------------------------------------------------

    def commit(self, step: int, state) -> None:
        """Commit ``state`` as of (completed) ``step``: flatten, slice this
        process's shards, and atomically replace the previous commit.
        ``state`` must be the replicated (every-rank-identical) training
        state — the data-parallel contract; the commit itself moves no
        bytes over the network."""
        import numpy as np

        leaves, treedef = _flatten_state(state)
        host_leaves = [np.asarray(a) for a in leaves]
        buf, meta = pack_leaves(host_leaves)
        k = self.world_size()
        table = self.placement_table(k)
        shard, padded = shard_bounds(buf.nbytes, k)
        if padded > buf.nbytes:
            buf = np.concatenate(
                [buf, np.zeros(padded - buf.nbytes, np.uint8)])
        shards = {
            s: bytes(buf[s * shard:(s + 1) * shard])
            for s in self.held_shards(k, table)
        }
        # the structural twin a cold joiner can unflatten with: the pure
        # spec matches jax.tree's structure on dict/list/tuple nests
        # (sorted dict keys).  Only computed when the grow path can use
        # it (the describe/adopt protocol), and validated STRICTLY —
        # re-flattening the pure reconstruction must reproduce the jax
        # treedef, so a custom pytree node can never ship a
        # coincidentally-leaf-count-equal wrong structure to a joiner.
        spec = self._validated_pure_spec(state, leaves, treedef, meta)
        record = {
            "step": int(step),
            "epoch": current_epoch(),
            "k": k,
            "shard": shard,
            "nbytes": int(len(meta) and sum(m[2] for m in meta)),
            "meta": meta,
            "treedef": treedef,
            "pure_spec": spec,
            "placement": table,
            "shards": shards,
        }
        with self._lock:
            self._committed = record
        _meter("elastic.commits")

    @staticmethod
    def _validated_pure_spec(state, leaves, treedef, meta):
        """The JSON-able structural spec for the cold-join description,
        or ``None`` when it cannot faithfully describe ``state``.  Costs
        a tree walk per commit, so it only runs when the grow path that
        consumes it is enabled."""
        if not config.elastic_grow():
            return None
        spec, pure_leaves = _pure_spec(state)
        if len(pure_leaves) != len(meta):
            return None
        if treedef[0] == "jax":
            try:
                import jax

                rebuilt = _unflatten_state(("pure", spec), list(leaves))
                if jax.tree.flatten(rebuilt)[1] != treedef[1]:
                    return None
            except Exception:
                return None
        return spec

    @property
    def committed_step(self) -> Optional[int]:
        with self._lock:
            return self._committed["step"] if self._committed else None

    @property
    def last_rank_map(self) -> Optional[Dict[int, int]]:
        """The ``{old_rank: new_rank}`` compaction the last boundary
        stamped on the committed record, or ``None`` when the commit
        pre-dates any reconfiguration (identity).  What a restored
        per-rank artifact — e.g. the compression layer's error-feedback
        residual (``mpx.compress.ef_reshard``) — needs to move its rows
        to their post-shrink owners and zero cold joiners."""
        with self._lock:
            rec = self._committed
            rmap = rec.get("rank_map") if rec else None
            return dict(rmap) if rmap is not None else None

    # -- restore -----------------------------------------------------------

    def _require_commit(self) -> dict:
        with self._lock:
            rec = self._committed
        if rec is None:
            raise RuntimeError(
                "ShardStore.restore: nothing committed yet — commit an "
                "initial state before entering the elastic loop so step-0 "
                "failures are recoverable"
            )
        return rec

    def _rec_placement(self, rec: dict) -> Tuple[Tuple[int, ...], ...]:
        """The placement table the commit was made under.  Records written
        before placement tables existed (or adopted from an old peer) fall
        back to the neighbor table — the only policy such commits can have
        used."""
        table = rec.get("placement")
        if table is None:
            table = neighbor_placement(rec["k"], self.redundancy)
        return table

    def restore_plan(self, failed: Iterable[int] = ()) -> Dict[int, int]:
        """Provider plan for restoring the last commit after losing
        ``failed`` — computed against the placement table *recorded on the
        commit*, never against current flags: a commit striped under one
        policy must be restored under the same table even if the flag
        changed since.  Raises :class:`RankFailure` when some shard lost
        every holder."""
        rec = self._require_commit()
        return plan_from_placement(frozenset(failed),
                                   self._rec_placement(rec))

    def can_describe_commit(self) -> bool:
        """Whether the last commit carries a validated structural spec —
        the admission gate: a world whose state cannot be described must
        not admit joiners (the coordinator refuses BEFORE any epoch
        moves, so the refusal is symmetric across ranks)."""
        with self._lock:
            rec = self._committed
        return bool(rec) and rec.get("pure_spec") is not None

    def describe_commit(self) -> dict:
        """JSON-able description of the last commit — everything a cold
        joiner needs to reconstruct the state from the restore exchange's
        bytes (step, shard geometry, per-leaf meta, structural spec) and
        NOTHING else (no shard payloads; those flow through the
        one-allreduce cold-join branch).  Requires a JSON-able state
        structure (dict/list/tuple nests — the pure spec must match the
        jax leaf order, which custom pytree nodes break)."""
        rec = self._require_commit()
        if rec["pure_spec"] is None:
            raise RuntimeError(
                "describe_commit: the committed state's structure is not "
                "JSON-able (custom pytree nodes?) — cold joins need "
                "dict/list/tuple state nests (docs/resilience.md)"
            )
        return {
            "step": rec["step"],
            "epoch": rec["epoch"],
            "k": rec["k"],
            "shard": rec["shard"],
            "nbytes": rec["nbytes"],
            "meta": [[list(shape), dtype, nbytes]
                     for shape, dtype, nbytes in rec["meta"]],
            "pure_spec": rec["pure_spec"],
            "placement": [list(holders)
                          for holders in self._rec_placement(rec)],
        }

    def adopt_commit(self, desc: dict) -> None:
        """The cold joiner's half of :func:`describe_commit`: install a
        commit record with the described geometry and NO shards, so the
        next :meth:`restore` (``force_exchange=True``) contributes zeros
        and receives everything."""
        spec = _spec_from_json(desc["pure_spec"])
        placement = (
            tuple(tuple(int(r) for r in holders)
                  for holders in desc["placement"])
            if desc.get("placement") is not None
            else neighbor_placement(int(desc["k"]), self.redundancy))
        record = {
            "step": int(desc["step"]),
            "epoch": int(desc["epoch"]),
            "k": int(desc["k"]),
            "shard": int(desc["shard"]),
            "nbytes": int(desc["nbytes"]),
            "meta": [(tuple(shape), str(dtype), int(nbytes))
                     for shape, dtype, nbytes in desc["meta"]],
            "treedef": ("pure", spec),
            "pure_spec": spec,
            "placement": placement,
            "shards": {},
            "cold": True,
        }
        with self._lock:
            self._committed = record

    def restore(self, failed: Iterable[int] = (), *,
                force_exchange: bool = False):
        """Reassemble the last committed state after losing ``failed``
        (old-world global ranks) and return ``(step, state)``.

        When this process holds every needed shard (single-controller
        meshes always do), reassembly is local.  Otherwise each surviving
        process contributes the shards :meth:`restore_plan` (the provider
        plan over the commit's recorded placement table) makes it the
        provider of, and ONE ``SUM`` allreduce over the *current*
        (post-shrink) comm reassembles the full buffer on every rank —
        the exchange runs over the new world, never the revoked one.

        ``force_exchange=True`` runs the allreduce even when local
        reassembly would suffice — the cold-join branch: after a grow,
        EVERY rank of the new world (the joiner included) must issue the
        same collective; the joiner's adopted commit holds no shards, so
        it contributes zeros and receives everything.
        """
        import numpy as np

        rec = self._require_commit()
        dead = frozenset(failed)
        k, shard = rec["k"], rec["shard"]
        have = set(rec["shards"])
        need_remote = force_exchange or any(s not in have
                                            for s in range(k))
        # the reconstruction plan (and its feasibility check) only
        # matters when shards must move: a process holding every shard —
        # single-controller meshes always do — reassembles locally even
        # when a whole contiguous replica block died (row-shrink)
        plan = (plan_from_placement(dead, self._rec_placement(rec))
                if need_remote else {})
        if rec.get("cold"):
            _meter("elastic.cold_restores")

        if not need_remote:
            buf = np.concatenate(
                [np.frombuffer(rec["shards"][s], np.uint8)
                 for s in range(k)]
            ) if shard else np.zeros((0,), np.uint8)
        else:
            buf = self._exchange_shards(rec, plan)

        total = sum(m[2] for m in rec["meta"])
        leaves = unpack_leaves(buf[:total], rec["meta"])
        state = _unflatten_state(rec["treedef"], leaves)
        _meter("elastic.restores")
        return rec["step"], state

    def exchange_contribution(self, rec: dict, plan: Dict[int, int]):
        """This process's flat contribution to the restore exchange: the
        shards it is the designated provider of, placed at their offsets,
        zeros elsewhere.  Factored out so the pure tests can pin the
        one-contributor-per-shard invariant (summing every process's
        contribution — the cold joiner's all-zeros included — must
        reproduce the full committed buffer bit-identically)."""
        import numpy as np

        k, shard = rec["k"], rec["shard"]
        contrib = np.zeros((k * shard,), np.uint8)
        for s, provider in plan.items():
            if s in rec["shards"] and self._provides(provider, rec):
                contrib[s * shard:(s + 1) * shard] = np.frombuffer(
                    rec["shards"][s], np.uint8)
        return contrib

    def _exchange_shards(self, rec: dict, plan: Dict[int, int]):
        """One SUM allreduce over the current (post-shrink) comm moves
        every old-world shard from its designated provider to every rank:
        each provider process places its shards in the flat contribution,
        everyone else zeros — exactly one contributor per shard
        (``plan``), so SUM is placement, and a uint8 sum cannot wrap."""
        import numpy as np

        from ..ops import SUM, allreduce

        comm = self.comm
        k, shard = rec["k"], rec["shard"]
        locals_ = set(
            r for r in self.local_ranks() if r < int(comm.world_size())
        )
        contrib = self.exchange_contribution(rec, plan)
        size = int(comm.world_size())
        glob = np.zeros((size, k * shard), np.uint8)
        for r in locals_:
            glob[r] = contrib
        out, _ = allreduce(glob, op=SUM, comm=comm)
        return np.asarray(out)[0]

    def _provides(self, old_provider: int, rec: dict) -> bool:
        """Whether THIS process is the provider: it is the process that
        holds ``old_provider``'s rank now.  After a shrink the old->new
        rank map recorded on the commit translates; with no shrink (a
        plain restore) old ranks ARE current ranks — either way, exactly
        one process answers True per provider, preserving the
        one-contributor-per-shard invariant of the SUM exchange."""
        rank_map = rec.get("rank_map")
        current = (old_provider if rank_map is None
                   else rank_map.get(old_provider))
        return current is not None and current in set(self.local_ranks())

    # -- failure handling entry points used by run() -----------------------

    def apply_shrink(self, failed: Iterable[int],
                     fail_unit: str = "rank") -> Dict[int, int]:
        """Rebuild the mesh and this store's comm as "all minus failed"
        and record the old->new rank map on the last commit (the restore
        exchange resolves providers through it).  Single-controller path:
        the surviving devices of the bound mesh form the new world.
        ``fail_unit`` widens the removal to whole grid rows/columns on
        Cartesian meshes (``failed`` may name individual ranks; the
        expansion happens here).  Returns the rank map."""
        from ..parallel.mesh import set_default_mesh, shrink_world_mesh
        from ..parallel import region as _region

        comm = self.comm
        if comm.mesh is None:
            raise RuntimeError("elastic shrink needs a comm bound to a mesh")
        shape = tuple(comm.mesh.shape.values())
        dead = expand_fail_unit(failed, shape, fail_unit)
        if len(shape) > 1 and fail_unit in ("row", "col"):
            _meter("elastic.row_shrinks")
        world = int(comm.world_size())
        rank_map = compact_rank_map(world, dead)
        new_mesh = shrink_world_mesh(comm.mesh, dead, fail_unit)
        self._comm = comm.shrink(dead, mesh=new_mesh)
        set_default_mesh(new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                self._committed["rank_map"] = dict(rank_map)
        if self._rank is not None and self._rank in rank_map:
            self._rank = rank_map[self._rank]
        return rank_map

    def apply_grow(self, added: int) -> None:
        """Single-controller grow: rebuild the mesh with ``added``
        replacement devices appended (new ranks ``k..k+added-1``), bind a
        fresh current-epoch comm, and record the identity rank map on the
        last commit — existing ranks keep their numbers on a grow, so the
        restore exchange's providers are unchanged."""
        from ..parallel.mesh import grow_world_mesh, set_default_mesh
        from ..parallel import region as _region

        comm = self.comm
        if comm.mesh is None:
            raise RuntimeError("elastic grow needs a comm bound to a mesh")
        from ..parallel.comm import Comm

        new_mesh = grow_world_mesh(comm.mesh, added)
        self._comm = Comm(comm.axes, mesh=new_mesh)
        set_default_mesh(new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                k = self._committed["k"]
                self._committed["rank_map"] = {r: r for r in range(k)}

    def _require_bootstrap(self) -> dict:
        bs = self.bootstrap
        for key in ("host", "port_base", "process_id", "num_processes"):
            if key not in bs:
                raise RuntimeError(
                    "elastic rebootstrap needs ShardStore(bootstrap="
                    "{'host', 'port_base', 'process_id', 'num_processes'})"
                    f"; missing {key!r}"
                )
        return bs

    def _reinit_distributed(self, new_world: int, me_new: int) -> None:
        """Tear down the revoked distributed world and re-initialize
        jax.distributed at the current epoch's coordinator port (wrapped
        within the declared span window); bind collisions from a wrapped
        port are absorbed by the bootstrap retry policy."""
        import jax

        from ..parallel import mesh as _mesh_mod
        from .retry import retry_with_backoff

        bs = self.bootstrap
        port = coordinator_port(int(bs["port_base"]), current_epoch())
        coord = f"{bs['host']}:{port}"

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        # drop compiled backends/devices of the revoked world before the
        # new one initializes (API name varies across jax versions)
        for clear in ("clear_backends",):
            fn = getattr(jax, clear, None)
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass

        retry_with_backoff(
            lambda: jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=new_world,
                process_id=me_new,
            ),
            what=f"elastic re-bootstrap (epoch {current_epoch()}, "
                 f"coordinator {coord})",
            deadline=config.bootstrap_deadline(),
            max_attempts=config.bootstrap_max_attempts() or None,
        )
        _mesh_mod._distributed_initialized = True
        bs["process_id"] = me_new
        bs["num_processes"] = new_world

    def rebootstrap(self, failed: Iterable[int],
                    fail_unit: str = "rank") -> Dict[int, int]:
        """Multi-process shrink: tear down the old distributed world and
        re-initialize jax.distributed over the survivors (compacted
        process ids; the lowest surviving old rank hosts the new
        coordinator on the epoch's wrapped port).  ``fail_unit`` widens
        the removal to whole grid rows/columns and the rebuilt mesh
        keeps the Cartesian shape minus the dead rows.  Requires
        ``bootstrap`` = {"host", "port_base", "process_id",
        "num_processes"} (one device per process).  Returns the old->new
        rank map."""
        from ..parallel.mesh import make_world_mesh, set_default_mesh
        from ..parallel import region as _region

        bs = self._require_bootstrap()
        old_mesh = self.comm.mesh
        old_axes = (tuple(old_mesh.axis_names)
                    if old_mesh is not None else None)
        old_shape = (tuple(old_mesh.shape.values())
                     if old_mesh is not None
                     else (int(bs["num_processes"]),))
        dead = expand_fail_unit(failed, old_shape, fail_unit)
        if len(old_shape) > 1 and fail_unit in ("row", "col"):
            _meter("elastic.row_shrinks")
        world = int(bs["num_processes"])
        rank_map = compact_rank_map(world, dead)
        me_old = int(bs["process_id"])
        if me_old in dead or me_old not in rank_map:
            raise RankFailure(dead, "this rank was declared failed")
        me_new = rank_map[me_old]
        new_world = len(rank_map)
        self._reinit_distributed(new_world, me_new)

        # preserve the old world's axes: Comm.shrink validates the new
        # mesh along the COMM's axes, and a row/column shrink keeps the
        # Cartesian structure (fewer rows, same columns, or vice versa)
        new_shape = shrunken_shape(old_shape, dead,
                                   fail_unit if len(old_shape) > 1
                                   else "rank")
        if old_axes is not None:
            new_mesh = make_world_mesh(new_shape, old_axes)
        else:
            new_mesh = make_world_mesh()
        set_default_mesh(new_mesh)
        self._comm = self.comm.shrink(dead, mesh=new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                self._committed["rank_map"] = dict(rank_map)
        if self._rank is not None:
            self._rank = rank_map.get(self._rank, self._rank)
        return rank_map

    def rebootstrap_grow(self, added: int) -> None:
        """Multi-process grow: re-initialize jax.distributed at
        ``world + added`` processes (existing ranks keep their ids — a
        grow never renumbers; the joiners take ``world..world+added-1``),
        rebuild the 1-D world mesh, and record the identity rank map on
        the last commit so the cold-join restore's providers are the
        unchanged old ranks."""
        from ..parallel.comm import Comm
        from ..parallel.mesh import make_world_mesh, set_default_mesh
        from ..parallel import region as _region

        bs = self._require_bootstrap()
        old_mesh = self.comm.mesh
        old_axes = (tuple(old_mesh.axis_names)
                    if old_mesh is not None else None)
        if old_axes is not None and len(old_axes) != 1:
            raise RuntimeError(
                "elastic grow needs a 1-D mesh (joiners append to the "
                "end of the rank line; docs/resilience.md)")
        world = int(bs["num_processes"])
        new_world = world + int(added)
        self._reinit_distributed(new_world, int(bs["process_id"]))
        new_mesh = make_world_mesh(
            (new_world,), old_axes if old_axes is not None else None)
        set_default_mesh(new_mesh)
        self._comm = Comm(self.comm.axes, mesh=new_mesh)
        _region._default_comm = None
        with self._lock:
            if self._committed is not None:
                k = self._committed["k"]
                self._committed["rank_map"] = {r: r for r in range(k)}

    def multiprocess(self) -> bool:
        return bool(self.bootstrap)


def reassemble_from_stores(stores: Dict[int, "ShardStore"],
                           failed: Iterable[int] = ()):
    """Pure simulation of the restore exchange: given per-rank stores
    (``{old_rank: rank-pinned ShardStore}``), reassemble ``(step, state)``
    from the SURVIVING stores only — byte-for-byte what the one-allreduce
    runtime exchange produces.  The protocol model the pure tests (and
    docs/resilience.md's redundancy math) pin: kill any ``redundancy``
    stores and the state must still come back bit-identical."""
    import numpy as np

    dead = frozenset(failed)
    survivors = {r: s for r, s in stores.items() if r not in dead}
    if not survivors:
        raise RankFailure(dead, "no surviving stores")
    first = next(iter(survivors.values()))
    rec = first._require_commit()
    k, shard = rec["k"], rec["shard"]
    plan = plan_from_placement(dead, first._rec_placement(rec))
    buf = np.zeros((k * shard,), np.uint8)
    for s, provider in plan.items():
        prec = survivors[provider]._require_commit()
        buf[s * shard:(s + 1) * shard] = np.frombuffer(
            prec["shards"][s], np.uint8)
    total = sum(m[2] for m in rec["meta"])
    leaves = unpack_leaves(buf[:total], rec["meta"])
    return rec["step"], _unflatten_state(rec["treedef"], leaves)


# ---------------------------------------------------------------------------
# revoke: make the old world unreachable
# ---------------------------------------------------------------------------


def revoke_epoch(failed: Iterable[int], *, rank: int = 0,
                 world: Optional[int] = None, added: int = 0,
                 cause: str = "failure") -> int:
    """Revoke the current comm epoch at an elastic boundary.  The
    boundary carries a world *delta* — ranks removed (a failure or a
    drain) and/or ranks added (a join):

    - advance the epoch (every compiled-program cache key folds it in,
      so old-world executables re-trace rather than replay), recording
      the delta in :func:`epoch_history`;
    - drain the watchdog's in-flight registry (arms from collectives of
      the revoked world must not kill the recovered job);
    - drop the eager compiled-program cache (entries pin revoked meshes);
    - journal exactly one ``epoch_change`` telemetry incident.

    Returns the new epoch.
    """
    from . import watchdog as _wd

    dead = sorted(frozenset(failed))
    new_world = (world - len(dead) + int(added)) if world else None
    if cause == "join":
        detail = (f"admitted {added} replacement rank(s)"
                  + (f" -> world {new_world}" if new_world else ""))
    elif cause == "drain":
        detail = (f"drained rank(s) {dead}"
                  + (f" of {world}" if world else ""))
    else:
        detail = (f"shrank out rank(s) {dead}"
                  + (f" of {world}" if world else ""))
    new_epoch = advance_epoch(world=new_world, cause=cause, detail=detail)
    _wd.drain_registry()
    # drop the eager program cache (entries pin revoked meshes) — via
    # sys.modules so the isolated pure-test loader, which never loads the
    # ops stack, does not pull it in here.  clear_caches may itself
    # import siblings (aot, analysis) that a PARTIAL isolated loader —
    # one that pulled the ops package in through a lazy byte-model
    # import, say — has only stubbed; a revocation must still succeed
    # there (nothing is cached under such loaders anyway).
    import sys

    ops = sys.modules.get(__package__.rsplit(".", 1)[0] + ".ops")
    clear = getattr(ops, "clear_caches", None)
    if callable(clear):
        try:
            clear()
        except ImportError:
            # a PARTIAL isolated loader (ops pulled in through a lazy
            # byte-model import, sibling packages stubbed): nothing is
            # cached there, so the revocation proceeds
            pass
    _incident(
        "elastic.epoch_changes", "epoch_change", rank,
        f"epoch {new_epoch - 1} -> {new_epoch}: {detail}",
    )
    return new_epoch


# ---------------------------------------------------------------------------
# the elastic training loop
# ---------------------------------------------------------------------------


def align_commit_every(commit_every: int, unroll: int) -> int:
    """Round a commit interval UP to a multiple of the megastep trip
    count: state only exists at megastep boundaries (the loop body is
    device-resident, aot/pinning.py ``ElasticStep``), so commits can
    only land there.  Pure — shared with tests/test_megastep_pure.py."""
    if unroll <= 1:
        return commit_every
    return ((commit_every + unroll - 1) // unroll) * unroll


def resolve_auto_commit_interval(step_time_s: float,
                                 commit_cost_s: float) -> int:
    """The ``commit_every='auto'`` decision (ROADMAP item 4c): the
    smallest interval that keeps the MEASURED commit (``ShardStore``
    pack) cost at or under the target fraction of the MEASURED step
    time (autotune/fit.auto_commit_interval).  The target comes from
    the active tuning layer's ``tuned.commit.target_overhead``
    (docs/autotune.md) when one is loaded, else the 5% default."""
    from ..autotune.fit import auto_commit_interval

    target = None
    try:
        tf = config.active_tuning()
    except ValueError:  # malformed env tuning file: keep the default
        tf = None
    if tf is not None:
        target = tf.commit_param("target_overhead")
    return auto_commit_interval(step_time_s, commit_cost_s, target)


def run(step_fn, state, store: ShardStore, *, steps: int,
        start_step: int = 0, commit_every: int = 1,
        claim_watchdog: bool = True, drain_on_sigterm: bool = True):
    """Run ``state = step_fn(state, step, comm)`` for ``steps`` steps,
    surviving rank loss AND world churn:

    - on a :class:`RankFailure` (raised by the step, posted by the
      claimed watchdog, or classified from a distributed death rattle)
      the loop commits the failure with the surviving peers, revokes the
      epoch, shrinks the world (by rank, or by whole grid row/column
      under ``MPI4JAX_TPU_ELASTIC_FAIL_UNIT``), restores the last
      committed state, and continues on ``k - f`` ranks from the
      committed step;
    - on a drain request (:func:`request_drain` — a SIGTERM, the
      ``preempt`` fault verb, or a simulated rank) the loop forces an
      early commit at the next step boundary and executes a PLANNED
      shrink: peers are notified with acks, no watchdog expiry fires, no
      gossip round runs, and exactly one ``drain`` incident is
      journalled.  A rank shrunk away (the leaver, or a row-mate on a
      Cartesian drain) returns its last state with ``store.drained``
      set;
    - with ``MPI4JAX_TPU_ELASTIC_GROW`` on, replacement processes that
      contacted the coordinator (:func:`request_join` /
      :func:`join_and_run`) are admitted at the next commit boundary:
      the epoch advances with a positive world delta, every process
      re-bootstraps at ``k + j``, and the committed state streams to the
      joiners through the cold-join restore.

    ``step_fn`` takes the CURRENT comm — after any boundary it is a new
    (resized, new-epoch) comm and the step re-traces at the new size.
    ``commit_every`` bounds the recovery replay window; the initial
    state is committed before step ``start_step`` so a first-step
    failure is recoverable.  ``commit_every='auto'`` measures instead
    of guessing (ROADMAP item 4c): the loop commits every boundary
    until it has timed one post-warmup step (the first call is
    skipped — it carries trace+compile) and one ``ShardStore`` pack,
    then
    locks in the smallest interval keeping commit overhead under the
    target fraction of step time
    (:func:`resolve_auto_commit_interval`; the target reads the active
    tuning layer's ``tuned.commit.target_overhead`` —
    docs/autotune.md).  ``claim_watchdog=True`` installs the
    elastic expiry handler (``resilience.set_on_timeout``) for the
    duration of the loop, so an expiry becomes a recovery instead of a
    process kill — the detection path a hung (not dead) peer needs.
    ``drain_on_sigterm=True`` additionally installs a SIGTERM handler
    that converts scheduler preemption notices into drain requests
    (main thread only; silently skipped elsewhere).
    """
    from . import watchdog as _wd

    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    # commit_every='auto': pick the interval from measured step time vs
    # measured ShardStore pack cost (resolve_auto_commit_interval) —
    # the loop commits every boundary until both measurements exist
    # (the first step and the first commit), then locks the interval in
    auto_commit: Optional[dict] = None
    if isinstance(commit_every, str):
        if commit_every != "auto":
            raise ValueError(
                f"commit_every must be an int >= 1 or 'auto', got "
                f"{commit_every!r}"
            )
        # warm=False skips the FIRST step's timing: for a jit/spmd step
        # it includes trace+compile, which would inflate step_s by
        # orders of magnitude and lock the interval at 1 forever
        auto_commit = {"step_s": None, "commit_s": None, "warm": False}
        commit_every = 1
    if commit_every < 1:
        raise ValueError(f"commit_every must be >= 1, got {commit_every}")

    # megastep granularity (docs/aot.md "Megastep execution"): a step_fn
    # advertising ``unroll`` (mpx.aot.compile_step(fn, unroll=N)) runs N
    # steps per call, so the loop advances by N, commits land on
    # megastep boundaries (commit_every rounded UP to a multiple of N),
    # and a StaleProgramError mid-megastep retries the WHOLE megastep
    # from the un-advanced state — restart-idempotent by construction,
    # since state only commits at boundaries.
    stride = getattr(step_fn, "unroll", 1) or 1
    try:
        stride = max(1, int(stride))
    except (TypeError, ValueError):
        stride = 1
    if stride > 1:
        if (steps - start_step) % stride:
            raise ValueError(
                f"steps - start_step ({steps - start_step}) must be a "
                f"multiple of the step function's megastep unroll "
                f"({stride}): a pinned megastep cannot run a partial "
                "trip (pad the budget or drop unroll)"
            )
        commit_every = align_commit_every(commit_every, stride)

    # the AOT layer's staleness signal (aot/invalidation.py): a pinned
    # step function refuses execution after an epoch/config change with
    # StaleProgramError (MPX129), and THIS loop is the re-entry point —
    # it re-pins (step_fn.repin()) and retries the same step, so an
    # elastic job keeps its pinned hot path across shrink/grow/drain
    # boundaries.  Lazy + guarded: the aot package needs jax, which the
    # isolated pure-test loaders do not have.
    try:
        from ..aot.invalidation import StaleProgramError as _Stale
    except Exception:  # aot layer unavailable (isolated loaders, no jax)
        class _Stale(BaseException):  # never raised without the aot layer
            pass

    claimed = False
    prev_handler = prev_fallback = None
    if claim_watchdog:
        # save whatever was installed (a user handler counts too) and
        # restore IT on exit, not the stock default
        prev_handler = _wd._registry.on_timeout
        prev_fallback = _wd._force_fallback
        _wd.set_on_timeout(_claimed_on_timeout)
        # the native C++ monitor kills on expiry and cannot hand the
        # expiry to a Python handler: route arms through the claimable
        # Python-fallback registry for the duration of the loop
        _wd.force_python_fallback(True)
        claimed = True
    prev_sigterm = None
    servers: dict = {}
    try:
        # setup that can fail (socket binds, bootstrap-dict access) runs
        # INSIDE the try: the finally below must restore the claimed
        # watchdog handler and the SIGTERM handler even when setup dies
        if drain_on_sigterm and store.multiprocess():
            prev_sigterm = install_preemption_handler()
        _restart_elastic_servers(servers, store)
        if store.committed_step is None:
            # deliberately NOT timed for commit_every='auto': the first
            # pack carries one-time costs (first-touch allocation) that
            # would overestimate commit_s — the first IN-LOOP commit is
            # the warmed measurement, symmetric with the step warmup
            store.commit(start_step, state)
        step = start_step
        while step < steps:
            try:
                t0 = time.perf_counter()
                state = step_fn(state, step, store.comm)
                _block_on(state)
                if auto_commit is not None and auto_commit["step_s"] is None:
                    if auto_commit["warm"]:
                        # per-STEP time: a megastep covers stride steps
                        auto_commit["step_s"] = \
                            (time.perf_counter() - t0) / stride
                    else:
                        auto_commit["warm"] = True  # first call compiles
                step += stride
                committed = False
                if (step - start_step) % commit_every == 0 or step == steps:
                    t0 = time.perf_counter()
                    store.commit(step, state)
                    if (auto_commit is not None
                            and auto_commit["commit_s"] is None):
                        auto_commit["commit_s"] = time.perf_counter() - t0
                    committed = True
                if (auto_commit is not None
                        and auto_commit["step_s"] is not None
                        and auto_commit["commit_s"] is not None):
                    commit_every = align_commit_every(
                        resolve_auto_commit_interval(
                            auto_commit["step_s"],
                            auto_commit["commit_s"]),
                        stride)
                    auto_commit = None  # locked in for the rest of the run
                    _meter("elastic.auto_commits")
                # health-detector tick BEFORE the boundary actions: a
                # suspect RankFailure raised here lands in the except
                # below and recovers like any peer death
                _health_boundary(store, step, committed)
                outcome = _boundary_actions(
                    store, step, steps, state, committed,
                    start_step, commit_every, servers)
                if outcome is not None:
                    kind, step, state = outcome
                    if kind == "leave":
                        return state
            except _Stale:
                # a pinned step refused the new world: re-pin and retry
                # the SAME step (state/step were not advanced).  No
                # repin hook means the caller pinned by hand — surface
                # the refusal rather than looping on it.
                repin = getattr(step_fn, "repin", None)
                if repin is None:
                    raise
                step_fn = repin() or step_fn
                _meter("elastic.repins")
            except BaseException as exc:  # noqa: B036 - KeyboardInterrupt too
                rf = classify_failure(exc)
                if rf is None:
                    raise
                _health_failure(rf)
                step, state = _recover(rf, store)
                _restart_elastic_servers(servers, store)
        return state
    finally:
        _stop_elastic_servers(servers)
        if claimed:
            _wd.set_on_timeout(prev_handler)
            _wd.force_python_fallback(prev_fallback)
        if prev_sigterm is not None:
            import signal as _signal

            try:
                _signal.signal(_signal.SIGTERM, prev_sigterm)
            except (ValueError, OSError):
                pass


def _block_on(state) -> None:
    """Force the step's device work to complete INSIDE the try: a peer
    death must surface here (as an error or a watchdog expiry), not at an
    uninstrumented later use."""
    try:
        import jax

        jax.block_until_ready(state)
    except ImportError:
        pass


# ---------------------------------------------------------------------------
# boundary control: planned reconfiguration between steps
# ---------------------------------------------------------------------------


def _restart_elastic_servers(servers: dict, store: ShardStore) -> None:
    """(Re)bind the epoch-scoped listeners: every multi-process rank runs
    a control listener (drain notices); the coordinator (rank 0)
    additionally runs the join listener when the grow flag is on.  Bind
    failures degrade silently — the listeners are conveniences of the
    PLANNED paths; the failure path never needs them."""
    _stop_elastic_servers(servers)
    if not store.multiprocess():
        return
    bs = store.bootstrap
    try:
        host, pb = bs["host"], int(bs["port_base"])
        me = int(bs["process_id"])
    except (KeyError, TypeError, ValueError):
        return  # joiner-style partial bootstrap: no listeners yet
    epoch = current_epoch()
    try:
        servers["control"] = _ControlServer(
            host, control_port(pb, me, epoch))
    except (OSError, ValueError):
        servers["control"] = None
    if me == 0 and config.elastic_grow():
        try:
            servers["join"] = _JoinServer(host, join_port(pb, epoch))
        except OSError:
            servers["join"] = None


def _stop_elastic_servers(servers: dict) -> None:
    for srv in servers.values():
        if srv is not None:
            srv.stop()
    servers.clear()


def _boundary_actions(store: ShardStore, step: int, steps: int, state,
                      committed: bool, start_step: int, commit_every: int,
                      servers: dict):
    """Planned world changes at a step boundary, in priority order:
    execute a scheduled drain (ours or a peer's), then admit pending
    joiners (commit boundaries only).  Returns ``None`` (nothing to do),
    ``("continue", step, state)`` (world changed, keep looping), or
    ``("leave", step, state)`` (this rank was drained out)."""
    mine = take_pending_drain()
    if mine is not None and store.multiprocess():
        bs = store.bootstrap
        my_rank = int(bs["process_id"])
        leaver = my_rank if mine["rank"] is None else int(mine["rank"])
        if leaver == my_rank:
            # announce our departure: boundary = NEXT step boundary, acks
            # collected before anyone steps toward it, so no peer can
            # race past the boundary into a collective we never enter
            boundary = step + 1
            notify_drain(bs["host"], int(bs["port_base"]), my_rank,
                         int(bs["num_processes"]), boundary,
                         grace=mine["grace"])
            mark_comm_draining(store.comm, boundary)
            _post_peer_drain(my_rank, boundary)
            mine = None
        else:
            mine = {"rank": leaver, "grace": mine["grace"]}
    if mine is not None:
        # single-controller simulated drain (or an explicit-rank drain):
        # executes at THIS boundary
        if mine["rank"] is None:
            raise RuntimeError(
                "request_drain() without a rank needs a multi-process "
                "world (a single controller cannot leave its own job); "
                "pass rank= to drain a simulated rank"
            )
        return _execute_drain(store, step, state, committed,
                              int(mine["rank"]), servers)
    peer = peek_peer_drain()
    if peer is not None and step >= int(peer["boundary"]):
        take_peer_drain()
        return _execute_drain(store, step, state, committed,
                              int(peer["rank"]), servers)
    if committed and step < steps:
        joins = _poll_joins(store)
        # never admit at a boundary with a drain already scheduled: the
        # joiner would miss the (already-delivered) drain notice and
        # desynchronize at the leave boundary.  Every old rank sees the
        # same pending notice here — the leaver collects acks BEFORE it
        # enters the poll allreduce — so the deferral is symmetric; the
        # joiners stay parked and are admitted at the next boundary.
        if joins and peek_peer_drain() is None:
            return _execute_grow(store, step, state, committed, joins,
                                 servers)
    return None


def _execute_drain(store: ShardStore, step: int, state, committed: bool,
                   leaver: int, servers: dict):
    """The planned shrink at the leave boundary: force the early commit,
    widen the removal to the declared fail unit, and either exit (this
    rank is leaving) or rebuild the world without the leavers.  No
    agreement round (the departure is announced, not suspected), no
    restore (every survivor's state is live), no majority guard (a
    planned drain cannot split-brain), exactly one ``drain`` incident
    per process."""
    from . import watchdog as _wd

    with _wd.suspend_expiries():
        if not committed:
            store.commit(step, state)
        comm = store.comm
        mesh = getattr(comm, "mesh", None)
        mesh_shape = (tuple(mesh.shape.values()) if mesh is not None
                      else (int(comm.world_size()),))
        unit = config.elastic_fail_unit()
        removed = expand_fail_unit({leaver}, mesh_shape, unit)
        world = int(store.bootstrap.get("num_processes")
                    or comm.world_size())
        me = (int(store.bootstrap["process_id"])
              if store.multiprocess() else None)
        _meter("elastic.drains")
        _incident(
            "elastic.drain_incidents", "drain", me if me is not None else 0,
            f"rank {leaver} drained at step {step} (removed "
            f"{sorted(removed)} of {world}, fail_unit={unit})",
        )
        seal_drained_comm(comm)
        if me is not None and me in removed:
            # we are leaving (the announcer, or a row-mate shrunk out
            # with it): the state as of the forced commit is the result
            store.drained = True
            return "leave", step, state
        revoke_epoch(removed, rank=me if me is not None else 0,
                     world=world, cause="drain")
        if store.multiprocess():
            store.rebootstrap(removed, unit)
        else:
            store.apply_shrink(removed, unit)
        _restart_elastic_servers(servers, store)
    return "continue", step, state


def _poll_joins(store: ShardStore) -> int:
    """How many joiners to admit at this boundary.  Single controller:
    the simulated-join queue.  Multi-process (grow flag on): one tiny
    SUM allreduce of the coordinator's pending count, so every rank
    learns the same delta at the same boundary."""
    if not store.multiprocess():
        return pending_join_count()
    if not config.elastic_grow():
        return 0
    import numpy as np

    from ..ops import SUM, allreduce

    comm = store.comm
    size = int(comm.world_size())
    me = int(store.bootstrap["process_id"])
    pending = pending_join_count()
    if pending and me == 0 and not store.can_describe_commit():
        # the committed state cannot be described to a joiner (custom
        # pytree nodes — docs/resilience.md): refuse admission HERE,
        # before any epoch moves, so every rank symmetrically sees 0
        # and the job keeps training instead of dying mid-admission
        _meter("elastic.joins_refused")
        pending = 0
    counts = np.zeros((size, 1), np.int32)
    counts[me, 0] = pending
    out, _ = allreduce(counts, op=SUM, comm=comm)
    return int(np.asarray(out)[0, 0])


def _execute_grow(store: ShardStore, step: int, state, committed: bool,
                  joins: int, servers: dict):
    """Admit ``joins`` replacement ranks at this commit boundary: advance
    the epoch with a positive world delta, send each parked joiner its
    admit message (identity, new world, commit geometry), re-bootstrap at
    ``k + joins``, and run the cold-join restore so every rank — joiners
    included — leaves the boundary with the committed state."""
    from . import watchdog as _wd

    with _wd.suspend_expiries():
        if not committed:
            store.commit(step, state)
        comm = store.comm
        world = int(store.bootstrap.get("num_processes")
                    or comm.world_size())
        me = (int(store.bootstrap["process_id"])
              if store.multiprocess() else 0)
        _meter("elastic.joins", joins)
        _incident(
            "elastic.join_incidents", "join", me,
            f"admitting {joins} replacement rank(s) at step "
            f"{store.committed_step}: world {world} -> {world + joins}",
        )
        revoke_epoch((), rank=me, world=world, added=joins, cause="join")
        if store.multiprocess():
            if me == 0:
                pending = _take_pending_joins()
                # a joiner that arrived after the poll stays parked for
                # the NEXT boundary (the polled count is what every rank
                # agreed to admit)
                if len(pending) > joins:
                    with _join_lock:
                        _pending_joins[0:0] = pending[joins:]
                desc = store.describe_commit()
                for i, j in enumerate(pending[:joins]):
                    admit = {
                        "kind": "admit",
                        "epoch": current_epoch(),
                        "process_id": world + i,
                        "num_processes": world + joins,
                        "step": store.committed_step,
                        "commit": desc,
                        "axes": list(comm.axes),
                    }
                    conn = j.get("conn")
                    if conn is not None:
                        try:
                            _send_json(conn, admit)
                        except OSError:
                            pass
                        finally:
                            conn.close()
            store.rebootstrap_grow(joins)
            new_step, new_state = store.restore(force_exchange=True)
        else:
            _take_pending_joins()
            store.apply_grow(joins)
            new_step, new_state = store.restore()
        _restart_elastic_servers(servers, store)
        _meter("elastic.resumes")
    return "continue", new_step, new_state


class BoundaryControl:
    """Planned-reconfiguration polling for an EXTERNAL loop.

    ``mpx.elastic.run`` owns a fixed step budget; loops that do not — the
    serving runtime (mpi4jax_tpu/serving/engine.py), whose iteration
    count depends on traffic — still need the same between-step boundary
    semantics: a SIGTERM/preemption notice becomes a drain request, the
    leaver announces its boundary with acks, every rank executes the
    planned shrink at that boundary (one ``drain`` incident, no watchdog
    expiry, no gossip), and pending joiners are admitted at committed
    boundaries.  This wraps exactly the helper ``run`` drives
    (:func:`_boundary_actions`) plus the listener lifecycle::

        with BoundaryControl(store) as bc:
            while serving:
                ...one megastep...
                outcome = bc.poll(step, state, committed=True)
                if outcome is not None:
                    kind, step, state = outcome
                    if kind == "leave":
                        break            # we drained out
                    rebuild_programs()   # world changed, keep going

    ``poll`` returns ``None`` (nothing happened), ``("continue", step,
    state)`` (the world changed — the store's comm is rebuilt), or
    ``("leave", step, state)`` (this rank was drained out;
    ``store.drained`` is set)."""

    def __init__(self, store: ShardStore, *, drain_on_sigterm: bool = True):
        self.store = store
        self.servers: dict = {}
        self._prev_sigterm = None
        self._drain_on_sigterm = drain_on_sigterm
        self._entered = False

    def __enter__(self) -> "BoundaryControl":
        if self._drain_on_sigterm and self.store.multiprocess():
            self._prev_sigterm = install_preemption_handler()
        _restart_elastic_servers(self.servers, self.store)
        self._entered = True
        return self

    def poll(self, step: int, state, *, committed: bool = True):
        """Run the boundary actions for step ``step`` (drain execution,
        join admission).  ``committed=False`` makes a drain force-commit
        ``state`` before the shrink (pass True when the caller's
        committed state is already current — e.g. static serving
        parameters committed once up front)."""
        return _boundary_actions(self.store, step, step + 1, state,
                                 committed, 0, 1, self.servers)

    def __exit__(self, *exc) -> bool:
        if not self._entered:
            return False
        self._entered = False
        _stop_elastic_servers(self.servers)
        if self._prev_sigterm is not None:
            import signal as _signal

            try:
                _signal.signal(_signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None
        return False


def join_and_run(step_fn, store: ShardStore, *, steps: int,
                 commit_every: int = 1, claim_watchdog: bool = True,
                 join_timeout: float = 300.0):
    """The replacement process's entry point: contact the running
    world's coordinator (scanning the declared port window for the live
    epoch), wait to be admitted at a commit boundary, adopt the admitted
    epoch and identity, receive the committed state through the
    cold-join restore (we contribute zeros, the survivors' shards sum to
    everything), and re-enter :func:`run` at the committed step.
    Returns the final state, exactly as :func:`run` does."""
    import jax

    from ..parallel.comm import Comm
    from ..parallel.mesh import make_world_mesh, set_default_mesh
    from ..parallel import mesh as _mesh_mod, region as _region
    from .retry import retry_with_backoff

    bs = store.bootstrap
    for key in ("host", "port_base"):
        if key not in bs:
            raise RuntimeError(
                "join_and_run needs ShardStore(bootstrap={'host', "
                f"'port_base'}}); missing {key!r}"
            )
    admit = request_join(bs["host"], int(bs["port_base"]),
                         timeout=join_timeout)
    _set_epoch(int(admit["epoch"]))
    bs["process_id"] = int(admit["process_id"])
    bs["num_processes"] = int(admit["num_processes"])
    port = coordinator_port(int(bs["port_base"]), current_epoch())
    retry_with_backoff(
        lambda: jax.distributed.initialize(
            coordinator_address=f"{bs['host']}:{port}",
            num_processes=int(bs["num_processes"]),
            process_id=int(bs["process_id"]),
        ),
        what=f"cold join (epoch {current_epoch()}, coordinator "
             f"{bs['host']}:{port})",
        deadline=config.bootstrap_deadline(),
        max_attempts=config.bootstrap_max_attempts() or None,
    )
    _mesh_mod._distributed_initialized = True
    axes = tuple(admit.get("axes") or ()) or None
    mesh = make_world_mesh((int(bs["num_processes"]),), axes)
    set_default_mesh(mesh)
    _region._default_comm = None
    store._comm = Comm(tuple(mesh.axis_names), mesh=mesh)
    store.adopt_commit(admit["commit"])
    _incident(
        "elastic.join_incidents", "join", int(bs["process_id"]),
        f"cold-joined epoch {current_epoch()} as rank "
        f"{bs['process_id']} of {bs['num_processes']} at step "
        f"{admit['step']}",
    )
    step, state = store.restore(force_exchange=True)
    _meter("elastic.resumes")
    return run(step_fn, state, store, steps=steps, start_step=step,
               commit_every=commit_every, claim_watchdog=claim_watchdog)


def _recover(rf: RankFailure, store: ShardStore):
    """The shrink-and-resume sequence: agree -> revoke -> shrink ->
    restore.  The agreed failed set is widened to the declared fail unit
    (``MPI4JAX_TPU_ELASTIC_FAIL_UNIT``) before the shrink, so Cartesian
    grids lose whole rows/columns and stay rectangular.  Returns
    ``(committed_step, state)``."""
    _meter("elastic.failures_detected")
    comm = store.comm
    world = int(store.bootstrap.get("num_processes") or comm.world_size())

    if store.multiprocess():
        bs = store.bootstrap
        my_rank = int(bs["process_id"])
        failed = negotiate_failed(
            my_rank, world, rf.suspects, bs["host"],
            agree_port_no=agree_port(int(bs["port_base"]),
                                     current_epoch()),
            gossip_port_base=int(bs.get("agree_port_base",
                                        int(bs["port_base"]) + 1000))
            + 17 * wrapped_epoch(current_epoch()),
            timeout=float(bs.get("agree_timeout", 20.0)),
        )
        if my_rank in failed:
            raise RankFailure(failed, "this rank was declared failed by "
                                      "its peers") from rf
    else:
        my_rank = 0
        failed = frozenset(rf.suspects)
    _meter("elastic.agreements")

    if not failed:
        raise RankFailure(
            (), "failure agreement produced an empty failed set: the "
                "suspects were not confirmed and no peer is unreachable — "
                "refusing to shrink a healthy world"
        ) from rf
    # the split-brain guard judges the ranks that actually FAILED — the
    # fail-unit expansion below removes healthy row-mates by policy, not
    # by partition, so it does not weigh against the majority
    if not majority_survives(failed, world):
        raise RankFailure(
            failed,
            f"only {world - len(failed)} of {world} ranks survive — below "
            "the majority threshold (split-brain guard): aborting instead "
            "of training a divergent minority partition",
        ) from rf
    # the failed set is now AGREED: every survivor reaches this line
    # with the identical verdict, so every survivor's journal gets the
    # health incident naming each failed rank (telemetry/health.py)
    _health_rank_failed(failed, rf)
    unit = config.elastic_fail_unit()
    mesh = getattr(comm, "mesh", None)
    mesh_shape = (tuple(mesh.shape.values()) if mesh is not None
                  else (world,))
    removed = expand_fail_unit(failed, mesh_shape, unit)
    if store.multiprocess() and my_rank in removed:
        raise RankFailure(
            removed,
            f"this rank's grid {unit} contains failed rank(s) "
            f"{sorted(failed)} — shrunk out with them (fail_unit={unit})",
        ) from rf
    if store.multiprocess():
        # raises RankFailure when a shard lost its whole replica set —
        # only meaningful when shards must move between processes (a
        # single controller holds every shard and restores locally);
        # judged against the placement table recorded on the commit
        store.restore_plan(removed)

    revoke_epoch(removed, rank=my_rank, world=world)
    if store.multiprocess():
        store.rebootstrap(removed, unit)
    else:
        store.apply_shrink(removed, unit)
    step, state = store.restore(removed)
    _meter("elastic.resumes")
    return step, state
