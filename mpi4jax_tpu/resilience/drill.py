"""Deterministic chaos drills for the elastic control plane.

A drill simulates a k-rank world entirely in-process — one rank-pinned
:class:`~.elastic.ShardStore` per simulated rank, the pure agreement
models as the failure-detection fabric — then scripts a kill pattern and
asserts the two invariants the control plane owes its operator:

- **agreement**: every survivor commits the SAME failed set, equal to
  the actually-killed ranks, and the coordinator-mediated star reaches
  exactly the pure ``gossip_agreement`` fixpoint with O(k) connections;
- **restore**: the committed state reassembles bit-identically from the
  surviving replicas (and, for the host-row pattern, provably CANNOT
  under the old neighbor placement — the negative control that makes
  the stripe's guarantee falsifiable).

Patterns (:data:`PATTERNS`):

``single``        one mid-world rank dies.
``host-row``      every rank of one host dies at once — the pattern
                  neighbor placement cannot survive and the stripe must.
``coordinator``   rank 0 (the agreement coordinator) dies: agreement
                  degrades to peer gossip and restore still completes.
``double``        cascading double fault: one rank dies, the world
                  shrinks and re-commits, then a second rank dies in the
                  shrunken world — the recommit-then-fail-again sequence.

Everything here is pure + numpy (no jax, no sockets, no clocks): the
isolated test loader runs drills under any JAX, CI replays them
byte-for-byte, and ``benchmarks/elastic_drill.py`` turns the metrics
into the committed ``BENCH_elastic.json``.  Runtime-transport coverage
(real TCP agreement rounds) lives in tests/test_elastic_pure.py; the
drills deliberately model transport cost analytically so a 64-rank
matrix costs milliseconds, not sockets.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .elastic import (
    RankFailure,
    ShardStore,
    coordinator_agreement,
    gossip_agreement,
    neighbor_placement,
    plan_from_placement,
    reassemble_from_stores,
)

__all__ = [
    "PATTERNS",
    "default_counts",
    "links_for",
    "kill_set",
    "agreement_connections",
    "run_drill",
    "drill_matrix",
]

PATTERNS = ("single", "host-row", "coordinator", "double")

# gossip rounds the TCP runtime form uses (exchange_suspects default)
_GOSSIP_ROUNDS = 2


def default_counts(k: int) -> Tuple[int, ...]:
    """The drill topology for ``k`` simulated ranks: the squarest
    uniform host split (8 -> 2 hosts x 4, 16 -> 4 x 4, 64 -> 8 x 8) —
    hosts of several ranks each, so a host-row kill is a genuinely
    correlated multi-rank loss."""
    if k < 1:
        raise ValueError(f"need at least one rank, got k={k}")
    hosts = max(1, int(k ** 0.5))
    while k % hosts:
        hosts -= 1
    return (k // hosts,) * hosts


def links_for(world: int, dead: Iterable[int]) -> List[List[bool]]:
    """The link matrix after ``dead`` die: every link touching a dead
    rank is down, every survivor pair healthy (partition-free — the
    partition cases are pinned directly on the pure models in
    tests/test_elastic_pure.py)."""
    gone = frozenset(dead)
    return [[i != j and i not in gone and j not in gone
             for j in range(world)] for i in range(world)]


def kill_set(pattern: str, k: int,
             counts: Sequence[int]) -> Tuple[int, ...]:
    """The ranks the FIRST wave of ``pattern`` kills (the ``double``
    pattern's second wave is derived inside :func:`run_drill` from the
    shrunken world)."""
    if pattern not in PATTERNS:
        raise ValueError(f"unknown drill pattern {pattern!r}; "
                         f"expected one of {PATTERNS}")
    if pattern == "single" or pattern == "double":
        return (k // 2,)
    if pattern == "coordinator":
        return (0,)
    # host-row: every rank of host 1 (host 0 keeps the coordinator)
    if len(counts) < 2:
        raise ValueError(
            f"host-row drill needs >= 2 hosts, got counts {tuple(counts)}")
    start = counts[0]
    return tuple(range(start, start + counts[1]))


def agreement_connections(world: int, dead: Iterable[int],
                          mode: str, coordinator: int = 0) -> int:
    """Analytic TCP connection count of one agreement round — the cost
    model the O(k) acceptance assertion pins.

    ``coordinator`` mode with a live coordinator: one report connection
    per non-coordinator survivor (the verdict rides the same socket
    back).  A dead coordinator costs every survivor one failed probe,
    then the full peer-gossip fallback.  ``gossip`` mode: every round,
    every survivor dials every other rank (the all-pairs O(k²) the star
    exists to replace)."""
    gone = frozenset(dead)
    s = world - len(gone)
    gossip = _GOSSIP_ROUNDS * s * (world - 1)
    if mode == "gossip":
        return gossip
    if mode != "coordinator":
        raise ValueError(f"unknown agreement mode {mode!r}")
    if coordinator in gone:
        return s + gossip  # s failed probes, then the fallback
    return s - 1


class _FixedComm:
    """The world-size stub rank-pinned simulated stores dial."""

    def __init__(self, k: int):
        self._k = k

    def world_size(self) -> int:
        return self._k


def _drill_state(seed: int = 0) -> dict:
    """The deterministic committed state: non-divisible byte sizes (the
    padding path) and two dtypes, same on every simulated rank."""
    import numpy as np

    return {
        "w": (np.arange(1000, dtype=np.float64) + seed),
        "b": (np.arange(333, dtype=np.float32) * 3 + seed),
        "step_scale": np.float32(1.5 + seed),
    }


def _states_equal(a: dict, b: dict) -> bool:
    import numpy as np

    return (sorted(a) == sorted(b)
            and all(np.array_equal(a[key], b[key]) for key in a))


def _build_stores(k: int, counts: Sequence[int], redundancy: int,
                  placement: str) -> Dict[int, ShardStore]:
    comm = _FixedComm(k)
    return {
        r: ShardStore(comm, redundancy=redundancy, rank=r,
                      topology=tuple(counts), placement=placement)
        for r in range(k)
    }


def _check_agreement(world: int, dead: frozenset,
                     coordinator: int = 0) -> None:
    """Assert both pure agreement models converge every survivor to
    exactly ``dead``.  Detection is deliberately asymmetric — only the
    lowest survivor names the dead ranks, everyone else reports the
    empty "something died but unnamed" set — so the drill exercises
    propagation, not just echo."""
    survivors = sorted(set(range(world)) - dead)
    observer = survivors[0]
    suspects = {r: (sorted(dead) if r == observer else [])
                for r in survivors}
    links = links_for(world, dead)
    gossip = gossip_agreement(suspects, links)
    coord = coordinator_agreement(suspects, links,
                                  coordinator=coordinator)
    for r in survivors:
        if gossip[r] != dead:
            raise AssertionError(
                f"gossip agreement diverged: survivor {r} committed "
                f"{sorted(gossip[r])}, expected {sorted(dead)}")
        if coord[r] != gossip[r]:
            raise AssertionError(
                f"coordinator agreement != gossip fixpoint at survivor "
                f"{r}: {sorted(coord[r])} vs {sorted(gossip[r])}")


def _restore_metrics(stores: Dict[int, ShardStore],
                     dead: frozenset) -> Dict[int, int]:
    """Byte accounting of one restore wave, from the commit geometry."""
    rec = next(s for r, s in stores.items()
               if r not in dead)._require_commit()
    k, shard = rec["k"], rec["shard"]
    repair_shards = sorted(s for s in range(k) if s in dead)
    survivors = k - len(dead)
    return {
        "state_bytes": int(rec["nbytes"]),
        "shard_bytes": int(shard),
        "repair_shards": len(repair_shards),
        "repair_bytes": len(repair_shards) * int(shard),
        "repair_bytes_per_survivor":
            (len(repair_shards) * int(shard) + survivors - 1) // survivors,
    }


def run_drill(pattern: str, k: int, *, redundancy: int = 1,
              counts: Optional[Sequence[int]] = None,
              placement: str = "stripe") -> dict:
    """Run one kill pattern over ``k`` simulated ranks and return the
    metrics dict (all-integer, deterministic — safe to commit).

    Raises ``AssertionError`` when an invariant breaks: agreement
    divergence, coordinator/gossip fixpoint mismatch, O(k) connection
    budget blown, non-bit-identical restore — or, for ``host-row``
    under the default stripe, when the NEGATIVE control unexpectedly
    passes (neighbor placement surviving the host row would mean the
    drill lost its teeth)."""
    counts = tuple(counts) if counts is not None else default_counts(k)
    if sum(counts) != k:
        raise ValueError(
            f"topology {counts} covers {sum(counts)} ranks, expected {k}")
    stores = _build_stores(k, counts, redundancy, placement)
    state0 = _drill_state()
    for store in stores.values():
        store.commit(0, state0)

    waves: List[frozenset] = [frozenset(kill_set(pattern, k, counts))]
    metrics = {
        "pattern": pattern,
        "k": k,
        "topology": list(counts),
        "redundancy": redundancy,
        "placement": placement,
        "killed": sorted(waves[0]),
        "epochs": 1,
    }

    coordinator = 0
    dead = waves[0]
    _check_agreement(k, dead, coordinator)
    conns = agreement_connections(k, dead, "coordinator", coordinator)
    if coordinator not in dead and conns > k:
        raise AssertionError(
            f"coordinator agreement used {conns} connections at k={k} — "
            "the O(k) star budget is blown")
    metrics["agreement"] = {
        "coordinator_connections": conns,
        "gossip_connections":
            agreement_connections(k, dead, "gossip", coordinator),
    }

    if pattern == "host-row" and placement == "stripe" \
            and redundancy >= 1 and redundancy < counts[1]:
        # negative control: the same kill under neighbor placement must
        # be unrecoverable (a contiguous block wider than the ring depth
        # wipes some shard's whole replica set)
        try:
            plan_from_placement(dead, neighbor_placement(k, redundancy))
        except RankFailure:
            metrics["neighbor_unrecoverable"] = True
        else:
            raise AssertionError(
                f"neighbor placement survived the host-row kill "
                f"{sorted(dead)} at k={k} — the drill's negative control "
                "lost its teeth")

    step, restored = reassemble_from_stores(stores, dead)
    if step != 0 or not _states_equal(state0, restored):
        raise AssertionError(
            f"restore after {pattern} kill {sorted(dead)} was not "
            "bit-identical to the committed state")
    metrics["restore"] = _restore_metrics(stores, dead)

    if pattern == "double":
        # wave 2: shrink to the survivors, re-commit the restored state,
        # then fail again in the SHRUNKEN world — the cascade that
        # catches placement tables stale from the old world size
        k2 = k - len(dead)
        survivors = sorted(set(range(k)) - dead)
        # hosts keep their surviving members (the dead rank's host just
        # gets smaller) — per-host counts of the compacted world
        host_of = [h for h, c in enumerate(counts) for _ in range(c)]
        counts2: List[int] = [0] * len(counts)
        for r in survivors:
            counts2[host_of[r]] += 1
        counts2 = [c for c in counts2 if c]
        stores2 = _build_stores(k2, counts2, redundancy, placement)
        for store in stores2.values():
            store.commit(1, restored)
        dead2 = frozenset({k2 // 2 if k2 // 2 != coordinator else k2 - 1})
        _check_agreement(k2, dead2, coordinator)
        conns2 = agreement_connections(k2, dead2, "coordinator",
                                       coordinator)
        if conns2 > k2:
            raise AssertionError(
                f"coordinator agreement used {conns2} connections at "
                f"k={k2} (wave 2) — the O(k) star budget is blown")
        step2, restored2 = reassemble_from_stores(stores2, dead2)
        if step2 != 1 or not _states_equal(state0, restored2):
            raise AssertionError(
                "restore after the cascading second fault was not "
                "bit-identical to the committed state")
        metrics["epochs"] = 2
        metrics["wave2"] = {
            "k": k2,
            "topology": counts2,
            "killed": sorted(dead2),
            "coordinator_connections": conns2,
            "restore": _restore_metrics(stores2, dead2),
        }

    metrics["recovered"] = True
    return metrics


def drill_matrix(ks: Sequence[int] = (8, 16, 64),
                 patterns: Sequence[str] = PATTERNS, *,
                 redundancy: int = 1) -> List[dict]:
    """The full drill matrix: every pattern at every world size.
    Deterministic — two runs return identical lists, which is what lets
    CI diff the committed ``BENCH_elastic.json`` against a fresh run."""
    return [run_drill(p, k, redundancy=redundancy)
            for k in ks for p in patterns]
