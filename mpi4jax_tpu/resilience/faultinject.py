"""Deterministic fault injection at the shared op dispatch point.

Production collectives fail in four characteristic ways: a rank goes slow
(stragglers, preemption), a rank dies (hardware loss, OOM-kill), a rank
*hangs* — alive but stuck forever, the realistic TPU failure mode: the
process holds its slice, heartbeats keep passing, and only the peers'
watchdogs can tell — or a rank computes garbage (silent data corruption,
bad reduction inputs).  This module injects all four *deterministically*
from a parsed spec, at the single dispatch point every one of the 12 ops
flows through (``ops/_base.py _run_body``) — so every op is injectable in
tests without touching per-op code, and a production incident can be
rehearsed with one environment variable.

A fifth verb, ``preempt``, rehearses the *announced* eviction (spot/
preemptible capacity): instead of killing or stalling the rank it posts
a SIGTERM-style drain notice (``resilience/elastic.request_drain``), so
the elastic loop executes a graceful drain at its next step boundary —
the one failure mode that should cost a commit interval, not a
detection timeout.

Spec grammar (``MPI4JAX_TPU_FAULT_SPEC``, full reference in
docs/resilience.md)::

    spec    := clause (';' clause)*
    clause  := verb (':' arg)* | 'die-host' ':' host ['@' op#]
    verb    := 'delay' | 'die' | 'hang' | 'corrupt' | 'preempt'
    arg     := 'nan' | 'inf' | key '=' value      # bare modes only for corrupt
    key     := 'rank' | 'host' | 'op' | 'after' | 'secs' | 'grace'

Examples::

    delay:rank=1:op=allreduce:after=3:secs=2   # rank 1 sleeps 2s in every
                                               # allreduce after its 3rd
    die:rank=0:op=barrier:after=1              # rank 0 exits in its 2nd barrier
    corrupt:nan:rank=2:op=allreduce            # rank 2 feeds NaN inputs
    preempt:rank=3:after=4:grace=2             # rank 3 gets a drain notice in
                                               # its 5th collective (2s ack
                                               # grace)
    die-host:1@3                               # every rank the topology maps
                                               # to host 1 exits in its 4th
                                               # collective (== die:host=1
                                               # :after=3) — the host-row kill

Semantics:

- ``rank`` is the GLOBAL mesh rank (row-major over the comm's full axes);
  omitted = every rank.
- ``host`` scopes a clause to every rank the ``MPI4JAX_TPU_TOPOLOGY``
  spec maps to that host (mutually exclusive with ``rank``) — the
  injection point for host-level failures, so the chaos drills and the
  CI faults lane express a whole-host kill through one clause.
  ``die-host:<h>[@<op#>]`` is shorthand for ``die:host=<h>[:after=<op#>]``.
  Without a declared topology a host clause matches nothing (warns once:
  a drill that silently no-ops would report false confidence).
- ``op`` is the lowercase op name as dispatched (``allreduce``, ``barrier``,
  ...); omitted = every op.
- ``after=N``: the first N matching calls (counted per rank, at run time —
  compiled-program reuse is counted correctly) run clean; the fault fires on
  every matching call after that.  Default 0 (fire immediately).
- ``delay`` sleeps ``secs`` (default 1.0) on the host before the collective;
  ``die`` kills the process (``os._exit(13)``), simulating a crashed rank;
  ``hang`` sleeps forever (the process stays alive but never enters the
  collective — unlike ``die``, the peers see no error, only silence, so a
  drill exercises the watchdog-expiry detection path);
  ``preempt`` posts a drain notice (``grace`` seconds of peer-ack budget,
  default the ``MPI4JAX_TPU_DRAIN_GRACE_S`` flag) and lets the collective
  proceed — the rank leaves gracefully at its next step boundary;
  ``corrupt`` overwrites the op's floating-point inputs with NaN (``nan``,
  default) or +Inf (``inf``) on the firing rank only.

Trigger decisions happen on the HOST at execution time (an ``io_callback``
probe threaded into the program with data dependencies), not at trace time:
``after=N`` keeps counting across reuses of one compiled program, which is
where real stragglers live.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Optional, Tuple

_VERBS = ("delay", "die", "hang", "corrupt", "preempt")
_KEYS = ("rank", "host", "op", "after", "secs", "grace")
_MODES = ("nan", "inf")

_GRAMMAR = (
    "expected 'verb[:arg]*' clauses joined by ';', verb in "
    f"{_VERBS}, args 'key=value' with key in {_KEYS} (plus a bare "
    f"mode in {_MODES} for corrupt; 'secs' only for delay, 'grace' "
    "only for preempt; 'rank' and 'host' are mutually exclusive), or "
    "the host-kill shorthand 'die-host:<h>[@<op#>]' — e.g. "
    "'delay:rank=1:op=allreduce:after=3:secs=2' or 'die-host:1@3'"
)


@dataclass(frozen=True)
class FaultClause:
    """One parsed fault clause (see module docstring for field semantics)."""

    verb: str
    mode: Optional[str] = None  # corrupt only: 'nan' | 'inf'
    rank: Optional[int] = None  # global rank; None = all ranks
    host: Optional[int] = None  # topology host id; None = no host scope
    op: Optional[str] = None    # lowercase dispatch op name; None = all ops
    after: int = 0
    secs: float = 1.0           # delay only
    grace: Optional[float] = None  # preempt only: peer-ack budget seconds

    def matches_op(self, opname: str) -> bool:
        return self.op is None or self.op == opname

    def canonical(self) -> str:
        """Canonical spec string; ``parse_fault_spec`` round-trips it
        (the ``die-host`` shorthand canonicalizes to its ``die:host=``
        long form)."""
        parts = [self.verb]
        if self.verb == "corrupt":
            parts.append(self.mode or "nan")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        if self.host is not None:
            parts.append(f"host={self.host}")
        if self.op is not None:
            parts.append(f"op={self.op}")
        if self.after:
            parts.append(f"after={self.after}")
        if self.verb == "delay":
            parts.append(f"secs={self.secs:g}")
        if self.verb == "preempt" and self.grace is not None:
            parts.append(f"grace={self.grace:g}")
        return ":".join(parts)


def _parse_clause(text: str) -> FaultClause:
    fields = [f.strip() for f in text.split(":")]
    verb = fields[0]
    if verb == "die-host":
        # shorthand: die-host:<h>[@<op#>] == die:host=<h>[:after=<op#>]
        if len(fields) != 2 or not fields[1]:
            raise ValueError(
                f"fault spec clause {text!r}: die-host takes exactly "
                f"'<host>[@<op#>]'; {_GRAMMAR}")
        h_s, sep, after_s = fields[1].partition("@")
        try:
            host = int(h_s)
            after = int(after_s) if sep else 0
        except ValueError as e:
            raise ValueError(
                f"fault spec clause {text!r}: bad die-host operand "
                f"{fields[1]!r}; {_GRAMMAR}") from e
        if host < 0 or after < 0:
            raise ValueError(
                f"fault spec clause {text!r}: host and op# must be >= 0")
        return FaultClause(verb="die", host=host, after=after)
    if verb not in _VERBS:
        raise ValueError(
            f"fault spec clause {text!r}: unknown verb {verb!r}; {_GRAMMAR}"
        )
    mode = None
    kw = {}
    for field in fields[1:]:
        if not field:
            raise ValueError(f"fault spec clause {text!r}: empty field; {_GRAMMAR}")
        if "=" not in field:
            if verb == "corrupt" and field in _MODES and mode is None:
                mode = field
                continue
            raise ValueError(
                f"fault spec clause {text!r}: bare field {field!r} is only "
                f"valid as a corrupt mode in {_MODES}; {_GRAMMAR}"
            )
        key, _, value = field.partition("=")
        key, value = key.strip(), value.strip()
        if key not in _KEYS:
            raise ValueError(
                f"fault spec clause {text!r}: unknown key {key!r}; {_GRAMMAR}"
            )
        if key in kw:
            raise ValueError(f"fault spec clause {text!r}: duplicate key {key!r}")
        try:
            if key == "rank":
                kw["rank"] = int(value)
            elif key == "host":
                kw["host"] = int(value)
            elif key == "after":
                kw["after"] = int(value)
            elif key == "secs":
                kw["secs"] = float(value)
            elif key == "grace":
                kw["grace"] = float(value)
            else:
                kw["op"] = value.lower()
        except ValueError as e:
            raise ValueError(
                f"fault spec clause {text!r}: bad value for {key}: {value!r}"
            ) from e
    if "rank" in kw and "host" in kw:
        raise ValueError(
            f"fault spec clause {text!r}: 'rank' and 'host' are mutually "
            "exclusive (a host clause already names every rank on that "
            "host)"
        )
    if kw.get("host") is not None and kw["host"] < 0:
        raise ValueError(f"fault spec clause {text!r}: host must be >= 0")
    if verb != "delay" and "secs" in kw:
        raise ValueError(
            f"fault spec clause {text!r}: 'secs' only applies to delay"
        )
    if verb != "preempt" and "grace" in kw:
        raise ValueError(
            f"fault spec clause {text!r}: 'grace' only applies to preempt"
        )
    if verb == "corrupt" and mode is None:
        mode = "nan"
    if kw.get("after", 0) < 0:
        raise ValueError(f"fault spec clause {text!r}: after must be >= 0")
    if kw.get("secs", 1.0) < 0:
        raise ValueError(f"fault spec clause {text!r}: secs must be >= 0")
    if kw.get("grace") is not None and kw["grace"] <= 0:
        raise ValueError(f"fault spec clause {text!r}: grace must be > 0")
    return FaultClause(verb=verb, mode=mode, **kw)


@functools.lru_cache(maxsize=32)
def parse_fault_spec(spec: str) -> Tuple[FaultClause, ...]:
    """Parse a ``MPI4JAX_TPU_FAULT_SPEC`` string into clauses.

    Raises ``ValueError`` (with the grammar) on malformed specs; '' -> ().
    """
    spec = spec.strip()
    if not spec:
        return ()
    return tuple(
        _parse_clause(c.strip()) for c in spec.split(";") if c.strip()
    )


def canonical_spec(clauses: Tuple[FaultClause, ...]) -> str:
    return ";".join(c.canonical() for c in clauses)


# one monitor-poll-sized nap at a time (not one giant sleep): a hung rank
# in a drill stays interruptible — ``_thread.interrupt_main`` (the elastic
# recovery's unblock path) and test harness timeouts both land between
# naps.  Patchable in tests so "forever" can be observed finitely.
_HANG_NAP_SECS = 1.0


def _hang_forever():  # pragma: no cover - exercised via drills/monkeypatch
    while True:
        time.sleep(_HANG_NAP_SECS)


# ---------------------------------------------------------------------------
# host-side trigger state
# ---------------------------------------------------------------------------


class _FaultState:
    """Per-process matching-call counters: (clause identity, rank) -> count.

    The count only advances for calls the clause matches (op and rank), so
    ``after=N`` means "the first N calls this fault WOULD hit run clean".
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.counts = {}

    def bump(self, clause: FaultClause, rank: int) -> int:
        key = (clause, rank)
        with self.lock:
            n = self.counts.get(key, 0) + 1
            self.counts[key] = n
        return n

    def reset(self) -> None:
        with self.lock:
            self.counts.clear()


_state = _FaultState()


def reset_fault_state() -> None:
    """Forget all per-rank trigger counts (test isolation)."""
    _state.reset()
    global _warned_no_topology
    _warned_no_topology = False


_warned_no_topology = False


def _rank_on_host(rank: int, host: int) -> bool:
    """Whether the declared ``MPI4JAX_TPU_TOPOLOGY`` spec maps ``rank``
    to ``host``.  No spec (or a rank past the spec's coverage) matches
    nothing — with a one-time warning, because a host-scoped drill that
    silently no-ops would report false confidence."""
    from ..utils import config

    counts = config.parse_topology_spec(config.topology_spec())
    if counts is None:
        global _warned_no_topology
        if not _warned_no_topology:
            _warned_no_topology = True
            warnings.warn(
                "fault spec uses a host-scoped clause but "
                "MPI4JAX_TPU_TOPOLOGY is not set — the clause matches no "
                "rank (set the topology spec so host ids are defined)",
                RuntimeWarning, stacklevel=3)
        return False
    edge = 0
    for h, c in enumerate(counts):
        edge += c
        if rank < edge:
            return h == host
    return False


def _fault_line(rank: int, text: str) -> None:
    print(f"r{rank} | FAULT | {text}", file=sys.stderr, flush=True)
    # injections are telemetry incidents too (metered; an events-tier
    # instant puts them on the merged timeline next to the collective
    # they disrupted).  Guarded — telemetry is optional under the
    # isolated test loader, and a fault probe must never die on
    # observability plumbing.
    try:
        from ..telemetry import journal
    except ImportError:
        return
    journal.incident("faults.injected", "fault", rank, text)


def probe_host(indexed_clauses, mpi_name: str, rank) -> int:
    """Host-side trigger: count, act (delay/die), and return the corrupt mask.

    ``indexed_clauses``: tuple of (bit, clause) for clauses whose ``op``
    matches the dispatching op.  Returns a bitmask with bit ``b`` set iff
    the corrupt clause at bit ``b`` fires for this rank on this call.
    """
    r = int(rank)
    mask = 0
    for bit, clause in indexed_clauses:
        if clause.rank is not None and clause.rank != r:
            continue
        if clause.host is not None and not _rank_on_host(r, clause.host):
            continue
        if _state.bump(clause, r) <= clause.after:
            continue
        if clause.verb == "delay":
            _fault_line(r, f"delay {clause.secs:g}s injected in {mpi_name} "
                           f"({clause.canonical()})")
            time.sleep(clause.secs)
        elif clause.verb == "die":
            _fault_line(r, f"die injected in {mpi_name} "
                           f"({clause.canonical()})")
            # the last chance to write a postmortem bundle: os._exit
            # skips every atexit/finally.  Guarded + armed-gated inside;
            # a fault probe must never die on observability plumbing.
            try:
                from ..telemetry import health as _health

                _health.maybe_postmortem(
                    f"fatal_fault: die injected in {mpi_name} on rank {r}")
            except Exception:
                pass
            sys.stderr.flush()
            os._exit(13)
        elif clause.verb == "hang":
            _fault_line(r, f"hang injected in {mpi_name} "
                           f"({clause.canonical()}) — sleeping forever")
            # bundle now, not later: the hung rank may be blocking
            # BEFORE its watchdog arm, so this is its one guaranteed
            # postmortem — with the fault incident in the ring tail,
            # which is what the postmortem CLI attributes the hang from
            try:
                from ..telemetry import health as _health

                _health.maybe_postmortem(
                    f"fault: hang injected in {mpi_name} on rank {r}")
            except Exception:
                pass
            sys.stderr.flush()
            _hang_forever()
        elif clause.verb == "preempt":
            _fault_line(r, f"preempt notice injected in {mpi_name} "
                           f"({clause.canonical()}) — drain at next "
                           "step boundary")
            # the SIGTERM-style path: post the drain and let the
            # collective proceed; the elastic loop executes the planned
            # shrink at its next step boundary (resilience/elastic.py)
            from .elastic import request_drain

            request_drain(clause.grace, rank=r)
        else:  # corrupt
            _fault_line(r, f"corrupt:{clause.mode} injected in {mpi_name} "
                           f"({clause.canonical()})")
            mask |= 1 << bit
    return mask
