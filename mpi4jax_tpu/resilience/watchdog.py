"""Collective watchdog: turn silent hangs into loud, diagnosable deaths.

In the SPMD multi-host model a single dead or stalled process leaves every
surviving process blocked *forever* inside its next collective — the classic
silent failure mode of production TPU training stacks (the job holds its
slice, burns no steps, and pages nobody).  The watchdog arms a host-side
monitor around each op's begin/end bracket (the same data-dependency
threading as the ``op_begin``/``op_end`` trace hooks, ops/_base.py
``_run_body``); when any collective stays in flight longer than
``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` seconds, it dumps every in-flight op on this
process (op name, call id, comm axes, elapsed) and kills the process through
the ``abort_if`` fail-fast path, so the scheduler can reschedule instead of
the job hanging.

Two implementations, chosen per availability:

- **native** (csrc/host_hooks.cc ``MpxWatchdogArm``/``MpxWatchdogDisarm``):
  registry and monitor thread live in C++ — they keep running even if every
  Python thread is wedged (e.g. the GIL is held by a stuck extension call);
  CPU backend with the hooks library built.
- **fallback** (this module): an ``io_callback`` pair updating a Python
  registry, watched by a daemon thread.  Collectives block with the GIL
  released, so the thread fires reliably in practice; works on any backend.

Arm is ordered *before* the collective by tying the op's inputs to the arm
token; disarm is tied *after* the first output — exactly the bracket the
runtime trace hooks use, so the elapsed time the diagnostics report is the
collective's true in-flight time on this host.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = [
    "arm_in_graph",
    "disarm_in_graph",
    "inflight_snapshot",
    "registry_empty",
    "set_on_timeout",
    "drain_registry",
    "suspend_expiries",
]

_POLL_INTERVAL = 0.1

# nesting depth of suspend_expiries() windows: while > 0 the monitor
# keeps tracking in-flight ops but treats none as expired.  Planned
# elastic reconfigurations (grow admission, graceful drain) hold the
# window open across their re-bootstrap + restore exchange — seconds of
# legitimate cross-rank skew that must not read as a hang.
_suspend_lock = threading.Lock()
_suspended = 0


class suspend_expiries:
    """Context manager: no watchdog expiry fires while any window is
    open (arms and disarms still track normally, so coverage resumes the
    moment the window closes)."""

    def __enter__(self):
        global _suspended
        with _suspend_lock:
            _suspended += 1
        return self

    def __exit__(self, *exc):
        global _suspended
        with _suspend_lock:
            _suspended = max(0, _suspended - 1)
        return False


def expiries_suspended() -> bool:
    with _suspend_lock:
        return _suspended > 0


def _telemetry_incident(meter_name, name, rank, detail=""):
    """Mirror a watchdog lifecycle event into the telemetry layer via the
    shared incident helper.  Guarded: the telemetry package is optional
    under the isolated test loader."""
    try:
        from ..telemetry import journal
    except ImportError:
        return
    journal.incident(meter_name, name, rank, detail)


def _default_on_timeout(entries, expired):
    """Dump per-rank in-flight diagnostics, then die via the abort path."""
    from .. import native

    for e in entries:
        native.host_line(
            e["rank"],
            f"WATCHDOG | in-flight: {e['opname']} (call {e['call_id']}, "
            f"axes={e['axes']}, elapsed {e['elapsed']:.2f}s)",
        )
    native.host_fatal(
        expired["rank"],
        f"collective watchdog: {expired['opname']} exceeded "
        f"{expired['timeout']:g}s (call {expired['call_id']}, "
        f"axes={expired['axes']})",
    )


class _Registry:
    """In-flight op registry + monitor thread (the Python fallback path).

    Keys are ``(call_id, rank)`` with a FIFO of start times per key — a trace
    site inside ``lax.fori_loop`` fires once per iteration with the same call
    id, and the data dependencies order iteration N+1's arm after iteration
    N's collective but not after N's disarm (the same aliasing the native
    trace hooks handle, csrc/host_hooks.cc ``begin_times``).
    """

    def __init__(self, on_timeout: Optional[Callable] = None,
                 clock=time.monotonic):
        self.lock = threading.Lock()
        self.entries = {}  # (call_id, rank) -> deque of (opname, axes, start, timeout)
        self.clock = clock
        self.on_timeout = on_timeout or _default_on_timeout
        self._thread = None

    def arm(self, opname: str, call_id: str, rank: int, axes: str,
            timeout: float) -> None:
        with self.lock:
            self.entries.setdefault((call_id, int(rank)), deque()).append(
                (opname, axes, self.clock(), float(timeout))
            )
            self._ensure_thread_locked()

    def disarm(self, call_id: str, rank: int) -> None:
        key = (call_id, int(rank))
        with self.lock:
            dq = self.entries.get(key)
            if dq:
                dq.popleft()
                if not dq:
                    del self.entries[key]

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._monitor, name="mpi4jax_tpu-watchdog", daemon=True
            )
            self._thread.start()

    def snapshot(self):
        """Diagnostic view of every in-flight op: list of dicts with opname,
        call_id, rank, axes, elapsed, timeout."""
        now = self.clock()
        with self.lock:
            return [
                {
                    "opname": opname, "call_id": call_id, "rank": rank,
                    "axes": axes, "elapsed": now - start, "timeout": timeout,
                }
                for (call_id, rank), dq in self.entries.items()
                for (opname, axes, start, timeout) in dq
            ]

    def check_expired(self):
        """One monitor scan; returns the expired snapshot entry or None
        (always None inside a ``suspend_expiries`` window — planned
        elastic reconfiguration, not a hang)."""
        if expiries_suspended():
            return None
        for e in self.snapshot():
            if e["elapsed"] > e["timeout"]:
                return e
        return None

    def empty(self) -> bool:
        with self.lock:
            return not self.entries

    def drain(self) -> int:
        """Forget every in-flight entry (epoch revocation: arms from
        collectives of a revoked world must not fire into the recovered
        job).  Returns the number of entries dropped."""
        with self.lock:
            n = sum(len(dq) for dq in self.entries.values())
            self.entries.clear()
        return n

    def drain_expired(self) -> int:
        """Forget only the entries whose timeout has elapsed (a claimed
        expiry): un-expired arms of unrelated concurrent collectives keep
        their coverage.  Returns the number of entries dropped."""
        now = self.clock()
        dropped = 0
        with self.lock:
            for key in list(self.entries):
                dq = self.entries[key]
                kept = deque(e for e in dq if now - e[2] <= e[3])
                dropped += len(dq) - len(kept)
                if kept:
                    self.entries[key] = kept
                else:
                    del self.entries[key]
        return dropped

    def _monitor(self) -> None:
        while True:
            time.sleep(_POLL_INTERVAL)
            expired = self.check_expired()
            if expired is not None:
                # the incident is journalled HERE, before the handler
                # runs: a handler that recovers (or kills) the process
                # must not be able to lose the expiry record, and a
                # replacement handler need not re-implement it
                _telemetry_incident(
                    "watchdog.expiries", "watchdog_expired",
                    expired["rank"],
                    f"{expired['opname']} call {expired['call_id']} "
                    f"exceeded {expired['timeout']:g}s",
                )
                # health-plane stall hook (telemetry/health.py): journal
                # the stall incident and write the postmortem bundle
                # while the in-flight registry + flight ring still show
                # the stuck op — also before the handler can abort
                try:
                    from ..telemetry import health as _health
                except ImportError:
                    pass
                else:
                    try:
                        _health.on_watchdog_expiry(expired)
                    except Exception:
                        pass
                self.on_timeout(self.snapshot(), expired)
                # only reachable with a non-fatal handler (the default
                # aborts the process): drop the EXPIRED entries — healthy
                # concurrent arms keep their coverage — and keep
                # monitoring; the handler's recovery (e.g. an elastic
                # shrink, which drains everything via revoke_epoch)
                # re-arms collectives of the NEW epoch under fresh entries
                self.drain_expired()


_registry = _Registry()


def registry_empty() -> bool:
    """True when no op is in flight in the Python-fallback registry."""
    return _registry.empty()


def inflight_snapshot():
    """Current in-flight ops in the Python-fallback registry (diagnostics)."""
    return _registry.snapshot()


# when True, arm/disarm skip the native C++ registry even where it is
# available: the C++ monitor always kills the process on expiry (its
# handler is not pluggable from Python), so a claimed recovery handler
# (elastic.run) needs the Python-fallback monitor to be the one watching
_force_fallback = False


def force_python_fallback(enable: bool) -> None:
    """Route watchdog arm/disarm through the Python-fallback registry
    even where the native C++ monitor is built.  Elastic recovery sets
    this for the duration of ``elastic.run`` (the native monitor cannot
    hand expiries to a Python handler); also useful in tests."""
    global _force_fallback
    _force_fallback = bool(enable)
    # arm sites are baked into traced programs per implementation: retrace
    from ..utils import config

    config.bump_config_epoch()


def native_active() -> bool:
    """Whether arm/disarm currently use the native C++ registry."""
    from .. import native

    return native.watchdog_supported() and not _force_fallback


def set_on_timeout(handler: Optional[Callable]) -> None:
    """Replace the expiry handler of the LIVE Python-fallback monitor at
    runtime (``None`` restores the default dump-and-die handler).

    ``handler(entries, expired)`` receives the full in-flight snapshot
    plus the expired entry, after the expiry was journalled as a
    telemetry incident.  A handler that returns (instead of killing the
    process) keeps the monitor alive: the expired entries are drained and
    monitoring continues — the hook elastic recovery
    (``resilience/elastic.py``) claims expiries through, without
    recreating the registry.  Only the Python-fallback monitor is
    pluggable; the native C++ monitor always dies loudly (its registry is
    not visible from Python), so elastic drills force the fallback.
    """
    _registry.on_timeout = handler or _default_on_timeout


def drain_registry() -> int:
    """Drop every in-flight entry of the Python-fallback registry (epoch
    revocation / test isolation); returns the count dropped."""
    return _registry.drain()


# ---------------------------------------------------------------------------
# in-graph arm/disarm
# ---------------------------------------------------------------------------


def _io_callback(fn, rank):
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    return io_callback(
        fn, jax.ShapeDtypeStruct((), jnp.uint32), rank, ordered=False
    )


def arm_in_graph(mpi_name: str, call_id: str, comm, rank, timeout: float):
    """Arm the watchdog for one collective; returns a u32 the op's inputs
    must be tied to (so arming precedes the collective's execution)."""
    from .. import native

    # metered HERE — the shared entry of both implementations — so the
    # native C++ path counts too (trace-time semantics: one per armed
    # collective site; the C++ registry's run-time arms are not visible
    # from Python)
    try:
        from ..telemetry import core as _tcore
    except ImportError:
        pass
    else:
        _tcore.meter("watchdog.arms")
    axes = repr(comm.axes)
    if native.watchdog_supported() and not _force_fallback:
        return native.watchdog_arm(mpi_name, call_id, rank, axes, timeout)

    import numpy as np

    def _arm(r):
        _registry.arm(mpi_name, call_id, int(r), axes, timeout)
        return np.uint32(r)

    import jax.numpy as jnp

    return _io_callback(_arm, jnp.asarray(rank, jnp.uint32))


def disarm_in_graph(mpi_name: str, call_id: str, comm, rank, dep):
    """Disarm after the collective: ``dep`` (the op's first output) orders
    the callback after completion."""
    from .. import native

    if native.watchdog_supported() and not _force_fallback:
        return native.watchdog_disarm(call_id, rank, dep)

    import numpy as np

    def _disarm(r):
        _registry.disarm(call_id, int(r))
        return np.uint32(r)

    import jax.numpy as jnp

    return _io_callback(_disarm, native._tie(jnp.asarray(rank, jnp.uint32), dep))
