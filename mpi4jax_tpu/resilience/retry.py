"""Exponential-backoff retry with jitter and a total deadline.

Built for the multi-host bootstrap (``init_distributed``'s
``jax.distributed`` coordinator connection — workers race the coordinator
process at job start, and transient refusals are the norm on preempted pods),
but generic: any callable whose failures are transient.

Full-jitter backoff (sleep ~ U(0, min(base * factor^n, max_delay))): the
standard cure for reconnection stampedes when hundreds of workers retry the
same coordinator.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["retry_with_backoff"]

# transient-looking failure classes for a network rendezvous; TypeError /
# ValueError and friends (programming errors) propagate immediately
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError,
    OSError,
    RuntimeError,
    TimeoutError,
)


def retry_with_backoff(
    fn: Callable,
    *,
    what: str = "operation",
    deadline: float = 300.0,
    max_attempts: Optional[int] = None,
    base_delay: float = 1.0,
    max_delay: float = 30.0,
    factor: float = 2.0,
    jitter: bool = True,
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
    giveup: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Call ``fn`` until it succeeds, a non-retryable error escapes, the
    total ``deadline`` (seconds) elapses, or ``max_attempts`` calls have
    failed (``None``/0 = attempts bounded only by the deadline).

    On either bound, raises ``RuntimeError`` naming ``what``, the attempt
    count, and the elapsed time, chained from the last underlying error —
    the "clear error at the deadline" a stuck bootstrap owes its operator.
    ``giveup(exc) -> True`` re-raises immediately even for a retryable class
    (escape hatch for permanent failures that share an exception type with
    transient ones).  ``sleep``/``clock`` are injectable for tests.
    """
    if deadline <= 0:
        raise ValueError(f"deadline must be positive, got {deadline}")
    if max_attempts is not None and max_attempts < 0:
        raise ValueError(f"max_attempts must be >= 0, got {max_attempts}")
    start = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            if giveup is not None and giveup(e):
                raise
            attempt += 1
            elapsed = clock() - start
            if max_attempts and attempt >= max_attempts:
                raise RuntimeError(
                    f"{what} failed after {attempt} attempt(s) over "
                    f"{elapsed:.1f}s (max_attempts {max_attempts}); last "
                    f"error: {type(e).__name__}: {e}"
                ) from e
            if elapsed >= deadline:
                raise RuntimeError(
                    f"{what} failed after {attempt} attempt(s) over "
                    f"{elapsed:.1f}s (deadline {deadline:g}s); last error: "
                    f"{type(e).__name__}: {e}"
                ) from e
            delay = min(base_delay * factor ** (attempt - 1), max_delay)
            if jitter:
                delay = random.uniform(0, delay)
            # never sleep past the deadline: fail at the promised time
            delay = min(delay, deadline - elapsed)
            if delay > 0:
                sleep(delay)
