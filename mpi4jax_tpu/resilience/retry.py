"""Exponential-backoff retry with jitter and a total deadline.

Built for the multi-host bootstrap (``init_distributed``'s
``jax.distributed`` coordinator connection — workers race the coordinator
process at job start, and transient refusals are the norm on preempted
pods) and the elastic agreement star (``coordinator_exchange_suspects``:
k-1 survivors dial one listener at once), but generic: any callable whose
failures are transient.

Full-jitter backoff (sleep ~ U(0, min(base * factor^n, max_delay))): the
standard cure for reconnection stampedes when hundreds of workers retry
the same coordinator.  :func:`backoff_delay` is the pure ceiling the
jitter draws under — the tests pin its bounds directly.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["backoff_delay", "retry_with_backoff"]

# transient-looking failure classes for a network rendezvous; TypeError /
# ValueError and friends (programming errors) propagate immediately
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError,
    OSError,
    RuntimeError,
    TimeoutError,
)


def backoff_delay(attempt: int, *, base_delay: float = 1.0,
                  factor: float = 2.0, max_delay: float = 30.0) -> float:
    """The backoff CEILING after failed attempt ``attempt`` (1-based):
    ``min(base_delay * factor**(attempt - 1), max_delay)``.

    This is the explicit cap the full-jitter sleep draws under —
    ``U(0, backoff_delay(n))`` — so the jitter bound is pure and
    testable: no sleep ever exceeds ``max_delay`` regardless of how
    many attempts have failed (``factor**n`` overflows long before an
    unbounded ceiling would matter; the min saturates first).
    """
    if attempt < 1:
        raise ValueError(f"attempt must be >= 1, got {attempt}")
    if base_delay < 0:
        raise ValueError(f"base_delay must be >= 0, got {base_delay}")
    if factor < 1:
        raise ValueError(f"factor must be >= 1, got {factor}")
    if max_delay <= 0:
        raise ValueError(f"max_delay must be positive, got {max_delay}")
    # compare in log space first: factor ** (attempt - 1) overflows to
    # inf for large attempt counts, and inf * 0.0 (base_delay 0) is NaN
    if base_delay == 0:
        return 0.0
    try:
        raw = base_delay * factor ** (attempt - 1)
    except OverflowError:
        return max_delay
    return min(raw, max_delay)


def retry_with_backoff(
    fn: Callable,
    *,
    what: str = "operation",
    deadline: float = 300.0,
    max_attempts: Optional[int] = None,
    base_delay: float = 1.0,
    max_delay: float = 30.0,
    factor: float = 2.0,
    jitter: bool = True,
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
    giveup: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
):
    """Call ``fn`` until it succeeds, a non-retryable error escapes, the
    total ``deadline`` (seconds) elapses, or ``max_attempts`` calls have
    failed (``None``/0 = attempts bounded only by the deadline).

    On either bound, raises ``RuntimeError`` naming ``what``, the attempt
    count, the elapsed time, and the total time spent sleeping between
    attempts, chained from the last underlying error — the "clear error
    at the deadline" a stuck bootstrap owes its operator (a large waited
    fraction says the budget went to backoff; a small one says ``fn``
    itself is slow).  ``giveup(exc) -> True`` re-raises immediately even
    for a retryable class (escape hatch for permanent failures that
    share an exception type with transient ones).  ``sleep``/``clock``
    are injectable for tests.

    Each sleep is full-jitter — drawn uniformly from
    ``[0, backoff_delay(attempt))`` — and never extends past the
    deadline, so the promised failure time holds exactly.
    """
    if deadline <= 0:
        raise ValueError(f"deadline must be positive, got {deadline}")
    if max_attempts is not None and max_attempts < 0:
        raise ValueError(f"max_attempts must be >= 0, got {max_attempts}")
    # validate the backoff shape up front: a bad factor must fail the
    # FIRST call loudly, not attempt 40 sleeps in
    backoff_delay(1, base_delay=base_delay, factor=factor,
                  max_delay=max_delay)
    start = clock()
    attempt = 0
    waited = 0.0
    while True:
        try:
            return fn()
        except retryable as e:
            if giveup is not None and giveup(e):
                raise
            attempt += 1
            elapsed = clock() - start
            if max_attempts and attempt >= max_attempts:
                raise RuntimeError(
                    f"{what} failed after {attempt} attempt(s) over "
                    f"{elapsed:.1f}s ({waited:.1f}s of it waiting between "
                    f"attempts; max_attempts {max_attempts}); last "
                    f"error: {type(e).__name__}: {e}"
                ) from e
            if elapsed >= deadline:
                raise RuntimeError(
                    f"{what} failed after {attempt} attempt(s) over "
                    f"{elapsed:.1f}s ({waited:.1f}s of it waiting between "
                    f"attempts; deadline {deadline:g}s); last error: "
                    f"{type(e).__name__}: {e}"
                ) from e
            delay = backoff_delay(attempt, base_delay=base_delay,
                                  factor=factor, max_delay=max_delay)
            if jitter:
                delay = random.uniform(0, delay)
            # never sleep past the deadline: fail at the promised time
            delay = min(delay, deadline - elapsed)
            if delay > 0:
                sleep(delay)
                waited += delay
