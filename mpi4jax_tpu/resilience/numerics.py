"""Numeric guards: fail fast on NaN/Inf flowing through collectives.

A NaN that enters an ``allreduce`` poisons every rank's copy of the result in
one hop; by the time a loss turns NaN the broken collective is thousands of
steps in the past.  With ``MPI4JAX_TPU_CHECK_NUMERICS=1`` every collective
checks its floating-point inputs and outputs for non-finite values and kills
the job through the ``abort_if`` fail-fast path (native.py) with an
op-identifying message — the data-dependent guard the reference's
``abort_on_error`` provided for MPI error codes, extended to the values
themselves.

Off by default, and zero-cost when off: the guard builder is simply never
called (ops/_base.py consults ``resilience.runtime.plan_for`` which returns
``None``), so the lowered HLO is byte-identical to an uninstrumented build —
pinned by tests/test_resilience.py.
"""

from __future__ import annotations

from functools import reduce

__all__ = ["guard_values"]


def guard_values(mpi_name: str, call_id: str, rank, values, stage: str):
    """Emit one ``abort_if`` over the non-finite predicate of ``values``.

    ``stage`` is ``"input"`` or ``"output"`` (named in the fatal message).
    Integer/bool arrays are skipped (always finite).  No-op (returns None)
    when nothing is checkable.
    """
    import jax.numpy as jnp

    from .. import native

    preds = [
        jnp.any(~jnp.isfinite(v))
        for v in values
        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)
    ]
    if not preds:
        return None
    from ..telemetry.core import meter

    meter("numeric_guard.sites")  # instrumented sites; trips metered in
    #                               native.abort_if's fallback callback
    pred = reduce(jnp.logical_or, preds)
    return native.abort_if(
        pred,
        rank,
        f"{mpi_name}: non-finite {stage} detected "
        f"(MPI4JAX_TPU_CHECK_NUMERICS, call {call_id})",
    )
