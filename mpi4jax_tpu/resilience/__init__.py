"""Resilience subsystem: fail loudly instead of hanging silently.

In the SPMD/multi-host model a single dead or stalled process leaves every
surviving process blocked *forever* inside its next collective — the classic
silent failure mode of production TPU training stacks.  The reference design
ships fail-fast semantics at the bridge level (``abort_on_error``, mirrored
here as ``native.abort_if``) but nothing above it; this package is the layer
above:

- :mod:`.watchdog` — a host-side monitor armed/disarmed around each op's
  begin/end bracket; a collective exceeding ``MPI4JAX_TPU_WATCHDOG_TIMEOUT``
  seconds dumps per-rank in-flight diagnostics and kills the process;
- :mod:`.faultinject` — deterministic delay/die/corrupt injection from a
  parsed ``MPI4JAX_TPU_FAULT_SPEC``, intercepting at the single shared
  dispatch point (``ops/_base.py``) so all 12 ops are injectable;
- :mod:`.numerics` — opt-in ``MPI4JAX_TPU_CHECK_NUMERICS`` NaN/Inf guards on
  each collective's inputs/outputs, tied into ``abort_if``;
- :mod:`.retry` — exponential-backoff (full-jitter) retry with a total
  deadline, used by ``init_distributed``'s coordinator connection and the
  elastic agreement star;
- :mod:`.elastic` — the RECOVERY half (ULFM-style shrink-and-resume):
  communication epochs, coordinator-mediated failure agreement (with
  gossip degradation), the :class:`~.elastic.ShardStore` in-memory sharded
  checkpoint with topology-aware striped replication (every replica on a
  different host than its owner), and :func:`~.elastic.run`, the training
  loop that survives rank — and whole-host — loss;
- :mod:`.drill` — the deterministic chaos-drill harness: simulated-rank
  kill patterns (single rank, host row, coordinator, cascading double
  fault) asserting the agreement + restore invariants at drill scale;
- :mod:`.runtime` — config resolution and the per-op :class:`~.runtime.Plan`
  the dispatch layer consults.  All features default OFF, and when off the
  lowered HLO is byte-identical to an uninstrumented build.

Failure model, spec grammar, recovery protocol, and knobs:
docs/resilience.md.
"""

from . import elastic  # noqa: F401
from .elastic import (  # noqa: F401
    RankFailure,
    ShardStore,
    coordinator_agreement,
    gossip_agreement,
    install_preemption_handler,
    neighbor_placement,
    request_drain,
    stripe_placement,
)
from .faultinject import (  # noqa: F401
    FaultClause,
    canonical_spec,
    parse_fault_spec,
    reset_fault_state,
)
from .retry import backoff_delay, retry_with_backoff  # noqa: F401
from .runtime import (  # noqa: F401
    cache_token,
    plan_for,
    reset_overrides,
    set_check_numerics,
    set_fault_spec,
    set_watchdog_timeout,
)
from .watchdog import (  # noqa: F401
    drain_registry,
    inflight_snapshot,
    registry_empty,
    set_on_timeout,
)

__all__ = [
    "FaultClause",
    "parse_fault_spec",
    "canonical_spec",
    "reset_fault_state",
    "backoff_delay",
    "retry_with_backoff",
    "plan_for",
    "cache_token",
    "set_watchdog_timeout",
    "set_fault_spec",
    "set_check_numerics",
    "set_on_timeout",
    "reset_overrides",
    "inflight_snapshot",
    "registry_empty",
    "drain_registry",
    "elastic",
    "RankFailure",
    "ShardStore",
    "stripe_placement",
    "neighbor_placement",
    "gossip_agreement",
    "coordinator_agreement",
    "request_drain",
    "install_preemption_handler",
]
