"""Resilience subsystem: fail loudly instead of hanging silently.

In the SPMD/multi-host model a single dead or stalled process leaves every
surviving process blocked *forever* inside its next collective — the classic
silent failure mode of production TPU training stacks.  The reference design
ships fail-fast semantics at the bridge level (``abort_on_error``, mirrored
here as ``native.abort_if``) but nothing above it; this package is the layer
above:

- :mod:`.watchdog` — a host-side monitor armed/disarmed around each op's
  begin/end bracket; a collective exceeding ``MPI4JAX_TPU_WATCHDOG_TIMEOUT``
  seconds dumps per-rank in-flight diagnostics and kills the process;
- :mod:`.faultinject` — deterministic delay/die/corrupt injection from a
  parsed ``MPI4JAX_TPU_FAULT_SPEC``, intercepting at the single shared
  dispatch point (``ops/_base.py``) so all 12 ops are injectable;
- :mod:`.numerics` — opt-in ``MPI4JAX_TPU_CHECK_NUMERICS`` NaN/Inf guards on
  each collective's inputs/outputs, tied into ``abort_if``;
- :mod:`.retry` — exponential-backoff (full-jitter) retry with a total
  deadline, used by ``init_distributed``'s coordinator connection;
- :mod:`.elastic` — the RECOVERY half (ULFM-style shrink-and-resume):
  communication epochs, failure agreement, the :class:`~.elastic.ShardStore`
  in-memory sharded checkpoint with k-redundant neighbor replication, and
  :func:`~.elastic.run`, the training loop that survives rank loss;
- :mod:`.runtime` — config resolution and the per-op :class:`~.runtime.Plan`
  the dispatch layer consults.  All features default OFF, and when off the
  lowered HLO is byte-identical to an uninstrumented build.

Failure model, spec grammar, recovery protocol, and knobs:
docs/resilience.md.
"""

from . import elastic  # noqa: F401
from .elastic import (  # noqa: F401
    RankFailure,
    ShardStore,
    install_preemption_handler,
    request_drain,
)
from .faultinject import (  # noqa: F401
    FaultClause,
    canonical_spec,
    parse_fault_spec,
    reset_fault_state,
)
from .retry import retry_with_backoff  # noqa: F401
from .runtime import (  # noqa: F401
    cache_token,
    plan_for,
    reset_overrides,
    set_check_numerics,
    set_fault_spec,
    set_watchdog_timeout,
)
from .watchdog import (  # noqa: F401
    drain_registry,
    inflight_snapshot,
    registry_empty,
    set_on_timeout,
)

__all__ = [
    "FaultClause",
    "parse_fault_spec",
    "canonical_spec",
    "reset_fault_state",
    "retry_with_backoff",
    "plan_for",
    "cache_token",
    "set_watchdog_timeout",
    "set_fault_spec",
    "set_check_numerics",
    "set_on_timeout",
    "reset_overrides",
    "inflight_snapshot",
    "registry_empty",
    "drain_registry",
    "elastic",
    "RankFailure",
    "ShardStore",
    "request_drain",
    "install_preemption_handler",
]
