"""Resilience runtime glue: config resolution and the per-op dispatch plan.

``ops/_base.py _run_body`` — the single point all 12 ops flow through —
asks ``plan_for(opname)`` what to do around each collective.  The answer is
``None`` when every resilience feature is off (the default): the op body
runs untouched and the lowered HLO is byte-identical to an uninstrumented
build.  Otherwise a :class:`Plan` brackets the op:

- ``before``: fault-injection probe (delay/die/corrupt — faultinject.py),
  then the input numeric guard (numerics.py), then watchdog arm
  (watchdog.py), each threaded into the program with data dependencies so
  ordering survives XLA scheduling;
- ``after``: watchdog disarm tied to the op's first output, then the output
  numeric guard.

Configuration layers: programmatic overrides (``set_*`` below, for tests and
embedding frameworks) shadow the environment variables
(``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` / ``_FAULT_SPEC`` / ``_CHECK_NUMERICS``,
utils/config.py).  ``cache_token()`` folds the effective configuration into
the compiled-program cache keys (ops/_base.py eager cache, parallel/region.py
spmd cache), so toggling a feature retraces instead of silently serving a
stale program.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..utils import config
from .faultinject import (
    FaultClause,
    canonical_spec,
    parse_fault_spec,
    probe_host,
)

__all__ = [
    "Plan",
    "plan_for",
    "cache_token",
    "set_watchdog_timeout",
    "set_fault_spec",
    "set_check_numerics",
]

_UNSET = object()

_watchdog_override = _UNSET
_fault_override = _UNSET
_numerics_override = _UNSET


def set_watchdog_timeout(seconds) -> None:
    """Override ``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` (``None``/0 disables;
    pass ``config.watchdog_timeout`` semantics).  ``reset_overrides()``
    returns control to the environment."""
    global _watchdog_override
    if not seconds:
        _watchdog_override = None
        config.bump_config_epoch()
        return
    val = float(seconds)
    # mirror the env path's validation (config.parse_env_float): a negative
    # timeout would declare the first collective hung on the monitor's
    # first scan and kill a healthy job; NaN would silently disable.
    # ``not (val > 0)`` catches both.
    if not (val > 0):
        raise ValueError(f"watchdog timeout must be > 0 seconds, got {seconds!r}")
    _watchdog_override = val
    config.bump_config_epoch()


def set_fault_spec(spec: Optional[str]) -> None:
    """Override ``MPI4JAX_TPU_FAULT_SPEC`` ('' or None disables).  The spec
    is validated immediately (ValueError on bad grammar)."""
    global _fault_override
    parse_fault_spec(spec or "")
    _fault_override = (spec or "").strip()
    config.bump_config_epoch()


def set_check_numerics(enabled) -> None:
    """Override ``MPI4JAX_TPU_CHECK_NUMERICS``."""
    global _numerics_override
    _numerics_override = bool(enabled)
    config.bump_config_epoch()


def reset_overrides() -> None:
    """Drop every programmatic override (environment variables rule again)."""
    global _watchdog_override, _fault_override, _numerics_override
    _watchdog_override = _fault_override = _numerics_override = _UNSET
    config.bump_config_epoch()


def effective_watchdog_timeout() -> Optional[float]:
    if _watchdog_override is not _UNSET:
        return _watchdog_override
    return config.watchdog_timeout()


def effective_fault_clauses() -> Tuple[FaultClause, ...]:
    raw = _fault_override if _fault_override is not _UNSET else config.fault_spec()
    return parse_fault_spec(raw)


def effective_check_numerics() -> bool:
    if _numerics_override is not _UNSET:
        return _numerics_override
    return config.check_numerics()


def cache_token() -> tuple:
    """Hashable fingerprint of the effective resilience configuration —
    belongs in every compiled-program cache key that caches op lowerings.

    The elastic token (resilience/elastic.py) rides here: the
    communication epoch plus the declared elastic knobs (grow, fail
    unit, drain grace, port span).  Advancing the epoch after a shrink
    or a grow changes this token, which changes both program-cache keys
    — every executable traced against the revoked world becomes
    unreachable and the next call re-traces at the new size.  A job that
    never churns, with every elastic knob at its default, carries the
    constant 0 and its keys match a build without the elastic layer
    engaged.
    """
    from .elastic import elastic_cache_token
    from .watchdog import _force_fallback

    return (
        effective_watchdog_timeout(),
        canonical_spec(effective_fault_clauses()),
        effective_check_numerics(),
        # the watchdog backend choice is baked into traced arm/disarm
        # callbacks, so flipping it must retrace too
        _force_fallback,
        elastic_cache_token(),
    )


class Plan:
    """What to weave around one op dispatch (trace-time object)."""

    __slots__ = ("clauses", "timeout", "numerics")

    def __init__(self, clauses, timeout, numerics):
        self.clauses = clauses      # ((bit, FaultClause), ...) matching this op
        self.timeout = timeout      # watchdog seconds or None
        self.numerics = numerics    # bool

    def before(self, mpi_name, call_id, comm, arrays, token):
        """Instrument the op's inputs; returns (arrays, token)."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        from .. import native
        from ..ops.token import Token
        from . import watchdog as wd
        from .numerics import guard_values

        rank = comm.global_rank()

        # Array-less, token-less dispatches (a bare ``barrier()``) give the
        # ties below nothing to anchor to — the probe/arm callbacks would
        # float unordered relative to the collective, and an orphaned arm
        # could outlive its disarm and kill a healthy job.  Synthesize the
        # token: the op body consumes it, restoring the data dependency.
        if not arrays and token is None and (self.clauses or self.timeout is not None):
            token = Token(jnp.zeros((), jnp.uint32))

        if self.clauses:
            clauses = self.clauses

            def _probe(r, _name=mpi_name):
                import numpy as np

                return np.uint32(probe_host(clauses, _name, int(r)))

            mask = io_callback(
                _probe, jax.ShapeDtypeStruct((), jnp.uint32),
                jnp.asarray(rank, jnp.uint32), ordered=False,
            )
            # delay/die must precede the collective: tie every input (and
            # the token, which is the only handle for array-less ops like
            # barrier) to the probe's completion
            arrays = tuple(native._tie(a, mask) for a in arrays)
            if token is not None:
                token = Token(native._tie(token.value, mask))
            arrays = self._apply_corrupt(arrays, mask)

        if self.numerics:
            guard_values(mpi_name, call_id, rank, arrays, "input")

        if self.timeout is not None:
            armed = wd.arm_in_graph(mpi_name, call_id, comm, rank, self.timeout)
            arrays = tuple(native._tie(a, armed) for a in arrays)
            if token is not None:
                token = Token(native._tie(token.value, armed))

        return arrays, token

    def _apply_corrupt(self, arrays, mask):
        import jax.numpy as jnp

        out = list(arrays)
        for bit, clause in self.clauses:
            if clause.verb != "corrupt":
                continue
            fired = ((mask >> bit) & 1) == 1
            fill = jnp.nan if clause.mode == "nan" else jnp.inf
            out = [
                jnp.where(fired, jnp.full_like(a, fill), a)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact) else a
                for a in out
            ]
        return tuple(out)

    def after(self, mpi_name, call_id, comm, dep, results):
        """Instrument the op's outputs (``dep`` = first output's array)."""
        from ..ops.token import Token
        from . import watchdog as wd
        from .numerics import guard_values

        rank = comm.global_rank()
        if self.timeout is not None:
            wd.disarm_in_graph(mpi_name, call_id, comm, rank, dep)
        if self.numerics:
            values = [r.value if isinstance(r, Token) else r for r in results]
            guard_values(mpi_name, call_id, rank, values, "output")


# plan_for memo: Plans are stateless across dispatches (before/after close
# over nothing mutable), so one Plan per (config stamp, opname) serves
# every dispatch until the configuration changes — the per-traced-op
# watchdog-float/fault-spec/numerics re-parsing leaves the hot path.
_plan_memo: list = [None, {}]


def plan_for(opname: str) -> Optional[Plan]:
    """The resilience plan for one op dispatch, or ``None`` when every
    feature is off (the zero-cost default — no graph change at all)."""
    stamp = config.config_stamp()
    if _plan_memo[0] != stamp:
        # publish the stamp LAST (a concurrent reader must never pair the
        # new stamp with the previous memo dict)
        _plan_memo[1] = {}
        _plan_memo[0] = stamp
    memo = _plan_memo[1]
    if opname in memo:
        return memo[opname]
    timeout = effective_watchdog_timeout()
    numerics = effective_check_numerics()
    clauses = tuple(
        (bit, c)
        for bit, c in enumerate(effective_fault_clauses())
        if c.matches_op(opname)
    )
    plan = (None if timeout is None and not numerics and not clauses
            else Plan(clauses, timeout, numerics))
    memo[opname] = plan
    return plan
