"""Persistent compile-cache key derivation (pure — no jax).

The on-disk compiled-program cache (diskcache.py) must produce the SAME
key for the same program on every rank of a multi-host job and across
process restarts, and a DIFFERENT key whenever anything that shapes the
lowered program moves.  A key is the SHA-256 over the canonical forms
of:

- the **jaxpr fingerprint** — the traced program itself (shapes, dtypes,
  the collective structure, every trace-shaping flag's effect);
- the **mesh/topology descriptor** — device kind, mesh shape and axis
  names, process count, and the host-topology override (the same jaxpr
  compiled for a different physical partition is a different artifact);
- the **full dynamic cache token** — the flag half of the in-memory
  program-cache keys (ops/_base.dynamic_cache_token): anything that
  retraces in memory must miss on disk too;
- the **version tuple** — jax, jaxlib, libtpu (when present), and this
  package: serialized executables are not portable across compilers.

Canonicalization is deliberately dumb and total: every structure the
token can contain (nested tuples, strings, numbers, None, the interned
hash-once wrappers of the dispatch fast path) renders to one
deterministic string.  Objects with unstable ``repr``s (anything showing
an ``0x...`` address) are rejected loudly rather than silently keyed by
process-local identity.
"""

from __future__ import annotations

import hashlib
import re

# the artifact/layout version: bump when the serialized payload format or
# the canonicalization below changes incompatibly (old entries then
# simply never match and age out via LRU eviction)
KEY_SCHEMA = "mpx-aot-v1"

_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def canonical(obj) -> str:
    """Deterministic string form of a cache-key part.

    Handles the shapes that actually occur in the dynamic token: scalars,
    strings, None, nested tuples/lists, dicts (sorted by key), and the
    dispatch fast path's hash-once ``_Interned`` wrappers (unwrapped via
    their ``key`` attribute).  Raises ``TypeError`` on anything whose
    repr carries a memory address — a process-local identity must never
    leak into a cross-process key.
    """
    # the interned wrapper (ops/_base._Interned) and anything else that
    # exposes a stable `.key` payload canonicalizes through it
    key = getattr(obj, "key", None)
    if key is not None and not isinstance(obj, (str, bytes, dict)):
        return canonical(key)
    if obj is None or isinstance(obj, (bool, int, float)):
        return repr(obj)
    if isinstance(obj, str):
        return repr(obj)
    if isinstance(obj, bytes):
        return "b:" + hashlib.sha256(obj).hexdigest()
    if isinstance(obj, (tuple, list)):
        return "(" + ",".join(canonical(x) for x in obj) + ")"
    if isinstance(obj, (set, frozenset)):
        return "{" + ",".join(sorted(canonical(x) for x in obj)) + "}"
    if isinstance(obj, dict):
        return ("{" + ",".join(
            f"{canonical(k)}:{canonical(v)}" for k, v in
            sorted(obj.items(), key=lambda kv: canonical(kv[0]))
        ) + "}")
    text = repr(obj)
    if _ADDR_RE.search(text):
        raise TypeError(
            f"cannot derive a stable cache key from {type(obj).__name__} "
            f"(repr carries a memory address): {text[:80]}"
        )
    return f"{type(obj).__name__}:{text}"


def fingerprint(text) -> str:
    """SHA-256 hex digest of a program text (jaxpr pretty-print or
    StableHLO).  Accepts str or bytes."""
    if isinstance(text, str):
        text = text.encode()
    return hashlib.sha256(text).hexdigest()


def derive_key(jaxpr_fingerprint: str, mesh_descriptor, dynamic_token,
               versions) -> str:
    """The persistent cache key: SHA-256 over the canonical parts.

    Returns a 64-char hex string — also the artifact's file name stem
    (diskcache.py shards on the first two chars).
    """
    parts = "\n".join((
        KEY_SCHEMA,
        str(jaxpr_fingerprint),
        canonical(mesh_descriptor),
        canonical(dynamic_token),
        canonical(versions),
    ))
    return hashlib.sha256(parts.encode()).hexdigest()
