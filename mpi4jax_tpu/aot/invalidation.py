"""Pinned-program staleness: capture the world once, refuse it moved.

A :class:`~.pinning.PinnedProgram` deliberately does NONE of the per-call
work the dispatch fast path still pays — no flag parsing, no cache-key
hashing, no program-cache lookup.  The price of that bargain is that a
pinned executable can silently serve **old-world code**: a config flag
flipped after the pin (different algorithm, different resilience plan,
different telemetry bracketing) or an elastic epoch advance (the world
shrank/grew; the program's mesh and group tables address dead ranks)
would execute without anyone noticing — exactly the failure mode the
program-cache key folding exists to prevent.

So pinning reuses the same revocation machinery, inverted: instead of
folding the world into a key that is REBUILT per call, a
:class:`WorldStamp` captures the world ONCE at pin time —

- the configuration stamp (the ``utils/config.config_stamp`` shape): the
  programmatic-override epoch plus the raw (unparsed) environment
  fingerprint of every declared flag EXCEPT the storage-only
  compile-cache knobs — retuning where artifacts are stored must not
  revoke live programs;
- the elastic communication epoch (``resilience/elastic.current_epoch``)
  — every ``advance_epoch`` also bumps the config epoch, but the epoch
  is kept separately so the error can say *which* world moved;

and validation is two comparisons: an int (almost always unequal on any
programmatic change, checked first) and a tuple of raw strings.  No
parsing, no hashing, no dict lookups beyond the ``os.environ`` reads the
fingerprint itself is made of.

A failed check raises :class:`StaleProgramError` tagged ``MPX129``
(``mpx.analyze`` converts the raise into a finding; the message names
the stale half and the re-pin recipe).  Staleness follows the WORLD,
not the program: restoring the exact captured configuration (flip a
flag and flip it back) legitimately revalidates the stamp — same stamp,
same trace.  An epoch advance, by contrast, is permanent (epochs are
monotonic): only a re-pin (``PinnedProgram.repin`` / ``mpx.compile``)
re-enters the new world.

Pure Python (no jax): the whole module runs under the isolated test
loader (tests/test_aot_pure.py).
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils import config
from ..analysis.report import mpx_error

# Flags that only decide where compiled artifacts are STORED — they never
# shape a trace, so retuning them must not revoke live pinned programs
# (a long-running server enabling the cache dir for future pins would
# otherwise stale its serving step for nothing).
STORAGE_ONLY_FLAGS = (
    "MPI4JAX_TPU_COMPILE_CACHE_DIR",
    "MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES",
)

# Like the storage-only knobs, the C++ fast-path toggle never shapes a
# trace — it only decides HOW an already-compiled pin is driven
# (aot/fastpath.py).  Flipping it affects future pins' call path, not
# the validity of live ones, so it must not revoke them either.
DISPATCH_ONLY_FLAGS = ("MPI4JAX_TPU_CPP_DISPATCH",)

_WORLD_FLAG_NAMES = tuple(
    n for n in config.FLAG_NAMES
    if n not in STORAGE_ONLY_FLAGS + DISPATCH_ONLY_FLAGS
)


def _world_stamp_value() -> tuple:
    """The trace-shaping configuration stamp: the programmatic epoch plus
    the raw environment fingerprint of every declared flag EXCEPT the
    storage-only ones (mirrors ``config.config_stamp`` otherwise)."""
    return (config.config_epoch(),
            tuple(map(os.environ.get, _WORLD_FLAG_NAMES)))


class StaleProgramError(RuntimeError):
    """A pinned program was called after the world it was compiled for
    was revoked (configuration stamp or elastic epoch change).  Carries
    ``mpx_code == "MPX129"``; re-pin with ``program.repin()`` or a fresh
    ``mpx.compile`` (``mpx.elastic.run`` does this automatically for
    step functions that expose ``repin``)."""


def _current_epoch() -> int:
    # lazy: the resilience package is optional under isolated loaders,
    # and a world that never imported it is at epoch 0 by definition
    try:
        from ..resilience.elastic import current_epoch
    except ImportError:
        return 0
    return current_epoch()


class WorldStamp:
    """One captured (config stamp, elastic epoch) pair + the check."""

    __slots__ = ("stamp", "epoch")

    def __init__(self, stamp, epoch: int):
        self.stamp = stamp
        self.epoch = epoch

    @classmethod
    def capture(cls) -> "WorldStamp":
        return cls(_world_stamp_value(), _current_epoch())

    def is_current(self) -> bool:
        """Cheap validity probe (no raise): epoch int first — every
        programmatic change bumps it — then the raw env fingerprint
        (storage-only flags excluded)."""
        return (self.epoch == _current_epoch()
                and self.stamp == _world_stamp_value())

    def describe_staleness(self) -> Optional[str]:
        """Human-readable account of what moved (``None`` if current)."""
        cur_epoch = _current_epoch()
        if self.epoch != cur_epoch:
            return (f"the elastic communication epoch advanced "
                    f"({self.epoch} -> {cur_epoch}): the world this "
                    "program was compiled for was revoked (shrink, grow, "
                    "or drain)")
        cur = _world_stamp_value()
        if self.stamp == cur:
            return None
        old_env, new_env = self.stamp[1], cur[1]
        changed = [name for name, a, b in
                   zip(_WORLD_FLAG_NAMES, old_env, new_env) if a != b]
        if changed:
            return ("configuration flag(s) changed since the pin: "
                    + ", ".join(changed))
        return ("the configuration epoch moved (a set_* override was "
                "applied since the pin)")

    def check(self, what: str = "pinned program") -> None:
        """Raise :class:`StaleProgramError` (MPX129) unless current."""
        why = None
        if not self.is_current():
            why = self.describe_staleness()
        if why is None:
            return
        raise mpx_error(
            StaleProgramError, "MPX129",
            f"{what} is stale: {why}.  A pinned executable does no "
            "per-call key work, so it cannot retrace itself — re-pin it "
            "(program.repin(), or a fresh mpx.compile) to pick up the "
            "new world; mpx.elastic.run re-pins step functions "
            "automatically (docs/aot.md)",
        )
