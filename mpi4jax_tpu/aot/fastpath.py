"""C++ fast-path dispatch for pinned executables.

``jax.jit`` owes its low per-call overhead to a C++ dispatch path
(``xla_client._xla.pjit``): argument flattening, signature matching, and
executable invocation all happen below Python.  The AOT surface exposes
the same machinery — ``MeshExecutable.create_cpp_call(no_kwargs,
in_tree, out_tree)`` builds a C++-backed callable for a compiled
executable — but ``jax.stages.Compiled.__call__`` still runs a Python
prologue per call (tree flatten, signature check, error mapping).  For a
:class:`~.pinning.PinnedProgram` that prologue is the LAST per-call
Python cost after PR 10 removed key work, so the pin path routes through
the C++ callable whenever the running jax/jaxlib exposes it.

Everything here is best-effort by design: the factory is a private jax
surface that has moved between releases, so every probe is wrapped and
ANY failure falls back to the plain ``Compiled`` call — a pinned program
never breaks because a jaxlib lacks the fast path, it just dispatches
through Python (``MPI4JAX_TPU_CPP_DISPATCH=false`` forces that fallback
explicitly).  Imports of jax internals are lazy and guarded, so this
module loads under the isolated test loader without jax
(tests/test_megastep_pure.py drives :func:`cpp_call_for` with fakes).
"""

from __future__ import annotations

__all__ = ["cpp_call_for", "supported"]


def _trees(compiled):
    """(in_tree, out_tree) of a ``Compiled``, probing the public
    properties first and the param record older releases kept them on."""
    in_tree = getattr(compiled, "in_tree", None)
    out_tree = getattr(compiled, "out_tree", None)
    if in_tree is None or out_tree is None:
        params = getattr(compiled, "_params", None)
        if in_tree is None:
            in_tree = getattr(params, "in_tree", None)
        if out_tree is None:
            out_tree = getattr(params, "out_tree", None)
    return in_tree, out_tree


def cpp_call_for(compiled):
    """Best-effort C++ fast-path callable for a ``jax.stages.Compiled``.

    Returns ``(call, used_fastpath)``: ``call`` is the C++-backed
    callable when the executable exposes ``create_cpp_call`` and the
    factory succeeds, else ``compiled`` itself; ``used_fastpath`` says
    which.  Pinned calls are positional-only, so the factory is asked
    for the ``no_kwargs`` form.
    """
    try:
        exe = getattr(compiled, "_executable", None)
        factory = getattr(exe, "create_cpp_call", None)
        if factory is None:
            return compiled, False
        in_tree, out_tree = _trees(compiled)
        if in_tree is None or out_tree is None:
            return compiled, False
        fast = factory(True, in_tree, out_tree)
        if not callable(fast):
            return compiled, False
        return fast, True
    except Exception:
        # a moved private surface must degrade to the Python call path,
        # never take the pin down
        return compiled, False


def supported(compiled) -> bool:
    """Non-installing probe: would :func:`cpp_call_for` hand back a C++
    callable for this executable's shape of object?"""
    exe = getattr(compiled, "_executable", None)
    if getattr(exe, "create_cpp_call", None) is None:
        return False
    in_tree, out_tree = _trees(compiled)
    return in_tree is not None and out_tree is not None
