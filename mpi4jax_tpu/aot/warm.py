"""Manifest-driven compile-cache warming (``python -m mpi4jax_tpu.aot warm``).

The persistent tier (diskcache.py) makes a fleet cold-start a
deserialization instead of a compilation — but only AFTER something has
compiled each program once.  The warming CLI closes that loop: a
**program manifest** names each program abstractly (function import path
+ abstract argument shapes), and ``warm`` pins every entry through
``mpx.compile`` with the cache dir set, so the artifacts exist before
the first real job boots.

Manifest (JSON)::

    {
      "programs": [
        {
          "fn": "my_model.serving:decode_step",
          "args": [
            {"shape": [8, 4096], "dtype": "float32"},
            {"static": 16}
          ],
          "unroll": 8,          // optional megastep trip count
          "donate_argnums": [0] // optional
        }
      ]
    }

- ``fn`` is ``"module.path:callable"`` (or dotted-attr after the colon);
- each ``args`` entry is either a template ``{"shape": [...], "dtype":
  "..."}`` (a dynamic argument — pinned abstractly, nothing executes)
  or ``{"static": <json value>}`` (folded; its position becomes a
  ``static_argnums`` entry);
- cache keys fold in the mesh descriptor, so warming must run on a mesh
  matching the fleet's (same device count/kinds/process layout — fake it
  with ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` where
  appropriate) and under the same flag configuration.

Exit codes (``__main__.py``): ``0`` every program warmed, ``1`` some
program failed to import/pin (the rest are still attempted), ``2`` the
manifest is unreadable or malformed, or the persistent tier is disabled
(warming without ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` would compile into
the void).  Each success bumps the ``aot.warmed`` meter and the
``warmed`` counter in ``mpx.cache_stats()["aot"]``.

Parsing (:func:`parse_manifest`) is pure Python — the isolated test
loader drives it without jax; only :func:`warm_program` touches jax.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

__all__ = ["ProgramSpec", "ManifestError", "parse_manifest",
           "load_manifest", "warm_program", "warm_from_manifest",
           "EXIT_OK", "EXIT_FAILED", "EXIT_BAD_MANIFEST"]

EXIT_OK = 0
EXIT_FAILED = 1
EXIT_BAD_MANIFEST = 2


class ManifestError(ValueError):
    """The manifest is structurally unusable (exit code 2)."""


@dataclass
class ProgramSpec:
    """One warmable program: the abstract form ``mpx.compile`` needs."""

    fn: str                                  # "module.path:attr.path"
    args: Tuple[dict, ...]                   # raw entries, validated
    static_argnums: Tuple[int, ...] = ()
    unroll: int = 1
    donate_argnums: Tuple[int, ...] = ()
    wrap: Optional[bool] = None
    label: str = field(default="", compare=False)

    def import_path(self) -> Tuple[str, str]:
        mod, _, attr = self.fn.partition(":")
        return mod, attr


def _check_template(i: int, entry, where: str) -> dict:
    if not isinstance(entry, dict):
        raise ManifestError(
            f"{where}: args[{i}] must be an object, got "
            f"{type(entry).__name__}")
    if "static" in entry:
        extra = set(entry) - {"static"}
        if extra:
            raise ManifestError(
                f"{where}: args[{i}] mixes 'static' with {sorted(extra)}")
        return entry
    missing = {"shape", "dtype"} - set(entry)
    if missing:
        raise ManifestError(
            f"{where}: args[{i}] needs 'shape' and 'dtype' (or 'static'); "
            f"missing {sorted(missing)}")
    shape = entry["shape"]
    if (not isinstance(shape, list)
            or any(not isinstance(d, int) or d < 0 for d in shape)):
        raise ManifestError(
            f"{where}: args[{i}].shape must be a list of non-negative "
            f"ints, got {shape!r}")
    if not isinstance(entry["dtype"], str) or not entry["dtype"]:
        raise ManifestError(
            f"{where}: args[{i}].dtype must be a non-empty string")
    return entry


def parse_manifest(obj) -> List[ProgramSpec]:
    """Validate a loaded manifest object into :class:`ProgramSpec`\\ s.

    Raises :class:`ManifestError` on any structural problem — a typo'd
    manifest must fail the whole run loudly (exit 2), not silently warm
    a subset."""
    if not isinstance(obj, dict) or "programs" not in obj:
        raise ManifestError(
            "manifest must be an object with a 'programs' array")
    programs = obj["programs"]
    if not isinstance(programs, list) or not programs:
        raise ManifestError("'programs' must be a non-empty array")
    specs = []
    for n, p in enumerate(programs):
        where = f"programs[{n}]"
        if not isinstance(p, dict):
            raise ManifestError(f"{where} must be an object")
        fn = p.get("fn")
        if not isinstance(fn, str) or ":" not in fn or not fn.partition(
                ":")[2]:
            raise ManifestError(
                f"{where}.fn must be 'module.path:callable', got {fn!r}")
        raw_args = p.get("args")
        if not isinstance(raw_args, list):
            raise ManifestError(f"{where}.args must be an array")
        args = tuple(_check_template(i, a, where)
                     for i, a in enumerate(raw_args))
        statics = tuple(i for i, a in enumerate(args) if "static" in a)
        unroll = p.get("unroll", 1)
        if not isinstance(unroll, int) or unroll < 1:
            raise ManifestError(
                f"{where}.unroll must be a positive int, got {unroll!r}")
        donate = p.get("donate_argnums", [])
        if (not isinstance(donate, list)
                or any(not isinstance(d, int) for d in donate)):
            raise ManifestError(
                f"{where}.donate_argnums must be an array of ints")
        wrap = p.get("wrap")
        if wrap is not None and not isinstance(wrap, bool):
            raise ManifestError(f"{where}.wrap must be a boolean")
        specs.append(ProgramSpec(
            fn=fn, args=args, static_argnums=statics, unroll=unroll,
            donate_argnums=tuple(donate), wrap=wrap,
            label=p.get("label") or fn,
        ))
    return specs


def load_manifest(path: str) -> List[ProgramSpec]:
    """Read + parse a manifest file (:class:`ManifestError` on any
    problem, including unreadable/invalid JSON)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except OSError as e:
        raise ManifestError(f"cannot read manifest {path!r}: {e}") from e
    except json.JSONDecodeError as e:
        raise ManifestError(f"manifest {path!r} is not valid JSON: {e}") from e
    return parse_manifest(obj)


def _resolve_fn(spec: ProgramSpec):
    import importlib

    mod_name, attr_path = spec.import_path()
    mod = importlib.import_module(mod_name)
    target = mod
    for part in attr_path.split("."):
        target = getattr(target, part)
    if not callable(target):
        raise TypeError(f"{spec.fn} resolved to a non-callable "
                        f"{type(target).__name__}")
    return target


def _materialize_args(spec: ProgramSpec) -> tuple:
    import jax
    import numpy as np

    out = []
    for entry in spec.args:
        if "static" in entry:
            v = entry["static"]
            out.append(tuple(v) if isinstance(v, list) else v)
        else:
            out.append(jax.ShapeDtypeStruct(
                tuple(entry["shape"]), np.dtype(entry["dtype"])))
    return tuple(out)


def warm_program(spec: ProgramSpec, comm=None) -> dict:
    """Pin one manifest entry (import -> templates -> ``mpx.compile``).

    Returns a JSON-ready result row; raises on failure (the CLI catches
    per program so one broken entry cannot block the rest)."""
    import time

    from . import pinning

    fn = _resolve_fn(spec)
    args = _materialize_args(spec)
    t0 = time.perf_counter()
    program = pinning.compile(
        fn, *args, comm=comm,
        static_argnums=spec.static_argnums or None,
        donate_argnums=spec.donate_argnums,
        wrap=spec.wrap, unroll=spec.unroll,
    )
    wall = time.perf_counter() - t0
    pinning._stats.warmed += 1
    pinning._meter("aot.warmed")
    return {
        "fn": spec.fn,
        "from_disk": program.from_disk,
        "fast_path": program.fast_path,
        "unroll": program.unroll,
        "key": program.key,
        "pin_wall_s": round(wall, 4),
    }


def warm_from_manifest(path: str, comm=None) -> Tuple[int, dict]:
    """Warm every program in ``path``; returns ``(exit_code, payload)``.

    The persistent tier must be enabled (``MPI4JAX_TPU_COMPILE_CACHE_DIR``)
    — warming compiles ONLY to populate it."""
    from ..utils.config import compile_cache_dir

    if not compile_cache_dir():
        return EXIT_BAD_MANIFEST, {
            "error": "MPI4JAX_TPU_COMPILE_CACHE_DIR is not set: warming "
                     "has no persistent tier to populate (docs/aot.md)",
        }
    try:
        specs = load_manifest(path)
    except ManifestError as e:
        return EXIT_BAD_MANIFEST, {"error": str(e)}
    results, failures = [], []
    for spec in specs:
        try:
            results.append(warm_program(spec, comm=comm))
        except Exception as e:  # noqa: BLE001 - keep warming the rest
            failures.append({"fn": spec.fn, "error": f"{type(e).__name__}: {e}"})
    payload = {
        "manifest": path,
        "warmed": len(results),
        "failed": len(failures),
        "programs": results,
        "failures": failures,
    }
    return (EXIT_OK if not failures else EXIT_FAILED), payload
