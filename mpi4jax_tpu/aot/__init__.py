"""AOT program pinning + the persistent compiled-program cache.

The last big single-host throughput lever the ROADMAP names (open item
4): once a program is fixed, the hot loop should execute a pinned
artifact with donated buffers and zero per-call key computation, and
identical SPMD programs should never be re-lowered on every rank of a
multi-host cold start.

- ``mpx.compile(fn, *abstract_args, comm=..., donate_argnums=...,
  unroll=N)`` -> :class:`PinnedProgram` (pinning.py; ``unroll=N`` pins
  a device-resident megastep — parallel/megastep.py), driven through
  jax's C++ fast-path dispatch where available (fastpath.py);
- ``mpx.aot.compile_step(fn, unroll=N)`` — the elastic adapter: pinned
  (mega)step functions that ``mpx.elastic.run`` re-pins across epoch
  changes;
- ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` — the persistent tier (diskcache.py
  + serialization.py), also consulted by ``mpx.spmd``'s program cache
  on miss, pre-populated fleet-wide by the cache-warming CLI
  (``python -m mpi4jax_tpu.aot warm manifest.json``, warm.py);
- staleness (invalidation.py): :class:`StaleProgramError` (MPX129) when
  a pinned program is called after a config-stamp or elastic-epoch
  change.

docs/aot.md is the full story (pinning model, cache layout,
invalidation rules, the multi-host cold-start recipe, flag table).
"""

from .invalidation import StaleProgramError, WorldStamp  # noqa: F401
from . import diskcache, fastpath, keys, warm  # noqa: F401
from .pinning import (  # noqa: F401
    ElasticStep,
    PinnedProgram,
    compile,
    compile_step,
    through_disk_cache,
)
from .pinning import reset_stats as _reset_pin_stats
from .pinning import stats as _pin_stats


def stats() -> dict:
    """The persistent tier of ``mpx.cache_stats()``: the AOT pin/call
    counters plus the disk-cache counters and on-disk footprint."""
    return {"aot": _pin_stats(), "disk_cache": diskcache.stats()}


def reset_stats() -> None:
    """Zero the process-local AOT and disk-cache counters (called by
    ``mpx.clear_caches``; on-disk artifacts are untouched)."""
    _reset_pin_stats()
    diskcache.reset_stats()


__all__ = [
    "compile",
    "compile_step",
    "PinnedProgram",
    "ElasticStep",
    "StaleProgramError",
    "WorldStamp",
    "through_disk_cache",
    "stats",
    "reset_stats",
]
