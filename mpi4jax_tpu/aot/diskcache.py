"""The persistent compiled-program cache: on-disk artifact store (pure).

Layout (under ``MPI4JAX_TPU_COMPILE_CACHE_DIR``)::

    <dir>/mpx-aot-v1/<key[:2]>/<key>.bin

One artifact per key (keys.derive_key — 64 hex chars).  The container
format is self-verifying so a torn write, a truncated copy, or plain
bit-rot reads as a MISS, never as a wrong program::

    MAGIC (8 bytes)  b"MPXAOT1\\n"
    LENGTH (8 bytes) big-endian payload byte count
    PAYLOAD          opaque bytes (aot/serialization.py owns the format)
    DIGEST (32)      sha256(PAYLOAD)

Writes are atomic (temp file in the same directory + ``os.replace``) so
concurrent ranks of a multi-host cold start can race on the same key
safely: last writer wins with an identical artifact.  Reads touch the
file's mtime, making eviction LRU: after each write the cache is
trimmed oldest-mtime-first until it fits
``MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES`` (0 = unbounded).

Counters (process-local, always on — ``mpx.cache_stats()``'s persistent
tier) are mirrored into the telemetry meters
(``disk_cache.{hits,misses,writes,evictions,bytes}``) when telemetry is
enabled.  Pure Python: importable under the isolated test loader
without JAX.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from typing import List, Optional, Tuple

from ..utils import config
from ..telemetry import core as _telemetry

# keys.KEY_SCHEMA names the subdirectory so an incompatible format bump
# starts from a clean namespace instead of mass-missing old entries
from .keys import KEY_SCHEMA

MAGIC = b"MPXAOT1\n"
_HEADER = len(MAGIC) + 8
_DIGEST = 32

_lock = threading.Lock()
_stats = {"hits": 0, "misses": 0, "writes": 0, "evictions": 0, "bytes": 0}


def enabled() -> bool:
    """True when ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` names a directory."""
    return bool(config.compile_cache_dir())


def cache_root(base: Optional[str] = None) -> Optional[str]:
    """The versioned cache root (``<dir>/mpx-aot-v1``), or ``None`` when
    the persistent tier is disabled."""
    base = config.compile_cache_dir() if base is None else base
    if not base:
        return None
    return os.path.join(base, KEY_SCHEMA)


def _path_for(root: str, key: str) -> str:
    return os.path.join(root, key[:2], key + ".bin")


def _bump(name: str, n: int = 1) -> None:
    with _lock:
        _stats[name] += n
    _telemetry.meter(f"disk_cache.{name}", n)


def pack(payload: bytes) -> bytes:
    """Wrap a payload in the self-verifying container."""
    return (MAGIC + len(payload).to_bytes(8, "big") + payload
            + hashlib.sha256(payload).digest())


def unpack(data: bytes) -> Optional[bytes]:
    """Unwrap a container; ``None`` on any corruption (bad magic, short
    read, length or digest mismatch)."""
    if len(data) < _HEADER + _DIGEST or not data.startswith(MAGIC):
        return None
    length = int.from_bytes(data[len(MAGIC):_HEADER], "big")
    if len(data) != _HEADER + length + _DIGEST:
        return None
    payload = data[_HEADER:_HEADER + length]
    if hashlib.sha256(payload).digest() != data[_HEADER + length:]:
        return None
    return payload


def get(key: str, base: Optional[str] = None) -> Optional[bytes]:
    """Fetch an artifact; ``None`` on miss.  A corrupt artifact is
    deleted and counted as a miss (the caller recompiles and rewrites)."""
    root = cache_root(base)
    if root is None:
        return None
    path = _path_for(root, key)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        _bump("misses")
        return None
    payload = unpack(data)
    if payload is None:
        # self-heal: a corrupt artifact would be re-read (and re-missed)
        # on every cold start forever
        try:
            os.remove(path)
        except OSError:
            pass
        _bump("misses")
        return None
    try:
        os.utime(path)  # LRU touch
    except OSError:
        pass
    _bump("hits")
    return payload


def put(key: str, payload: bytes, base: Optional[str] = None) -> bool:
    """Store an artifact atomically, then trim the cache to the byte cap.
    Returns False (without raising) when the tier is disabled or the
    filesystem refuses — a cache must never take the program down."""
    root = cache_root(base)
    if root is None:
        return False
    path = _path_for(root, key)
    data = pack(payload)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp-" + key[:8])
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
    except OSError:
        return False
    _bump("writes")
    _bump("bytes", len(data))
    _evict_to_fit(root, config.compile_cache_max_bytes(), keep=path)
    return True


def _entries(root: str) -> List[Tuple[float, int, str]]:
    """(mtime, size, path) of every artifact under ``root``."""
    out = []
    for dirpath, _, names in os.walk(root):
        for name in names:
            if not name.endswith(".bin"):
                continue
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, p))
    return out


def _evict_to_fit(root: str, max_bytes: int, keep: Optional[str] = None) -> int:
    """Remove oldest-mtime artifacts until the cache fits ``max_bytes``
    (0 = unbounded).  The just-written artifact (``keep``) is evicted
    last — writing must never evict the entry it just produced while
    older ones remain."""
    if not max_bytes:
        return 0
    entries = _entries(root)
    total = sum(size for _, size, _ in entries)
    if total <= max_bytes:
        return 0
    evicted = 0
    entries.sort(key=lambda e: (e[2] == keep, e[0]))
    for _, size, path in entries:
        if total <= max_bytes:
            break
        try:
            os.remove(path)
        except OSError:
            continue
        total -= size
        evicted += 1
    if evicted:
        _bump("evictions", evicted)
    return evicted


def stats(base: Optional[str] = None) -> dict:
    """Process-local counters plus the on-disk entry count/size:
    ``{"enabled", "dir", "hits", "misses", "writes", "evictions",
    "bytes", "entries", "disk_bytes"}``."""
    with _lock:
        out = dict(_stats)
    root = cache_root(base)
    out["enabled"] = root is not None
    out["dir"] = config.compile_cache_dir() if base is None else base
    entries = _entries(root) if root is not None and os.path.isdir(root) \
        else []
    out["entries"] = len(entries)
    out["disk_bytes"] = sum(size for _, size, _ in entries)
    return out


def reset_stats() -> None:
    """Zero the process-local counters (test isolation; on-disk artifacts
    are untouched)."""
    with _lock:
        for k in _stats:
            _stats[k] = 0
