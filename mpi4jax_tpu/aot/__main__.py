"""AOT CLI: ``python -m mpi4jax_tpu.aot warm manifest.json``.

Pre-populates the persistent compiled-program cache
(``MPI4JAX_TPU_COMPILE_CACHE_DIR``) from a program manifest — the fleet
cold-start recipe of docs/aot.md run ahead of the fleet, so the first
real boot of every rank deserializes instead of lowering.

Exit codes: 0 = every program warmed; 1 = some program failed to
import/pin (the rest were still attempted; failures are listed); 2 =
the manifest is unreadable/malformed or the cache dir is unset.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.aot",
        description="AOT compiled-program cache tools (docs/aot.md)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    warm_p = sub.add_parser(
        "warm",
        help="pre-populate MPI4JAX_TPU_COMPILE_CACHE_DIR from a program "
             "manifest (fn import path + abstract shapes per program)",
    )
    warm_p.add_argument("manifest", help="path to the manifest JSON")
    warm_p.add_argument("--json", action="store_true",
                        help="machine-readable result payload on stdout")
    args = parser.parse_args(argv)

    from .warm import warm_from_manifest

    code, payload = warm_from_manifest(args.manifest)
    if args.json:
        print(json.dumps(payload))
    else:
        if "error" in payload:
            print(f"warm: {payload['error']}", file=sys.stderr)
        else:
            for row in payload["programs"]:
                src = "disk" if row["from_disk"] else "compiled"
                extra = f", unroll={row['unroll']}" if row["unroll"] > 1 else ""
                print(f"warmed {row['fn']} ({src}{extra}, "
                      f"{row['pin_wall_s']}s)")
            for row in payload["failures"]:
                print(f"FAILED {row['fn']}: {row['error']}", file=sys.stderr)
            print(f"warm: {payload['warmed']} warmed, "
                  f"{payload['failed']} failed")
    return code


if __name__ == "__main__":
    sys.exit(main())
