"""AOT CLI: ``python -m mpi4jax_tpu.aot warm [--emit-manifest] ...``.

Pre-populates the persistent compiled-program cache
(``MPI4JAX_TPU_COMPILE_CACHE_DIR``) from a program manifest — the fleet
cold-start recipe of docs/aot.md run ahead of the fleet, so the first
real boot of every rank deserializes instead of lowering.

``--emit-manifest`` writes the manifest instead of consuming one: the
serving runtime's bucket table (docs/serving.md) expands into one entry
per (bucket, phase) program — prefill and decode megastep at every
declared batch bucket — so a single ``emit`` + ``warm`` pair pre-compiles
EVERYTHING a serving fleet will ever ask for and the first serving run
reports ``disk_cache.misses == 0`` (asserted by the CI serving lane)::

    python -m mpi4jax_tpu.aot warm --emit-manifest serving.json --world 8
    MPI4JAX_TPU_COMPILE_CACHE_DIR=... \\
      python -m mpi4jax_tpu.aot warm serving.json

Exit codes: 0 = every program warmed (or the manifest was emitted); 1 =
some program failed to import/pin (the rest were still attempted;
failures are listed); 2 = the manifest is unreadable/malformed, the
cache dir is unset, or the serving config cannot be emitted.
"""

from __future__ import annotations

import argparse
import json
import sys


def _emit_manifest(args) -> int:
    from ..serving.engine import ServingConfig, warm_manifest

    overrides = {}
    if args.max_batch:
        overrides["max_batch"] = args.max_batch
    if args.unroll:
        overrides["unroll"] = args.unroll
    try:
        cfg = ServingConfig.from_env(**overrides)
        world = args.world
        if world is None:
            import jax

            world = jax.device_count()
        manifest = warm_manifest(cfg, world)
        with open(args.manifest, "w") as f:
            json.dump(manifest, f, indent=2)
            f.write("\n")
    except (ValueError, RuntimeError, OSError) as e:
        # any emit failure — bad config, unshardable world, unwritable
        # output path — is the "unusable manifest" exit (2), never the
        # partial-warm code (1)
        print(f"warm --emit-manifest: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"manifest": args.manifest, "world": world,
                          "programs": len(manifest["programs"])}))
    else:
        print(f"emitted {len(manifest['programs'])} serving program(s) "
              f"(world {world}) to {args.manifest}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.aot",
        description="AOT compiled-program cache tools (docs/aot.md)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    warm_p = sub.add_parser(
        "warm",
        help="pre-populate MPI4JAX_TPU_COMPILE_CACHE_DIR from a program "
             "manifest (fn import path + abstract shapes per program), "
             "or --emit-manifest one from the serving bucket table",
    )
    warm_p.add_argument("manifest",
                        help="path to the manifest JSON (the OUTPUT path "
                             "under --emit-manifest)")
    warm_p.add_argument("--json", action="store_true",
                        help="machine-readable result payload on stdout")
    warm_p.add_argument("--emit-manifest", action="store_true",
                        help="write the serving-fleet manifest (one entry "
                             "per (bucket, phase) program from the "
                             "MPI4JAX_TPU_SERVING_* config — "
                             "docs/serving.md) to MANIFEST and exit")
    warm_p.add_argument("--world", type=int, default=None,
                        help="--emit-manifest: tensor-parallel world size "
                             "the fleet runs at (default: this host's "
                             "device count)")
    warm_p.add_argument("--max-batch", type=int, default=0,
                        help="--emit-manifest: override "
                             "MPI4JAX_TPU_SERVING_MAX_BATCH")
    warm_p.add_argument("--unroll", type=int, default=0,
                        help="--emit-manifest: override "
                             "MPI4JAX_TPU_SERVING_UNROLL")
    args = parser.parse_args(argv)

    if args.emit_manifest:
        return _emit_manifest(args)

    from .warm import warm_from_manifest

    code, payload = warm_from_manifest(args.manifest)
    if args.json:
        print(json.dumps(payload))
    else:
        if "error" in payload:
            print(f"warm: {payload['error']}", file=sys.stderr)
        else:
            for row in payload["programs"]:
                src = "disk" if row["from_disk"] else "compiled"
                extra = f", unroll={row['unroll']}" if row["unroll"] > 1 else ""
                print(f"warmed {row['fn']} ({src}{extra}, "
                      f"{row['pin_wall_s']}s)")
            for row in payload["failures"]:
                print(f"FAILED {row['fn']}: {row['error']}", file=sys.stderr)
            print(f"warm: {payload['warmed']} warmed, "
                  f"{payload['failed']} failed")
    return code


if __name__ == "__main__":
    sys.exit(main())
