"""AOT program pinning: ``mpx.compile`` and the persistent-tier glue.

BENCH_r05 put host-side dispatch at ~14% of the shallow-water wall even
after the flag-parse fast path (PR 5): a cache-HIT ``spmd`` call still
normalizes statics, rebuilds the key, probes the program cache, and
meters — per call, forever.  The AOT layer ends that: once the program
is fixed, the hot loop should execute a **pinned artifact** (JAX's
``lower().compile()`` AOT path; the CUDA-Graphs capture-and-replay
lesson) —

- :func:`compile` ``(fn, *abstract_args, comm=..., donate_argnums=...)``
  returns a :class:`PinnedProgram`: the fully lowered+compiled
  executable.  Its call path does no env-flag parsing, no cache-key
  hashing, and no program-cache lookups — the config stamp, every
  algo/fusion/analysis/resilience token, and the elastic epoch were
  captured ONCE at compile time (``invalidation.WorldStamp``), and a
  moved world raises :class:`~.invalidation.StaleProgramError` (MPX129)
  instead of silently serving old-world code;
- the **persistent tier** (``MPI4JAX_TPU_COMPILE_CACHE_DIR``,
  diskcache.py): pinned programs — and ``mpx.spmd`` program-cache
  misses, via :func:`through_disk_cache` — are keyed by (jaxpr
  fingerprint, mesh/topology, full dynamic cache token, toolchain
  versions) and serialized, so repeated cold starts and every rank of a
  multi-host job deserialize instead of re-lowering identical SPMD
  programs;
- :func:`compile_step` adapts a ``(state, step, comm)`` elastic step
  function: first call pins; a world change (new comm/epoch) raises
  ``StaleProgramError``, and ``mpx.elastic.run`` catches it and
  ``repin()``s transparently across shrink/grow boundaries.

Tracing a pin runs the IDENTICAL region body ``spmd`` traces
(``parallel/region.make_region_body``), so pinned HLO is byte-identical
to the jit path (pinned by tests/test_aot.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import diskcache, keys, serialization
from .invalidation import StaleProgramError, WorldStamp

__all__ = ["PinnedProgram", "compile", "compile_step", "stats",
           "reset_stats", "through_disk_cache", "tracing_pinned"]


# ---------------------------------------------------------------------------
# counters (always on — the persistent tier of mpx.cache_stats(); mirrored
# into the telemetry meters when telemetry is enabled)
# ---------------------------------------------------------------------------


class _Stats:
    __slots__ = ("pins", "calls", "stale_raises", "disk_loads", "compiles",
                 "fast_path_pins", "warmed")

    def __init__(self):
        self.reset()

    def reset(self):
        self.pins = 0
        self.calls = 0
        self.stale_raises = 0
        self.disk_loads = 0
        self.compiles = 0
        self.fast_path_pins = 0
        self.warmed = 0


_stats = _Stats()


def stats() -> dict:
    """AOT-layer counters: ``pins`` (programs pinned), ``calls`` (pinned
    executions), ``stale_raises`` (MPX129 refusals), ``disk_loads``
    (pins served by deserializing a persistent artifact), ``compiles``
    (pins that lowered+compiled fresh), ``fast_path_pins`` (pins driven
    through jax's C++ fast-path dispatch — aot/fastpath.py), ``warmed``
    (programs pre-compiled by the cache-warming CLI — aot/warm.py)."""
    return {k: getattr(_stats, k) for k in _Stats.__slots__}


def reset_stats() -> None:
    _stats.reset()


def _meter(name: str, n: int = 1) -> None:
    from ..telemetry import core as _telemetry

    _telemetry.meter(name, n)


# ---------------------------------------------------------------------------
# pinned-trace marker (the MPX128 gate: a trace that is ALREADY being
# pinned must not be advised to pin itself)
# ---------------------------------------------------------------------------

_pinning_depth = 0


def tracing_pinned() -> bool:
    """True while a pin's trace/lower/compile is running (read by
    ``analysis.hook.config_snapshot`` so the MPX128 advisory never fires
    on a program that is being pinned right now)."""
    return _pinning_depth > 0


class _pinned_trace_scope:
    def __enter__(self):
        global _pinning_depth
        _pinning_depth += 1

    def __exit__(self, *exc):
        global _pinning_depth
        _pinning_depth -= 1
        return False


# ---------------------------------------------------------------------------
# key parts
# ---------------------------------------------------------------------------


def mesh_descriptor(mesh) -> Optional[tuple]:
    """Stable cross-process description of the physical partition a
    program was compiled for: axis names, mesh shape, the global device
    ids IN MESH ORDER, device kinds, platform, and process count.

    The device ids matter: the jaxpr text carries none, so two meshes
    over different device subsets (or the same devices permuted) would
    otherwise derive one key and serve an executable whose baked-in
    device assignment targets the wrong chips.  Global ids are
    identical on every process of a multi-host job, so the multi-host
    same-key contract still holds."""
    if mesh is None:
        return None
    devices = mesh.devices
    ids = tuple(int(getattr(d, "id", -1)) for d in devices.flat)
    kinds = tuple(sorted({
        getattr(d, "device_kind", "") for d in devices.flat
    }))
    platforms = tuple(sorted({
        getattr(d, "platform", "") for d in devices.flat
    }))
    return (tuple(mesh.axis_names), tuple(devices.shape), ids, kinds,
            platforms, jax.process_count())


def toolchain_versions() -> tuple:
    """(jax, jaxlib, libtpu, mpi4jax_tpu) — serialized executables are
    not portable across compilers, so all four are key parts."""
    import jaxlib

    from importlib.metadata import PackageNotFoundError, version

    def probe(name):
        try:
            return version(name)
        except PackageNotFoundError:
            return ""

    libtpu = probe("libtpu") or probe("libtpu-nightly")
    return (jax.__version__, getattr(jaxlib, "__version__", ""), libtpu,
            probe("mpi4jax_tpu"))


def _dynamic_token():
    from ..ops._base import dynamic_cache_token

    return dynamic_cache_token()


def _abstract(args: tuple) -> tuple:
    """Arguments -> ``ShapeDtypeStruct`` templates (arrays pass through
    by aval; templates are kept as given)."""
    leaves, treedef = jax.tree.flatten(args)
    return jax.tree.unflatten(treedef, [
        leaf if isinstance(leaf, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(jnp.shape(leaf), jnp.result_type(leaf))
        for leaf in leaves
    ])


# ---------------------------------------------------------------------------
# the pin core: trace -> persistent-cache consult -> compiled callable
# ---------------------------------------------------------------------------


def _consts_digest(closed_jaxpr) -> tuple:
    """Fingerprint the VALUES of a jaxpr's closed-over constants.

    ``str(jaxpr)`` prints constants by shape/dtype only — two programs
    differing in a baked-in weight array would render identically and
    collide on one disk key, serving the wrong executable.  Hash the
    bytes; anything unhashable falls back to a type marker plus a
    process-independent best-effort repr (and, being unrecognizable,
    simply keys conservatively)."""
    import numpy as np

    out = []
    for c in getattr(closed_jaxpr, "consts", ()):
        try:
            arr = np.asarray(c)
            out.append((str(arr.dtype), arr.shape,
                        keys.fingerprint(arr.tobytes())))
        except Exception:
            out.append((type(c).__name__, repr(c)[:256]))
    return tuple(out)


class _null_scope:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def _pin_executable(jitted, mesh, avals, label: str,
                    mark_pinned: bool = True):
    """Lower+compile ``jitted`` at ``avals`` through the persistent tier.

    Returns ``(call, key, from_disk)``: ``call`` is the loaded
    ``jax.stages.Compiled``; ``key`` is the persistent cache key (None
    when the tier is disabled); ``from_disk`` says whether the artifact
    was deserialized instead of compiled.

    ``mark_pinned=False`` (the spmd disk-consult path) keeps
    ``tracing_pinned()`` False during the trace: those programs still
    dispatch per call, so the MPX128 hot-loop advisory must keep firing
    for them — only a true ``mpx.compile`` pin is exempt.
    """
    with (_pinned_trace_scope() if mark_pinned else _null_scope()):
        use_disk = diskcache.enabled() and serialization.supported()
        trace_fn = getattr(jitted, "trace", None)
        if trace_fn is not None:
            traced = trace_fn(*avals)
            program_text = str(traced.jaxpr)
            consts = _consts_digest(traced.jaxpr)
            lower = traced.lower
        else:  # older AOT API: no .trace — fingerprint the lowering
            lowered = jitted.lower(*avals)
            program_text = lowered.as_text()
            consts = ()
            lower = lambda: lowered  # noqa: E731

        key = None
        if use_disk:
            key = keys.derive_key(
                keys.fingerprint(program_text) + ":"
                + keys.fingerprint(keys.canonical(consts)),
                mesh_descriptor(mesh),
                _dynamic_token(),
                toolchain_versions(),
            )
            payload = diskcache.get(key)
            if payload is not None:
                loaded = serialization.loads(payload)
                if loaded is not None:
                    _stats.disk_loads += 1
                    return loaded, key, True
                # version-skew the key should have caught, or a pickle
                # the running process cannot reconstruct: recompile and
                # overwrite the artifact
        compiled = lower().compile()
        _stats.compiles += 1
        if key is not None:
            data = serialization.dumps(compiled)
            if data is not None:
                diskcache.put(key, data)
        return compiled, key, False


def _dispatch_call(compiled):
    """The call the hot loop will drive: jax's C++ fast-path dispatch
    when available and not disabled (``MPI4JAX_TPU_CPP_DISPATCH``), else
    the plain ``Compiled`` — returns ``(call, used_fastpath)``."""
    from ..utils.config import cpp_dispatch

    if not cpp_dispatch():
        return compiled, False
    from . import fastpath

    return fastpath.cpp_call_for(compiled)


def through_disk_cache(jitted, c, label: str = "fn"):
    """Route a jitted SPMD program through the persistent tier (the
    ``mpx.spmd`` program-cache miss hook, parallel/region.py).

    Returns a thin callable that, once per argument signature, traces
    the program, consults the on-disk cache, and thereafter calls the
    loaded/compiled executable directly.  Only installed when
    ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` is set — unset, the jitted
    program is used as-is (keys and HLO byte-identical to a build
    without the AOT layer)."""
    mesh = c.mesh
    memo: dict = {}

    def cached_call(*args):
        leaves, treedef = jax.tree.flatten(args)
        sig = (treedef, tuple(
            (jnp.shape(leaf), str(jnp.result_type(leaf))) for leaf in leaves
        ))
        call = memo.get(sig)
        if call is None:
            call, _, _ = _pin_executable(jitted, mesh, _abstract(args),
                                         label, mark_pinned=False)
            # spmd misses served through the disk tier get the same C++
            # fast-path dispatch a pin would (fallback: the Compiled)
            call, _ = _dispatch_call(call)
            memo[sig] = call
        return call(*args)

    return cached_call


# ---------------------------------------------------------------------------
# PinnedProgram: the public artifact
# ---------------------------------------------------------------------------


class PinnedProgram:
    """A fully lowered+compiled SPMD program with a zero-work call path.

    ``program(*dynamic_args)`` validates the captured world — one epoch
    int compare plus one raw-environment fingerprint compare; no flag
    parsing, no key hashing, no cache probe — and executes the pinned
    executable.  Where the running jaxlib exposes the C++ fast-path
    dispatch (aot/fastpath.py; ``fast_path`` records it), that execution
    is ONE C++ call — no Python tree flattening or signature re-checking
    either.  A moved world (config stamp or elastic epoch) raises
    :class:`StaleProgramError` (MPX129); ``repin()`` rebuilds against
    the current world.

    Static arguments were folded at pin time: call with the dynamic
    arguments only, shaped exactly like the abstract templates given to
    :func:`compile` (an AOT executable accepts exactly one signature).
    ``unroll`` records the megastep trip count (1 = single-step): a
    megastep program runs ``unroll`` state iterations per call and
    returns the final carry (docs/aot.md "Megastep execution").
    """

    __slots__ = ("_call", "_world", "_stats", "_respec", "fn_name", "key",
                 "from_disk", "donate_argnums", "fast_path", "unroll",
                 "_traceable", "_donate_call")

    def __init__(self, call, world: WorldStamp, respec, fn_name: str,
                 key, from_disk: bool, donate_argnums,
                 fast_path: bool = False, unroll: int = 1,
                 traceable=None, donate_call=None):
        self._call = call
        self._world = world
        self._stats = _stats
        self._respec = respec
        self.fn_name = fn_name
        self.key = key
        self.from_disk = from_disk
        self.donate_argnums = donate_argnums
        self.fast_path = fast_path
        self.unroll = unroll
        # the traceable jit twin of the pinned executable (same fn, same
        # donation semantics): the dataflow hazard verifier's re-trace
        # routes through it, because a Compiled cannot accept tracers
        self._traceable = traceable
        # donated positions in CALL-TIME coordinates (statics are folded
        # at pin time and not passed) — what record_donation indexes
        self._donate_call = tuple(donate_call) if donate_call is not None \
            else tuple(donate_argnums)

    def __call__(self, *args):
        world = self._world
        if not world.is_current():
            self._stats.stale_raises += 1
            _meter("aot.stale_raises")
            world.check(f"pinned program {self.fn_name!r}")
        self._stats.calls += 1
        # dataflow hazard bookkeeping (analysis/hazards.py MPX139/MPX140):
        # donation-free programs skip both branches on one attribute test
        # each, keeping the zero-work call path intact
        if self._donate_call:
            _note_donation(self, args)
        if self._traceable is not None and _analysis_recording():
            return self._traceable(*args)
        return self._call(*args)

    def is_stale(self) -> bool:
        """Non-raising probe: would the next call raise MPX129?"""
        return not self._world.is_current()

    def repin(self) -> "PinnedProgram":
        """Re-lower/re-compile (or re-load from the persistent tier)
        against the CURRENT world: the re-entry path after a
        ``StaleProgramError``."""
        return self._respec()

    def __repr__(self):
        src = "disk" if self.from_disk else "compiled"
        return (f"PinnedProgram({self.fn_name!r}, {src}, "
                f"epoch={self._world.epoch}"
                + (f", unroll={self.unroll}" if self.unroll > 1 else "")
                + (", cpp" if self.fast_path else "")
                + (", STALE" if self.is_stale() else "") + ")")


def _analysis_recording() -> bool:
    """Is any analysis recorder capturing this call site?  Explicit
    ``mpx.analyze`` (global recorder stack) or an armed env-mode region
    context enclosing the call."""
    try:
        from ..analysis import hook
        from ..parallel.region import _region_stack
    except ImportError:  # pragma: no cover - isolated loaders
        return False
    if hook.recording():
        return True
    ctx = _region_stack[-1] if _region_stack else None
    return ctx is not None and \
        getattr(ctx, "analysis_recorder", None) is not None


def _note_donation(program: "PinnedProgram", args) -> None:
    """Hand this call's donated argument identities to the dataflow
    hazard verifier (analysis/hook.record_donation — fully self-gating:
    a no-op unless a recorder is active or the env mode is armed)."""
    try:
        from ..analysis import hook
        from ..parallel.region import _region_stack
    except ImportError:  # pragma: no cover - isolated loaders
        return
    ctx = _region_stack[-1] if _region_stack else None
    donated = [args[i] for i in program._donate_call if i < len(args)]
    hook.record_donation(donated, f"pinned call {program.fn_name!r}",
                         ctx=ctx)


def _normalize_statics(static_argnums, nargs: int) -> tuple:
    if static_argnums is None:
        raw = ()
    elif isinstance(static_argnums, int):
        raw = (static_argnums,)
    else:
        raw = tuple(static_argnums)
    statics = tuple(sorted({i if i >= 0 else i + nargs for i in raw}))
    for i in statics:
        if not 0 <= i < nargs:
            raise ValueError(
                f"static_argnums entry {i} out of range for {nargs} "
                "positional arguments"
            )
    return statics


def compile(fn, *abstract_args, comm=None, donate_argnums=(),
            static_argnums=None, in_specs=None, out_specs=None,
            wrap: Optional[bool] = None,
            unroll: Optional[int] = None) -> PinnedProgram:
    """Pin ``fn(*abstract_args)`` to a fully compiled executable.

    ``fn`` follows the same three conventions as ``mpx.analyze``:

    - an ``mpx.spmd``-decorated function: pinned as-is (its comm,
      specs, static_argnums, and unroll breadcrumbs are adopted; pass
      overrides to replace them);
    - a plain per-rank function: wrapped over ``comm`` (or the default
      comm) exactly like ``mpx.spmd`` would — same region body, same
      HLO;
    - ``wrap=False``: jitted exactly as given (eager-style functions
      taking global arrays and calling ops outside a region).

    ``abstract_args`` are example arrays or ``jax.ShapeDtypeStruct``
    templates — nothing is executed at pin time.  Arguments named by
    ``static_argnums`` must be concrete hashable values; they are folded
    into the program and NOT passed at call time.  ``donate_argnums``
    indexes the original argument positions; donated buffers are reused
    for outputs (the hot-loop double-buffer idiom).

    ``unroll=N`` (N > 1) pins a **megastep**: the body is rewritten into
    a device-resident ``lax.fori_loop`` over N iterations with the
    dynamic arguments as the carry, so each pinned call executes N steps
    for one host dispatch — the per-step host cost falls as 1/N
    (docs/aot.md "Megastep execution"; requires the region convention,
    not ``wrap=False``).  ``None`` resolves
    ``MPI4JAX_TPU_UNROLL_DEFAULT`` (1 = single-step, trace and HLO
    byte-identical to a pin without the megastep layer).

    With ``MPI4JAX_TPU_COMPILE_CACHE_DIR`` set, the lowered+compiled
    artifact is served from / written to the persistent cache
    (docs/aot.md); the call path is identical either way.
    """
    from ..parallel.megastep import validate_unroll
    from ..parallel.region import (
        make_region_body,
        region_axes_spec,
        resolve_comm,
    )

    spec = dict(comm=comm, donate_argnums=donate_argnums,
                static_argnums=static_argnums, in_specs=in_specs,
                out_specs=out_specs, wrap=wrap, unroll=unroll)

    inner = fn
    if wrap is None:
        wrap = True
    if wrap and getattr(fn, "_mpx_spmd", False):
        crumbs = fn._mpx_spmd_kwargs
        inner = fn._mpx_fn
        if comm is None:
            comm = crumbs.get("comm")
        if in_specs is None:
            in_specs = crumbs.get("in_specs")
        if out_specs is None:
            out_specs = crumbs.get("out_specs")
        if static_argnums is None:
            static_argnums = crumbs.get("static_argnums")
        if unroll is None:
            unroll = crumbs.get("unroll")
    # only an EXPLICIT unroll= errors on a shape that cannot carry the
    # loop (wrap=False, no dynamic args); the MPI4JAX_TPU_UNROLL_DEFAULT
    # fleet default degrades those to a single-step pin instead
    explicit_unroll = unroll is not None
    if explicit_unroll:
        n_unroll = validate_unroll(unroll)
    else:
        from ..utils.config import unroll_default

        n_unroll = unroll_default()
    name = getattr(inner, "__name__", "fn")

    donate = _normalize_statics(donate_argnums, len(abstract_args)) \
        if donate_argnums else ()
    statics = _normalize_statics(static_argnums, len(abstract_args))
    overlap_ = set(donate) & set(statics)
    if overlap_:
        raise ValueError(
            f"cannot donate static argument(s) {sorted(overlap_)}: statics "
            "are folded into the program and never buffered"
        )

    c = resolve_comm(comm)
    if wrap is False:
        if n_unroll > 1:
            if not explicit_unroll:
                n_unroll = 1
            else:
                raise ValueError(
                    "mpx.compile(unroll=N) needs the region calling "
                    "convention (a per-rank or spmd-decorated function): "
                    "an eager-style wrap=False function has no per-rank "
                    "carry to thread through the device-resident loop"
                )
        if c.mesh is None and comm is not None:
            raise RuntimeError(
                "mpx.compile(wrap=False) with an explicit comm needs it "
                "bound to a mesh (comm.bind(mesh))"
            )
        jitted = jax.jit(fn, static_argnums=statics or None,
                         donate_argnums=donate or None)
        trace_args = tuple(
            a if i in statics else _abstract((a,))[0]
            for i, a in enumerate(abstract_args)
        )
        # call-time coordinates: statics are folded and not passed, so a
        # donated original position shifts left past every static below
        # it (donate ∩ statics already rejected above)
        donate_call = tuple(i - sum(1 for s in statics if s < i)
                            for i in donate)
        # with statics the jit twin's signature differs from the pinned
        # call's — no traceable reroute there
        traceable = jitted if not statics else None
        mesh = c.mesh
    else:
        if c.mesh is None:
            raise RuntimeError(
                "mpx.compile requires a comm bound to a mesh "
                "(comm.bind(mesh)) or an available default mesh"
            )
        static_vals = tuple(abstract_args[i] for i in statics)
        try:
            hash(static_vals)
        except TypeError as e:
            raise TypeError(
                f"mpx.compile static argument values must be hashable "
                f"(like jax.jit static_argnums); got {static_vals!r}"
            ) from e
        dyn_args = tuple(a for i, a in enumerate(abstract_args)
                         if i not in statics)
        # donation indexes the ORIGINAL positions; the executable takes
        # only the dynamic args, so remap
        dyn_pos = {orig: j for j, orig in enumerate(
            i for i in range(len(abstract_args)) if i not in statics)}
        donate_dyn = tuple(dyn_pos[i] for i in donate)
        axes_spec = region_axes_spec(c)
        ispecs = in_specs if in_specs is not None else axes_spec
        ospecs = out_specs if out_specs is not None else axes_spec
        if n_unroll > 1 and not dyn_args:
            if not explicit_unroll:
                n_unroll = 1
            else:
                raise ValueError(
                    "mpx.compile(unroll=N) needs at least one dynamic "
                    "argument to carry through the device-resident loop"
                )
        body = make_region_body(
            inner, c, statics, static_vals, (), len(dyn_args),
            squeeze_in=in_specs is None, squeeze_out=out_specs is None,
            unroll=n_unroll,
        )
        sm = jax.shard_map(body, mesh=c.mesh, in_specs=ispecs,
                           out_specs=ospecs)
        jitted = jax.jit(sm, donate_argnums=donate_dyn or None)
        trace_args = _abstract(dyn_args)
        donate_call = donate_dyn
        traceable = jitted
        mesh = c.mesh

    # capture BEFORE the trace: a flag that moves mid-compile leaves a
    # stamp that (correctly, conservatively) refuses the first call
    world = WorldStamp.capture()
    call, key, from_disk = _pin_executable(jitted, mesh, trace_args, name)
    call, fast = _dispatch_call(call)
    _stats.pins += 1
    if fast:
        _stats.fast_path_pins += 1
    _meter("aot.pins")

    def respec():
        return compile(fn, *abstract_args, **spec)

    return PinnedProgram(call, world, respec, name, key, from_disk, donate,
                         fast_path=fast, unroll=n_unroll,
                         traceable=traceable, donate_call=donate_call)


# ---------------------------------------------------------------------------
# the elastic adapter: pin-per-world step functions
# ---------------------------------------------------------------------------


class ElasticStep:
    """A ``(state, step, comm)`` step function that executes as a pinned
    program per world.

    The state contract matches the elastic examples: ``state`` is a
    REPLICATED pytree (identical on every rank — parameters after a
    gradient allreduce), carried WITHOUT a rank axis.  Each call tiles
    it to the global convention, runs the pinned program, and returns
    rank 0's row — so the state that crosses commit/restore boundaries
    is world-size-free and survives shrink/grow unchanged.  The step
    index rides as a tiny per-rank array, so stepping never retraces.

    The first call pins ``fn`` over the comm it is handed.  When the
    world moves — ``mpx.elastic.run`` hands a NEW comm after a
    shrink/grow/drain boundary, or the config stamp changes — the next
    call raises :class:`StaleProgramError` (MPX129) and ``repin()``
    drops the pin; ``mpx.elastic.run`` performs exactly that dance
    automatically, so an elastic loop keeps its pinned hot path across
    epochs without serving a single old-world execution.

    ``unroll=N`` pins a **megastep** step: each call executes N
    consecutive ``fn(state, step + i, comm)`` iterations device-resident
    (``lax.fori_loop``; the step index rides in the carry) and returns
    the state after step ``step + N``.  ``mpx.elastic.run`` reads the
    ``unroll`` attribute, aligns ``commit_every`` up to a multiple of N,
    and advances its step counter by N per call; a mid-megastep
    ``StaleProgramError`` retries the whole megastep from the same
    state — restart-idempotent by construction, since state only commits
    at megastep boundaries (docs/aot.md "Megastep execution").
    """

    def __init__(self, fn, donate_state: bool = False, unroll: int = 1):
        from ..parallel.megastep import validate_unroll

        self._fn = fn
        self._donate_state = donate_state
        self.unroll = validate_unroll(unroll)
        self._pinned: Optional[PinnedProgram] = None
        self._world_key = None

    def _step_array(self, comm, step: int):
        return jnp.full((comm.world_size(),), step, jnp.int32)

    @staticmethod
    def _tile(state, k: int):
        """Replicated pytree -> global convention (leading rank axis)."""
        return jax.tree.map(
            lambda v: jnp.tile(jnp.asarray(v)[None],
                               (k,) + (1,) * jnp.ndim(v)), state)

    def __call__(self, state, step: int, comm):
        pinned = self._pinned
        if pinned is not None and self._world_key != (
                comm.uid, getattr(comm, "epoch", 0)):
            from ..analysis.report import mpx_error

            _stats.stale_raises += 1
            _meter("aot.stale_raises")
            raise mpx_error(
                StaleProgramError, "MPX129",
                f"pinned elastic step {getattr(self._fn, '__name__', 'fn')!r} "
                f"was handed a different communicator (uid/epoch "
                f"{self._world_key} -> "
                f"{(comm.uid, getattr(comm, 'epoch', 0))}): the world "
                "moved — repin() and retry (mpx.elastic.run does this "
                "automatically)",
            )
        k = comm.world_size()
        g = self._tile(state, k)
        if pinned is None:
            n_unroll = self.unroll

            def per_rank(st, step_scalar):
                if n_unroll == 1:
                    return self._fn(st, step_scalar, comm)
                from ..parallel.megastep import megastep_loop

                # the megastep form: N device-resident iterations with
                # the state as the carry; the step index advances inside
                # the loop, so one pinned call covers steps
                # [step, step + N)
                def one(i, carry):
                    return self._fn(carry, step_scalar + i, comm)

                return megastep_loop(
                    one, st, n_unroll, comm,
                    label=getattr(self._fn, "__name__", "fn"))

            per_rank.__name__ = getattr(self._fn, "__name__", "fn")
            # unroll=1 here on purpose: the loop (when any) is built
            # above — a non-1 MPI4JAX_TPU_UNROLL_DEFAULT must not wrap a
            # second fori_loop around it
            self._pinned = compile(
                per_rank, g, self._step_array(comm, step), comm=comm,
                donate_argnums=(0,) if self._donate_state else (),
                unroll=1,
            )
            self._world_key = (comm.uid, getattr(comm, "epoch", 0))
            pinned = self._pinned
        out = pinned(g, self._step_array(comm, step))
        return jax.tree.map(lambda v: v[0], out)

    def repin(self) -> "ElasticStep":
        """Drop the pin; the next call re-pins against the comm (and
        state shapes) it is handed."""
        self._pinned = None
        self._world_key = None
        return self


def compile_step(fn, *, donate_state: bool = False,
                 unroll: int = 1) -> ElasticStep:
    """Adapt a per-rank ``fn(state, step, comm)`` for ``mpx.elastic.run``
    with a pinned hot path: see :class:`ElasticStep` (replicated-state
    contract).  ``donate_state`` donates the tiled state buffers into
    each step (they are rebuilt per call, so donation is safe) — the
    double-buffer idiom.  ``unroll=N`` makes each pinned call a megastep
    of N device-resident iterations; ``mpx.elastic.run`` aligns its
    commit cadence to the megastep boundary automatically (docs/aot.md
    "Megastep execution")."""
    return ElasticStep(fn, donate_state=donate_state, unroll=unroll)
