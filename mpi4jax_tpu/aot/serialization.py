"""Compiled-executable (de)serialization — gated on JAX support.

The persistent tier stores *loaded-executable* artifacts: the XLA
executable bytes plus the call signature trees, via
``jax.experimental.serialize_executable`` (the same machinery JAX's own
persistent compilation cache rides).  Everything here degrades
gracefully:

- ``supported()`` probes the API once; absent (old JAX, or a backend
  whose PjRt client cannot serialize executables) the persistent tier
  simply stores nothing — pinning still works, it just recompiles;
- ``dumps`` returns ``None`` instead of raising on any serialization
  failure (an unserializable program must not take the pin down);
- ``loads`` returns ``None`` on any deserialization failure — the
  caller treats it as a cache miss and recompiles (diskcache's container
  digest already filtered bit-rot; this filters version skew the key
  should have caught and anything pickle-level).

Payload format (inside the diskcache container): pickle of
``(SERIALIZED_EXECUTABLE_BYTES, in_tree, out_tree)``.  PyTreeDefs of
standard containers pickle portably; exotic custom nodes may not — that
is one of the graceful-``None`` paths above.
"""

from __future__ import annotations

import pickle
from typing import Optional

_PROTO = 4  # stable across the supported Pythons


def _api():
    from jax.experimental import serialize_executable as se

    return se


def supported() -> bool:
    """True when this JAX exposes the executable-serialization API."""
    try:
        se = _api()
    except ImportError:
        return False
    return hasattr(se, "serialize") and hasattr(se, "deserialize_and_load")


def dumps(compiled) -> Optional[bytes]:
    """Serialize a ``jax.stages.Compiled`` into an artifact payload, or
    ``None`` when this program/backend cannot serialize."""
    if not supported():
        return None
    try:
        payload, in_tree, out_tree = _api().serialize(compiled)
        return pickle.dumps((payload, in_tree, out_tree), protocol=_PROTO)
    except Exception:
        return None


def loads(data: bytes):
    """Deserialize an artifact payload back into a callable
    ``jax.stages.Compiled``, or ``None`` on any failure (caller
    recompiles)."""
    if not supported():
        return None
    try:
        payload, in_tree, out_tree = pickle.loads(data)
        return _api().deserialize_and_load(payload, in_tree, out_tree)
    except Exception:
        return None
