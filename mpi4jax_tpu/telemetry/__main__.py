"""CLI: ``python -m mpi4jax_tpu.telemetry merge <dir> --perfetto out.json``.

Merges every rank's events-tier JSONL journal into one Chrome-trace-
event timeline (rank = pid, op rows = tids — open in Perfetto or
``chrome://tracing``) and prints the straggler attribution table.
Exits non-zero on malformed journal lines (the CI telemetry lane's
validation contract).  See mpi4jax_tpu/telemetry/merge.py.

``python -m mpi4jax_tpu.telemetry postmortem <dir>`` instead reads the
per-rank crash bundles the health plane wrote (``postmortem-p*.json``,
docs/observability.md "Runtime health"), aligns the flight-recorder
rings by call id, and prints each rank's last-known frontier with
straggler attribution.
"""

import sys

from .merge import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
