"""Events-tier journal: per-rank begin/end records + per-process JSONL.

Each instrumented collective contributes one record per rank per
execution: the begin bracket fires when the rank's inputs are
materialized (its *arrival* at the collective — the number cross-rank
skew is computed from) and the end bracket when its first output is
ready, so ``t_end - t_begin`` is the collective's true in-flight time on
this host, exactly the bracket the watchdog and the native trace hooks
use.  Pairing is FIFO per ``(call_id, rank)`` — a trace site inside
``lax.fori_loop`` fires once per iteration under one call id, the same
aliasing the watchdog registry handles — and each completed pair gets a
monotonically increasing ``seq`` so the N-th execution of a call site
matches across ranks and processes (legal because SPMD executes one
schedule everywhere).

Two clocks per timestamp: ``mono`` (monotonic seconds, the latency
clock — shared with ``native.wallclock``'s base when the native module
is importable, pure ``time.perf_counter`` otherwise) and ``wall``
(``time.time()``, the cross-process alignment clock the merge CLI lays
the timeline out on; NTP-grade accuracy, see docs/observability.md).

With ``MPI4JAX_TPU_TELEMETRY_DIR`` set, every completed record is also
appended as one JSON line to ``events-p{process}.jsonl`` in that
directory — the input of ``python -m mpi4jax_tpu.telemetry merge``.
Pure Python except a guarded lazy import of ``native``/``jax``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from . import health

__all__ = ["begin", "end", "instant", "snapshot_events", "reset",
           "process_index", "JOURNAL_FILE_PREFIX"]

JOURNAL_FILE_PREFIX = "events-p"

# in-memory record cap: a runaway events-mode loop must degrade (drop
# oldest + count) rather than eat the host's memory; the JSONL file keeps
# everything
MAX_RECORDS = 100_000

_py_base: Optional[float] = None


def _clocks():
    """(mono, wall) seconds.  ``mono`` shares ``native.wallclock``'s
    process base when the native module imports, so journal timestamps
    are directly comparable with in-graph ``wallclock()`` values; the
    pure-Python fallback keeps its own base."""
    try:
        from .. import native

        return native.host_clock()
    except Exception:
        global _py_base
        if _py_base is None:
            _py_base = time.perf_counter()
        return time.perf_counter() - _py_base, time.time()


_proc_index: Optional[int] = None


def process_index() -> int:
    """This host's process index (0 on single-process; lazy so the module
    imports without JAX)."""
    global _proc_index
    if _proc_index is None:
        try:
            import jax

            _proc_index = int(jax.process_index())
        except Exception:
            _proc_index = 0
    return _proc_index


class _Journal:
    def __init__(self):
        self.lock = threading.Lock()
        # (call_id, rank) -> deque of (mono, wall, meta)
        self.pending = {}
        # (call_id, rank) -> completed-pair count (the seq counter)
        self.seqs = {}
        self.records = []
        self.dropped = 0
        self._file = None
        self._file_dir = None

    def _writer(self):
        """The JSONL appender for the configured dir (lazy-opened, reopened
        if the dir changes, line-buffered so readers see records as soon
        as the producing program has drained)."""
        from ..utils import config

        d = config.telemetry_dir()
        if not d:
            return None
        if self._file is not None and self._file_dir == d:
            return self._file
        if self._file is not None:
            self._file.close()
            self._file = None
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"{JOURNAL_FILE_PREFIX}{process_index()}.jsonl"
        )
        self._file = open(path, "a", buffering=1)
        self._file_dir = d
        return self._file

    def _emit(self, record: dict) -> None:
        self.records.append(record)
        if len(self.records) > MAX_RECORDS:
            del self.records[0]
            self.dropped += 1
            # surfaced, not silent: the meter rides the snapshot and
            # report(), and merge/postmortem warn from the counts
            from . import core

            core.meter("telemetry.dropped")
        # flight-recorder spill (telemetry/health.py): completed op
        # records and instants land in the bounded ring — no extra
        # callbacks, just the record the journal already built
        health.record_event(record)
        f = self._writer()
        if f is not None:
            f.write(json.dumps(record, sort_keys=True) + "\n")

    def begin(self, call_id: str, rank: int, meta: dict) -> None:
        mono, wall = _clocks()
        with self.lock:
            self.pending.setdefault((call_id, rank), deque()).append(
                (mono, wall, meta)
            )
        # arrivals reach the ring immediately: the begin a rank never
        # pairs with an end is the hung collective a postmortem needs
        health.record_begin(call_id, rank, meta, mono, wall)

    def end(self, call_id: str, rank: int, end_meta: dict) -> None:
        mono, wall = _clocks()
        key = (call_id, rank)
        with self.lock:
            dq = self.pending.get(key)
            if not dq:
                return  # unmatched end: begin was dropped by a reset
            mono0, wall0, meta = dq.popleft()
            if not dq:
                del self.pending[key]
            seq = self.seqs.get(key, 0)
            self.seqs[key] = seq + 1
            record = dict(
                meta,
                type="op",
                call_id=call_id,
                seq=seq,
                rank=rank,
                process=process_index(),
                t_begin=wall0,
                t_end=wall,
                mono_begin=mono0,
                mono_end=mono,
                latency=mono - mono0,
            )
            record.update(end_meta)
            self._emit(record)
        from . import core

        core.record_latency(
            core.op_key(record.get("op", "?"), record.get("comm_uid", "?"),
                        record.get("algo", "native"),
                        record.get("dtype", "")),
            record["latency"],
        )
        # megastep brackets (parallel/megastep.py) synthesize a per-step
        # latency estimate on close: bracket latency / trip count into
        # the megastep_step histogram — pure host bucket math, no extra
        # io_callbacks on the hot path
        unroll = record.get("unroll")
        if unroll and unroll > 1 and record.get("op") == "megastep":
            core.record_latency(
                core.op_key("megastep_step", record.get("comm_uid", "?"),
                            "estimate", ""),
                record["latency"] / unroll,
            )

    def instant(self, name: str, rank: int, meta: dict) -> None:
        mono, wall = _clocks()
        with self.lock:
            self._emit(dict(
                meta, type="instant", name=name, rank=int(rank),
                process=process_index(), t=wall, mono=mono,
            ))

    def flush(self) -> None:
        with self.lock:
            if self._file is not None:
                self._file.flush()

    def reset(self) -> None:
        with self.lock:
            self.pending.clear()
            self.seqs.clear()
            del self.records[:]
            self.dropped = 0
            if self._file is not None:
                self._file.close()
                self._file = None
                self._file_dir = None


_journal = _Journal()


def begin(call_id: str, rank: int, meta: dict) -> None:
    _journal.begin(call_id, rank, meta)


def end(call_id: str, rank: int, end_meta: dict) -> None:
    _journal.end(call_id, rank, end_meta)


def instant(name: str, rank: int, meta: Optional[dict] = None) -> None:
    """Journal a point event (fault injection, watchdog expiry, numeric
    guard trip) so infrastructure incidents land on the same timeline as
    the collectives they disrupted.  No-op unless the events tier is on."""
    from . import core

    if not core.events_on():
        return
    _journal.instant(name, rank, meta or {})


def incident(meter_name: str, name: str, rank, detail: str = "") -> None:
    """THE incident entry point for the infrastructure around the ops
    (watchdog expiries, fault injections, numeric-guard trips): bump the
    meter (counters tier and up) and journal an instant with the detail
    line (events tier), flushed so a record survives an imminent process
    death.  Callers guard the telemetry import themselves (the package
    is optional under the isolated test loaders)."""
    from . import core

    core.meter(meter_name)
    instant(name, int(rank), {"detail": detail} if detail else {})
    flush()


def snapshot_events() -> list:
    """Copy of the in-memory records (JSON-ready dicts)."""
    with _journal.lock:
        return list(_journal.records)


def dropped_records() -> int:
    with _journal.lock:
        return _journal.dropped


def flush() -> None:
    _journal.flush()


def reset() -> None:
    _journal.reset()
