"""Mergeable log2-bucketed latency histograms.

The reference got per-op latency "for free" from host-side
``perf_counter`` brackets around every libmpi call (ref
mpi_xla_bridge.pyx:47-60, 100-112) but only ever *printed* it; nothing
aggregated.  This histogram is the aggregation primitive of the telemetry
layer: fixed buckets at powers of two of a second (bucket ``b`` covers
``[2^b, 2^(b+1))`` seconds), so two histograms recorded on different
ranks — or different processes, or different days — merge by plain
bucket-wise addition with no rebinning, and a p50/p99 read off the merged
histogram is as accurate as either input's (half-bucket, i.e. ~sqrt(2),
relative error).

Pure Python on purpose: it runs inside host callbacks on the hot path and
under the isolated test loader where JAX may be unimportable.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

__all__ = ["Histogram", "bucket_index", "bucket_value"]

# latencies outside [2^MIN_BUCKET, 2^(MAX_BUCKET+1)) seconds clamp to the
# edge buckets: ~6e-10 s is below any host-callback resolution, and 2^16 s
# (~18 h) is longer than any collective that has not already tripped the
# watchdog
MIN_BUCKET = -31
MAX_BUCKET = 16


def bucket_index(value: float) -> int:
    """The log2 bucket of ``value`` seconds: ``floor(log2(value))``,
    clamped to the fixed range (non-positive values clamp to the bottom
    bucket — a begin/end pair on one host clock cannot be negative, but a
    defensive clamp beats a crash inside a host callback)."""
    if value <= 0:
        return MIN_BUCKET
    return max(MIN_BUCKET, min(MAX_BUCKET, math.floor(math.log2(value))))


def bucket_value(index: int) -> float:
    """Representative value of a bucket: its geometric midpoint
    ``2^(b+0.5)`` — the point estimate minimizing worst-case relative
    error within ``[2^b, 2^(b+1))``."""
    return 2.0 ** (index + 0.5)


class Histogram:
    """Fixed-log2-bucket histogram with exact count/sum/min/max sidecars.

    The sidecars make ``min``/``mean``/``max`` exact while quantiles are
    bucket-resolution estimates (clamped into ``[min, max]`` so a
    single-sample histogram reports its sample, not a bucket midpoint).
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def record(self, value: float) -> None:
        b = bucket_index(value)
        self.counts[b] = self.counts.get(b, 0) + 1
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def merge(self, other: "Histogram") -> "Histogram":
        """Bucket-wise sum into a NEW histogram (inputs untouched)."""
        out = Histogram()
        for src in (self, other):
            for b, n in src.counts.items():
                out.counts[b] = out.counts.get(b, 0) + n
        out.count = self.count + other.count
        out.sum = self.sum + other.sum
        mins = [m for m in (self.min, other.min) if m is not None]
        maxs = [m for m in (self.max, other.max) if m is not None]
        out.min = min(mins) if mins else None
        out.max = max(maxs) if maxs else None
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (``0 <= q <= 1``) from the buckets:
        the geometric midpoint of the bucket where the cumulative count
        crosses ``q * count``, clamped into ``[min, max]``."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0
        est = None
        for b in sorted(self.counts):
            cum += self.counts[b]
            if cum >= target:
                est = bucket_value(b)
                break
        if est is None:  # q > 1 fed in; be defensive
            est = bucket_value(max(self.counts))
        return max(self.min, min(self.max, est))

    def to_dict(self) -> dict:
        """JSON-ready form (bucket keys become strings)."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "buckets": {str(b): n for b, n in sorted(self.counts.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = d.get("min")
        h.max = d.get("max")
        h.counts = {int(b): int(n) for b, n in d.get("buckets", {}).items()}
        return h

    def __repr__(self):
        return (
            f"Histogram(count={self.count}, min={self.min}, "
            f"p50={self.quantile(0.5)}, max={self.max})"
        )
