"""Cross-rank telemetry report: snapshot gathering + the per-op table.

``report(comm=...)`` allgathers every process's snapshot *through our
own collectives* (a MAX allreduce sizes the buffer, then one allgather
moves JSON-encoded uint8 payloads — no side channel, so it works
anywhere the ops work, multi-host included), deduplicates by process
(on a single-host virtual mesh every rank returns the same process
snapshot), and renders one table per (op, comm, algorithm, dtype) with
calls, bytes, min/p50/p99 latency, and the straggler columns: max
cross-rank arrival skew and the rank most often last to arrive
(``merge.skew_table`` over the merged events).

Heavy imports (jax, the ops) happen inside the functions: this module
must import cleanly without JAX so ``mpi4jax_tpu.telemetry`` stays
loadable under the isolated test loader.
"""

from __future__ import annotations

import json
import sys
from typing import List

from . import core, merge
from .hist import Histogram

__all__ = ["snapshot", "report", "dump", "gather_snapshots"]

snapshot = core.snapshot


def dump(path: str, include_events: bool = True) -> str:
    """Write this process's full snapshot (events included by default) as
    JSON to ``path``; returns ``path``."""
    with open(path, "w") as f:
        json.dump(core.snapshot(include_events=include_events), f,
                  indent=2, sort_keys=True)
        f.write("\n")
    return path


def gather_snapshots(comm=None) -> List[dict]:
    """Every process's snapshot, gathered through our own collectives.

    Must run OUTSIDE any parallel region (it dispatches eager ops).  Each
    rank contributes its process's snapshot; the result is deduplicated
    to one snapshot per process.  Events are included when the events
    tier is on (they carry the arrival times the skew columns need).
    """
    import numpy as np

    from .. import MAX, allgather, allreduce
    from ..parallel.region import resolve_comm

    comm = resolve_comm(comm)
    if comm.mesh is None:
        raise RuntimeError(
            "telemetry.report/gather_snapshots needs a comm bound to a "
            "mesh (they dispatch eager collectives to move snapshots)"
        )
    local = json.dumps(
        core.snapshot(include_events=core.events_on()), sort_keys=True
    ).encode()
    size = comm.world_size()

    # size the buffer: MAX-allreduce the encoded lengths (every process
    # supplies the full global array; the mesh takes each device's row
    # from the process that owns the device, so row r is rank r's length)
    lengths = np.full((size, 1), len(local), np.int32)
    maxlen_g, _ = allreduce(lengths, op=MAX, comm=comm)
    maxlen = int(np.asarray(maxlen_g)[0, 0])

    payload = np.zeros((size, maxlen), np.uint8)
    payload[:, :len(local)] = np.frombuffer(local, np.uint8)
    gathered, _ = allgather(payload, comm=comm)
    rows = np.asarray(gathered)[0]  # (size, maxlen), row r = rank r

    snaps = {}
    for row in rows:
        text = bytes(row).rstrip(b"\x00").decode()
        snap = json.loads(text)
        snaps.setdefault(snap.get("process", 0), snap)
    return [snaps[p] for p in sorted(snaps)]


def _merge_counters(snaps: List[dict]) -> dict:
    """Sum op counters and merge latency histograms across process
    snapshots; returns ``{key: row}`` in snapshot-row format."""
    out: dict = {}
    for snap in snaps:
        for key, row in snap.get("ops", {}).items():
            dst = out.setdefault(key, {
                **{k: row[k] for k in
                   ("op", "comm_uid", "algo", "dtype")},
                "calls": 0, "bytes": 0, "intra_bytes": 0,
                "inter_bytes": 0, "hist": Histogram(),
            })
            dst["calls"] += row.get("calls", 0)
            dst["bytes"] += row.get("bytes", 0)
            dst["intra_bytes"] += row.get("intra_bytes", 0)
            dst["inter_bytes"] += row.get("inter_bytes", 0)
            if "latency" in row:
                dst["hist"] = dst["hist"].merge(
                    Histogram.from_dict(row["latency"])
                )
    return out


def _merged_events(snaps: List[dict]) -> list:
    events = []
    for snap in snaps:
        events.extend(snap.get("events", []))
    return events


def _fmt_us(seconds) -> str:
    if seconds is None:
        return "-"
    return f"{seconds * 1e6:,.1f}"


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):,.1f}M"
    if n >= 1 << 10:
        return f"{n / (1 << 10):,.1f}K"
    return str(n)


def render(snaps: List[dict]) -> str:
    """The per-op table for a set of gathered process snapshots."""
    ops = _merge_counters(snaps)
    events = _merged_events(snaps)
    skews = merge.skew_table(events) if events else {"per_op": {},
                                                    "per_rank": {}}

    header = (
        f"{'op':<16} {'comm':>4} {'algo':<10} {'dtype':<9} {'calls':>7} "
        f"{'bytes':>9} {'intra B':>9} {'inter B':>9} {'execs':>6} "
        f"{'min us':>9} {'p50 us':>9} "
        f"{'p99 us':>9} {'skew us':>9} {'straggler':>9}"
    )
    lines = [header, "-" * len(header)]
    # the table's straggler column charges the rank with the most
    # last-arrivals overall; the per-rank chart below has the full story
    worst_rank = None
    if skews["per_rank"]:
        worst_rank = max(
            skews["per_rank"],
            key=lambda r: skews["per_rank"][r]["last_arrivals"],
        )
    for key in sorted(ops):
        row = ops[key]
        h = row["hist"]
        sk = skews["per_op"].get(row["op"])
        lines.append(
            f"{row['op']:<16} {row['comm_uid']:>4} {row['algo']:<10} "
            f"{row['dtype']:<9} {row['calls']:>7} "
            f"{_fmt_bytes(row['bytes']):>9} "
            f"{_fmt_bytes(row['intra_bytes']):>9} "
            f"{_fmt_bytes(row['inter_bytes']):>9} {h.count:>6} "
            f"{_fmt_us(h.min):>9} {_fmt_us(h.quantile(0.5)):>9} "
            f"{_fmt_us(h.quantile(0.99)):>9} "
            f"{_fmt_us(sk['max_skew']) if sk else '-':>9} "
            f"{('r' + str(worst_rank)) if sk else '-':>9}"
        )
    total_meters = {}
    for snap in snaps:
        for name, n in snap.get("meters", {}).items():
            total_meters[name] = total_meters.get(name, 0) + n
    if total_meters:
        lines.append("")
        lines.append("meters:")
        for name in sorted(total_meters):
            lines.append(f"  {name:<40} {total_meters[name]:>10}")
    # dropped-record accounting: bounded buffers (journal overflow,
    # flight-ring overwrites) degrade by dropping — which must be SAID,
    # or the tables above silently claim completeness they don't have
    total_dropped = {}
    for snap in snaps:
        for src, n in snap.get("dropped", {}).items():
            total_dropped[src] = total_dropped.get(src, 0) + n
    if any(total_dropped.values()):
        lines.append("")
        lines.append("dropped: " + ", ".join(
            f"{n} {src} record(s)"
            for src, n in sorted(total_dropped.items()) if n))
    # compile-cache section (docs/aot.md): AOT pins/calls/stale refusals
    # summed across processes, disk-cache traffic per process — the one-
    # glance answer to "did the second cold start actually deserialize?"
    cc_snaps = [(snap.get("process", 0), snap["compile_cache"])
                for snap in snaps if "compile_cache" in snap]
    if cc_snaps:
        agg = {k: 0 for k in ("pins", "calls", "stale_raises",
                              "disk_loads", "compiles")}
        disk = {k: 0 for k in ("hits", "misses", "writes", "evictions",
                               "bytes")}
        enabled_dirs = set()
        entries = disk_bytes = 0
        for _, cc in cc_snaps:
            for k in agg:
                agg[k] += cc.get("aot", {}).get(k, 0)
            d = cc.get("disk_cache", {})
            for k in disk:
                disk[k] += d.get(k, 0)
            if d.get("enabled"):
                enabled_dirs.add(d.get("dir", ""))
            entries = max(entries, d.get("entries", 0))
            disk_bytes = max(disk_bytes, d.get("disk_bytes", 0))
        lines.append("")
        lines.append("compile cache:")
        lines.append(
            f"  aot: {agg['pins']} pin(s), {agg['calls']} pinned call(s), "
            f"{agg['stale_raises']} stale refusal(s) "
            f"({agg['disk_loads']} loaded from disk, "
            f"{agg['compiles']} compiled fresh)"
        )
        if enabled_dirs:
            lines.append(
                f"  disk: {disk['hits']} hit(s), {disk['misses']} "
                f"miss(es), {disk['writes']} write(s), "
                f"{disk['evictions']} eviction(s); "
                f"{entries} artifact(s), "
                f"{_fmt_bytes(disk_bytes)} on disk "
                f"({', '.join(sorted(enabled_dirs))})"
            )
        else:
            lines.append("  disk: persistent tier disabled "
                         "(MPI4JAX_TPU_COMPILE_CACHE_DIR unset)")
    # the active tuning layer (docs/autotune.md): the stamp every
    # advisory cites as tuned@<stamp>, plus each knob's tuned value
    # against the static default (and whether an explicit env flag is
    # overriding the file — default < tuning < env)
    tunings = {}
    for snap in snaps:
        t = snap.get("tuning")
        if t:
            tunings.setdefault(str(t.get("stamp")), t)
    if tunings:
        lines.append("")
        lines.append("tuning:")
        for stamp in sorted(tunings):
            t = tunings[stamp]
            src = t.get("path") or "<in-memory>"
            lines.append(f"  tuned@{stamp}  ({src})")
            for name in sorted(t.get("knobs", {})):
                row = t["knobs"][name]
                if row.get("tuned") is None:
                    continue
                mark = ("  [env wins: "
                        f"{row.get('effective')}]"
                        if row.get("env_wins") else "")
                lines.append(
                    f"    {name:<22} tuned {str(row['tuned']):>10}  "
                    f"(default {row.get('default')}){mark}"
                )
            commit = t.get("commit") or {}
            if commit:
                parts = ", ".join(f"{k}={v}" for k, v in
                                  sorted(commit.items()))
                lines.append(f"    commit: {parts}")
    # serving section (docs/serving.md): the request-level story the
    # per-phase op rows above (serving.prefill / serving.decode, with
    # p50/p99 and — in the events tier — cross-rank skew + straggler)
    # do not carry: admissions, completions, failures, tokens, megastep
    # count, and elastic re-admissions, summed across processes
    srv = {name[len("serving."):]: n for name, n in total_meters.items()
           if name.startswith("serving.")}
    if srv:
        lines.append("")
        lines.append("serving:")
        for label, key in (("requests admitted", "requests_admitted"),
                           ("requests completed", "requests_completed"),
                           ("requests failed", "requests_failed"),
                           ("tokens generated", "tokens_generated"),
                           ("prefill dispatches", "prefills"),
                           ("decode megasteps", "megasteps"),
                           ("drain re-admissions", "readmissions")):
            if key in srv:
                lines.append(f"  {label:<22} {srv[key]:>10}")
    # pipeline section (docs/pipeline.md): the MEASURED bubble story the
    # modeled MPX144/MPX135 advisories cannot carry — host-bracket time
    # inside the steady-state rounds ("stage") vs the warmup/cooldown
    # phases ("bubble_wait"), summed across processes, and the measured
    # bubble fraction they imply
    pipe = {name[len("pipeline."):]: n for name, n in total_meters.items()
            if name.startswith("pipeline.")}
    if pipe:
        lines.append("")
        lines.append("pipeline:")
        for label, key in (("steady rounds", "rounds"),
                           ("stage time (us)", "stage_us"),
                           ("bubble wait (us)", "bubble_wait_us")):
            if key in pipe:
                lines.append(f"  {label:<22} {pipe[key]:>10}")
        stage_us = pipe.get("stage_us", 0)
        bubble_us = pipe.get("bubble_wait_us", 0)
        if stage_us + bubble_us > 0:
            frac = bubble_us / float(stage_us + bubble_us)
            lines.append(f"  {'bubble fraction':<22} {frac:>10.1%}")
    epochs = {}
    for snap in snaps:
        for rec in snap.get("epochs", ()):
            epochs.setdefault(int(rec["epoch"]), rec)
    if epochs:
        # the elastic audit trail: every world change with its cause, so
        # a churn run is auditable post-hoc (docs/resilience.md)
        lines.append("")
        lines.append("epoch history:")
        for e in sorted(epochs):
            rec = epochs[e]
            world = rec.get("world")
            lines.append(
                f"  epoch {e:>3}  world {world if world is not None else '?':>4}"
                f"  {rec.get('cause', '?'):<8} {rec.get('detail', '')}"
            )
    if events:
        lines.append("")
        lines.append(merge.render_skew(skews))
    return "\n".join(lines)


def report(comm=None, file=None) -> str:
    """Gather every process's snapshot over ``comm`` and print/return the
    per-op table (the straggler columns need the ``events`` tier; with
    ``counters`` they render as ``-``)."""
    from . import journal

    journal.flush()
    text = render(gather_snapshots(comm))
    print(text, file=file if file is not None else sys.stdout)
    return text
