"""Merge per-rank JSONL journals into one Chrome-trace-event timeline.

``python -m mpi4jax_tpu.telemetry merge <dir> --perfetto out.json`` reads
every ``*.jsonl`` the events tier wrote under ``<dir>`` (one file per
process; records carry their rank), validates each line, and renders:

- a **Chrome trace-event file** (the JSON Array/Object format Perfetto
  and ``chrome://tracing`` open): rank = pid, one tid row per op name,
  one complete (``ph: "X"``) slice per collective execution with call
  id / seq / bytes / dtype / algorithm in ``args``, and instant events
  for journalled incidents (fault injections, watchdog expiries);
- a **straggler attribution table**: executions of the same call site
  are matched across ranks by ``(op, call_id, seq)`` (legal because SPMD
  executes one schedule everywhere); per group, skew = max − min arrival
  (``t_begin``), and the rank arriving last is charged.  A healthy job
  spreads last-arrivals evenly; a straggling host collects them.

Timeline placement uses the records' ``t_begin`` wall clock (cross-
process comparable at NTP accuracy); durations use the monotonic
latency.  Pure Python — also the unit under the isolated-loader tests,
so it must import without JAX.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

from . import health as _health

__all__ = ["read_journal", "merge_dir", "chrome_trace", "skew_table",
           "render_skew", "read_bundles", "postmortem_report",
           "render_postmortem", "main", "MalformedJournal"]

_OP_REQUIRED = ("op", "call_id", "seq", "rank", "t_begin", "t_end",
                "latency")
_INSTANT_REQUIRED = ("name", "rank", "t")

# pid/tid sort: the "events" row (instants) sits above the op rows
_INSTANT_TID = 0


class MalformedJournal(ValueError):
    """A journal line that does not parse or lacks required fields
    (the CI lane fails the build on this)."""


def read_journal(path: str) -> List[dict]:
    """Parse one JSONL journal, validating every line."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise MalformedJournal(
                    f"{path}:{lineno}: not valid JSON: {e}"
                ) from e
            if not isinstance(rec, dict):
                raise MalformedJournal(
                    f"{path}:{lineno}: expected a JSON object, got "
                    f"{type(rec).__name__}"
                )
            kind = rec.get("type")
            required = {"op": _OP_REQUIRED, "instant": _INSTANT_REQUIRED}
            if kind not in required:
                raise MalformedJournal(
                    f"{path}:{lineno}: unknown record type {kind!r}"
                )
            missing = [k for k in required[kind] if k not in rec]
            if missing:
                raise MalformedJournal(
                    f"{path}:{lineno}: {kind} record missing field(s) "
                    f"{missing}"
                )
            records.append(rec)
    return records


def merge_dir(directory: str) -> List[dict]:
    """Read and concatenate every ``*.jsonl`` journal under ``directory``,
    deduplicated (re-running a report in the producing process can journal
    a record twice) and deterministically ordered."""
    paths = sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.endswith(".jsonl")
    )
    if not paths:
        raise FileNotFoundError(f"no *.jsonl journals under {directory}")
    records = []
    seen = set()
    for path in paths:
        for rec in read_journal(path):
            key = (rec.get("process"), rec.get("rank"), rec.get("type"),
                   rec.get("op"), rec.get("name"), rec.get("call_id"),
                   rec.get("seq"), rec.get("t_begin"), rec.get("t"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    records.sort(key=lambda r: (r.get("t_begin", r.get("t", 0.0)),
                                r.get("rank", 0), r.get("seq", 0)))
    return records


def chrome_trace(records: List[dict]) -> dict:
    """Render merged records as a Chrome trace-event object
    (Perfetto / ``chrome://tracing``): rank = pid, op rows = tids."""
    op_names = sorted({r["op"] for r in records if r["type"] == "op"})
    tids = {op: i + 1 for i, op in enumerate(op_names)}  # 0 = instants
    ranks = sorted({int(r["rank"]) for r in records})
    base = min(
        (r.get("t_begin", r.get("t")) for r in records), default=0.0
    )

    events = []
    for rank in ranks:
        events.append({
            "ph": "M", "name": "process_name", "pid": rank,
            "args": {"name": f"rank {rank}"},
        })
        events.append({
            "ph": "M", "name": "process_sort_index", "pid": rank,
            "args": {"sort_index": rank},
        })
        events.append({
            "ph": "M", "name": "thread_name", "pid": rank,
            "tid": _INSTANT_TID, "args": {"name": "events"},
        })
        for op, tid in tids.items():
            events.append({
                "ph": "M", "name": "thread_name", "pid": rank, "tid": tid,
                "args": {"name": op},
            })
    for r in records:
        if r["type"] == "op":
            events.append({
                "ph": "X",
                "name": r["op"],
                "cat": "collective",
                "pid": int(r["rank"]),
                "tid": tids[r["op"]],
                "ts": (r["t_begin"] - base) * 1e6,
                "dur": r["latency"] * 1e6,
                "args": {
                    k: r[k]
                    for k in ("call_id", "seq", "process", "bytes",
                              "dtype", "algo", "comm_uid", "axes")
                    if k in r
                },
            })
        else:
            events.append({
                "ph": "i",
                "s": "p",
                "name": r["name"],
                "cat": "incident",
                "pid": int(r["rank"]),
                "tid": _INSTANT_TID,
                "ts": (r["t"] - base) * 1e6,
                "args": {
                    k: r[k] for k in ("process", "detail") if k in r
                },
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "mpi4jax_tpu.telemetry",
            "ranks": ranks,
            "ops": op_names,
        },
    }


def skew_table(records: List[dict]) -> dict:
    """Cross-rank skew + straggler attribution from merged records.

    Returns ``{"per_op": {op: {max_skew, mean_skew, groups}},
    "per_rank": {rank: {last_arrivals, groups}}}`` — skews in seconds,
    computed over execution groups matched by ``(op, call_id, seq)``
    that span at least two ranks."""
    groups: Dict[tuple, List[dict]] = {}
    for r in records:
        if r["type"] == "op":
            groups.setdefault(
                (r["op"], r["call_id"], r["seq"]), []
            ).append(r)

    per_op: Dict[str, dict] = {}
    per_rank: Dict[int, dict] = {}
    for (op, _cid, _seq), members in groups.items():
        by_rank = {}
        for m in members:  # one record per rank per group; keep earliest
            rank = int(m["rank"])
            if rank not in by_rank or m["t_begin"] < by_rank[rank]:
                by_rank[rank] = m["t_begin"]
        if len(by_rank) < 2:
            continue
        arrivals = sorted(by_rank.items(), key=lambda kv: kv[1])
        skew = arrivals[-1][1] - arrivals[0][1]
        straggler = arrivals[-1][0]
        row = per_op.setdefault(
            op, {"max_skew": 0.0, "skew_sum": 0.0, "groups": 0}
        )
        row["max_skew"] = max(row["max_skew"], skew)
        row["skew_sum"] += skew
        row["groups"] += 1
        for rank in by_rank:
            rrow = per_rank.setdefault(
                rank, {"last_arrivals": 0, "groups": 0}
            )
            rrow["groups"] += 1
            if rank == straggler:
                rrow["last_arrivals"] += 1
    for row in per_op.values():
        row["mean_skew"] = row.pop("skew_sum") / row["groups"]
    return {"per_op": per_op, "per_rank": per_rank}


def _us(seconds: float) -> str:
    return f"{seconds * 1e6:,.1f}"


def render_skew(table: dict) -> str:
    """Human-readable straggler attribution (also what ``report()``
    embeds as its skew columns' standalone form)."""
    lines = []
    if not table["per_op"]:
        return ("no cross-rank execution groups found (need events from "
                ">= 2 ranks)")
    lines.append(f"{'op':<16} {'groups':>7} {'mean skew us':>13} "
                 f"{'max skew us':>12}")
    for op in sorted(table["per_op"]):
        row = table["per_op"][op]
        lines.append(
            f"{op:<16} {row['groups']:>7} {_us(row['mean_skew']):>13} "
            f"{_us(row['max_skew']):>12}"
        )
    lines.append("")
    lines.append(f"{'rank':<6} {'last arrivals':>14} {'of groups':>10}   "
                 "(a healthy job spreads these evenly)")
    for rank in sorted(
        table["per_rank"],
        key=lambda r: -table["per_rank"][r]["last_arrivals"],
    ):
        row = table["per_rank"][rank]
        lines.append(
            f"r{rank:<5} {row['last_arrivals']:>14} {row['groups']:>10}"
        )
    return "\n".join(lines)


def read_bundles(directory: str) -> List[dict]:
    """Parse every per-rank postmortem bundle
    (``postmortem-p*.json``, written by ``health.dump_postmortem``)
    under ``directory``, sorted by process index."""
    paths = sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.startswith(_health.POSTMORTEM_FILE_PREFIX)
        and name.endswith(".json")
    )
    if not paths:
        raise FileNotFoundError(
            f"no {_health.POSTMORTEM_FILE_PREFIX}*.json bundles under "
            f"{directory} (set MPI4JAX_TPU_HEALTH=on and "
            f"MPI4JAX_TPU_TELEMETRY_DIR to produce them)"
        )
    bundles = []
    for path in paths:
        try:
            with open(path) as f:
                bundle = json.load(f)
        except ValueError as e:
            raise MalformedJournal(f"{path}: not valid JSON: {e}") from e
        if (not isinstance(bundle, dict)
                or bundle.get("schema") != _health.POSTMORTEM_SCHEMA):
            raise MalformedJournal(
                f"{path}: not a {_health.POSTMORTEM_SCHEMA} bundle"
            )
        bundles.append(bundle)
    return sorted(bundles, key=lambda b: b.get("process", 0))


def _bundle_dropped(directory: str) -> Dict[str, int]:
    """Best-effort dropped-record totals from any postmortem bundles in
    ``directory`` (the merge CLI's completeness warning — the JSONL
    journals themselves never drop, but the in-memory ring/journal the
    bundles snapshot do)."""
    totals: Dict[str, int] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return totals
    for name in names:
        if not (name.startswith(_health.POSTMORTEM_FILE_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                bundle = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(bundle, dict):
            continue
        for src, n in (bundle.get("dropped") or {}).items():
            if n:
                totals[src] = totals.get(src, 0) + int(n)
    return totals


def postmortem_report(bundles: List[dict]) -> dict:
    """Merge per-rank postmortem bundles into the "who was stuck where"
    answer.

    Flight-recorder rings are aligned across ranks by call id: per rank,
    the last *completed* op and the last *begun* op (a begin without a
    matching completion is an op still in flight when the bundle was
    written); across ranks, the **frontier call** is the call id with
    the latest arrival anywhere — ranks that never arrived at it are the
    stragglers everyone else was waiting for.  Attribution order:

    1. a journalled ``fault`` incident in a rank's ring (deterministic
       under fault injection — the injected rank journals before dying
       or hanging);
    2. ranks missing their arrival at the frontier call while peers
       arrived;
    3. the rank with the largest in-flight watchdog elapsed time.
    """
    processes: Dict[int, dict] = {}
    # call_id -> {"op", "began": {rank: t}, "ended": {rank: t}}
    calls: Dict[str, dict] = {}
    all_ranks = set()
    dropped: Dict[str, int] = {}
    times = []
    for b in bundles:
        proc = int(b.get("process", 0))
        for src, n in (b.get("dropped") or {}).items():
            if n:
                dropped[src] = dropped.get(src, 0) + int(n)
        pinfo = processes.setdefault(proc, {
            "reasons": list(b.get("reasons") or ()),
            "inflight": list(b.get("watchdog_inflight") or ()),
            "ranks": {},
        })
        for e in pinfo["inflight"]:
            if "rank" in e:
                all_ranks.add(int(e["rank"]))
        for rec in (b.get("flight") or {}).get("records", ()):
            if not isinstance(rec, dict) or "rank" not in rec:
                continue  # dispatch records carry no rank
            rank = int(rec["rank"])
            all_ranks.add(rank)
            rinfo = pinfo["ranks"].setdefault(rank, {
                "last_completed": None, "last_begin": None,
                "incidents": [],
            })
            if rec.get("type") == "op":
                times.append(rec["t_end"])
                cur = rinfo["last_completed"]
                if cur is None or rec["t_end"] > cur["t_end"]:
                    rinfo["last_completed"] = rec
                call = calls.setdefault(rec.get("call_id"),
                                        {"op": rec.get("op", "?"),
                                         "began": {}, "ended": {}})
                call["ended"][rank] = max(call["ended"].get(rank, 0.0),
                                          rec["t_end"])
                call["began"][rank] = max(call["began"].get(rank, 0.0),
                                          rec["t_begin"])
            elif rec.get("type") == "instant":
                times.append(rec["t"])
                rinfo["incidents"].append(rec)
            elif rec.get("kind") == "begin":
                times.append(rec["t"])
                cur = rinfo["last_begin"]
                if cur is None or rec["t"] > cur["t"]:
                    rinfo["last_begin"] = rec
                call = calls.setdefault(rec.get("call_id"),
                                        {"op": rec.get("op", "?"),
                                         "began": {}, "ended": {}})
                call["began"][rank] = max(call["began"].get(rank, 0.0),
                                          rec["t"])
    # the frontier: the call somebody arrived at last
    frontier = None
    if calls:
        fid = max(calls, key=lambda c: max(calls[c]["began"].values(),
                                           default=0.0))
        call = calls[fid]
        began = sorted(call["began"])
        frontier = {
            "call_id": fid,
            "op": call["op"],
            "t": max(call["began"].values(), default=0.0),
            "began": began,
            "ended": sorted(call["ended"]),
            "missing": sorted(all_ranks - set(began)),
        }
    suspects = []
    seen_ranks = set()

    def _suspect(rank, op, call_id, why):
        if rank in seen_ranks:
            return
        seen_ranks.add(rank)
        suspects.append({"rank": int(rank), "op": op,
                         "call_id": call_id, "why": why})

    for proc in sorted(processes):
        for rank in sorted(processes[proc]["ranks"]):
            for inc in processes[proc]["ranks"][rank]["incidents"]:
                if inc.get("name") == "fault":
                    _suspect(rank, None, None,
                             "fault incident journalled on this rank: "
                             + str(inc.get("detail", "")))
    if frontier and frontier["began"] and frontier["missing"]:
        for rank in frontier["missing"]:
            _suspect(
                rank, frontier["op"], frontier["call_id"],
                f"never arrived at {frontier['op']} call "
                f"{frontier['call_id']} "
                f"({len(frontier['began'])} peer rank(s) arrived)",
            )
    if not suspects:
        stuck = [
            (e.get("elapsed", 0.0), e)
            for proc in processes
            for e in processes[proc]["inflight"]
        ]
        if stuck:
            elapsed, e = max(stuck, key=lambda x: x[0])
            _suspect(e.get("rank", 0), e.get("opname"), e.get("call_id"),
                     f"largest in-flight time: {e.get('opname', '?')} "
                     f"call {e.get('call_id', '?')} stuck {elapsed:.1f}s")
    return {
        "processes": processes,
        "frontier": frontier,
        "suspects": suspects,
        "dropped": dropped,
        "base_t": min(times) if times else 0.0,
    }


def render_postmortem(report: dict) -> str:
    """Human-readable postmortem: per-rank frontier + attribution."""
    base = report["base_t"]

    def _rel(t):
        return f"+{t - base:.3f}s"

    lines = []
    nranks = sum(len(p["ranks"]) for p in report["processes"].values())
    lines.append(f"postmortem: {len(report['processes'])} bundle(s), "
                 f"{nranks} rank(s) with flight records")
    for proc in sorted(report["processes"]):
        pinfo = report["processes"][proc]
        lines.append("")
        lines.append(f"process {proc}:")
        if pinfo["reasons"]:
            lines.append("  reasons: " + "; ".join(pinfo["reasons"]))
        for rank in sorted(pinfo["ranks"]):
            rinfo = pinfo["ranks"][rank]
            lines.append(f"  rank {rank}:")
            done = rinfo["last_completed"]
            if done is not None:
                lines.append(
                    f"    last completed: {done.get('op', '?')} call "
                    f"{done.get('call_id', '?')} seq {done.get('seq', '?')}"
                    f" @ {_rel(done['t_end'])}")
            beg = rinfo["last_begin"]
            if beg is not None:
                lines.append(
                    f"    last begin:     {beg.get('op', '?')} call "
                    f"{beg.get('call_id', '?')} @ {_rel(beg['t'])}")
            for inc in rinfo["incidents"][-3:]:
                detail = inc.get("detail", "")
                lines.append(
                    f"    incident @ {_rel(inc['t'])}: {inc.get('name')}"
                    + (f" — {detail}" if detail else ""))
        for e in pinfo["inflight"]:
            lines.append(
                f"  in flight: {e.get('opname', '?')} call "
                f"{e.get('call_id', '?')} rank {e.get('rank', '?')} "
                f"(elapsed {e.get('elapsed', 0.0):.1f}s of "
                f"{e.get('timeout', 0.0):g}s budget)")
    frontier = report["frontier"]
    if frontier is not None:
        lines.append("")
        ranks_s = ",".join(str(r) for r in frontier["began"])
        line = (f"frontier: {frontier['op']} call {frontier['call_id']} "
                f"@ {_rel(frontier['t'])} — arrived: rank(s) {ranks_s}")
        if frontier["missing"]:
            line += ("; MISSING: rank(s) "
                     + ",".join(str(r) for r in frontier["missing"]))
        lines.append(line)
    if report["dropped"]:
        lines.append("")
        lines.append("dropped: " + ", ".join(
            f"{n} {src} record(s)"
            for src, n in sorted(report["dropped"].items())))
    lines.append("")
    if report["suspects"]:
        for s in report["suspects"]:
            lines.append(f"suspected straggler: rank {s['rank']} — "
                         f"{s['why']}")
    else:
        lines.append("no straggler attribution (no fault incidents, no "
                     "missing arrivals, no in-flight ops)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``merge <dir> [--perfetto OUT] [--no-skew]`` and
    ``postmortem <dir> [--out OUT]`` (exit 2 on a malformed journal or
    bundle, or when no bundles exist — the CI contract)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.telemetry",
        description="merge per-rank telemetry journals "
                    "(docs/observability.md)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser(
        "merge", help="merge a journal dir into a Chrome trace"
    )
    mp.add_argument("dir", help="MPI4JAX_TPU_TELEMETRY_DIR of the run")
    mp.add_argument("--perfetto", metavar="OUT",
                    help="write the merged Chrome-trace-event JSON here "
                         "(open in Perfetto / chrome://tracing)")
    mp.add_argument("--no-skew", action="store_true",
                    help="skip the straggler attribution table")
    pp = sub.add_parser(
        "postmortem",
        help="merge per-rank postmortem bundles: last-known frontier "
             "per rank + straggler attribution",
    )
    pp.add_argument("dir", help="MPI4JAX_TPU_TELEMETRY_DIR of the run")
    pp.add_argument("--out", metavar="OUT",
                    help="also write the rendered report here")
    args = parser.parse_args(argv)

    if args.cmd == "postmortem":
        try:
            bundles = read_bundles(args.dir)
        except (MalformedJournal, FileNotFoundError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        text = render_postmortem(postmortem_report(bundles))
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
            print(f"wrote {args.out}", file=sys.stderr)
        return 0

    try:
        records = merge_dir(args.dir)
    except (MalformedJournal, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    ranks = {int(r["rank"]) for r in records}
    ops = {r["op"] for r in records if r["type"] == "op"}
    print(f"merged {len(records)} records from {len(ranks)} rank(s), "
          f"{len(ops)} op(s)")
    dropped = _bundle_dropped(args.dir)
    if dropped:
        print("warning: bounded in-memory buffers dropped records ("
              + ", ".join(f"{src}: {n}"
                          for src, n in sorted(dropped.items()))
              + ") — snapshots/reports from that run were incomplete "
              "(the JSONL timeline above is not; see the postmortem "
              "bundles)", file=sys.stderr)
    if args.perfetto:
        trace = chrome_trace(records)
        with open(args.perfetto, "w") as f:
            json.dump(trace, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.perfetto} "
              f"({len(trace['traceEvents'])} trace events)")
    if not args.no_skew:
        print()
        print(render_skew(skew_table(records)))
    return 0
