"""Events-tier in-graph brackets: host callbacks around each collective.

Reuses the native ``op_begin``/``op_end`` hooks' data-dependency
threading (ops/_base.py ``_run_body``): the begin callback's operand is
the rank tied to the op's first input (and token), so it fires when this
rank's inputs are materialized — the rank's *arrival* at the collective,
the timestamp cross-rank skew is computed from; the end callback is tied
to the op's first output, so begin→end is the collective's true
in-flight bracket on this host (the watchdog uses the same one).  The
callbacks are pure-Python ``io_callback``\\ s feeding the journal —
``time.perf_counter`` precision everywhere, no native library required
(the native runtime's C++ ``op_begin``/``op_end`` log path composes
independently via ``MPI4JAX_TPU_TRACE``).

Like every host callback in this codebase (fault probes, watchdog
fallback), one fires per rank per execution on the host that owns the
rank — which is what makes per-rank arrival times observable even on a
single-host virtual mesh.
"""

from __future__ import annotations

from typing import Optional

from . import core, journal

__all__ = ["bracket_for", "EventBracket"]


def bracket_for(rec) -> Optional["EventBracket"]:
    """The events bracket for one dispatch, or ``None`` unless the
    ``events`` tier is on (``rec`` is the dispatch's open
    :class:`~.core.OpRecord`)."""
    if rec is None or not core.events_on():
        return None
    return EventBracket(rec)


def _io_callback(fn, operand):
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    return io_callback(
        fn, jax.ShapeDtypeStruct((), jnp.uint32), operand, ordered=False
    )


class EventBracket:
    """Begin/end journal callbacks for one op dispatch."""

    __slots__ = ("rec",)

    def __init__(self, rec):
        self.rec = rec

    def begin(self, call_id: str, comm, arrays, token):
        """Emit the begin callback; returns ``(arrays, token)`` tied after
        it so the collective cannot start before the arrival timestamp."""
        import jax.numpy as jnp
        import numpy as np

        from .. import native
        from ..ops.token import Token

        rec = self.rec
        meta = {
            "op": rec.op,
            "comm_uid": str(rec.comm_uid),
            "axes": list(rec.comm_axes),
            "bytes": rec.bytes,
            "dtype": rec.dtype,
        }

        def _begin(r):
            journal.begin(call_id, int(r), meta)
            return np.uint32(r)

        rank = jnp.asarray(comm.global_rank(), jnp.uint32)
        # arrival semantics: the callback operand depends on the op's
        # first input (and token), so the timestamp is taken when this
        # rank's inputs are ready — after any upstream compute, prior
        # collectives, or injected straggler delay
        if arrays:
            rank = native._tie(rank, arrays[0])
        if token is not None:
            rank = native._tie(rank, token.value)
        dep = _io_callback(_begin, rank)
        # array-less, token-less dispatches (a bare barrier) give the tie
        # below nothing to anchor to; synthesize the token exactly like
        # resilience.runtime.Plan.before does
        if not arrays and token is None:
            token = Token(jnp.zeros((), jnp.uint32))
        arrays = tuple(native._tie(a, dep) for a in arrays)
        if token is not None:
            token = Token(native._tie(token.value, dep))
        return arrays, token

    def end(self, call_id: str, comm, dep):
        """Emit the end callback, tied after ``dep`` (the op's first
        output).  Reads the algorithm annotation now — the op body has
        run, so the selection is known."""
        import jax.numpy as jnp
        import numpy as np

        from .. import native

        end_meta = {"algo": self.rec.algo}

        def _end(r):
            journal.end(call_id, int(r), end_meta)
            return np.uint32(r)

        rank = jnp.asarray(comm.global_rank(), jnp.uint32)
        _io_callback(_end, native._tie(rank, dep))
