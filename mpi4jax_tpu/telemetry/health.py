"""Runtime health plane: flight recorder, degradation detector, postmortems.

Everything shipped before this module is post-hoc or trace-time: the
events journals are merged after the run, the analyzers critique
programs before they run, and the watchdog can only kill.  This module
is the *in-flight* surface (``MPI4JAX_TPU_HEALTH=on``):

- **flight recorder** — a bounded lock-free in-memory ring of the most
  recent op begin/end/incident records, fed exclusively from hooks the
  host already runs (the counter commit points in ``telemetry/core.py``
  and the journal emit point in ``telemetry/journal.py`` — no new
  ``io_callback``\\ s, so it is cheap enough to stay on in ``counters``
  mode).  ``flight_snapshot()`` returns the window; postmortem bundles
  embed it.
- **degradation detector** — rolling latency digests per op key fed
  from ``core.record_latency``, checked at megastep/commit boundaries
  (``on_boundary``, driven by the elastic run loop and the serving
  engine's boundary-hook registry).  Every
  ``MPI4JAX_TPU_HEALTH_INTERVAL``-th boundary runs the local
  window-vs-baseline slowdown check and, when a mesh-bound comm is
  available, ONE tiny allgather of digest summaries for the cross-rank
  skew check.  Findings journal ``health`` incidents and bump
  ``health.*`` meters; under ``MPI4JAX_TPU_HEALTH_SUSPECTS`` a
  persistent straggler is posted as a *suspect* into the elastic
  agreement machinery (``resilience/elastic.py``) and surfaced as a
  :class:`RankFailure` so the elastic plane can act on slow-but-alive
  ranks — the failure mode the ``hang`` fault verb simulates.
- **postmortem bundles** — ``dump_postmortem()`` (and the automatic
  triggers: watchdog expiry, fatal fault injection, a classified
  ``RankFailure``) writes one JSON bundle per process under
  ``MPI4JAX_TPU_TELEMETRY_DIR`` with the ring contents, the in-flight
  watchdog registry, config/tuning snapshots, epoch history, compile
  cache stats, and every dropped-record count.  Merged and attributed
  by ``python -m mpi4jax_tpu.telemetry postmortem <dir>``.
- **metrics export** — ``prometheus_text()`` renders counters, meters,
  latency digests, drop counts, and the health gauges (the serving
  boundary feeds SLO-headroom and KV-occupancy) in Prometheus
  exposition format; ``MPI4JAX_TPU_HEALTH_PROM`` additionally writes it
  to ``prom-p<process>.prom`` at detector boundaries.

The layer is host-side only: no flag here shapes a trace, and with
``MPI4JAX_TPU_HEALTH=off`` (the default) every entry point returns
before touching state — HLO and both program-cache tokens stay
byte-identical (pinned in tests/test_telemetry.py).

Pure Python: importable under the isolated test loaders without JAX
(jax, the ops, elastic, and the watchdog are lazy guarded imports).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..utils import config
from .hist import Histogram

__all__ = [
    "armed",
    "flight_snapshot",
    "dump_postmortem",
    "prometheus_text",
    "on_boundary",
    "set_gauge",
    "reset",
    "POSTMORTEM_SCHEMA",
    "POSTMORTEM_FILE_PREFIX",
    "PROM_FILE_PREFIX",
]

POSTMORTEM_SCHEMA = "mpx-postmortem/1"
POSTMORTEM_FILE_PREFIX = "postmortem-p"
PROM_FILE_PREFIX = "prom-p"

# detector thresholds (documented in docs/observability.md "Runtime
# health"; module-level so tests can tighten them without new flags)
SLOW_RATIO = 2.0     # window p50 > ratio * baseline p50 -> degraded
SKEW_RATIO = 2.0     # rank mean > ratio * cross-rank median -> slow rank
MIN_SAMPLES = 3      # digests below this sample count are not judged
STRIKE_LIMIT = 2     # consecutive flagged exchanges -> persistent


def armed() -> bool:
    """Whether the health plane is on (``MPI4JAX_TPU_HEALTH=on``)."""
    return config.health_mode() == "on"


def _meter(name: str, n: int = 1) -> None:
    # lazy: core imports this module at top level (the ring feed), so
    # the reverse edge must stay function-local
    from . import core

    core.meter(name, n)


def _incident(meter_name: str, rank: int, detail: str) -> None:
    try:
        from . import journal

        journal.incident(meter_name, "health", int(rank), detail)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class _Ring:
    """Fixed-capacity overwrite ring.  Lock-free by construction: a push
    is one index read, one increment, one list store — a racing pair of
    pushes may overwrite each other's slot, which only costs a record
    the ring was about to evict anyway."""

    __slots__ = ("capacity", "buf", "total")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.buf: List[Optional[dict]] = [None] * self.capacity
        self.total = 0

    def push(self, record: dict) -> None:
        i = self.total
        self.total = i + 1
        self.buf[i % self.capacity] = record

    def window(self) -> List[dict]:
        n = min(self.total, self.capacity)
        start = self.total - n
        out = []
        for i in range(start, start + n):
            rec = self.buf[i % self.capacity]
            if rec is not None:
                out.append(rec)
        return out


_ring: Optional[_Ring] = None


def _ring_for() -> Optional[_Ring]:
    global _ring
    if not armed():
        return None
    cap = config.flight_ring_capacity()
    r = _ring
    if r is None or r.capacity != cap:
        r = _Ring(cap)
        _ring = r
    return r


def record_dispatch(rec) -> None:
    """Spill one committed dispatch record (``core.OpRecord``) — the
    counters-tier feed: fires once per trace (traced programs) or once
    per call (eager), exactly like the counter it rides next to."""
    r = _ring_for()
    if r is None:
        return
    r.push({
        "kind": "dispatch", "op": rec.op, "comm_uid": str(rec.comm_uid),
        "algo": rec.algo, "dtype": rec.dtype, "bytes": rec.bytes,
        "t": time.time(),
    })


def record_begin(call_id: str, rank: int, meta: dict,
                 mono: float, wall: float) -> None:
    """Spill one events-tier BEGIN (arrival) — begins are not journal
    records until their end arrives, but the ring must hold them: the op
    a hung rank never finished is exactly the one a postmortem needs,
    and a rank that never *began* a call every peer began is the
    straggler the ``postmortem`` CLI attributes."""
    r = _ring_for()
    if r is None:
        return
    r.push(dict(meta, kind="begin", call_id=call_id, rank=int(rank),
                t=wall, mono=mono))


def record_event(record: dict) -> None:
    """Spill one completed journal record (type ``op`` or ``instant``).
    The dict is shared, not copied — the journal never mutates a record
    after emitting it."""
    r = _ring_for()
    if r is None:
        return
    r.push(record)


def ring_dropped() -> int:
    r = _ring
    if r is None:
        return 0
    return max(0, r.total - r.capacity)


def flight_snapshot() -> dict:
    """JSON-ready view of the flight-recorder ring (oldest first)."""
    r = _ring
    if r is None:
        return {"version": 1, "capacity": 0, "total": 0, "dropped": 0,
                "records": []}
    return {
        "version": 1,
        "capacity": r.capacity,
        "total": r.total,
        "dropped": max(0, r.total - r.capacity),
        "records": r.window(),
    }


# ---------------------------------------------------------------------------
# degradation detector
# ---------------------------------------------------------------------------


class _Detector:
    def __init__(self):
        self.lock = threading.Lock()
        self.window: Dict[str, Histogram] = {}
        self.baseline: Dict[str, Histogram] = {}
        self.boundaries = 0
        self.exchanges = 0
        # consecutive flagged exchanges per (process) rank
        self.strikes: Dict[int, int] = {}

    def reset(self) -> None:
        with self.lock:
            self.window.clear()
            self.baseline.clear()
            self.boundaries = 0
            self.exchanges = 0
            self.strikes.clear()


_detector = _Detector()

_gauges: Dict[str, float] = {}


def set_gauge(name: str, value: float) -> None:
    """Set a health gauge (rendered by :func:`prometheus_text`)."""
    _gauges[name] = float(value)


def feed_latency(key: str, seconds: float) -> None:
    """Detector feed, called by ``core.record_latency`` for every
    measured op latency (events-tier journal ends, serving host
    brackets, megastep per-step estimates)."""
    if not armed():
        return
    det = _detector
    with det.lock:
        h = det.window.get(key)
        if h is None:
            h = det.window[key] = Histogram()
        h.record(seconds)


def _summarize_window() -> dict:
    """Pop the current window into ``{key: summary}`` and fold it into
    the baseline (the long-run reference the slowdown check compares
    against)."""
    det = _detector
    findings = []
    with det.lock:
        summary = {}
        for key, h in det.window.items():
            if not h.count:
                continue
            summary[key] = {
                "count": h.count,
                "mean": h.sum / h.count,
                "p50": h.quantile(0.5),
                "max": h.max,
            }
            base = det.baseline.get(key)
            if (base is not None and base.count >= MIN_SAMPLES
                    and h.count >= MIN_SAMPLES):
                bp50 = base.quantile(0.5)
                wp50 = h.quantile(0.5)
                if bp50 and wp50 and wp50 > SLOW_RATIO * bp50:
                    findings.append({
                        "kind": "degraded", "key": key,
                        "window_p50": wp50, "baseline_p50": bp50,
                        "ratio": wp50 / bp50,
                    })
            det.baseline[key] = (base.merge(h) if base is not None
                                 else h)
        det.window = {}
    return {"summary": summary, "findings": findings}


def _gather_json(comm, payload: dict) -> List[dict]:
    """One process's JSON payload from every process, moved through our
    own collectives — the ``report.gather_snapshots`` recipe (MAX-
    allreduce the encoded lengths, allgather uint8 rows), deduplicated
    by process."""
    import numpy as np

    from .. import MAX, allgather, allreduce
    from ..parallel.region import resolve_comm

    comm = resolve_comm(comm)
    if comm.mesh is None:
        return [payload]
    local = json.dumps(payload, sort_keys=True).encode()
    size = comm.world_size()
    lengths = np.full((size, 1), len(local), np.int32)
    maxlen_g, _ = allreduce(lengths, op=MAX, comm=comm)
    maxlen = int(np.asarray(maxlen_g)[0, 0])
    buf = np.zeros((size, maxlen), np.uint8)
    buf[:, :len(local)] = np.frombuffer(local, np.uint8)
    gathered, _ = allgather(buf, comm=comm)
    rows = np.asarray(gathered)[0]
    out = {}
    for row in rows:
        text = bytes(row).rstrip(b"\x00").decode()
        if not text:
            continue
        peer = json.loads(text)
        out.setdefault(int(peer.get("process", 0)), peer)
    return [out[p] for p in sorted(out)]


def judge_exchange(peers: List[dict], my_process: int) -> List[dict]:
    """The cross-rank verdicts for one digest exchange: for every op key
    at least two processes measured (>= ``MIN_SAMPLES`` each), a process
    whose mean exceeds ``SKEW_RATIO`` x the cross-process median is a
    *slow rank*.  Pure — every process computes identical verdicts from
    the identical gathered payload, which is what makes the incidents
    symmetric across survivors."""
    by_key: Dict[str, Dict[int, dict]] = {}
    for peer in peers:
        proc = int(peer.get("process", 0))
        for key, s in (peer.get("summary") or {}).items():
            if s.get("count", 0) >= MIN_SAMPLES:
                by_key.setdefault(key, {})[proc] = s
    findings = []
    for key in sorted(by_key):
        rows = by_key[key]
        if len(rows) < 2:
            continue
        means = sorted(s["mean"] for s in rows.values())
        median = means[len(means) // 2]
        if median <= 0:
            continue
        for proc in sorted(rows):
            mean = rows[proc]["mean"]
            if mean > SKEW_RATIO * median:
                findings.append({
                    "kind": "slow_rank", "rank": proc, "key": key,
                    "mean": mean, "median": median,
                    "ratio": mean / median,
                })
    return findings


def _exchange(comm, summary: dict) -> List[dict]:
    from . import journal

    det = _detector
    my_process = journal.process_index()
    peers = _gather_json(comm, {"process": my_process, "summary": summary})
    det.exchanges += 1
    _meter("health.exchanges")
    findings = judge_exchange(peers, my_process)
    flagged = {f["rank"] for f in findings}
    for f in findings:
        _incident(
            "health.slow_ranks", f["rank"],
            f"rank {f['rank']} slow on {f['key'].split('|')[0]}: mean "
            f"{f['mean'] * 1e6:.1f}us vs cross-rank median "
            f"{f['median'] * 1e6:.1f}us (x{f['ratio']:.2f})",
        )
    suspect_rf = None
    with det.lock:
        for proc in list(det.strikes):
            if proc not in flagged:
                det.strikes.pop(proc)
        for proc in flagged:
            det.strikes[proc] = det.strikes.get(proc, 0) + 1
        persistent = sorted(p for p, n in det.strikes.items()
                            if n >= STRIKE_LIMIT)
    for proc in persistent:
        detail = (f"rank {proc} persistently slow: flagged in "
                  f"{det.strikes.get(proc, STRIKE_LIMIT)} consecutive "
                  "digest exchanges")
        _incident("health.stragglers", proc, detail)
    if persistent and config.health_suspects_enabled():
        suspect_rf = _post_suspects(persistent)
    for f in findings:
        f["persistent"] = f["rank"] in persistent
    if suspect_rf is not None:
        raise suspect_rf
    return findings


def _post_suspects(ranks: List[int]):
    """Hand persistent stragglers to the elastic agreement machinery
    (opt-in): post them as a pending suspected failure and return the
    ``RankFailure`` for the caller to raise — inside ``elastic.run`` the
    raise enters the normal classify -> agree -> shrink path, so the
    slow rank is negotiated out exactly like a dead one."""
    try:
        from ..resilience import elastic as _elastic
    except ImportError:
        return None
    rf = _elastic.RankFailure(
        frozenset(int(r) for r in ranks),
        "health detector: persistent straggler(s) "
        + ", ".join(str(r) for r in sorted(ranks)),
    )
    _elastic._post_failure(rf)
    _meter("health.suspects_posted", len(ranks))
    return rf


def on_boundary(step, comm=None, engine=None, **info) -> Optional[list]:
    """Detector tick at one megastep/commit boundary.

    Called by the elastic run loop (with its mesh-bound ``comm``) and by
    the serving engine's boundary-hook registry (with ``engine=``).
    Every ``MPI4JAX_TPU_HEALTH_INTERVAL``-th boundary runs the local
    slowdown check, the cross-rank digest exchange (when a comm is
    available), the serving gauges, and the optional Prometheus file
    write.  Raises :class:`RankFailure` only when the suspect handoff is
    opted in AND a persistent straggler was confirmed.
    """
    if not armed():
        return None
    det = _detector
    with det.lock:
        det.boundaries += 1
        due = det.boundaries % config.health_interval() == 0
    if not due:
        return None
    window = _summarize_window()
    findings = list(window["findings"])
    for f in window["findings"]:
        _incident(
            "health.degradations", _process_index(),
            f"{f['key'].split('|')[0]} degraded on this process: window "
            f"p50 {f['window_p50'] * 1e6:.1f}us vs baseline "
            f"{f['baseline_p50'] * 1e6:.1f}us (x{f['ratio']:.2f})",
        )
    if engine is not None:
        _serving_gauges(engine)
    try:
        if comm is not None and _world_of(comm) > 1:
            findings.extend(_exchange(comm, window["summary"]))
    finally:
        if config.health_prom_enabled():
            _write_prom()
    return findings


def _process_index() -> int:
    try:
        from . import journal

        return journal.process_index()
    except Exception:
        return 0


def _world_of(comm) -> int:
    try:
        return int(comm.world_size())
    except Exception:
        return 1


def _serving_gauges(engine) -> None:
    """SLO-headroom and KV-occupancy gauges from a live serving engine
    (best-effort: every attribute is probed, never required)."""
    try:
        alloc = getattr(engine, "_alloc", None)
        if alloc is not None:
            cap = int(getattr(alloc, "capacity", 0) or 0)
            used = len(getattr(alloc, "_used", ()) or ())
            set_gauge("serving_kv_slots_total", cap)
            set_gauge("serving_kv_slots_in_use", used)
            if cap:
                set_gauge("serving_kv_occupancy", used / cap)
        sched = getattr(engine, "_sched", None)
        cfg = getattr(engine, "cfg", None)
        if sched is not None and cfg is not None:
            lat = sorted(
                s.finish_s - s.arrival_s
                for s in (getattr(sched, "finished", None) or ())
                if getattr(s, "finish_s", None) is not None
            )
            if lat:
                from ..serving.metrics import percentile

                p99 = percentile(lat, 0.99)
                set_gauge("serving_p99_ms", p99 * 1e3)
                set_gauge("serving_slo_headroom_ms",
                          float(cfg.slo_p99_ms) - p99 * 1e3)
    except Exception:
        pass


_hook_registered = False


def ensure_boundary_hook() -> None:
    """Register :func:`on_boundary` in the megastep boundary-hook
    registry (idempotent, guarded) so the serving engine's
    ``run_boundary_hooks`` drives the detector.  The elastic run loop
    calls ``on_boundary`` directly instead — it does not run the
    registry, and its boundary carries the mesh-bound comm."""
    global _hook_registered
    if _hook_registered or not armed():
        return
    try:
        from ..parallel import megastep as _megastep
    except Exception:
        return

    def _hook(step, **info):
        # a boundary consumer that fails stops the loop by design; an
        # OBSERVER must not — swallow everything (the suspect handoff
        # never fires here: no comm, no exchange)
        try:
            return on_boundary(step, **info)
        except Exception:
            return None

    _megastep.register_boundary_hook("health", _hook)
    _hook_registered = True


# ---------------------------------------------------------------------------
# stall / failure notifications (watchdog + elastic glue)
# ---------------------------------------------------------------------------


def on_watchdog_expiry(expired: dict) -> None:
    """Called by the watchdog monitor next to its expiry incident: the
    stall is a health event (journal + meter) and a postmortem trigger —
    the op that never finished is still in the ring and the in-flight
    registry, which is exactly what the bundle must capture."""
    if not armed():
        return
    opname = expired.get("opname", "?")
    call_id = expired.get("call_id", "?")
    _incident(
        "health.stalls", expired.get("rank", 0),
        f"{opname} call {call_id} stalled in flight: exceeded "
        f"{expired.get('timeout', 0):g}s without completing",
    )
    maybe_postmortem(f"watchdog_expired: {opname} call {call_id}")


def on_failure_classified(rf) -> None:
    """Called by the elastic run loop once an exception classifies as a
    rank failure, before recovery mutates any state: snapshot the world
    as the failure saw it."""
    if not armed():
        return
    maybe_postmortem(f"rank_failure: {getattr(rf, 'detail', rf)}")


def frontier_hint() -> str:
    """One line of local last-known-frontier context (the in-flight
    watchdog registry) for incident details."""
    try:
        from ..resilience import watchdog as _wd

        inflight = _wd.inflight_snapshot()
    except Exception:
        return ""
    if not inflight:
        return ""
    e = max(inflight, key=lambda x: x.get("elapsed", 0))
    return (f"{e.get('opname', '?')} call {e.get('call_id', '?')} "
            f"in flight {e.get('elapsed', 0):.1f}s")


def on_rank_failed(failed, detail: str = "") -> None:
    """Called by the elastic recovery path once the failed set is AGREED
    (post-negotiation, pre-shrink): journal one ``health`` incident per
    failed rank — every survivor runs this with the identical verdict,
    so every survivor's journal names the failed rank.  Also drops the
    detector's strike counters for the failed ranks: the verdict is
    settled, and a live strike for a removed rank must not be able to
    re-raise a suspect that is no longer in the world."""
    if not armed():
        return
    det = _detector
    with det.lock:
        for r in failed:
            det.strikes.pop(int(r), None)
    hint = frontier_hint()
    for r in sorted(failed):
        _incident(
            "health.ranks_failed", int(r),
            f"rank {int(r)} agreed failed: {detail}"
            + (f" [local frontier: {hint}]" if hint else ""),
        )


# ---------------------------------------------------------------------------
# postmortem bundles
# ---------------------------------------------------------------------------


def maybe_postmortem(reason: str) -> Optional[str]:
    """Armed-gated, never-raising bundle write for the automatic
    triggers (which run on dying or about-to-abort paths)."""
    if not armed():
        return None
    try:
        return dump_postmortem(reason)
    except Exception:
        return None


def dump_postmortem(reason: str = "on_demand") -> Optional[str]:
    """Write this process's postmortem bundle under the telemetry dir.

    Returns the path, or ``None`` without a directory
    (``MPI4JAX_TPU_TELEMETRY_DIR`` unset — there is nowhere durable to
    write).  Repeated dumps overwrite the bundle with fresh state and
    accumulate their reasons, so the last writer documents the whole
    cascade (watchdog expiry, then the classified failure).
    """
    d = config.telemetry_dir()
    if not d:
        return None
    from . import core, journal

    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"{POSTMORTEM_FILE_PREFIX}{journal.process_index()}.json")
    reasons = [reason]
    try:
        with open(path) as f:
            prev = json.load(f)
        if prev.get("schema") == POSTMORTEM_SCHEMA:
            reasons = list(prev.get("reasons", ())) + [reason]
    except (OSError, ValueError):
        pass
    det = _detector
    bundle = {
        "schema": POSTMORTEM_SCHEMA,
        "reason": reason,
        "reasons": reasons,
        "process": journal.process_index(),
        "t": time.time(),
        "snapshot": core.snapshot(include_events=False),
        "flight": flight_snapshot(),
        "dropped": {
            "journal": journal.dropped_records(),
            "flight_ring": ring_dropped(),
        },
        "config": {
            "epoch": config.config_epoch(),
            "env": {
                name: val
                for name, val in zip(config.FLAG_NAMES,
                                     config.env_fingerprint())
                if val is not None
            },
        },
        "health": {
            "boundaries": det.boundaries,
            "exchanges": det.exchanges,
            "strikes": {str(k): v for k, v in det.strikes.items()},
            "gauges": dict(_gauges),
        },
    }
    tuning = config.tuning_snapshot()
    if tuning:
        bundle["tuning"] = tuning
    try:
        from ..resilience import watchdog as _wd
    except Exception:
        pass
    else:
        try:
            bundle["watchdog_inflight"] = _wd.inflight_snapshot()
        except Exception:
            pass
    try:
        from ..resilience import elastic as _elastic
    except Exception:
        pass
    else:
        history = _elastic.epoch_history()
        if history:
            bundle["epochs"] = history
    # pinned-program inventory + persistent-cache traffic (docs/aot.md);
    # guarded — the aot package needs jax
    try:
        from ..aot import stats as _aot_stats
    except Exception:
        pass
    else:
        try:
            bundle["compile_cache"] = _aot_stats()
        except Exception:
            pass
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _meter("health.postmortems")
    return path


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def _esc(value) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _op_labels(row: dict) -> str:
    return (f'op="{_esc(row["op"])}",comm="{_esc(row["comm_uid"])}",'
            f'algo="{_esc(row["algo"])}",dtype="{_esc(row["dtype"])}"')


def prometheus_text() -> str:
    """Counters, meters, latency digests, drop counts, and health gauges
    in Prometheus exposition format (deterministically ordered)."""
    from . import core, journal

    snap = core.snapshot(include_events=False)
    lines = [
        "# HELP mpx_meter_total infrastructure meters "
        "(mpi4jax_tpu telemetry)",
        "# TYPE mpx_meter_total counter",
    ]
    for name in sorted(snap.get("meters", {})):
        lines.append(f'mpx_meter_total{{name="{_esc(name)}"}} '
                     f'{snap["meters"][name]}')
    ops = snap.get("ops", {})
    lines += ["# HELP mpx_op_calls_total per-op dispatch counts",
              "# TYPE mpx_op_calls_total counter"]
    for key in sorted(ops):
        lines.append(f"mpx_op_calls_total{{{_op_labels(ops[key])}}} "
                     f"{ops[key]['calls']}")
    lines += ["# HELP mpx_op_bytes_total per-op payload bytes",
              "# TYPE mpx_op_bytes_total counter"]
    for key in sorted(ops):
        lines.append(f"mpx_op_bytes_total{{{_op_labels(ops[key])}}} "
                     f"{ops[key]['bytes']}")
    lines += ["# HELP mpx_op_latency_seconds measured op latency digest",
              "# TYPE mpx_op_latency_seconds summary"]
    for key in sorted(ops):
        row = ops[key]
        if "latency" not in row:
            continue
        h = Histogram.from_dict(row["latency"])
        labels = _op_labels(row)
        for q in (0.5, 0.99):
            val = h.quantile(q)
            if val is not None:
                lines.append(
                    f'mpx_op_latency_seconds{{{labels},quantile="{q}"}} '
                    f"{val:.9g}")
        lines.append(f"mpx_op_latency_seconds_count{{{labels}}} {h.count}")
        lines.append(f"mpx_op_latency_seconds_sum{{{labels}}} "
                     f"{h.sum:.9g}")
    lines += ["# HELP mpx_dropped_records_total telemetry records "
              "dropped by bounded buffers",
              "# TYPE mpx_dropped_records_total counter",
              f'mpx_dropped_records_total{{source="journal"}} '
              f"{journal.dropped_records()}",
              f'mpx_dropped_records_total{{source="flight_ring"}} '
              f"{ring_dropped()}"]
    det = _detector
    lines += ["# HELP mpx_health_boundaries_total detector boundary ticks",
              "# TYPE mpx_health_boundaries_total counter",
              f"mpx_health_boundaries_total {det.boundaries}",
              "# HELP mpx_health_exchanges_total cross-rank digest "
              "exchanges",
              "# TYPE mpx_health_exchanges_total counter",
              f"mpx_health_exchanges_total {det.exchanges}"]
    for name in sorted(_gauges):
        metric = f"mpx_{name}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_gauges[name]:.9g}")
    return "\n".join(lines) + "\n"


def _write_prom() -> None:
    d = config.telemetry_dir()
    if not d:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"{PROM_FILE_PREFIX}{_process_index()}.prom")
        with open(path, "w") as f:
            f.write(prometheus_text())
    except Exception:
        pass


def reset() -> None:
    """Forget the ring, the detector state, and the gauges (test
    isolation; wired into ``telemetry.reset()``)."""
    global _ring
    _ring = None
    _detector.reset()
    _gauges.clear()
