"""Runtime telemetry: per-collective metrics, timelines, stragglers.

The reference got per-op latency for free from host brackets around
every libmpi call; the TPU-native lowering has no host call per
collective, so this package rebuilds the observability ladder every
production stack needs, in three always-cheap tiers gated by
``MPI4JAX_TPU_TELEMETRY`` (docs/observability.md):

- ``off`` (default) — nothing collected; HLO byte-identical to an
  uninstrumented build (pinned by tests/test_telemetry.py);
- ``counters`` — host-side per-(op, comm, algorithm, dtype) call/byte
  counters and infrastructure meters (cache hits/misses/evictions,
  recompiles per op, watchdog arms/expiries, fault injections).  Zero
  device-side ops: HLO still byte-identical;
- ``events`` — additionally journals a host begin/end bracket around
  every collective (per-rank arrival + latency) to memory and, with
  ``MPI4JAX_TPU_TELEMETRY_DIR``, per-process JSONL files.

Read it back with :func:`snapshot` (this process), :func:`report`
(cross-rank table with latency percentiles and the straggler column),
:func:`dump` (JSON to disk), or merge the JSONL journals of all ranks
into one Perfetto/``chrome://tracing`` timeline::

    python -m mpi4jax_tpu.telemetry merge $MPI4JAX_TPU_TELEMETRY_DIR \\
        --perfetto trace.json

``MPI4JAX_TPU_HEALTH=on`` additionally arms the live health plane
(telemetry/health.py): a bounded flight-recorder ring
(:func:`flight_snapshot`), an online straggler/degradation detector at
megastep/commit boundaries, crash postmortem bundles
(:func:`dump_postmortem`, merged by ``python -m mpi4jax_tpu.telemetry
postmortem <dir>``), and :func:`prometheus_text` exposition.
"""

from . import health  # noqa: F401
from .core import (  # noqa: F401
    effective_mode,
    meter,
    reset,
    set_telemetry_mode,
    snapshot,
    telemetry_cache_token,
)
from .health import (  # noqa: F401
    dump_postmortem,
    flight_snapshot,
    prometheus_text,
)
from .hist import Histogram  # noqa: F401
from .merge import chrome_trace, merge_dir, skew_table  # noqa: F401
from .report import dump, gather_snapshots, report  # noqa: F401

__all__ = [
    "set_telemetry_mode",
    "effective_mode",
    "telemetry_cache_token",
    "meter",
    "snapshot",
    "report",
    "dump",
    "reset",
    "gather_snapshots",
    "Histogram",
    "merge_dir",
    "chrome_trace",
    "skew_table",
    "health",
    "flight_snapshot",
    "dump_postmortem",
    "prometheus_text",
]
