"""Telemetry core: mode resolution, per-op counters, infrastructure meters.

The reference measured every collective because every collective WAS a
host call (``perf_counter`` brackets inside the libmpi bridge, ref
mpi_xla_bridge.pyx:47-60); our TPU-native lowering deliberately has no
host call per collective, so observability has to ride the points the
host *does* see:

- **dispatch** (``ops/_base.py``) — every op call flows through one
  Python function; counting there is pure host bookkeeping and costs
  nothing on the device.  That is the ``counters`` tier: per-(op,
  comm uid, algo, dtype) call counts and payload bytes, plus meters for
  the infrastructure around the ops (program-cache hits/misses/
  evictions, recompiles, watchdog arms/expiries, fault injections,
  numeric-guard trips, algorithm selections);
- **host callbacks** (``telemetry/bracket.py``) — the ``events`` tier
  adds begin/end ``io_callback`` brackets threaded around each
  collective with data dependencies (the same threading as the native
  ``op_begin``/``op_end`` trace hooks), feeding the per-rank journal.

Counting semantics (documented, not accidental): a dispatch inside a
traced program counts once per TRACE (the host only sees the trace); an
eager op counts once per CALL (dispatch runs per call, cache hit or
not).  Per-execution, per-rank truth lives in the ``events`` journal,
whose callbacks are compiled into the program.

Mode is ``MPI4JAX_TPU_TELEMETRY={off,counters,events}`` with a
programmatic override (``set_telemetry_mode``), folded into both
compiled-program cache keys via ``telemetry_cache_token()`` exactly like
the resilience and analyze flags.  Pure Python: importable under the
isolated test loader without JAX.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils import config
from . import health
from .hist import Histogram

__all__ = [
    "set_telemetry_mode",
    "effective_mode",
    "telemetry_cache_token",
    "meter",
    "snapshot",
    "reset",
]

_UNSET = object()
_mode_override = _UNSET


def set_telemetry_mode(mode: Optional[str]) -> None:
    """Programmatic override of ``MPI4JAX_TPU_TELEMETRY`` (``None``
    returns control to the environment), mirroring ``set_analyze_mode``
    and the resilience ``set_*`` overrides."""
    global _mode_override
    if mode is None:
        _mode_override = _UNSET
        config.bump_config_epoch()
        return
    if mode not in config.TELEMETRY_MODES:
        raise ValueError(
            f"telemetry mode must be one of {config.TELEMETRY_MODES}, "
            f"got {mode!r}"
        )
    _mode_override = mode
    config.bump_config_epoch()


def effective_mode() -> str:
    if _mode_override is not _UNSET:
        return _mode_override
    return config.telemetry_mode()


def events_on() -> bool:
    return effective_mode() == "events"


def telemetry_cache_token() -> tuple:
    """Folded into the compiled-program cache keys (ops/_base.py eager
    cache, parallel/region.py spmd cache): flipping the tier must
    retrace — the counters hook at trace time, and the events brackets
    change the traced program."""
    return (effective_mode(),)


# ---------------------------------------------------------------------------
# the counter registry
# ---------------------------------------------------------------------------


def op_key(op: str, comm_uid, algo: str, dtype: str) -> str:
    """The per-op counter key (also the JSON snapshot key)."""
    return f"{op}|{comm_uid}|{algo}|{dtype}"


class _Counters:
    """Process-wide counter state.  Locked: meters and latency records
    arrive from host-callback threads as well as the dispatch thread."""

    def __init__(self):
        self.lock = threading.Lock()
        self.ops: Dict[str, dict] = {}
        self.meters: Dict[str, int] = {}
        self.latency: Dict[str, Histogram] = {}

    def count_op(self, key: str, nbytes: int,
                 intra: Optional[int] = None,
                 inter: Optional[int] = None,
                 wire_inter: Optional[int] = None) -> None:
        with self.lock:
            row = self.ops.setdefault(
                key, {"calls": 0, "bytes": 0,
                      "intra_bytes": 0, "inter_bytes": 0,
                      "wire_inter_bytes": 0}
            )
            row["calls"] += 1
            row["bytes"] += int(nbytes)
            # link-class attribution (docs/topology.md): modeled per-rank
            # wire bytes by ICI (intra_host) vs DCN (inter_host), filled
            # by the algorithm layer; ops without a model (p2p, gather
            # family, native HLO) default to payload-on-intra
            row["intra_bytes"] += int(nbytes if intra is None else intra)
            inter_logical = int(0 if inter is None else inter)
            row["inter_bytes"] += inter_logical
            # DCN wire bytes after the codec (docs/compression.md): equal
            # to the logical inter bytes unless the hierarchy compressed
            # the inter-host leg — the logical/wire split is how the
            # snapshot shows what the codec actually saved
            row["wire_inter_bytes"] += (
                inter_logical if wire_inter is None else int(wire_inter)
            )

    def bump(self, name: str, n: int) -> None:
        with self.lock:
            self.meters[name] = self.meters.get(name, 0) + n

    def record_latency(self, key: str, seconds: float) -> None:
        with self.lock:
            h = self.latency.get(key)
            if h is None:
                h = self.latency[key] = Histogram()
            h.record(seconds)

    def reset(self) -> None:
        with self.lock:
            self.ops.clear()
            self.meters.clear()
            self.latency.clear()


_counters = _Counters()


def meter(name: str, n: int = 1) -> None:
    """Bump an infrastructure meter (no-op when telemetry is off).

    Meter names are dotted paths (``eager_cache.hits``,
    ``watchdog.expiries``, ``algo.allreduce.ring``, ...); the snapshot
    returns them verbatim.
    """
    if effective_mode() == "off":
        return
    _counters.bump(name, n)


def record_latency(key: str, seconds: float) -> None:
    """Feed one measured op latency into the per-op histogram (called by
    the journal when an events-tier end bracket completes) — and into
    the health detector's rolling window (telemetry/health.py)."""
    _counters.record_latency(key, seconds)
    health.feed_latency(key, seconds)


def count_host_op(key: str, nbytes: int) -> None:
    """Count one HOST-level phase execution into the per-op table — the
    serving runtime's prefill/decode brackets (serving/engine.py), which
    wrap a whole pinned dispatch rather than one collective.  Gated like
    :func:`meter` (no-op when telemetry is off)."""
    if effective_mode() == "off":
        return
    _counters.count_op(key, nbytes)


# ---------------------------------------------------------------------------
# dispatch-point op records
# ---------------------------------------------------------------------------


class OpRecord:
    """One in-flight dispatch's telemetry view (host-side, trace-time)."""

    __slots__ = ("op", "comm_uid", "comm_axes", "bytes", "dtype", "algo",
                 "counted", "intra_bytes", "inter_bytes", "wire_inter_bytes")

    def __init__(self, op, comm_uid, comm_axes, nbytes, dtype, counted):
        self.op = op
        self.comm_uid = comm_uid
        self.comm_axes = comm_axes
        self.bytes = nbytes
        self.dtype = dtype
        self.algo = "native"
        self.counted = counted
        # per-link-class modeled wire bytes (None until the algorithm
        # layer annotates them; count_op defaults payload-on-intra)
        self.intra_bytes = None
        self.inter_bytes = None
        # post-codec DCN bytes (None -> same as inter_bytes; only the
        # compressed hierarchy leg sets this, docs/compression.md)
        self.wire_inter_bytes = None

    def key(self) -> str:
        return op_key(self.op, self.comm_uid, self.algo, self.dtype)


# innermost-wins stack of open dispatches (annotate targets the top);
# single-threaded like the region stack it mirrors
_open_ops: List[OpRecord] = []

# active eager-capture cell: while set, closed records are captured on the
# cell instead of counted (the eager dispatch loop counts per CALL itself,
# and the traced program may be compiled once and reused many times)
_eager_cell: Optional["EagerCell"] = None


class EagerCell:
    """Per-eager-cache-entry stash of trace records, keyed by the call's
    argument signature (shapes + dtypes).

    A pure cache hit re-runs no Python trace, so the dispatch loop counts
    the call from the stash.  jit retraces internally per signature, and
    each retrace lands its records under ITS signature — so a
    shape-alternating workload counts every call with the bytes, dtype,
    and selected algorithm of the program that actually serves it, not
    whichever shape happened to trace last."""

    __slots__ = ("by_sig",)

    def __init__(self):
        self.by_sig: dict = {}

    def records_for(self, sig) -> List[OpRecord]:
        recs = self.by_sig.get(sig)
        if recs is not None:
            return recs
        # a hit implies the signature traced at some point; this fallback
        # only covers state loss (e.g. telemetry enabled mid-entry)
        return next(reversed(self.by_sig.values())) if self.by_sig else []


def call_signature(arrays) -> tuple:
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


class capture_eager:
    """Context manager for the eager dispatch path: records closed during
    the ``with`` land on ``cell`` under ``sig`` instead of the counters.
    A raising call does NOT refresh the stash — a partial trace must not
    poison the counts of later successful calls."""

    def __init__(self, cell: EagerCell, sig: tuple):
        self.cell = cell
        self.sig = sig
        self._pending: List[OpRecord] = []

    def __enter__(self):
        global _eager_cell
        self._saved = _eager_cell
        _eager_cell = self
        return self.cell

    def __exit__(self, exc_type, exc, tb):
        global _eager_cell
        _eager_cell = self._saved
        if self._pending and exc_type is None:
            self.cell.by_sig[self.sig] = self._pending
        return False


def open_op(opname: str, comm, arrays) -> Optional[OpRecord]:
    """Open a telemetry record for one dispatch (``None`` when telemetry
    is off — the zero-cost default)."""
    if effective_mode() == "off":
        return None
    health.ensure_boundary_hook()
    a0 = arrays[0] if arrays else None
    nbytes = 0
    dtype = ""
    if a0 is not None:
        nbytes = int(a0.size) * a0.dtype.itemsize
        dtype = str(a0.dtype)
    rec = OpRecord(opname, comm.uid, tuple(comm.axes), nbytes, dtype,
                   counted=_eager_cell is None)
    _open_ops.append(rec)
    return rec


def annotate(**fields) -> None:
    """Record trace-time facts only the op body knows — the selected
    algorithm, and the modeled per-link-class wire bytes
    (``link_bytes=(intra_host, inter_host)``, see
    ``ops/_hierarchy.annotate_selection``).  No-op when nothing is open
    (safe to call unconditionally from op bodies, mirroring
    ``analysis.hook.annotate``)."""
    if not _open_ops:
        return
    rec = _open_ops[-1]
    algo = fields.get("algo")
    if algo is not None:
        rec.algo = algo
        meter(f"algo.{rec.op}.{algo}")
    link = fields.get("link_bytes")
    if link is not None:
        rec.intra_bytes, rec.inter_bytes = link
    wire = fields.get("wire_bytes")
    if wire is not None:
        # (intra, inter) after the DCN codec — the intra leg is never
        # compressed, so only the inter component is recorded
        rec.wire_inter_bytes = wire[1]


def close_op(rec: Optional[OpRecord]) -> None:
    """Commit a record: count it (traced dispatch), or stash it on the
    active eager cell for per-call counting by the dispatch loop."""
    if rec is None:
        return
    if _open_ops and _open_ops[-1] is rec:
        _open_ops.pop()
    if _eager_cell is not None:
        _eager_cell._pending.append(rec)
        return
    if rec.counted:
        _counters.count_op(rec.key(), rec.bytes,
                           rec.intra_bytes, rec.inter_bytes,
                           rec.wire_inter_bytes)
        health.record_dispatch(rec)


def abort_op(rec: Optional[OpRecord]) -> None:
    """Unwind a record whose op body raised (nothing is counted)."""
    if rec is not None and _open_ops and _open_ops[-1] is rec:
        _open_ops.pop()


def count_eager_call(cell: EagerCell, sig: tuple) -> None:
    """Count one eager CALL from the entry's stashed trace records for
    this call's signature (cache hits included — dispatch runs per call,
    the trace does not)."""
    if effective_mode() == "off":
        return
    for rec in cell.records_for(sig):
        _counters.count_op(rec.key(), rec.bytes,
                           rec.intra_bytes, rec.inter_bytes,
                           rec.wire_inter_bytes)
        health.record_dispatch(rec)


def current_open() -> Optional[OpRecord]:
    return _open_ops[-1] if _open_ops else None


# ---------------------------------------------------------------------------
# snapshot / reset
# ---------------------------------------------------------------------------


def snapshot(include_events: bool = False) -> dict:
    """JSON-ready view of everything collected so far on THIS process.

    ``include_events`` additionally embeds the events-tier journal
    records (used by ``report()`` for cross-rank skew and by ``dump()``).
    """
    from . import journal

    with _counters.lock:
        ops = {
            key: {
                "op": key.split("|")[0],
                "comm_uid": key.split("|")[1],
                "algo": key.split("|")[2],
                "dtype": key.split("|")[3],
                "calls": row["calls"],
                "bytes": row["bytes"],
                "intra_bytes": row.get("intra_bytes", 0),
                "inter_bytes": row.get("inter_bytes", 0),
                "wire_inter_bytes": row.get(
                    "wire_inter_bytes", row.get("inter_bytes", 0)),
            }
            for key, row in _counters.ops.items()
        }
        for key, h in _counters.latency.items():
            ops.setdefault(key, {
                "op": key.split("|")[0],
                "comm_uid": key.split("|")[1],
                "algo": key.split("|")[2],
                "dtype": key.split("|")[3],
                "calls": 0,
                "bytes": 0,
                "intra_bytes": 0,
                "inter_bytes": 0,
                "wire_inter_bytes": 0,
            })["latency"] = h.to_dict()
        meters = dict(_counters.meters)
    snap = {
        "version": 1,
        "mode": effective_mode(),
        "process": journal.process_index(),
        "ops": ops,
        "meters": meters,
    }
    # the elastic epoch audit trail (epoch, world size, cause) rides
    # every snapshot so report() can render a churn run's history;
    # guarded — the resilience package is optional under the isolated
    # test loaders, and a never-churned job contributes nothing
    try:
        from ..resilience import elastic as _elastic
    except ImportError:
        pass
    else:
        history = _elastic.epoch_history()
        if history:
            snap["epochs"] = history
    # the compile-cache tier (docs/aot.md): AOT pin/call counters + the
    # persistent disk-cache counters, so report() can render the
    # cold-start before/after evidence.  Guarded — the aot package needs
    # jax (absent under the isolated loaders), and a process that never
    # pinned nor enabled the cache dir contributes nothing.
    try:
        from ..aot import stats as _aot_stats
    except ImportError:
        pass
    else:
        cc = _aot_stats()
        if (any(cc["aot"].values()) or cc["disk_cache"]["enabled"]
                or any(v for k, v in cc["disk_cache"].items()
                       if isinstance(v, int))):
            snap["compile_cache"] = cc
    # the active tuning layer (docs/autotune.md): stamp + per-knob
    # tuned-vs-default values, so report() renders what this process is
    # actually running with.  Absent entirely when no layer is loaded —
    # the snapshot stays byte-identical to a build without autotune.
    from ..utils import config as _config

    tuning = _config.tuning_snapshot()
    if tuning:
        snap["tuning"] = tuning
    # dropped-record accounting (journal overflow + flight-ring
    # overwrites): present only when something was actually dropped, so
    # a healthy snapshot stays byte-identical to the pre-health shape
    dropped = {"journal": journal.dropped_records(),
               "flight_ring": health.ring_dropped()}
    if any(dropped.values()):
        snap["dropped"] = dropped
    if include_events:
        snap["events"] = journal.snapshot_events()
    return snap


def reset() -> None:
    """Forget every counter, meter, histogram, and journal record (test
    isolation; also the per-sweep reset ``benchmarks/micro.py`` uses)."""
    from . import journal

    _counters.reset()
    del _open_ops[:]
    journal.reset()
    health.reset()
