"""mpi4jax_tpu — MPI-style communication primitives, TPU-native.

A brand-new framework with the capabilities of mpi4jax (reference:
Silv3S/mpi4jax): the reference's 12 MPI communication primitives (plus
``reduce_scatter``, which it lacks) usable inside ``jax.jit``, with
explicit token-chaining *and* implicit ordering, and autodiff (JVP +
transpose) through the communication — re-designed for TPU:

- every primitive lowers to **native XLA collective HLO** (AllReduce,
  AllGather, AllToAll, CollectivePermute) scheduled over ICI/DCN — no libmpi,
  no custom calls, no Cython bridge (replaces ref mpi4jax/_src/xla_bridge/*);
- processes are replaced by the **SPMD device mesh**: a ``Comm`` is a set of
  mesh axes, a rank is a device coordinate, and one traced program serves all
  ranks (replaces ref's ``mpirun`` + per-process programs);
- launched with plain ``python`` — multi-host pods via
  ``init_distributed()`` (replaces ref _src/__init__.py:1-3 MPI_Init).

Public API parity with ref mpi4jax/__init__.py:9-41 (12 ops + capability
probes), plus the mesh/comm/region surface that replaces mpi4py.
"""

from .ops import (  # noqa: F401
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    PROD,
    SUM,
    AsyncHandle,
    Op,
    P2PHandle,
    Status,
    Token,
    allgather,
    allreduce,
    allreduce_start,
    allreduce_wait,
    alltoall,
    alltoall_start,
    alltoall_wait,
    barrier,
    bcast,
    cache_stats,
    clear_caches,
    create_token,
    gather,
    overlap,
    p2p_wait,
    recv,
    recv_start,
    reduce,
    reduce_scatter,
    reduce_scatter_start,
    reduce_scatter_wait,
    scan,
    scatter,
    send,
    send_start,
    sendrecv,
    set_fusion_mode,
    varying,
)
from .parallel import (  # noqa: F401
    Comm,
    PipelineProgram,
    get_default_comm,
    get_default_mesh,
    init_distributed,
    make_world_mesh,
    moe,
    pipeline,
    run,
    set_default_mesh,
    shift,
    spmd,
)
from .utils import (  # noqa: F401
    flush,
    has_cuda_support,
    has_sycl_support,
    has_tpu_support,
)
from .resilience import (  # noqa: F401
    RankFailure,
    ShardStore,
    elastic,
    install_preemption_handler,
    request_drain,
    set_check_numerics,
    set_fault_spec,
    set_watchdog_timeout,
)
from .analysis import (  # noqa: F401
    AnalysisError,
    Finding,
    Report,
    analyze,
    set_analyze_mode,
)
from . import aot  # noqa: F401
from .aot import (  # noqa: F401
    PinnedProgram,
    StaleProgramError,
    compile,
)
from . import telemetry  # noqa: F401
from .telemetry import set_telemetry_mode  # noqa: F401
# the serving runtime (docs/serving.md): continuous batching under a
# p99 latency SLO on the pinned megastep decode path
from . import serving  # noqa: F401
# wire compression + error feedback for the DCN leg (docs/compression.md)
from . import compress  # noqa: F401
# the tuning layer (docs/autotune.md): mpx.autotune() measures, the
# config layer serves (default < tuning < env).  NOTE this rebinds the
# package attribute `mpi4jax_tpu.autotune` to the FUNCTION — the
# callable is the public API; the subpackage stays reachable through
# the path-based forms only (`python -m mpi4jax_tpu.autotune`,
# `from mpi4jax_tpu.autotune import ...`), never via attribute access
from .autotune import TuningFile, autotune  # noqa: F401
from .utils.config import active_tuning, load_tuning  # noqa: F401
from .utils.profiling import ProfileSummary, profile_ops  # noqa: F401

# JAX version advisory at import (ref mpi4jax/_src/__init__.py:6-8).
from .utils.jax_compat import check_jax_version as _check_jax_version

_check_jax_version()
del _check_jax_version

# Exit-time flush: keep the reference's guarantee that pending async
# communication completes before interpreter teardown
# (ref mpi4jax/_src/__init__.py:13-17).
import atexit as _atexit

_atexit.register(flush)
del _atexit

__all__ = [
    # ops (ref mpi4jax/__init__.py:26-41)
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "gather",
    "recv",
    "reduce",
    "reduce_scatter",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "has_cuda_support",
    "has_sycl_support",
    "has_tpu_support",
    # reductions
    "Op",
    "SUM",
    "PROD",
    "MIN",
    "MAX",
    "LAND",
    "LOR",
    "LXOR",
    "BAND",
    "BOR",
    "BXOR",
    # tokens / status
    "Token",
    "create_token",
    "varying",
    "Status",
    # runtime
    "Comm",
    "get_default_comm",
    "get_default_mesh",
    "set_default_mesh",
    "make_world_mesh",
    "init_distributed",
    "spmd",
    "run",
    "shift",
    "flush",
    "clear_caches",
    "cache_stats",
    "profile_ops",
    "ProfileSummary",
    # throughput layer: fusion + async overlap (docs/overlap.md)
    "allreduce_start",
    "allreduce_wait",
    "alltoall_start",
    "alltoall_wait",
    "reduce_scatter_start",
    "reduce_scatter_wait",
    "send_start",
    "recv_start",
    "p2p_wait",
    "AsyncHandle",
    "P2PHandle",
    "overlap",
    "set_fusion_mode",
    # pipeline-parallel schedule compiler (docs/pipeline.md)
    "pipeline",
    "PipelineProgram",
    # expert-parallel MoE helper (docs/moe.md)
    "moe",
    # AOT pinning + persistent compile cache (docs/aot.md)
    "aot",
    "compile",
    "PinnedProgram",
    "StaleProgramError",
    # runtime telemetry (docs/observability.md)
    "telemetry",
    "set_telemetry_mode",
    # serving runtime (docs/serving.md)
    "serving",
    # wire compression + error feedback (docs/compression.md)
    "compress",
    # resilience (docs/resilience.md)
    "set_watchdog_timeout",
    "set_fault_spec",
    "set_check_numerics",
    # elastic recovery (docs/resilience.md "Elastic recovery")
    "elastic",
    "RankFailure",
    "ShardStore",
    "request_drain",
    "install_preemption_handler",
    # trace-time collective verifier (docs/analysis.md)
    "analyze",
    "Report",
    "Finding",
    "AnalysisError",
    "set_analyze_mode",
]

# Version comes from git tags via setuptools-scm at build time
# (pyproject.toml [tool.setuptools_scm]); installed packages answer through
# their metadata.  A source checkout on sys.path that was never installed
# has no metadata — fall back to the scm-style local version.
try:
    from importlib.metadata import PackageNotFoundError, version as _version

    __version__ = _version("mpi4jax_tpu")
except PackageNotFoundError:  # uninstalled source tree
    __version__ = "0.0.0+unknown"
del PackageNotFoundError, _version
