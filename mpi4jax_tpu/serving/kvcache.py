"""Sharded KV-cache management: slot pool + device-resident updates.

The KV cache is the serving runtime's only long-lived device state: one
tensor pair per rank, shaped ``[slots + 1, max_len, local_heads,
head_dim]`` — the head axis SHARDED over the tensor-parallel group (each
rank holds ``heads / k`` heads, the ``comm.Split``/Megatron layout), the
slot axis a fixed pool of sequence rows.  Admission binds a sequence to
a free slot; eviction frees the integer — the tensors never change
shape, so the pinned per-bucket programs survive arbitrary admit/evict
churn (slot ids enter the program as a tiny dynamic ``int32`` array and
all writes are scatter updates at ``[slot, position]``).

Row ``slots`` (the +1) is the SCRATCH row: padding lanes of a bucketed
batch point their writes there, so padded compute can never corrupt a
live sequence (several padding lanes may collide on it — its content is
garbage by design).

:class:`SlotAllocator` is the pure half (isolated-loader tested); the
jax helpers below import lazily so this module loads under any JAX.
"""

from __future__ import annotations

from typing import List, Tuple

__all__ = ["SlotAllocator", "kv_shape", "scatter_prefill", "scatter_step"]


class SlotAllocator:
    """A deterministic free-list over ``capacity`` KV slots (lowest id
    first, so every rank of a lockstep host loop allocates identically)."""

    __slots__ = ("capacity", "_free", "_used")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._free: List[int] = list(range(capacity))
        self._used: set = set()

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError(
                f"KV slot pool exhausted ({self.capacity} slots in use); "
                "admission must check free() first"
            )
        slot = self._free.pop(0)
        self._used.add(slot)
        return slot

    def free_slot(self, slot: int) -> None:
        if slot not in self._used:
            raise ValueError(f"slot {slot} is not allocated")
        self._used.remove(slot)
        # keep the free list sorted: allocation order stays deterministic
        # regardless of eviction order
        self._free.append(slot)
        self._free.sort()

    def free(self) -> int:
        return len(self._free)

    def used(self) -> Tuple[int, ...]:
        return tuple(sorted(self._used))

    def reset(self) -> None:
        self._free = list(range(self.capacity))
        self._used.clear()

    @property
    def scratch(self) -> int:
        """The scratch row's slot id (the ``+1`` row padding lanes write
        to — outside the allocatable pool by construction)."""
        return self.capacity


def kv_shape(slots: int, max_len: int, local_heads: int,
             head_dim: int) -> Tuple[int, int, int, int]:
    """Per-rank KV tensor shape — ``slots + 1`` rows (pool + scratch)."""
    return (slots + 1, max_len, local_heads, head_dim)


# ---------------------------------------------------------------------------
# device-resident updates (lazy jax: traced inside the serving programs)
# ---------------------------------------------------------------------------


def scatter_step(kv, slots, lens, new):
    """Write one decode step's K (or V) rows at ``[slot, len]`` per lane:
    ``kv [S+1, L, H, d]``, ``slots``/``lens`` ``int32 [B]``, ``new``
    ``[B, H, d]``.  Pure scatter — the program shape is independent of
    which slots are live."""
    return kv.at[slots, lens].set(new)


def scatter_prefill(kv, slots, new):
    """Write a whole prompt's K (or V) rows: ``new [B, P, H, d]`` lands
    at ``kv[slot, 0:P]`` per lane (positions beyond the live prompt
    carry garbage that is masked by the length array and overwritten as
    the sequence grows — docs/serving.md)."""
    import jax.numpy as jnp

    pos = jnp.arange(new.shape[1], dtype=jnp.int32)
    return kv.at[slots[:, None], pos[None, :]].set(new)
