"""Serving runtime: continuous batching under a p99 latency SLO.

The serving workload (ROADMAP item 1, docs/serving.md): a
tensor-parallel transformer decode loop behind an iteration-level
(continuous) batching scheduler — requests admitted and evicted BETWEEN
decode megasteps against a bucketed batch-shape table, a KV slot
budget, and a p99 latency objective, with every ``(bucket, phase)``
program pinned once through ``mpx.compile`` and decode driven as a
device-resident megastep.  ``examples/serving/serve.py`` is the
runnable deployment + benchmark + elastic drain drill; the serving
number (tokens/s/chip at the p99 bound, continuous vs static) lands in
``BENCH_serving.json``.

Every module here imports jax LAZILY (inside the methods that trace or
dispatch), so the isolated test loaders — and the
``aot warm --emit-manifest`` path — load the whole package, config and
manifest emission included, under any installed JAX.
"""

from .buckets import (  # noqa: F401
    BucketTable,
    bucket_payload_bytes,
    clear_declared_buckets,
    declare_buckets,
    declared_buckets,
    powers_of_two,
)
from .engine import ServingConfig, ServingEngine, warm_manifest  # noqa: F401
from .kvcache import SlotAllocator  # noqa: F401
from .metrics import BENCH_SCHEMA, bench_payload, summarize  # noqa: F401
from .scheduler import (  # noqa: F401
    ContinuousScheduler,
    Request,
    Sequence,
    StaticScheduler,
    poisson_trace,
)

__all__ = [
    "BENCH_SCHEMA",
    "BucketTable",
    "ContinuousScheduler",
    "Request",
    "Sequence",
    "ServingConfig",
    "ServingEngine",
    "SlotAllocator",
    "StaticScheduler",
    "bench_payload",
    "bucket_payload_bytes",
    "clear_declared_buckets",
    "declare_buckets",
    "declared_buckets",
    "poisson_trace",
    "powers_of_two",
    "summarize",
    "warm_manifest",
]
