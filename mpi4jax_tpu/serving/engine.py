"""The serving engine: pinned per-bucket programs under the scheduler.

This is where the whole perf stack converges on one loop (ROADMAP item
1, docs/serving.md):

- each ``(bucket, phase)`` pair maps to ONE program — prefill and
  decode pinned separately through ``mpx.compile`` (zero per-call key
  work, PR 10), decode driven as a **megastep**
  (``unroll=MPI4JAX_TPU_SERVING_UNROLL``, PR 11) so one host dispatch
  generates N tokens per live lane;
- the scheduler (serving/scheduler.py) admits/evicts ONLY at megastep
  boundaries: batch composition changes between dispatches, never
  inside one, and the bucket table pads the live batch up so composition
  churn cannot force a retrace;
- KV state lives in a slot pool (serving/kvcache.py) sharded over the
  tensor-parallel comm; admission binds slot ids, eviction frees them —
  scatter updates, no reshapes;
- every shape-derived knob is consulted with the PADDED bucket payload
  (serving/buckets.bucket_payload_bytes), so two requests in one bucket
  hit one cache key by construction;
- elastic integration (PR 9): a ``resilience.elastic.BoundaryControl``
  is polled at every megastep boundary — a SIGTERM'd (preempted) rank
  drains at the boundary, survivors re-shard the committed master
  parameters at the new world size, re-pin the bucket table, and
  RE-ADMIT every in-flight sequence by re-prefilling it from its
  committed token history (prompt + generated so far, which IS the KV
  state's content — recompute-style recovery).  Zero failed requests.

The module imports jax lazily: :class:`ServingConfig` and
:func:`warm_manifest` are pure (the ``aot warm --emit-manifest`` path
and the isolated test loaders run them without jax).
"""

from __future__ import annotations

import itertools
import time
from contextlib import contextmanager
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from . import model
from .buckets import BucketTable, bucket_payload_bytes, declare_buckets
from .kvcache import SlotAllocator, kv_shape
from .metrics import summarize
from .scheduler import ContinuousScheduler, Request, StaticScheduler

__all__ = ["ServingConfig", "ServingEngine", "warm_manifest"]

PHASES = ("prefill", "decode")
# + the elastic-replay prefill (full-width prompt buffer): pinned on
# demand at a drain boundary, warmed by the manifest so a drain-ready
# fleet cold-starts those too
ALL_PHASES = ("prefill", "decode", "replay")

_engine_ids = itertools.count()


@dataclass(frozen=True)
class ServingConfig:
    """Static shape of one serving deployment (pure; hashable).

    ``heads`` and ``ffn`` must divide by every world size the deployment
    can shrink to (24 and 384 cover 1/2/3/4/6/8 — the default drill
    sizes); ``max_len`` bounds prompt + generated + megastep overshoot.
    ``clock`` is ``"wall"`` (real time) or ``"virtual"`` (one
    ``tick_s`` per megastep boundary — the deterministic clock the
    multi-process drill needs: every rank of a lockstep host loop must
    make identical admission decisions, which wall clocks cannot
    guarantee).
    """

    vocab: int = 64
    heads: int = 24
    head_dim: int = 4
    ffn: int = 384
    max_len: int = 48
    max_prompt: int = 16
    max_batch: int = 8
    buckets: Tuple[int, ...] = ()
    kv_slots: int = 0
    unroll: int = 4
    slo_p99_ms: float = 1000.0
    seed: int = 0
    clock: str = "wall"
    tick_s: float = 0.01

    @property
    def dim(self) -> int:
        return self.heads * self.head_dim

    @classmethod
    def from_env(cls, **overrides) -> "ServingConfig":
        """Defaults from the ``MPI4JAX_TPU_SERVING_*`` flag registry
        (utils/config.py), explicit keyword overrides winning."""
        from ..utils import config

        base = cls(
            max_batch=config.serving_max_batch(),
            kv_slots=config.serving_kv_slots(),
            unroll=config.serving_unroll(),
            slo_p99_ms=config.serving_slo_p99_ms(),
        )
        spec = config.serving_buckets()
        if spec:
            base = replace(base, buckets=BucketTable.from_spec(spec).buckets)
        return replace(base, **overrides) if overrides else base

    def table(self) -> BucketTable:
        if self.buckets:
            t = BucketTable(self.buckets)
            if t.max_batch != self.max_batch:
                raise ValueError(
                    f"bucket table {t.buckets} must top out at max_batch "
                    f"({self.max_batch})"
                )
            return t
        return BucketTable.from_spec("", self.max_batch)

    def slots(self) -> int:
        return self.kv_slots or 2 * self.max_batch

    def validate_world(self, k: int) -> None:
        if k < 1:
            raise ValueError(f"world size must be >= 1, got {k}")
        if self.heads % k or self.ffn % k:
            raise ValueError(
                f"serving config (heads={self.heads}, ffn={self.ffn}) "
                f"cannot shard over {k} ranks: both must divide by every "
                "world size the deployment runs at (docs/serving.md)"
            )
        if self.unroll < 1:
            raise ValueError(f"unroll must be >= 1, got {self.unroll}")
        if not 1 <= self.max_prompt <= self.max_len:
            raise ValueError(
                f"max_prompt ({self.max_prompt}) must be in "
                f"[1, max_len={self.max_len}]"
            )

    def budget_check(self, prompt_len: int, max_new: int) -> None:
        """A request must fit the prompt buffer AND the KV row: prompt +
        generated + one megastep's overshoot + the trailing token
        column."""
        if prompt_len > self.max_prompt:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds max_prompt "
                f"({self.max_prompt}) — the admission prefill's padded "
                "width (docs/serving.md)"
            )
        need = prompt_len + max_new + self.unroll + 1
        if need > self.max_len:
            raise ValueError(
                f"request needs up to {need} KV positions (prompt "
                f"{prompt_len} + max_new {max_new} + unroll "
                f"{self.unroll} + 1) but max_len is {self.max_len}"
            )

    # -- program shapes (pure: shared by the pin path and the warm
    #    manifest, so warming hits the exact keys serving will ask for) --

    def _param_shapes(self, k: int) -> List[Tuple[Tuple[int, ...], str]]:
        hl, fl = self.heads // k, self.ffn // k
        d, dh = self.dim, self.head_dim
        return [
            ((k, self.vocab, d), "float32"),           # emb
            ((k, d, 3 * hl * dh), "float32"),          # wqkv
            ((k, hl * dh, d), "float32"),              # wo
            ((k, d, fl), "float32"),                   # w1
            ((k, fl, d), "float32"),                   # w2
        ]

    def prompt_width(self, phase: str) -> int:
        """The padded prompt width of a prefill-family program:
        ``prefill`` (admission) pads to the tight ``max_prompt``;
        ``replay`` (elastic re-admission of an in-flight sequence from
        its committed token history) pads to the full ``max_len`` —
        the history can be as long as the KV row."""
        return self.max_prompt if phase == "prefill" else self.max_len

    def program_args(self, phase: str, bucket: int,
                     k: int) -> List[Tuple[Tuple[int, ...], str]]:
        """Abstract (global) argument shapes of one (phase, bucket)
        program at world size ``k``."""
        if phase not in ALL_PHASES:
            raise ValueError(
                f"phase must be one of {ALL_PHASES}, got {phase!r}")
        hl = self.heads // k
        kv = (k,) + kv_shape(self.slots(), self.max_len, hl, self.head_dim)
        args = self._param_shapes(k) + [
            (kv, "float32"),                           # kk
            (kv, "float32"),                           # vv
            ((k, self.slots() + 1, self.max_len), "int32"),  # tok_table
        ]
        if phase in ("prefill", "replay"):
            width = self.prompt_width(phase)
            args += [
                ((k, bucket, width), "int32"),         # prompts
                ((k, bucket), "int32"),                # plens
                ((k, bucket), "int32"),                # slots
            ]
        else:
            args += [
                ((k, bucket), "int32"),                # last_tok
                ((k, bucket), "int32"),                # lens
                ((k, bucket), "int32"),                # slots
            ]
        return args

    def collective_payload_bytes(self, bucket: int) -> int:
        """Per-collective payload of a decode step at ``bucket`` — the
        PADDED bytes every payload-bucketed knob must be consulted with
        (buckets.bucket_payload_bytes; the MPX136/one-key rule)."""
        return bucket_payload_bytes(bucket, self.dim * 4)

    def workload_meta(self, k: int) -> Dict:
        return {
            "model": (f"tp-decoder d={self.dim} h={self.heads} "
                      f"ffn={self.ffn} L={self.max_len}"),
            "buckets": list(self.table().buckets),
            "kv_slots": self.slots(),
            "unroll": self.unroll,
            "tensor_parallel": k,
        }


def warm_manifest(cfg: ServingConfig, world: int) -> dict:
    """The ``python -m mpi4jax_tpu.aot warm`` manifest covering EVERY
    (bucket, phase) program of a deployment: one command pre-populates
    the persistent compile cache for a whole fleet cold start, and a
    subsequent serving run compiles nothing (``disk_cache.misses == 0``
    — asserted by the CI serving lane).  Pure (no jax)."""
    cfg.validate_world(world)
    programs = []
    for bucket in cfg.table().buckets:
        for phase in ALL_PHASES:
            fn = "decode_step" if phase == "decode" else "prefill_step"
            programs.append({
                "fn": f"mpi4jax_tpu.serving.model:{fn}",
                "label": f"serving.{phase}.b{bucket}",
                "args": [
                    {"shape": list(shape), "dtype": dtype}
                    for shape, dtype in cfg.program_args(phase, bucket,
                                                         world)
                ],
                # the prefill family pins explicitly at 1 so a
                # fleet-wide MPI4JAX_TPU_UNROLL_DEFAULT can never
                # megastep-ify a non-carry-shaped body; decode IS the
                # megastep
                "unroll": cfg.unroll if phase == "decode" else 1,
            })
    return {"programs": programs,
            "meta": {"kind": "serving", "world": world,
                     "buckets": list(cfg.table().buckets)}}


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class ServingEngine:
    """One tensor-parallel serving replica (see module docstring).

    ``pin="auto"`` drives programs through ``mpx.compile`` pinned
    executables on a single-controller world and through the ``mpx.spmd``
    program cache on multi-process worlds (same traced bodies, same
    per-bucket one-program rule; the jit path is the one the
    multi-controller input plumbing is proven on).  ``store`` (an
    ``mpx.ShardStore``) arms the elastic boundary: SIGTERM/preemption
    drains execute between megasteps.
    """

    def __init__(self, cfg: ServingConfig, comm=None, *, store=None,
                 pin: object = "auto"):
        from ..parallel.region import resolve_comm

        self.cfg = cfg
        self.comm = resolve_comm(comm)
        self.world = int(self.comm.world_size())
        cfg.validate_world(self.world)
        self.table = cfg.table()
        self.store = store
        # the store's comm IS the drain/shrink world: a store bound to a
        # different comm would announce boundaries on one world while
        # the engine serves another (note ShardStore.comm lazily binds
        # the default comm, so identity is checked by uid, not None)
        if store is not None and store.comm.uid != self.comm.uid:
            raise ValueError(
                "the elastic store must be built over the serving comm "
                f"(store comm uid {store.comm.uid} != serving comm uid "
                f"{self.comm.uid})"
            )
        self.master = model.init_master(cfg.vocab, cfg.dim, cfg.heads,
                                        cfg.head_dim, cfg.ffn, cfg.seed)
        if pin == "auto":
            import jax

            pin = jax.process_count() == 1
        self.pin = bool(pin)
        self.drained = False
        self._uid = next(_engine_ids)
        self._programs: Dict[Tuple[str, int], object] = {}
        self._alloc = SlotAllocator(cfg.slots())
        self._phase_seq = {p: 0 for p in ALL_PHASES}
        self._boundary = 0
        self._state = None   # (emb, wqkv, wo, w1, w2, kk, vv, tok)
        self._build_device_state()

    # -- device state ------------------------------------------------------

    def _build_device_state(self) -> None:
        import numpy as np

        k = self.world
        hl = self.cfg.heads // k
        params = model.shard_params(self.master, k)
        kv = np.zeros((k,) + kv_shape(self.cfg.slots(), self.cfg.max_len,
                                      hl, self.cfg.head_dim), np.float32)
        tok = np.zeros((k, self.cfg.slots() + 1, self.cfg.max_len),
                       np.int32)
        self._state = tuple(self._prep(a) for a in
                            params + (kv, kv.copy(), tok))

    def _prep(self, arr):
        """Host array -> program input.  Single-controller: a committed
        device array (the pinned AOT path).  Multi-process: the plain
        numpy array — every process passes the identical global value
        and jit commits it against the mesh (the elastic-drill
        convention)."""
        import jax

        if jax.process_count() == 1:
            import jax.numpy as jnp

            return jnp.asarray(arr)
        return arr

    def _lane(self, values, fill) -> "object":
        """Per-lane host array [bucket], padded with ``fill``, tiled to
        the global convention [k, bucket]."""
        import numpy as np

        bucket = self.table.bucket_for(len(values))
        row = np.full((bucket,), fill, np.int32)
        row[:len(values)] = np.asarray(values, np.int32)
        return self._prep(np.tile(row[None], (self.world, 1)))

    @staticmethod
    def _host(x):
        """One rank's row of a global array, on host."""
        import numpy as np

        return np.asarray(x[0])

    # -- programs ----------------------------------------------------------

    def _program(self, phase: str, bucket: int):
        key = (phase, bucket)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        import jax
        import numpy as np

        fn = model.decode_step if phase == "decode" else model.prefill_step
        unroll = self.cfg.unroll if phase == "decode" else 1
        if self.pin:
            from ..aot.pinning import compile as aot_compile

            avals = tuple(
                jax.ShapeDtypeStruct(shape, np.dtype(dtype))
                for shape, dtype in self.cfg.program_args(phase, bucket,
                                                          self.world)
            )
            prog = aot_compile(fn, *avals, comm=self.comm, unroll=unroll)
        else:
            from ..parallel.region import spmd

            prog = spmd(comm=self.comm, unroll=unroll)(fn)
        self._programs[key] = prog
        self._meter(f"serving.programs.{phase}")
        return prog

    # -- telemetry ---------------------------------------------------------

    def _meter(self, name: str, n: int = 1) -> None:
        from ..telemetry import core as tcore

        tcore.meter(name, n)

    @contextmanager
    def _phase(self, phase: str, bucket: int, nbytes: int):
        """Per-phase serving bracket: a host-side begin/end pair around
        one prefill/decode dispatch — an op-table row per (phase,
        bucket) with p50/p99 (and, in the events tier, a journal record
        whose deterministic call id matches across processes, feeding
        ``telemetry.report()``'s straggler attribution)."""
        from ..telemetry import core as tcore

        if tcore.effective_mode() == "off":
            yield
            return
        from ..telemetry import journal

        key = tcore.op_key(f"serving.{phase}", self.comm.uid,
                           f"b{bucket}", "")
        events = tcore.events_on()
        call_id = None
        rank = journal.process_index()
        if events:
            call_id = f"srv{self._uid}.{phase}.{self._phase_seq[phase]}"
            self._phase_seq[phase] += 1
            journal.begin(call_id, rank, {
                "op": f"serving.{phase}", "comm_uid": self.comm.uid,
                "bucket": bucket, "bytes": nbytes, "dtype": "",
                "unroll": self.cfg.unroll if phase == "decode" else 1,
            })
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # close the bracket even when the dispatch raises: an
            # unmatched journal begin would corrupt the cross-process
            # pairing the straggler attribution matches on
            dt = time.perf_counter() - t0
            tcore.count_host_op(key, nbytes)
            if events:
                journal.end(call_id, rank, {"algo": f"b{bucket}"})
            else:
                tcore.record_latency(key, dt)

    # -- phases ------------------------------------------------------------

    def _prefill(self, seqs, phase: str = "prefill") -> None:
        import jax
        import numpy as np

        bucket = self.table.bucket_for(len(seqs))
        width = self.cfg.prompt_width(phase)
        prompts = []
        for s in seqs:
            row = list(s.tokens)
            if len(row) > width:
                raise RuntimeError(
                    f"{phase} history of {len(row)} tokens exceeds the "
                    f"padded prompt width {width}"
                )
            prompts.append(row + [0] * (width - len(row)))
        prompts += [[0] * width] * (bucket - len(seqs))
        prompts_g = self._prep(np.tile(
            np.asarray(prompts, np.int32)[None], (self.world, 1, 1)))
        plens_g = self._lane([len(s.tokens) for s in seqs], 1)
        slots_g = self._lane([s.slot for s in seqs], self._alloc.scratch)
        nbytes = bucket_payload_bytes(bucket, width * self.cfg.dim * 4)
        with self._phase(phase, bucket, nbytes):
            out = self._program(phase, bucket)(
                *self._state, prompts_g, plens_g, slots_g)
            jax.block_until_ready(out)
        kk, vv, tok, _first = out
        self._state = self._state[:5] + (kk, vv, tok)
        self._meter("serving.prefills")

    def _decode(self) -> None:
        import jax

        seqs = self._sched.running
        bucket = self.table.bucket_for(len(seqs))
        last_g = self._lane([s.tokens[-1] for s in seqs], 0)
        lens_g = self._lane([len(s.tokens) - 1 for s in seqs], 0)
        slots_g = self._lane([s.slot for s in seqs], self._alloc.scratch)
        with self._phase("decode", bucket,
                         self.cfg.collective_payload_bytes(bucket)):
            out = self._program("decode", bucket)(
                *self._state, last_g, lens_g, slots_g)
            jax.block_until_ready(out)
        self._state = out[:8]
        self._meter("serving.megasteps")

    def _collect_tokens(self, seqs, stride: int, now: float) -> int:
        """Read newly generated tokens off the token table (host mirror
        of one rank's row — the table is replicated content).  A lane's
        token columns run through ``len(tokens) - 1``; the dispatch just
        executed appended ``stride`` more (1 for prefill, ``unroll`` for
        a decode megastep)."""
        tok = self._host(self._state[7])
        produced = 0
        for s in seqs:
            have = len(s.tokens)
            row = tok[s.slot]
            fresh = row[have:min(self.cfg.max_len, have + stride)]
            if len(fresh):
                s.record(fresh, now)
                produced += len(fresh)
        return produced

    # -- elastic boundary --------------------------------------------------

    def _world_changed(self) -> None:
        """Survivor side of a drain/grow boundary: adopt the store's
        rebuilt comm, re-shard the committed master at the new world
        size, re-pin every bucket, and re-admit in-flight sequences by
        re-prefilling their committed token history."""
        self.comm = self.store.comm
        self.world = int(self.comm.world_size())
        self.cfg.validate_world(self.world)
        self._programs.clear()
        self._build_device_state()
        # pull every in-flight sequence out of the OLD slot pool, then
        # swap in a fresh pool (the KV tensors were rebuilt empty) and
        # re-point the live scheduler at it before re-seating
        moved = self._sched.requeue_running()
        self._alloc = SlotAllocator(self.cfg.slots())
        self._sched.alloc = self._alloc
        if moved:
            # <= max_batch sequences by the scheduler's residency cap,
            # so one full-width replay prefill re-seats them all: the
            # committed history (prompt + generated) becomes the
            # prompt, rebuilding the KV content on the survivors; the
            # one token it samples is the sequence's NEXT token and is
            # discarded here (the next decode megastep regenerates it
            # into the token table before the host ever reads it)
            self._sched.readmit(moved)
            self._meter("serving.readmissions", len(moved))
            self._prefill(moved, phase="replay")

    # -- the loop ----------------------------------------------------------

    def _now(self, t0: float) -> float:
        if self.cfg.clock == "virtual":
            return self._boundary * self.cfg.tick_s
        return time.monotonic() - t0

    def run(self, trace: List[Request], *, scheduler: str = "continuous",
            max_boundaries: Optional[int] = None) -> Dict:
        """Serve ``trace`` to completion; returns the metric block of
        serving/metrics.summarize plus engine bookkeeping.  A drained
        rank (elastic preemption) exits early with ``self.drained``
        set — its in-flight sequences continue on the survivors, so it
        reports zero failures by construction."""
        from ..parallel import megastep as _megastep
        from ..resilience.elastic import BoundaryControl

        if self.drained:
            raise RuntimeError(
                "this engine drained out of its world (elastic "
                "preemption); build a fresh ServingEngine over the "
                "current comm"
            )
        sched_cls = (ContinuousScheduler if scheduler == "continuous"
                     else StaticScheduler)
        self._alloc.reset()
        self._sched = sched_cls(self.table, self._alloc)
        self._boundary = 0
        for r in trace:
            self.cfg.budget_check(r.prompt_len, r.max_new_tokens)

        # the MPX136 gate is scoped to the serving loop: the engine's
        # own traces happen inside run(), and a bucket table declared
        # forever would flag unrelated later traces in the process
        from .buckets import clear_declared_buckets, declared_buckets

        prev_table = declared_buckets()
        declare_buckets(self.table)

        boundary = BoundaryControl(self.store) if self.store is not None \
            else None
        if boundary is not None and self.store.committed_step is None:
            # the committed state a survivor re-shards after a world
            # change; parameters are static in serving, so ONE commit
            # covers the whole run
            self.store.commit(0, {"params": self.master})

        t0 = time.monotonic()
        wall0 = time.perf_counter()
        try:
            if boundary is not None:
                boundary.__enter__()
            while not self._sched.idle(trace):
                now = self._now(t0)
                self._sched.offer(trace, now)
                new = self._sched.admit(now)
                if new:
                    self._meter("serving.requests_admitted", len(new))
                    self._prefill(new)
                    self._collect_tokens(new, 1, self._now(t0))
                if self._sched.running:
                    self._decode()
                    self._collect_tokens(self._sched.running,
                                         self.cfg.unroll, self._now(t0))
                elif self.cfg.clock == "wall":
                    nxt = self._sched.next_arrival_s(trace)
                    if nxt is not None:
                        time.sleep(min(0.05, max(0.0, nxt - now)))
                done = self._sched.finish_ready(self._now(t0))
                if done:
                    self._meter("serving.requests_completed", len(done))
                self._boundary += 1
                _megastep.run_boundary_hooks(self._boundary, engine=self)
                if boundary is not None:
                    outcome = boundary.poll(
                        self._boundary, {"params": self.master},
                        committed=True)
                    if outcome is not None:
                        kind = outcome[0]
                        if kind == "leave":
                            self.drained = True
                            break
                        self._world_changed()
                if max_boundaries is not None \
                        and self._boundary >= max_boundaries:
                    break
        finally:
            if boundary is not None:
                boundary.__exit__(None, None, None)
            if prev_table is not None:
                declare_buckets(prev_table)
            else:
                clear_declared_buckets()

        wall = time.perf_counter() - wall0
        if self.cfg.clock == "virtual":
            wall = self._boundary * self.cfg.tick_s
        finished = self._sched.finished
        failed = 0 if self.drained else (
            len(trace) - len(finished))
        self._meter("serving.tokens_generated",
                    sum(len(s.generated) for s in finished))
        if failed:
            self._meter("serving.requests_failed", failed)
        out = summarize(finished, wall_s=wall, chips=self.world,
                        slo_p99_ms=self.cfg.slo_p99_ms, failed=failed,
                        scheduler=scheduler)
        out["boundaries"] = self._boundary
        out["programs"] = sorted(f"{p}.b{b}" for p, b in self._programs)
        out["drained"] = self.drained
        out["world"] = self.world
        return out
