"""Continuous-batching request scheduler + the synthetic arrival trace.

The scheduler is the serving runtime's control plane: requests arrive on
a Poisson process, wait in a FIFO queue, are ADMITTED into free KV slots
between decode megasteps, decode as one bucketed batch, and are EVICTED
the megastep boundary after they finish — iteration-level (continuous)
batching in the Orca/vLLM sense, where the batch composition changes
between decode steps instead of between whole batches.  The static
baseline (:class:`StaticScheduler`) is the classical alternative the
serving benchmark measures against: a batch is admitted only when the
PREVIOUS batch has fully drained, so early finishers idle their lanes
until the batch's straggler completes.

Everything here is deterministic pure Python — the device side
(serving/engine.py) and the cost-model replay (serving/sim.py) drive
the SAME scheduler, so the benchmarked admission policy is the shipped
one.  The isolated test loaders run the whole module without jax.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .buckets import BucketTable
from .kvcache import SlotAllocator

__all__ = ["ContinuousScheduler", "Request", "Sequence", "StaticScheduler",
           "poisson_trace"]


@dataclass(frozen=True)
class Request:
    """One inference request of the synthetic trace."""

    rid: int
    arrival_s: float          # offset from trace start
    prompt: Tuple[int, ...]   # token ids
    max_new_tokens: int

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)


@dataclass
class Sequence:
    """A request holding a KV slot: the scheduler's unit of residency."""

    request: Request
    slot: int
    admitted_s: float
    generated: List[int] = field(default_factory=list)
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    preempt_readmissions: int = 0

    @property
    def rid(self) -> int:
        return self.request.rid

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.request.max_new_tokens

    @property
    def tokens(self) -> Tuple[int, ...]:
        """Full committed token history (prompt + generated): what a
        survivor re-prefills from after an elastic drain."""
        return self.request.prompt + tuple(self.generated)

    def record(self, token_ids, now: float) -> None:
        """Append one megastep's worth of generated tokens, capped at the
        request budget (a megastep may overshoot by up to unroll-1
        tokens; the overshoot is computed but discarded — the price of
        boundary-only eviction, docs/serving.md)."""
        room = self.request.max_new_tokens - len(self.generated)
        take = list(token_ids)[:max(0, room)]
        if take and self.first_token_s is None:
            self.first_token_s = now
        self.generated.extend(int(t) for t in take)
        if self.done and self.finish_s is None:
            self.finish_s = now


def poisson_trace(n_requests: int, rate_rps: float, *, seed: int = 0,
                  prompt_len: Tuple[int, int] = (2, 8),
                  max_new: Tuple[int, int] = (4, 16),
                  long_frac: float = 0.0,
                  long_new: Tuple[int, int] = (0, 0),
                  vocab: int = 64) -> List[Request]:
    """A deterministic synthetic arrival trace: exponential interarrival
    times at ``rate_rps``, uniform prompt lengths and generation
    budgets, all drawn from one seeded generator — the same seed
    replays the same trace bit-for-bit (pinned by
    tests/test_serving_pure.py).

    ``long_frac > 0`` makes the generation lengths HEAVY-TAILED: that
    fraction of requests draws its budget from ``long_new`` instead —
    the realistic regime (production length distributions are
    heavy-tailed) and the one where batch-level scheduling loses most:
    a static batch runs at its longest member's length while every
    short member's lane idles (Yu et al., OSDI '22)."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if not 0.0 <= long_frac <= 1.0:
        raise ValueError(f"long_frac must be in [0, 1], got {long_frac}")
    rng = random.Random(seed)
    out: List[Request] = []
    t = 0.0
    for rid in range(n_requests):
        t += rng.expovariate(rate_rps)
        plen = rng.randint(*prompt_len)
        budget = rng.randint(*(
            long_new if long_frac and rng.random() < long_frac else max_new
        ))
        out.append(Request(
            rid=rid,
            arrival_s=t,
            prompt=tuple(rng.randrange(1, vocab) for _ in range(plen)),
            max_new_tokens=budget,
        ))
    return out


class ContinuousScheduler:
    """Iteration-level batching against a slot budget and a bucket table.

    The engine drives it strictly at megastep boundaries::

        sched.offer(trace, now)          # move arrivals into the queue
        new = sched.admit(now)           # -> sequences to prefill
        ...decode megastep...
        done = sched.finish_ready(now)   # evict finished, free slots

    Admission is FIFO and bounded by (a) free KV slots and (b) the
    bucket table's ``max_batch`` residency cap.  ``decode_bucket()``
    maps the live batch to its padded program shape.
    """

    continuous = True

    def __init__(self, table: BucketTable, alloc: SlotAllocator):
        self.table = table
        self.alloc = alloc
        self.waiting: deque = deque()
        self.running: List[Sequence] = []
        self.finished: List[Sequence] = []
        self._offered = 0

    # -- arrivals ----------------------------------------------------------

    def offer(self, trace: List[Request], now: float) -> int:
        """Move every not-yet-offered request with ``arrival_s <= now``
        into the waiting queue (the trace must be arrival-ordered).
        Returns how many arrived."""
        n = 0
        while self._offered < len(trace) \
                and trace[self._offered].arrival_s <= now:
            self.waiting.append(trace[self._offered])
            self._offered += 1
            n += 1
        return n

    def next_arrival_s(self, trace: List[Request]) -> Optional[float]:
        if self._offered >= len(trace):
            return None
        return trace[self._offered].arrival_s

    # -- admission / eviction ---------------------------------------------

    def _admissible(self) -> bool:
        return (bool(self.waiting)
                and len(self.running) < self.table.max_batch
                and self.alloc.free() > 0)

    def admit(self, now: float) -> List[Sequence]:
        """FIFO admission at a megastep boundary; assigns KV slots."""
        new: List[Sequence] = []
        while self._admissible():
            req = self.waiting.popleft()
            seq = Sequence(request=req, slot=self.alloc.alloc(),
                           admitted_s=now)
            self.running.append(seq)
            new.append(seq)
        return new

    def finish_ready(self, now: float) -> List[Sequence]:
        """Evict every finished sequence, freeing its slot."""
        done = [s for s in self.running if s.done]
        for s in done:
            if s.finish_s is None:
                s.finish_s = now
            self.alloc.free_slot(s.slot)
            self.running.remove(s)
            self.finished.append(s)
        return done

    def decode_bucket(self) -> Optional[int]:
        """The padded program shape of the current live batch (``None``
        when nothing is running)."""
        if not self.running:
            return None
        return self.table.bucket_for(len(self.running))

    def idle(self, trace: List[Request]) -> bool:
        """Nothing running, nothing waiting, nothing left to arrive."""
        return (not self.running and not self.waiting
                and self._offered >= len(trace))

    # -- elastic drain support --------------------------------------------

    def requeue_running(self) -> List[Sequence]:
        """Pull every in-flight sequence out of its slot (world change:
        the KV pool is rebuilt on the surviving ranks).  The sequences
        keep their token history — the engine re-prefills them from
        ``Sequence.tokens`` — and re-enter the running set with FRESH
        slots, ahead of the waiting queue (they are the oldest work)."""
        moved = list(self.running)
        for s in moved:
            self.alloc.free_slot(s.slot)
        self.running = []
        return moved

    def readmit(self, seqs: List[Sequence]) -> List[Sequence]:
        """Re-seat requeued sequences after a world change (fresh
        slots).  Caller guarantees capacity: the slot pool was rebuilt
        empty and the running set cannot exceed max_batch by
        construction."""
        for s in seqs:
            s.slot = self.alloc.alloc()
            s.preempt_readmissions += 1
            self.running.append(s)
        return seqs


class StaticScheduler(ContinuousScheduler):
    """The batch-level baseline: a new batch is admitted ONLY when the
    previous one has fully drained — no admission while anything runs,
    which is exactly the lane idling continuous batching removes."""

    continuous = False

    def admit(self, now: float) -> List[Sequence]:
        # a closed batch admits nothing until it fully drains; once
        # empty, one whole batch is admitted in a single boundary (the
        # parent's loop fills up to max_batch / free slots as usual)
        if self.running:
            return []
        return super().admit(now)
