"""Bucketed batch shapes: the serving runtime's one-program-per-shape rule.

A serving fleet cannot afford one trace per request count: each distinct
batch shape re-lowers (and, pinned, re-pins) a whole SPMD program.  The
bucket table quantizes every live batch UP to a small declared set of
shapes — powers of two by default, vLLM/Orca-style — so each
``(bucket, phase)`` pair maps to exactly ONE pinned program for the
lifetime of the server, and admission/eviction changes which *lanes* are
live, never which *program* runs.

The padded bucket shape is also what every shape-derived knob must be
consulted with at trace time — payload-bucketed ``overlap_chunks``
included (:func:`bucket_payload_bytes`): consulting with the live
payload would let two requests in one bucket derive different chunk
counts and split one bucket across two programs
(tests/test_serving_pure.py pins the regression).

:func:`declare_buckets` registers the active table process-wide; the
MPX136 advisory (analysis/checkers.py) uses it to flag traced programs
whose batch dimension is not in the declared set — the exact shapes that
would force an unpinned retrace per request count.

Pure Python (no jax): the isolated test loaders drive everything here
under any installed JAX.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

__all__ = ["BucketTable", "bucket_payload_bytes", "clear_declared_buckets",
           "declare_buckets", "declared_buckets", "powers_of_two"]


def powers_of_two(max_batch: int) -> Tuple[int, ...]:
    """The default bucket set: ``1, 2, 4, ... , max_batch`` (the cap is
    always included so the table covers it even when it is not itself a
    power of two)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


class BucketTable:
    """An ascending set of declared batch sizes and the pad-up rule."""

    __slots__ = ("buckets",)

    def __init__(self, buckets: Sequence[int]):
        bs = tuple(int(b) for b in buckets)
        if not bs:
            raise ValueError("bucket table must declare at least one bucket")
        if any(b < 1 for b in bs):
            raise ValueError(f"bucket sizes must be >= 1, got {bs}")
        if len(set(bs)) != len(bs) or tuple(sorted(bs)) != bs:
            raise ValueError(
                f"bucket sizes must be strictly ascending, got {bs}"
            )
        self.buckets = bs

    @classmethod
    def from_spec(cls, spec: str, max_batch: Optional[int] = None
                  ) -> "BucketTable":
        """Parse the ``MPI4JAX_TPU_SERVING_BUCKETS`` grammar: a
        comma-separated ascending list, or empty for powers of two up to
        ``max_batch``."""
        spec = (spec or "").strip()
        if not spec:
            if max_batch is None:
                raise ValueError(
                    "an empty bucket spec needs max_batch to derive the "
                    "default power-of-two table"
                )
            return cls(powers_of_two(max_batch))
        try:
            buckets = tuple(int(tok) for tok in spec.split(","))
        except ValueError:
            raise ValueError(
                f"MPI4JAX_TPU_SERVING_BUCKETS={spec!r} could not be "
                "parsed: expected comma-separated ascending batch sizes "
                "(e.g. '1,2,4,8')"
            ) from None
        return cls(buckets)

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """The smallest declared bucket covering a live batch of ``n``."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch size {n} exceeds the largest declared bucket "
            f"{self.max_batch} (buckets: {self.buckets})"
        )

    def pad(self, n: int) -> int:
        """Lanes of padding a live batch of ``n`` rides with."""
        return self.bucket_for(n) - n

    def __contains__(self, n) -> bool:
        return n in self.buckets

    def __eq__(self, other) -> bool:
        return (isinstance(other, BucketTable)
                and other.buckets == self.buckets)

    def __hash__(self) -> int:
        return hash(self.buckets)

    def __repr__(self) -> str:
        return f"BucketTable{self.buckets}"


def bucket_payload_bytes(bucket: int, per_item_bytes: int) -> int:
    """The PADDED payload a bucketed program ships per collective: what
    shape-derived knobs (payload-bucketed ``overlap_chunks``,
    ``MPI4JAX_TPU_OVERLAP_CHUNKS`` tuning buckets) must be consulted
    with at trace time.  Consulting with the live ``n * per_item_bytes``
    instead would give two requests in one bucket different chunk
    counts — two traces, two cache keys, one bucket
    (docs/serving.md)."""
    if bucket < 1 or per_item_bytes < 0:
        raise ValueError(
            f"need bucket >= 1 and per_item_bytes >= 0, got "
            f"({bucket}, {per_item_bytes})"
        )
    return bucket * per_item_bytes


# ---------------------------------------------------------------------------
# the declared-bucket registry (the MPX136 gate)
# ---------------------------------------------------------------------------
#
# The serving engine declares its table on construction; the analysis
# config snapshot (analysis/hook.py) records it, and the MPX136 checker
# flags traced collectives whose leading (batch) dimension is not in the
# set.  Nothing outside the serving runtime declares buckets, so the
# advisory is silent — and the snapshot byte-identical — everywhere else.

_declared: Optional[BucketTable] = None


def declare_buckets(table) -> BucketTable:
    """Install ``table`` (a :class:`BucketTable` or an iterable of batch
    sizes) as the process's declared serving bucket set.  Returns the
    installed table."""
    global _declared
    if not isinstance(table, BucketTable):
        table = BucketTable(tuple(table) if isinstance(table, Iterable)
                            else (table,))
    _declared = table
    return table


def declared_buckets() -> Optional[BucketTable]:
    """The declared table, or ``None`` when no serving runtime declared
    one (the MPX136 checker is then inert)."""
    return _declared


def clear_declared_buckets() -> None:
    global _declared
    _declared = None
