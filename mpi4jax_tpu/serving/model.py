"""The serving workload: a tiny tensor-parallel transformer decoder.

Megatron-style tensor parallelism over the serving comm: QKV and the
MLP up-projection are COLUMN-parallel (each rank holds ``heads / k``
attention heads and ``ffn / k`` hidden units), the attention output and
MLP down-projections ROW-parallel — each rank computes a partial sum
that exactly TWO allreduces per layer complete.  Those two allreduces
(payload ``[batch, dim]``) are the serving hot path's whole
communication surface, so the batch dimension every collective carries
is the BUCKET shape (leading dim = padded batch — what the MPX136
advisory checks).

Both step functions are **module-level and shape-polymorphic** (every
size is derived from the argument shapes, no closed-over config), so
the cache-warming CLI can name them in a manifest
(``mpi4jax_tpu.serving.model:prefill_step``) and warm the exact
programs the engine pins — same function, same abstract shapes, same
persistent-cache key (docs/serving.md "Fleet cold start").

Conventions (per-rank views; ``B`` = bucket, ``L`` = max_len, ``S`` =
KV slots, ``Hl`` = local heads, ``dh`` = head dim, ``Fl`` = local ffn):

- ``kk``/``vv`` ``[S+1, L, Hl, dh]`` — the sharded KV pool; row ``S``
  is the padding-lane scratch row (serving/kvcache.py);
- ``tok_table [S+1, L] int32`` — token ``i`` of a sequence at column
  ``i`` (prompt at ``0..plen-1``, generated from ``plen`` on);
- ``lens [B] int32`` — KV entries present per lane; the lane's latest
  token sits at column ``lens`` and its KV is written by the NEXT
  decode step (so after prefill ``lens == plen`` with the first
  generated token already at column ``plen``);
- sampling is greedy argmax: bit-deterministic, and identical on every
  rank because the logits are computed from allreduced (replicated)
  activations.

``decode_step`` obeys the megastep carry contract (11 dynamic arguments
in, like-structured 11-tuple out) so ``mpx.compile(..., unroll=N)``
drives it as a device-resident multi-token program.
"""

from __future__ import annotations

__all__ = ["decode_step", "init_master", "prefill_step", "shard_params"]

NEG_INF = -1e9


def _attention_mix(x, wo, w1, w2):
    """Row-parallel attention-out + MLP: the two partial-sum matmuls and
    their completing allreduces (the serving comm pattern)."""
    import jax

    from ..ops import SUM, allreduce

    attn_full, _ = allreduce(x @ wo, op=SUM)
    return attn_full, lambda y: allreduce(
        jax.nn.relu(y @ w1) @ w2, op=SUM)[0]


def decode_step(emb, wqkv, wo, w1, w2, kk, vv, tok_table, last_tok, lens,
                slots):
    """One token step for a bucketed batch of lanes (per-rank body).

    Embeds each lane's latest token (column ``lens``), writes its K/V at
    position ``lens``, attends over ``0..lens``, and records the
    sampled next token at column ``lens + 1``.  Returns the full carry
    (params included, unchanged) — the megastep contract.
    """
    import jax.numpy as jnp

    from ..ops import varying
    from .kvcache import scatter_step

    n_local_heads, head_dim = kk.shape[2], kk.shape[3]
    max_len = kk.shape[1]

    x = emb[last_tok]                              # [B, D]
    qkv = (x @ wqkv).reshape(x.shape[0], 3, n_local_heads, head_dim)
    q = qkv[:, 0] * (head_dim ** -0.5)
    kk = scatter_step(kk, slots, lens, qkv[:, 1])
    vv = scatter_step(vv, slots, lens, qkv[:, 2])

    krows = kk[slots]                              # [B, L, Hl, dh]
    vrows = vv[slots]
    scores = jnp.einsum("bhd,blhd->bhl", q, krows)
    live = jnp.arange(max_len, dtype=jnp.int32)[None, :] <= lens[:, None]
    scores = jnp.where(live[:, None, :], scores, NEG_INF)
    att = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    att = att / att.sum(axis=-1, keepdims=True)
    ctx = jnp.einsum("bhl,blhd->bhd", att, vrows)
    ctx = ctx.reshape(x.shape[0], n_local_heads * head_dim)

    attn_full, mlp = _attention_mix(ctx, wo, w1, w2)
    x = x + attn_full
    x = x + mlp(x)

    logits = x @ emb.T                             # [B, V], replicated math
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tok_table = tok_table.at[slots, lens + 1].set(nxt)
    return varying((emb, wqkv, wo, w1, w2, kk, vv, tok_table, nxt,
                    lens + jnp.int32(1), slots))


def prefill_step(emb, wqkv, wo, w1, w2, kk, vv, tok_table, prompts, plens,
                 slots):
    """Prompt processing for a bucketed batch (per-rank body).

    Causal self-attention over the padded prompt buffer ``[B, L]``,
    K/V written for every position (garbage beyond ``plen`` is masked
    by ``lens`` downstream and overwritten as the sequence grows), and
    the FIRST generated token sampled from the last live position and
    recorded at column ``plen``.  Returns ``(kk, vv, tok_table,
    first_token)``.
    """
    import jax.numpy as jnp

    from ..ops import varying
    from .kvcache import scatter_prefill

    n_local_heads, head_dim = kk.shape[2], kk.shape[3]
    batch, pad_len = prompts.shape

    x = emb[prompts]                               # [B, P, D]
    qkv = (x @ wqkv).reshape(batch, pad_len, 3, n_local_heads, head_dim)
    q = qkv[:, :, 0] * (head_dim ** -0.5)
    kk = scatter_prefill(kk, slots, qkv[:, :, 1])
    vv = scatter_prefill(vv, slots, qkv[:, :, 2])

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, qkv[:, :, 1])
    causal = jnp.tril(jnp.ones((pad_len, pad_len), bool))
    scores = jnp.where(causal[None, None, :, :], scores, NEG_INF)
    att = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    att = att / att.sum(axis=-1, keepdims=True)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", att, qkv[:, :, 2])
    ctx = ctx.reshape(batch, pad_len, n_local_heads * head_dim)

    attn_full, mlp = _attention_mix(ctx, wo, w1, w2)
    x = x + attn_full
    x = x + mlp(x)

    x_last = x[jnp.arange(batch), plens - 1]       # [B, D]
    logits = x_last @ emb.T
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tok_table = tok_table.at[slots, plens].set(first)
    return varying((kk, vv, tok_table, first))


# ---------------------------------------------------------------------------
# parameters: one unsharded master copy, re-sharded per world size
# ---------------------------------------------------------------------------
#
# The master lives host-side (numpy) and is what the elastic ShardStore
# commits: after a drain shrinks the tensor-parallel group, survivors
# re-derive the k'-way shards from the same master — deterministic on
# every rank, no exchange needed.


def init_master(vocab: int, dim: int, heads: int, head_dim: int, ffn: int,
                seed: int = 0) -> dict:
    """Seeded unsharded parameters (numpy, float32)."""
    import numpy as np

    if dim != heads * head_dim:
        raise ValueError(
            f"dim ({dim}) must equal heads * head_dim "
            f"({heads} * {head_dim})"
        )
    rng = np.random.default_rng(seed)

    def w(*shape, scale):
        return rng.normal(0.0, scale, shape).astype(np.float32)

    return {
        "emb": w(vocab, dim, scale=0.1),
        "wqkv": w(dim, 3, heads, head_dim, scale=dim ** -0.5),
        "wo": w(heads, head_dim, dim, scale=dim ** -0.5),
        "w1": w(dim, ffn, scale=dim ** -0.5),
        "w2": w(ffn, dim, scale=ffn ** -0.5),
    }


def shard_params(master: dict, k: int) -> tuple:
    """Master -> the 5 GLOBAL param arrays (leading rank axis, numpy):
    ``emb`` replicated, QKV/MLP-up column-parallel (head / hidden-unit
    blocks), attention-out/MLP-down row-parallel."""
    import numpy as np

    heads, head_dim = master["wqkv"].shape[2], master["wqkv"].shape[3]
    dim, ffn = master["w1"].shape
    if heads % k or ffn % k:
        raise ValueError(
            f"heads ({heads}) and ffn ({ffn}) must both divide by the "
            f"tensor-parallel world size {k} (docs/serving.md)"
        )
    hl, fl = heads // k, ffn // k
    emb_g = np.tile(master["emb"][None], (k, 1, 1))
    wqkv_g = np.stack([
        master["wqkv"][:, :, r * hl:(r + 1) * hl, :].reshape(
            dim, 3 * hl * head_dim)
        for r in range(k)
    ])
    wo_g = np.stack([
        master["wo"][r * hl:(r + 1) * hl].reshape(hl * head_dim, dim)
        for r in range(k)
    ])
    w1_g = np.stack([master["w1"][:, r * fl:(r + 1) * fl]
                     for r in range(k)])
    w2_g = np.stack([master["w2"][r * fl:(r + 1) * fl, :]
                     for r in range(k)])
    return emb_g, wqkv_g, wo_g, w1_g, w2_g
