"""Cost-model-driven serving replay: the scheduler under a virtual clock.

The replay runs the REAL scheduler (serving/scheduler.py — the same
admission/eviction code the engine drives) against the real arrival
trace, but replaces each device dispatch with its predicted latency from
the static communication cost model (analysis/costmodel.py: alpha-beta
per link class + roofline compute + host dispatch): per decode step, the
two tensor-parallel allreduces of ``[bucket, dim]`` plus the attention/
MLP math; per megastep, ONE host dispatch amortized over ``unroll``
steps — so the continuous-vs-static comparison measures exactly the
scheduling policy, on a clock that is deterministic and runs anywhere
(no accelerator, no jax).

This is the capture path of the committed ``BENCH_serving.json`` in
containers without an accelerator (docs/serving.md "Capture protocol");
the CI serving lane runs the REAL engine on the 8-device mesh and
uploads its measured payload alongside.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .engine import ServingConfig
from .kvcache import SlotAllocator
from .metrics import bench_payload, summarize
from .scheduler import ContinuousScheduler, Request, StaticScheduler

__all__ = ["replay", "replay_bench", "step_costs_us"]


def step_costs_us(cfg: ServingConfig, k: int, model=None) -> Dict[str, float]:
    """Predicted per-dispatch costs (microseconds) at world size ``k``:
    ``decode_step(bucket)`` (one token step: 2 allreduces + compute),
    ``dispatch`` (host cost per megastep), ``prefill(bucket)``."""
    from ..analysis import costmodel

    m = model if model is not None else costmodel.load_model()
    weights = (cfg.vocab * cfg.dim + 3 * cfg.dim * cfg.dim
               + cfg.dim * cfg.dim + 2 * cfg.dim * cfg.ffn) * 4
    out: Dict[str, float] = {"dispatch": m.dispatch_us}
    for bucket in cfg.table().buckets:
        nbytes = cfg.collective_payload_bytes(bucket)
        if k > 1:
            wire = 2 * m.time_us(costmodel.collective_cost(
                "allreduce", None, nbytes, k))
        else:
            wire = 0.0
        # roofline compute: the weight streaming dominates at tiny
        # batches (every step reads all local weights), KV read scales
        # with bucket * max_len
        kv_read = bucket * cfg.max_len * cfg.heads // k * cfg.head_dim * 4 * 2
        compute = m.compute_us(weights // k + kv_read)
        out[f"decode.b{bucket}"] = wire + compute
        # prefill: the same pattern over the padded prompt width at once
        width = cfg.max_prompt
        pre_wire = 2 * m.time_us(costmodel.collective_cost(
            "allreduce", None, nbytes * width, k)) if k > 1 else 0.0
        out[f"prefill.b{bucket}"] = (
            pre_wire + m.compute_us(weights // k
                                    + bucket * width * cfg.dim * 4)
        )
    return out


def replay(cfg: ServingConfig, trace: List[Request], *, k: int,
           scheduler: str = "continuous", model=None) -> Dict:
    """One scheduler policy over ``trace`` on the virtual clock; returns
    the serving metric block (metrics.summarize schema)."""
    costs = step_costs_us(cfg, k, model=model)
    table = cfg.table()
    sched_cls = (ContinuousScheduler if scheduler == "continuous"
                 else StaticScheduler)
    sched = sched_cls(table, SlotAllocator(cfg.slots()))
    for r in trace:
        cfg.budget_check(r.prompt_len, r.max_new_tokens)

    now = 0.0
    boundaries = 0
    guard = 200_000
    while not sched.idle(trace) and boundaries < guard:
        sched.offer(trace, now)
        new = sched.admit(now)
        if new:
            bucket = table.bucket_for(len(new))
            now += (costs[f"prefill.b{bucket}"] + costs["dispatch"]) * 1e-6
            for s in new:
                s.record([0], now)   # the prefill's first sampled token
        if sched.running:
            bucket = table.bucket_for(len(sched.running))
            now += (cfg.unroll * costs[f"decode.b{bucket}"]
                    + costs["dispatch"]) * 1e-6
            for s in sched.running:
                s.record([0] * cfg.unroll, now)
        elif not sched.waiting:
            nxt = sched.next_arrival_s(trace)
            if nxt is None:
                break
            now = max(now, nxt)
        sched.finish_ready(now)
        boundaries += 1
    finished = sched.finished
    out = summarize(finished, wall_s=now, chips=k,
                    slo_p99_ms=cfg.slo_p99_ms,
                    failed=len(trace) - len(finished), scheduler=scheduler)
    out["boundaries"] = boundaries
    return out


def replay_bench(cfg: ServingConfig, trace: List[Request], *, k: int,
                 trace_meta: Dict, model=None,
                 environment: Optional[str] = None) -> Tuple[Dict, Dict, Dict]:
    """Both policies over one trace -> the BENCH_serving payload."""
    cont = replay(cfg, trace, k=k, scheduler="continuous", model=model)
    stat = replay(cfg, trace, k=k, scheduler="static", model=model)
    payload = bench_payload(
        workload=cfg.workload_meta(k), trace_meta=trace_meta, chips=k,
        continuous=cont, static=stat,
        environment=environment or (
            "simulated: cost-model-driven replay of the shipped "
            "scheduler (analysis/costmodel.py); capture protocol in "
            "docs/serving.md"
        ),
    )
    return payload, cont, stat
