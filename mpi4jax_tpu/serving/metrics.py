"""Serving metrics: per-request latency, the SLO verdict, BENCH payload.

THE serving number is *tokens/s/chip at a p99 latency bound*: raw
throughput is meaningless if the tail waits unboundedly (a static batch
maximizes device math and still starves late arrivals), so the metric
pairs the token rate with the p99 request latency it was achieved at
and the bound it is judged against (``MPI4JAX_TPU_SERVING_SLO_P99_MS``).
``BENCH_serving.json`` carries BOTH schedulers' numbers over the SAME
trace — the continuous-vs-static speedup is the headline
(docs/serving.md).

Pure Python; shared verbatim by the real engine and the cost-model
replay.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["BENCH_SCHEMA", "bench_payload", "percentile", "summarize"]

BENCH_SCHEMA = "mpx-serving-bench/1"


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (``q`` in [0, 1]); ``None`` on empty."""
    if not values:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def summarize(finished, *, wall_s: float, chips: int, slo_p99_ms: float,
              failed: int = 0, scheduler: str = "continuous") -> Dict:
    """One scheduler run -> its metric block.

    ``finished`` is the scheduler's finished-sequence list; request
    latency is ``finish_s - arrival_s`` (queueing included — the SLO is
    the USER'S latency, not the device's), first-token latency
    ``first_token_s - arrival_s``."""
    lat = [s.finish_s - s.request.arrival_s for s in finished
           if s.finish_s is not None]
    ttft = [s.first_token_s - s.request.arrival_s for s in finished
            if s.first_token_s is not None]
    tokens = sum(len(s.generated) for s in finished)
    p99 = percentile(lat, 0.99)
    p99_ms = p99 * 1e3 if p99 is not None else None
    p50 = percentile(lat, 0.5)
    return {
        "scheduler": scheduler,
        "completed": len(lat),
        "failed": int(failed),
        "tokens": int(tokens),
        "wall_s": round(float(wall_s), 6),
        "tokens_per_s_per_chip": (
            round(tokens / wall_s / chips, 3) if wall_s > 0 else None
        ),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
        "ttft_p99_ms": (
            round(percentile(ttft, 0.99) * 1e3, 3) if ttft else None
        ),
        "slo_p99_ms": float(slo_p99_ms),
        "slo_met": bool(p99_ms is not None and p99_ms <= slo_p99_ms),
        "preempt_readmissions": sum(s.preempt_readmissions
                                    for s in finished),
    }


def bench_payload(*, workload: Dict, trace_meta: Dict, chips: int,
                  continuous: Dict, static: Optional[Dict],
                  environment: str, provenance: Optional[Dict] = None
                  ) -> Dict:
    """The ``BENCH_serving.json`` document: both schedulers' numbers over
    one trace, the SLO they were judged at, and the speedup."""
    payload = {
        "schema": BENCH_SCHEMA,
        "metric": "serving tokens/s/chip at a p99 latency bound",
        "workload": dict(workload),
        "trace": dict(trace_meta),
        "chips": int(chips),
        "slo_p99_ms": continuous["slo_p99_ms"],
        "continuous": dict(continuous),
        "environment": environment,
    }
    if static is not None:
        payload["static"] = dict(static)
        c, s = (continuous.get("tokens_per_s_per_chip"),
                static.get("tokens_per_s_per_chip"))
        if c and s:
            payload["speedup_tokens_per_s"] = round(c / s, 3)
    if provenance:
        payload["provenance"] = dict(provenance)
    return payload
