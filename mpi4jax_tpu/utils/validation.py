"""Runtime type validation for public API functions.

Re-design of the reference's ``@enforce_types`` decorator
(ref: mpi4jax/_src/validation.py:8-94): binds call args against the declared
per-argument type specs and raises ``TypeError`` with the argument name; a
special-cased error message tells users to mark communicator/rank arguments
static when they accidentally pass JAX tracers
(ref: mpi4jax/_src/validation.py:77-88).
"""

import functools
import inspect

import numpy as np

import jax.core

# dependency-free by contract (no cycle), and needed on the per-call
# validation path below — module-level so the hot loop pays no repeated
# import-machinery lookups
from ..analysis.schedule import is_rank_concrete


def _type_name(t) -> str:
    if isinstance(t, tuple):
        return " or ".join(_type_name(x) for x in t)
    return getattr(t, "__name__", str(t))


def enforce_types(**type_specs):
    """Decorator: check named arguments against type specs at call time.

    ``type_specs`` maps argument names to a type or tuple of types.  ``None``
    inside a tuple means the argument may be ``None``.
    """
    # normalize: allow None as shorthand for NoneType; int-typed specs also
    # accept numpy integer scalars (np.int64(0) etc.) — the reference checks
    # via np.issubdtype so ported MPI code passing numpy ints must keep
    # working (ref mpi4jax/_src/validation.py:66)
    norm = {}
    for name, spec in type_specs.items():
        if not isinstance(spec, tuple):
            spec = (spec,)
        spec = tuple(type(None) if s is None else s for s in spec)
        if int in spec:
            spec = spec + (np.integer,)
        norm[name] = spec

    def decorator(fn):
        sig = inspect.signature(fn)
        for name in norm:
            if name not in sig.parameters:
                raise ValueError(
                    f"enforce_types: {fn.__name__} has no argument {name!r}"
                )

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            for name, spec in norm.items():
                val = bound.arguments[name]
                if isinstance(val, spec):
                    if is_rank_concrete(val):
                        # the cross-rank verifier's concretized rank: an
                        # int for data, but structure must stay
                        # rank-uniform — a per-rank re-trace must refuse
                        # exactly what the real (traced-rank) trace
                        # refuses (analysis/schedule.RankConcrete)
                        from ..analysis.report import mpx_error

                        raise mpx_error(
                            TypeError, "MPX104",
                            f"{fn.__name__}: argument {name!r} is the "
                            "comm rank (concretized for per-rank "
                            "analysis); structural arguments like "
                            "roots, tags, and routing specs must be "
                            "rank-uniform static Python values — one "
                            "program's structure serves all ranks. Use "
                            "a static value, or derive per-rank DATA "
                            "from the rank instead.",
                        )
                    continue
                if isinstance(val, jax.core.Tracer):
                    # Ref: mpi4jax/_src/validation.py:77-88 — the "abstract
                    # tracer" error. In this framework rank-valued tracers are
                    # fine for data, but structural args (roots, tags) must be
                    # static Python values.
                    from ..analysis.report import mpx_error

                    raise mpx_error(
                        TypeError, "MPX104",
                        f"{fn.__name__}: argument {name!r} was a JAX tracer "
                        f"(expected static {_type_name(spec)}). Structural "
                        "arguments like roots, tags, and routing specs must be "
                        "Python values known at trace time; if you are passing "
                        "them through jit, mark them static "
                        "(e.g. static_argnums).",
                    )
                raise TypeError(
                    f"{fn.__name__}: argument {name!r} has wrong type "
                    f"{type(val).__name__} (expected {_type_name(spec)})"
                )
            return fn(*args, **kwargs)

        return wrapped

    return decorator
