"""JAX version advisory.

Analog of ref mpi4jax/_src/jax_compat.py:11-47: the reference pins a
latest-validated JAX version (shipped as ``_latest_jax_version.txt``) and
warns when the installed JAX is newer (its custom-call lowerings reach into
JAX internals that move between releases).  This framework touches far fewer
internals (public ``jax.lax`` collectives + ``shard_map``), so the advisory
is informational: warn above the validated ceiling, error below the hard
floor (``shard_map``/VMA typing requirements).

``MPI4JAX_TPU_NO_WARN_JAX_VERSION=1`` silences the warning
(ref jax_compat.py:35-36 ``MPI4JAX_NO_WARN_JAX_VERSION``).

The rest of the reference module — ``custom_call`` shims, ``ShapedArray``
import paths, effect allow-list registration (ref jax_compat.py:51-120) —
has no analog here: there are no custom calls and no manually-registered
effects.  ``axis_bound`` is this framework's one internals shim: the
"am I inside a shard_map over this axis?" probe, with a public-behavior
fallback, pinned by tests/test_comm_infra.py.
"""

import warnings

from .config import parse_env_bool


def axis_bound(axis_name: str) -> bool:
    """True iff ``axis_name`` is bound in the current trace's axis
    environment (i.e. we are inside a ``shard_map``/``pmap`` body over it).

    Primary probe: ``jax._src.core.get_axis_env().axis_exists`` — explicit,
    but a private module path.  Fallback if that moves in a future JAX:
    call ``lax.axis_size`` and catch the documented unbound-axis ``NameError``
    ("unbound axis name: ...").  Both behaviors are pinned by
    tests/test_comm_infra.py::test_axis_bound_probe so a JAX upgrade that
    changes either fails loudly instead of silently rerouting every
    in-region op through the eager path.
    """
    try:
        from jax._src.core import get_axis_env

        return bool(get_axis_env().axis_exists(axis_name))
    except (ImportError, AttributeError):
        pass
    from jax import lax

    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


def tracer_is_live(tracer) -> bool:
    """True iff ``tracer`` belongs to a trace that is still active (the
    ambient trace or one of its parents) — i.e. using it now is legal.

    Used by the eager deferred send/recv pairing (ops/recv.py) to convert
    a dead queued payload into a clear staleness error *before* JAX's own
    leak detection produces an opaque UnexpectedTracerError at a much later
    point (outer-jit argument checking).  Probe: walk ``parent_trace`` from
    ``jax._src.core.trace_ctx.trace``; if the internals move in a future
    JAX, fall back to "assume live" — the recv-side UnexpectedTracerError
    backstop still fires, just less prettily.  Pinned by
    tests/test_send_recv.py::test_eager_send_traced_then_recv_outside_raises_clearly.
    """
    try:
        from jax._src.core import trace_ctx

        target = tracer._trace
        cur = trace_ctx.trace
    except (ImportError, AttributeError):
        return True
    seen = set()
    while cur is not None and id(cur) not in seen:
        if cur is target:
            return True
        seen.add(id(cur))
        cur = getattr(cur, "parent_trace", None)
    return False


# oldest JAX with the shard_map/VMA semantics the ops rely on
MIN_JAX_VERSION = "0.6.0"
# newest JAX this package was validated against
LATEST_JAX_VERSION = "0.9.0"


def versiontuple(v: str):
    """'0.9.0' -> (0, 9, 0); tolerates dev/rc suffixes
    (ref jax_compat.py:11-21)."""
    parts = []
    for p in v.split("."):
        digits = ""
        for ch in p:
            if ch.isdigit():
                digits += ch
            else:
                break
        parts.append(int(digits) if digits else 0)
    return tuple(parts[:3])


def check_jax_version(jax_version: str = None) -> None:
    """Warn/raise on unvalidated JAX versions (ref jax_compat.py:24-47)."""
    if jax_version is None:
        import jax

        jax_version = jax.__version__

    if versiontuple(jax_version) < versiontuple(MIN_JAX_VERSION):
        raise RuntimeError(
            f"mpi4jax_tpu requires jax>={MIN_JAX_VERSION} (found "
            f"{jax_version}): the collective ops rely on jax.shard_map and "
            "collective (VMA) typing introduced there."
        )

    if versiontuple(jax_version) > versiontuple(LATEST_JAX_VERSION):
        if parse_env_bool("MPI4JAX_TPU_NO_WARN_JAX_VERSION", False):
            return
        warnings.warn(
            f"The latest supported JAX version with this release of "
            f"mpi4jax_tpu is {LATEST_JAX_VERSION} (found {jax_version}). "
            "If you encounter problems, consider pinning "
            f"jax=={LATEST_JAX_VERSION}. Set "
            "MPI4JAX_TPU_NO_WARN_JAX_VERSION=1 to silence this warning.",
            UserWarning,
            stacklevel=3,
        )
