"""Exit-time flush of pending async dispatch.

Analog of the reference's atexit flush handler
(ref: mpi4jax/_src/flush.py:4-6 and _src/__init__.py:13-17), which runs
``jax.effects_barrier()`` before teardown so in-flight MPI ops complete and
the process does not deadlock at MPI_Finalize.  On TPU there is no MPI
finalizer, but JAX's async dispatch can still hold in-flight collectives at
interpreter exit; blocking on the effects barrier keeps shutdown clean and
keeps the reference's user-visible guarantee.
"""

import jax


def flush() -> None:
    """Wait for all pending XLA operations (incl. collectives) to complete.

    Also raises if a standalone eager ``send`` is still unmatched (deferred
    pairing, ops/send.py): its transfer can never happen after exit, which
    in the reference would be a silent deadlock at MPI_Finalize.
    """
    from ..ops.send import check_eager_drained

    # barrier FIRST: even on the unmatched-send error path the process must
    # quiesce in-flight collectives (the module's clean-shutdown guarantee)
    jax.effects_barrier()
    check_eager_drained()
