"""Exit-time flush of pending async dispatch.

Analog of the reference's atexit flush handler
(ref: mpi4jax/_src/flush.py:4-6 and _src/__init__.py:13-17), which runs
``jax.effects_barrier()`` before teardown so in-flight MPI ops complete and
the process does not deadlock at MPI_Finalize.  On TPU there is no MPI
finalizer, but JAX's async dispatch can still hold in-flight collectives at
interpreter exit; blocking on the effects barrier keeps shutdown clean and
keeps the reference's user-visible guarantee.
"""

import jax


def flush() -> None:
    """Wait for all pending XLA operations (incl. collectives) to complete."""
    jax.effects_barrier()
