"""Supported dtype table.

TPU-native analog of the reference's ``MPI_TYPE_MAP``
(ref: mpi4jax/_src/utils.py:100-115), which maps numpy dtypes to MPI datatype
handles.  Here there is no wire format to pick — XLA collectives are typed by
the HLO — so the table only *gates* which dtypes the public API accepts, and
records TPU-specific notes:

- ``bfloat16`` is first-class (it was not representable in the reference's MPI
  type map at all).
- ``float128``/``complex256`` are dropped (unsupported by XLA on every
  platform this framework targets; ref had them via MPI_LONG_DOUBLE).
- ``float64`` works on the CPU backend and is software-emulated (slow) on TPU.
"""

import jax.numpy as jnp
import numpy as np

# dtype -> short display name used by debug logging
SUPPORTED_DTYPES = {
    np.dtype(jnp.bfloat16): "bf16",
    np.dtype(np.float16): "f16",
    np.dtype(np.float32): "f32",
    np.dtype(np.float64): "f64",
    np.dtype(np.int8): "i8",
    np.dtype(np.int16): "i16",
    np.dtype(np.int32): "i32",
    np.dtype(np.int64): "i64",
    np.dtype(np.uint8): "u8",
    np.dtype(np.uint16): "u16",
    np.dtype(np.uint32): "u32",
    np.dtype(np.uint64): "u64",
    np.dtype(np.bool_): "bool",
    np.dtype(np.complex64): "c64",
    np.dtype(np.complex128): "c128",
}


def check_dtype(arr, opname: str) -> None:
    """Reject dtypes outside the supported table with a clear error.

    Analog of the KeyError raised by the reference's ``to_dtype_handle``
    (ref: mpi4jax/_src/utils.py:118-127).
    """
    dt = np.dtype(arr.dtype)
    if dt not in SUPPORTED_DTYPES:
        supported = ", ".join(sorted(str(k) for k in SUPPORTED_DTYPES))
        raise TypeError(
            f"{opname}: unsupported dtype {dt}. Supported dtypes: {supported}. "
            "Note: float128/complex256 are not available on TPU/XLA "
            "(the reference supported them only via MPI_LONG_DOUBLE on CPU)."
        )


def dtype_shortname(dtype) -> str:
    return SUPPORTED_DTYPES.get(np.dtype(dtype), str(np.dtype(dtype)))
