"""Environment-variable flag system.

TPU-native analog of the reference's env-flag configuration
(ref: mpi4jax/_src/decorators.py:29-34 truthy parser; mpi4jax/_src/utils.py:175-177
``MPI4JAX_PREFER_NOTOKEN``; mpi4jax/_src/xla_bridge/__init__.py:24-28
``MPI4JAX_DEBUG``).

Recognized variables:

- ``MPI4JAX_TPU_DEBUG``     — per-op debug logging (``r{rank} | {id} | …`` format).
- ``MPI4JAX_TPU_TRACE``     — native runtime op tracing: host-side begin/end
  log lines with measured wall-clock latency per collective, via the C++
  host-hooks library (CPU backend; see mpi4jax_tpu/native.py).
- ``MPI4JAX_TPU_PREFER_NOTOKEN`` — make the token API delegate to the notoken
  (implicit-ordering) implementation.
- ``MPI4JAX_TPU_NO_WARN_JAX_VERSION`` — silence the JAX version advisory.
- ``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` — collective watchdog (resilience/watchdog.py):
  seconds a single collective may stay in flight before the process is killed
  with per-rank in-flight-op diagnostics.  Unset/0 disables (default).
- ``MPI4JAX_TPU_FAULT_SPEC`` — deterministic fault injection
  (resilience/faultinject.py): semicolon-separated clauses, e.g.
  ``delay:rank=1:op=allreduce:after=3:secs=2``, ``die:rank=0:op=barrier:after=1``,
  ``corrupt:nan:rank=2:op=allreduce``.  Empty disables (default).
- ``MPI4JAX_TPU_CHECK_NUMERICS`` — abort (via the ``abort_if`` fail-fast path)
  when a collective's inputs or outputs contain NaN/Inf, naming the op.
  Off by default; when off, the lowered HLO is byte-identical to a build
  without the guards (resilience/numerics.py).
- ``MPI4JAX_TPU_COLLECTIVE_ALGO`` — ``auto`` (default) / ``butterfly`` /
  ``ring``: the reduction-family algorithm (ops/_algos.py).  ``auto`` picks
  per call from static payload bytes and group size; the explicit values
  force one lowering (benchmarks, equivalence tests, escape hatch).
- ``MPI4JAX_TPU_RING_CROSSOVER_BYTES`` — payload size (bytes) at which
  ``auto`` switches from the log-depth butterfly to the bandwidth-optimal
  ring lowerings.  Default 1 MiB.
"""

import math
import os
from typing import Optional

TRUTHY = ("true", "1", "on", "yes")
FALSY = ("false", "0", "off", "no", "")


def parse_env_bool(name: str, default: bool = False) -> bool:
    """Parse a truthy/falsy environment variable.

    Raises ``ValueError`` on unrecognized values, like the reference's
    truthy/falsy parser (ref: mpi4jax/_src/decorators.py:29-34).
    """
    raw = os.environ.get(name)
    if raw is None:
        return default
    val = raw.lower().strip()
    if val in TRUTHY:
        return True
    if val in FALSY:
        return False
    raise ValueError(
        f"Environment variable {name}={raw!r} could not be parsed as a boolean "
        f"(truthy values: {TRUTHY}, falsy values: {FALSY})"
    )


def debug_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_DEBUG", False)


def trace_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_TRACE", False)


def parse_env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """Parse a non-negative finite float environment variable (empty/unset ->
    ``default``)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError as e:
        raise ValueError(
            f"Environment variable {name}={raw!r} could not be parsed as a "
            "number of seconds"
        ) from e
    # NaN would pass a plain `val < 0` check and then silently defeat every
    # comparison downstream (a NaN watchdog timeout never expires while
    # still instrumenting each op); Inf is equally meaningless as seconds
    if not math.isfinite(val) or val < 0:
        raise ValueError(
            f"Environment variable {name}={raw!r} must be a finite "
            "number >= 0"
        )
    return val


def watchdog_timeout() -> Optional[float]:
    """Collective watchdog timeout in seconds; ``None`` = disabled.

    ``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` unset, empty, or ``0`` disables the
    watchdog (see mpi4jax_tpu/resilience/watchdog.py).
    """
    val = parse_env_float("MPI4JAX_TPU_WATCHDOG_TIMEOUT", None)
    if val is None or val == 0:
        return None
    return val


def fault_spec() -> str:
    """Raw ``MPI4JAX_TPU_FAULT_SPEC`` string ('' = no injection).

    Parsed by ``mpi4jax_tpu.resilience.parse_fault_spec`` (grammar in
    docs/resilience.md).
    """
    return os.environ.get("MPI4JAX_TPU_FAULT_SPEC", "").strip()


def check_numerics() -> bool:
    """Whether collectives guard their inputs/outputs against NaN/Inf
    (``MPI4JAX_TPU_CHECK_NUMERICS``; see mpi4jax_tpu/resilience/numerics.py)."""
    return parse_env_bool("MPI4JAX_TPU_CHECK_NUMERICS", False)


COLLECTIVE_ALGOS = ("auto", "butterfly", "ring")

# default ring/butterfly crossover: 1 MiB — below it the butterfly's
# ~2·log2(k) rounds beat the ring's ~2·(k-1) per-round latencies; above it
# the ring's O(size) vs O(size·log k) byte volume dominates.  Measured per
# platform by ``benchmarks/micro.py --save`` (docs/microbenchmarks.md).
DEFAULT_RING_CROSSOVER_BYTES = 1 << 20


def collective_algo() -> str:
    """Reduction-family algorithm selection (``MPI4JAX_TPU_COLLECTIVE_ALGO``).

    ``auto`` (default): pick butterfly vs ring per call from static payload
    bytes and group size (ops/_algos.py).  ``butterfly`` / ``ring`` force
    one lowering everywhere it is expressible.
    """
    raw = os.environ.get("MPI4JAX_TPU_COLLECTIVE_ALGO")
    if raw is None or not raw.strip():
        return "auto"
    val = raw.lower().strip()
    if val not in COLLECTIVE_ALGOS:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_COLLECTIVE_ALGO={raw!r} must "
            f"be one of {COLLECTIVE_ALGOS}"
        )
    return val


def ring_crossover_bytes() -> int:
    """Payload bytes at which ``auto`` prefers the ring lowerings
    (``MPI4JAX_TPU_RING_CROSSOVER_BYTES``; default 1 MiB)."""
    raw = os.environ.get("MPI4JAX_TPU_RING_CROSSOVER_BYTES")
    if raw is None or not raw.strip():
        return DEFAULT_RING_CROSSOVER_BYTES
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_RING_CROSSOVER_BYTES={raw!r} "
            "could not be parsed as an integer number of bytes"
        ) from e
    if val < 0:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_RING_CROSSOVER_BYTES={raw!r} "
            "must be >= 0"
        )
    return val


def prefer_notoken() -> bool:
    """Whether the token API should delegate to implicit (notoken) ordering.

    Ref: mpi4jax/_src/utils.py:175-177 (``MPI4JAX_PREFER_NOTOKEN``).  In this
    framework the two paths share one lowering, so this only controls whether
    tokens are threaded through ``optimization_barrier`` chains.
    """
    return parse_env_bool("MPI4JAX_TPU_PREFER_NOTOKEN", False)
