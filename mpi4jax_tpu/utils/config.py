"""Environment-variable flag system: the declared registry.

TPU-native analog of the reference's env-flag configuration
(ref: mpi4jax/_src/decorators.py:29-34 truthy parser; mpi4jax/_src/utils.py:175-177
``MPI4JAX_PREFER_NOTOKEN``; mpi4jax/_src/xla_bridge/__init__.py:24-28
``MPI4JAX_DEBUG``).

Every ``MPI4JAX_TPU_*`` variable the library reads is DECLARED in
``FLAGS`` below (name, type, default, docstring) and read through
``_getenv`` — reading an undeclared flag raises here at runtime, and the
in-repo lint pack (tests/test_lint.py) statically rejects any
``os.environ``/``os.getenv``/``parse_env_*`` read of an undeclared
``MPI4JAX_TPU_*`` name anywhere under ``mpi4jax_tpu/``.  The same lint
asserts every declared flag is documented in the docs flag tables
(docs/usage.md / docs/resilience.md).
"""

import math
import os
from typing import NamedTuple, Optional, Tuple


class Flag(NamedTuple):
    """One declared environment flag."""

    name: str
    type: str            # "bool" | "float" | "int" | "str" | "choice"
    default: object
    doc: str
    choices: Optional[Tuple[str, ...]] = None


ANALYZE_MODES = ("off", "warn", "error")
COLLECTIVE_ALGOS = ("auto", "butterfly", "ring", "hier")
COMPRESS_MODES = ("off", "bf16", "fp8", "auto")
TELEMETRY_MODES = ("off", "counters", "events")
FUSION_MODES = ("off", "auto", "force")
ELASTIC_FAIL_UNITS = ("rank", "row", "col")
ELASTIC_PLACEMENTS = ("stripe", "neighbor")
ELASTIC_AGREEMENTS = ("coordinator", "gossip")

# default fusion bucket: 4 MiB — large enough that a typical optimizer
# step's small gradient leaves coalesce into a handful of collectives,
# small enough that packing latency (concat + slice traffic) stays below
# the per-collective dispatch cost it removes (Horovod ships 64 MiB,
# PyTorch DDP 25 MiB; our collectives are in-graph, so the sweet spot is
# smaller — measured by ``benchmarks/micro.py --fusion-sweep``).
DEFAULT_FUSION_BUCKET_BYTES = 4 << 20

# default overlap chunk count: 2 = classic double buffering (while chunk
# i's allgather phase is on the wire, chunk i+1's reduce-scatter can run,
# and independent compute interleaves with both).
DEFAULT_OVERLAP_CHUNKS = 2

# bootstrap retry policy defaults (resilience/retry.py semantics): the
# same policy serves the first `init_distributed` rendezvous AND every
# elastic re-bootstrap after a shrink (resilience/elastic.py), so both
# are declared flags instead of constants buried in call sites
DEFAULT_BOOTSTRAP_DEADLINE = 300.0
DEFAULT_BOOTSTRAP_MAX_ATTEMPTS = 0  # 0 = bounded by the deadline only

# default shard replication budget for the elastic in-memory checkpoint
# (resilience/elastic.py ShardStore): each shard lives on redundancy+1
# ranks, tolerating that many simultaneous rank losses at a memory cost
# of (redundancy+1)/k of the state per rank
DEFAULT_ELASTIC_REDUNDANCY = 1

# default port window for the per-epoch elastic rendezvous ports: the
# coordinator of epoch e listens on port_base + (e % span), so a job
# that churns through hundreds of epochs stays inside a declared
# span-wide window instead of walking out of the ephemeral port range.
# 64 keeps the wrapped ports identical to the unwrapped pre-span scheme
# for the first 64 epochs while bounding the footprint at 5*span ports
# (coordinator / join / two control banks / agreement listener —
# resilience/elastic.py).
DEFAULT_ELASTIC_PORT_SPAN = 64

# default seconds a draining (preempted) rank waits for its peers to
# acknowledge the drain notice before it proceeds to the leave boundary
# (resilience/elastic.py request_drain): long enough for a localhost or
# DCN round trip under load, far below any eviction deadline
DEFAULT_DRAIN_GRACE_S = 5.0

# default size cap of the persistent compiled-program cache
# (mpi4jax_tpu/aot/diskcache.py): 1 GiB — a few hundred lowered+compiled
# SPMD programs at typical sizes; oldest-used entries are evicted first
# once the cap is crossed (docs/aot.md).
DEFAULT_COMPILE_CACHE_MAX_BYTES = 1 << 30

# serving-runtime defaults (mpi4jax_tpu/serving/, docs/serving.md): the
# continuous-batching scheduler admits/evicts between decode megasteps
# against a bucketed batch-shape table (powers of two up to the max
# batch), a KV slot budget, and a p99 latency objective.  Every knob
# here only parameterizes the serving engine's own programs — none of
# them shapes a non-serving trace, so none folds into the generic
# cache tokens (a serving pin captures them through the world stamp
# like every other flag).
DEFAULT_SERVING_MAX_BATCH = 8
DEFAULT_SERVING_UNROLL = 4
DEFAULT_SERVING_SLO_P99_MS = 1000.0

# default ring/butterfly crossover: 1 MiB — below it the butterfly's
# ~2·log2(k) rounds beat the ring's ~2·(k-1) per-round latencies; above it
# the ring's O(size) vs O(size·log k) byte volume dominates.  Measured per
# platform by ``benchmarks/micro.py --save`` (docs/microbenchmarks.md).
DEFAULT_RING_CROSSOVER_BYTES = 1 << 20

# default DCN ring crossover for the inter-host phase of the hierarchical
# lowerings (ops/_hierarchy.py): 4 MiB — a DCN round-trip costs roughly an
# order of magnitude more latency than an ICI hop, so the inter-host ring's
# 2·(h-1) rounds need a correspondingly larger shard before they beat the
# butterfly's 2·ceil(log2 h).  Measured per pod by
# ``benchmarks/micro.py --hierarchy-sweep`` (docs/topology.md).
DEFAULT_DCN_CROSSOVER_BYTES = 4 << 20

# default crossover for the two-level hierarchical alltoall
# (ops/_hierarchy.apply_hier_alltoall): 1 MiB — below it the single
# monolithic AllToAll HLO's latency wins; above it the hierarchical
# split's intra-host aggregation pays for itself by cutting the DCN
# message count to 1/r of flat (r·h·(h−1) contiguous host-aggregated
# messages instead of r²·h·(h−1) per-rank ones — docs/moe.md).
# Measured per pod by ``benchmarks/micro.py --alltoall-sweep``.
DEFAULT_ALLTOALL_CROSSOVER_BYTES = 1 << 20

# default relative-error budget for the autotune codec sweep
# (autotune/runner.py compression phase): the cheapest codec whose
# measured round-trip relative error stays under this bound is the one
# recorded in the tuning file.  1e-2 admits fp8's per-chunk-scaled
# quantization on typical gradient distributions while rejecting it for
# payloads whose dynamic range blows the 4-bit exponent; bf16 (rel err
# ~2^-8) always clears it.
DEFAULT_COMPRESS_ERROR_BUDGET = 1e-2

# default capacity-chunk count of the expert-parallel MoE helper
# (parallel/moe.py): the per-expert compute and the combine-alltoall
# split into this many capacity chunks so chunk i's combine exchange
# (issued via alltoall_start) overlaps chunk i+1's expert MLP — the
# same double-buffering default as MPI4JAX_TPU_OVERLAP_CHUNKS.
DEFAULT_MOE_CAPACITY_CHUNKS = 2

# pipeline-parallel schedule knobs (parallel/pipeline.py).  0 means
# "unset": split_microbatches falls back to no splitting and
# PipelineProgram derives the interleaved virtual-stage count from the
# stage-function list.  Tuned values (mpx-tuning/1 knob records) are
# always >= 1.
DEFAULT_PIPELINE_MICROBATCHES = 0
DEFAULT_PIPELINE_VIRTUAL_STAGES = 0

FLAGS = {
    f.name: f
    for f in (
        Flag("MPI4JAX_TPU_DEBUG", "bool", False,
             "Per-op debug logging (``r{rank} | {id} | ...`` format)."),
        Flag("MPI4JAX_TPU_TRACE", "bool", False,
             "Native runtime op tracing: host-side begin/end log lines "
             "with measured wall-clock latency per collective, via the "
             "C++ host-hooks library (see mpi4jax_tpu/native.py)."),
        Flag("MPI4JAX_TPU_PREFER_NOTOKEN", "bool", False,
             "Make the token API delegate to the notoken "
             "(implicit-ordering) implementation."),
        Flag("MPI4JAX_TPU_NO_WARN_JAX_VERSION", "bool", False,
             "Silence the JAX version advisory."),
        Flag("MPI4JAX_TPU_WATCHDOG_TIMEOUT", "float", None,
             "Collective watchdog (resilience/watchdog.py): seconds a "
             "single collective may stay in flight before the process is "
             "killed with per-rank in-flight-op diagnostics.  Unset/0 "
             "disables."),
        Flag("MPI4JAX_TPU_FAULT_SPEC", "str", "",
             "Deterministic fault injection (resilience/faultinject.py): "
             "semicolon-separated clauses, e.g. "
             "``delay:rank=1:op=allreduce:after=3:secs=2``.  Empty "
             "disables."),
        Flag("MPI4JAX_TPU_BOOTSTRAP_DEADLINE", "float",
             DEFAULT_BOOTSTRAP_DEADLINE,
             "Total seconds the ``init_distributed`` coordinator "
             "rendezvous (and every elastic re-bootstrap after a "
             "shrink) may spend retrying before failing with a clear "
             "error (resilience/retry.py).  Default 300."),
        Flag("MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS", "int",
             DEFAULT_BOOTSTRAP_MAX_ATTEMPTS,
             "Attempt cap for the bootstrap retry policy; 0 (default) "
             "bounds retries by the deadline only.  Applies to "
             "``init_distributed`` and elastic re-bootstrap alike."),
        Flag("MPI4JAX_TPU_ELASTIC_REDUNDANCY", "int",
             DEFAULT_ELASTIC_REDUNDANCY,
             "Replication budget of the elastic in-memory shard "
             "checkpoint (resilience/elastic.py ShardStore): each state "
             "shard is copied to this many neighbor ranks beyond its "
             "owner, so this many SIMULTANEOUS rank losses are "
             "recoverable.  Memory cost per rank is (redundancy+1)/k of "
             "the registered state.  Default 1."),
        Flag("MPI4JAX_TPU_ELASTIC_GROW", "bool", False,
             "Elastic grow (resilience/elastic.py): accept replacement "
             "ranks back into the world.  The current coordinator "
             "listens for join requests and ``mpx.elastic.run`` admits "
             "joiners at commit boundaries (epoch advance + cold-join "
             "state restore).  Off (default) keeps the run loop free of "
             "the per-boundary join poll and the lowered HLO "
             "byte-identical to a build without the grow path."),
        Flag("MPI4JAX_TPU_DRAIN_GRACE_S", "float", DEFAULT_DRAIN_GRACE_S,
             "Graceful-drain notice window in seconds "
             "(resilience/elastic.py request_drain): how long a leaving "
             "rank waits for every peer to acknowledge its drain notice "
             "before stepping to the leave boundary.  Also the default "
             "grace of the ``preempt`` fault verb.  Default 5."),
        Flag("MPI4JAX_TPU_ELASTIC_FAIL_UNIT", "choice", "rank",
             "Granularity of an elastic shrink "
             "(parallel/mesh.shrink_world_mesh): ``rank`` (default) "
             "removes exactly the failed ranks and requires a 1-D mesh; "
             "``row``/``col`` remove every WHOLE grid row/column that "
             "contains a failed rank, so Cartesian (tensor x data) "
             "meshes shrink structurally instead of erroring "
             "(docs/resilience.md 'Grow and graceful drain').",
             choices=ELASTIC_FAIL_UNITS),
        Flag("MPI4JAX_TPU_ELASTIC_PLACEMENT", "choice", "stripe",
             "Shard-replica placement policy for the elastic ShardStore "
             "(resilience/elastic.py): ``stripe`` (default) consults the "
             "host topology so every replica lands on a different host "
             "than the shard's owner — a whole-host loss leaves >=1 live "
             "copy of every shard whenever redundancy >= 1 and hosts >= "
             "2; ``neighbor`` is the classic ring (shard s on ranks "
             "s..s+redundancy mod k).  Without topology information "
             "stripe degrades to neighbor.  Host-side only (never folded "
             "into compiled-program cache keys) but MUST match across "
             "processes — commits record the table in force, and "
             "restores follow the recorded table "
             "(docs/resilience.md 'Replica placement').",
             choices=ELASTIC_PLACEMENTS),
        Flag("MPI4JAX_TPU_ELASTIC_AGREEMENT", "choice", "coordinator",
             "Failure-agreement transport (resilience/elastic.py): "
             "``coordinator`` (default) routes suspect reports through "
             "the epoch coordinator (rank 0) — O(k) connections, with "
             "automatic degradation to peer gossip when the coordinator "
             "is itself a suspect or unreachable; ``gossip`` forces the "
             "all-pairs O(k^2) peer exchange everywhere.  Both converge "
             "to the same pure gossip_agreement fixpoint.  Host-side "
             "only but MUST match across processes "
             "(docs/resilience.md 'Failure agreement').",
             choices=ELASTIC_AGREEMENTS),
        Flag("MPI4JAX_TPU_ELASTIC_PORT_SPAN", "int",
             DEFAULT_ELASTIC_PORT_SPAN,
             "Width of the per-epoch elastic port window: epoch e's "
             "coordinator (and join/control listeners) derive their "
             "ports from ``port_base + (e % span)`` instead of the "
             "unbounded ``port_base + e``, so long-churning jobs never "
             "walk out of the ephemeral range (bind collisions are "
             "absorbed by the bootstrap retry policy).  Default 64."),
        Flag("MPI4JAX_TPU_CHECK_NUMERICS", "bool", False,
             "Abort (via the ``abort_if`` fail-fast path) when a "
             "collective's inputs or outputs contain NaN/Inf, naming the "
             "op (resilience/numerics.py).  When off, the lowered HLO is "
             "byte-identical to a build without the guards."),
        Flag("MPI4JAX_TPU_COLLECTIVE_ALGO", "choice", "auto",
             "Reduction-family algorithm (ops/_algos.py): ``auto`` picks "
             "per call from static payload bytes, group size, and host "
             "topology; ``butterfly``/``ring``/``hier`` force one "
             "lowering (``hier`` = the two-level ICI/DCN lowering of "
             "ops/_hierarchy.py, falling back to flat where "
             "inexpressible).",
             choices=COLLECTIVE_ALGOS),
        Flag("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "int",
             DEFAULT_RING_CROSSOVER_BYTES,
             "Payload size (bytes) at which ``auto`` switches from the "
             "log-depth butterfly to the bandwidth-optimal ring "
             "lowerings.  Default 1 MiB."),
        Flag("MPI4JAX_TPU_TOPOLOGY", "str", "",
             "Host-topology override for the hierarchical collective "
             "layer (parallel/topology.py): ``<hosts>x<ranks_per_host>`` "
             "(e.g. ``2x4``) for uniform pods, or comma-separated "
             "per-host rank counts (e.g. ``3,5``) for heterogeneous "
             "clusters.  Empty (default) derives the topology from the "
             "JAX process layout of the bound mesh.  A spec whose total "
             "rank count does not match a communicator's world falls "
             "back to the flat (single-level) algorithms for that comm "
             "(docs/topology.md)."),
        Flag("MPI4JAX_TPU_DCN_CROSSOVER_BYTES", "int",
             DEFAULT_DCN_CROSSOVER_BYTES,
             "Shard size (bytes) at which the hierarchical lowerings' "
             "inter-host (DCN) phase switches from the log-depth "
             "butterfly to the bandwidth-optimal ring.  Default 4 MiB "
             "(DCN rounds cost ~10x an ICI hop, so the ring needs a "
             "larger payload to win than on ICI)."),
        Flag("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "int",
             DEFAULT_ALLTOALL_CROSSOVER_BYTES,
             "Payload size (bytes) at which a multi-host alltoall "
             "switches from the flat single-exchange lowering to the "
             "two-level hierarchical one (ops/_hierarchy.py: intra-host "
             "transpose over ICI, inter-host exchange of host-aggregated "
             "contiguous blocks over DCN — 1/r the DCN message count of "
             "flat).  Default 1 MiB; bit-identical results either way "
             "(docs/moe.md)."),
        Flag("MPI4JAX_TPU_COMPRESS", "choice", "off",
             "Wire compression for the inter-host (DCN) leg of the "
             "hierarchical lowerings (ops/_compress.py): ``bf16`` casts "
             "float32 DCN payloads to bfloat16 on the wire (2x fewer "
             "bytes), ``fp8`` quantizes to float8 with a per-chunk "
             "scale (~3.7x fewer), ``auto`` takes the tuning layer's "
             "measured pick (bf16 without one).  ICI stays exact in "
             "every mode; compressed results are NOT bit-identical to "
             "the exact run — pair with the error-feedback API "
             "(mpx.compress.ef_allreduce) for unbiased training "
             "(docs/compression.md).  ``off`` (default) keeps cache "
             "tokens and HLO byte-identical to a build without the "
             "codec layer.",
             choices=COMPRESS_MODES),
        Flag("MPI4JAX_TPU_COMPRESS_ERROR_BUDGET", "float",
             DEFAULT_COMPRESS_ERROR_BUDGET,
             "Relative-error budget of the autotune codec sweep "
             "(``mpx.autotune()`` compression phase): the cheapest codec "
             "whose measured round-trip relative error stays under this "
             "bound becomes the tuned ``compress`` knob.  Default 1e-2 "
             "(docs/compression.md)."),
        Flag("MPI4JAX_TPU_MOE_CAPACITY_CHUNKS", "int",
             DEFAULT_MOE_CAPACITY_CHUNKS,
             "Capacity-chunk count of the expert-parallel MoE helper "
             "(parallel/moe.py): expert compute and the combine-alltoall "
             "split into this many chunks so chunk i's combine exchange "
             "(alltoall_start) overlaps chunk i+1's expert MLP.  1 "
             "disables the overlap pipeline (one synchronous combine).  "
             "Default 2 (docs/moe.md)."),
        Flag("MPI4JAX_TPU_PIPELINE_MICROBATCHES", "int",
             DEFAULT_PIPELINE_MICROBATCHES,
             "Microbatch count of the pipeline schedule compiler "
             "(``mpx.pipeline``, parallel/pipeline.py): "
             "``split_microbatches`` slices the global batch into this "
             "many microbatches when no explicit count is passed.  0 "
             "(default) means unset — the tuned ``pipeline_microbatches`` "
             "knob applies if a tuning file is loaded, else no split "
             "(docs/pipeline.md)."),
        Flag("MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES", "int",
             DEFAULT_PIPELINE_VIRTUAL_STAGES,
             "Virtual stage-chunk count per rank of the interleaved "
             "pipeline schedule (``mpx.pipeline(..., "
             "schedule='interleaved')``).  0 (default) means unset — the "
             "tuned ``pipeline_virtual_stages`` knob applies if a tuning "
             "file is loaded, else the count is derived from the "
             "stage-function list (docs/pipeline.md)."),
        Flag("MPI4JAX_TPU_ANALYZE", "choice", "off",
             "Trace-time collective verifier (analysis/): ``warn`` runs "
             "the MPX checkers over every spmd region / eager op as it "
             "traces and warns on findings; ``error`` raises "
             "``AnalysisError`` instead.  ``off`` (default) records "
             "nothing; the lowered HLO is byte-identical in every mode.",
             choices=ANALYZE_MODES),
        Flag("MPI4JAX_TPU_TUNING", "str", "",
             "Tuning layer (mpi4jax_tpu/autotune/, docs/autotune.md): a "
             "``mpx-tuning/1`` JSON file — what ``mpx.autotune()`` / "
             "``python -m mpi4jax_tpu.autotune`` emits — loaded as a "
             "configuration layer between the static defaults and the "
             "environment: a knob's tuned value applies unless its own "
             "flag is explicitly set (default < tuning < env).  Serves "
             "measured ring/DCN crossovers, fusion bucket bytes, and "
             "overlap chunk counts per (payload, topology) bucket, plus "
             "the cost-model alpha/beta section when "
             "``MPI4JAX_TPU_COST_MODEL`` is unset.  The file's content "
             "stamp folds into every compiled-program cache key, so "
             "loading or changing a file retraces; empty (default) "
             "keeps cache keys and HLO byte-identical to a build "
             "without the tuning layer.  ``mpx.load_tuning(path)`` is "
             "the programmatic form (it wins over this flag)."),
        Flag("MPI4JAX_TPU_COST_MODEL", "str", "",
             "Tuning file for the static communication cost model "
             "(analysis/costmodel.py): a JSON file with measured "
             "alpha/beta parameters per link class (the "
             "``benchmarks/micro.py --cost-calibrate`` output schema, "
             "``mpx-cost-model/1``).  Empty (default) keeps the "
             "documented analytic defaults.  When set, "
             "``mpx.analyze(..., cost=True)`` predicts with measured "
             "numbers and the MPX111/MPX113 advisories cite the "
             "measured crossovers instead of the static env defaults "
             "(docs/analysis.md 'Cost model')."),
        Flag("MPI4JAX_TPU_ANALYZE_COST", "choice", "off",
             "Cost pass of the ambient verifier "
             "(``MPI4JAX_TPU_ANALYZE=warn|error`` + the analysis CLI's "
             "``--cost``): ``on`` extends every cross-rank schedule "
             "pass into the critical-path timing simulation and "
             "attaches ``Report.cost`` (predicted step time, per-op / "
             "per-link-class breakdown, MPX131-MPX135 advisories).  "
             "``off`` (default) keeps reports, cache keys, and HLO "
             "byte-identical to a build without the cost model.",
             choices=("off", "on")),
        Flag("MPI4JAX_TPU_ANALYZE_RANKS", "str", "auto",
             "Cross-rank schedule verification (analysis/crossrank.py) "
             "under ``MPI4JAX_TPU_ANALYZE=warn|error``: each spmd "
             "region is re-traced once per rank at trace time and the "
             "per-rank schedules are matched for deadlock/progress "
             "(MPX120-MPX125).  ``auto`` (default) runs the pass "
             "whenever the comm's size is statically known; ``off`` "
             "disables it; a positive integer N runs it only for comms "
             "of at most N ranks (a cost cap — the pass re-traces once "
             "per rank).  ``python -m mpi4jax_tpu.analysis --ranks N`` "
             "sets this."),
        Flag("MPI4JAX_TPU_TELEMETRY", "choice", "off",
             "Runtime telemetry tier (telemetry/): ``counters`` keeps "
             "host-side per-(op, comm, algo, dtype) call/byte counters "
             "and infrastructure meters (zero device-side ops — the "
             "lowered HLO stays byte-identical to ``off``); ``events`` "
             "additionally journals host-side begin/end brackets around "
             "every collective (per-rank latency + arrival timestamps, "
             "JSONL under ``MPI4JAX_TPU_TELEMETRY_DIR``).  ``off`` "
             "(default) collects nothing.",
             choices=TELEMETRY_MODES),
        Flag("MPI4JAX_TPU_TELEMETRY_DIR", "str", "",
             "Directory for the ``events``-tier per-process JSONL "
             "journals (telemetry/journal.py); merged across ranks by "
             "``python -m mpi4jax_tpu.telemetry merge``.  Empty "
             "(default) keeps the journal in memory only."),
        Flag("MPI4JAX_TPU_FUSION", "choice", "off",
             "Collective fusion (ops/_fusion.py): ``auto`` coalesces "
             "adjacent same-(op, comm, reduction, root) small "
             "collectives inside a managed parallel region into one "
             "flat-buffer collective per dtype bucket (Horovod-style "
             "tensor fusion); ``force`` additionally ignores the bucket "
             "byte cap and packs single-member buckets through the "
             "flat-buffer path.  ``off`` (default) keeps the lowered "
             "HLO byte-identical to a build without the fusion layer.",
             choices=FUSION_MODES),
        Flag("MPI4JAX_TPU_FUSION_BUCKET_BYTES", "int",
             DEFAULT_FUSION_BUCKET_BYTES,
             "Byte cap per fusion bucket (per dtype): a bucket closes "
             "when adding the next member would exceed it.  Default "
             "4 MiB."),
        Flag("MPI4JAX_TPU_COMPILE_CACHE_DIR", "str", "",
             "Persistent compiled-program cache directory "
             "(mpi4jax_tpu/aot/diskcache.py): lowered+compiled SPMD "
             "programs — ``mpx.compile`` pins and ``mpx.spmd`` "
             "program-cache misses — are serialized here keyed by "
             "(jaxpr fingerprint, mesh/topology, dynamic cache token, "
             "jax/jaxlib/libtpu versions), so repeated cold starts and "
             "every rank of a multi-host job deserialize instead of "
             "re-lowering identical programs.  Empty (default) disables "
             "the persistent tier entirely — cache keys and HLO are "
             "byte-identical to a build without the AOT layer "
             "(docs/aot.md)."),
        Flag("MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES", "int",
             DEFAULT_COMPILE_CACHE_MAX_BYTES,
             "Byte cap of the persistent compiled-program cache: after "
             "each write, least-recently-used artifacts are evicted "
             "until the cache fits.  Default 1 GiB; 0 disables "
             "eviction (unbounded)."),
        Flag("MPI4JAX_TPU_OVERLAP_CHUNKS", "int",
             DEFAULT_OVERLAP_CHUNKS,
             "Chunk count for the async start/wait collectives "
             "(ops/_async.py): the payload splits into this many "
             "independent double-buffered ring pipelines so the XLA "
             "scheduler can interleave independent compute between "
             "chunk phases.  Default 2."),
        Flag("MPI4JAX_TPU_UNROLL_DEFAULT", "int", 1,
             "Default megastep unroll factor (parallel/megastep.py): "
             "``mpx.spmd`` / ``mpx.compile`` calls without an explicit "
             "``unroll=`` keep N step iterations device-resident per "
             "host dispatch by rewriting the step body into a "
             "``lax.fori_loop`` carry (docs/aot.md 'Megastep "
             "execution').  1 (default) disables the rewrite — the "
             "traced body and HLO are byte-identical to a build "
             "without the megastep layer."),
        Flag("MPI4JAX_TPU_SERVING_MAX_BATCH", "int",
             DEFAULT_SERVING_MAX_BATCH,
             "Serving runtime (mpi4jax_tpu/serving/, docs/serving.md): "
             "the continuous-batching scheduler's decode batch cap — the "
             "largest bucket in the batch-shape table, and the most "
             "sequences resident in one decode megastep.  Default 8."),
        Flag("MPI4JAX_TPU_SERVING_BUCKETS", "str", "",
             "Explicit serving batch-bucket table: comma-separated "
             "ascending batch sizes (e.g. ``1,2,4,8``); every live batch "
             "is padded UP to the smallest covering bucket so each "
             "(bucket, phase) maps to exactly ONE pinned program.  Empty "
             "(default) uses powers of two up to "
             "MPI4JAX_TPU_SERVING_MAX_BATCH (docs/serving.md)."),
        Flag("MPI4JAX_TPU_SERVING_KV_SLOTS", "int", 0,
             "KV-cache slot budget of the serving runtime: how many "
             "sequences can hold device KV state at once (admission "
             "blocks when no slot is free; eviction frees slots without "
             "reshaping the pinned programs — slots are scatter-updated "
             "rows).  0 (default) sizes the pool at twice the max "
             "batch."),
        Flag("MPI4JAX_TPU_SERVING_UNROLL", "int", DEFAULT_SERVING_UNROLL,
             "Decode megastep trip count of the serving runtime: each "
             "pinned decode call runs this many device-resident token "
             "steps (mpx.compile unroll=N), and the scheduler "
             "admits/evicts only at megastep boundaries — the "
             "granularity/dispatch-amortization trade of docs/serving.md. "
             " Default 4."),
        Flag("MPI4JAX_TPU_SERVING_SLO_P99_MS", "float",
             DEFAULT_SERVING_SLO_P99_MS,
             "The serving latency objective: the p99 request latency "
             "bound (milliseconds) the serving metric is reported "
             "against (tokens/s/chip AT this p99 bound — "
             "BENCH_serving.json), and the bound the CI serving lane "
             "asserts.  Default 1000."),
        Flag("MPI4JAX_TPU_CPP_DISPATCH", "bool", True,
             "Drive pinned executables (``mpx.compile`` -> "
             "``PinnedProgram``) through jax's C++ fast-path dispatch "
             "(``MeshExecutable.create_cpp_call``) where the installed "
             "jaxlib supports it, so a pinned call costs one "
             "world-stamp check plus one C++ call "
             "(mpi4jax_tpu/aot/fastpath.py).  ``false`` forces the "
             "plain Python ``Compiled`` call path (debugging, or a "
             "jaxlib whose fast path misbehaves).  Never shapes a "
             "trace: flipping it does not stale live pins."),
        Flag("MPI4JAX_TPU_HEALTH", "choice", "off",
             "Runtime health plane (mpi4jax_tpu/telemetry/health.py, "
             "docs/observability.md 'Runtime health'): ``on`` arms the "
             "flight-recorder ring, the online degradation detector at "
             "megastep/commit boundaries, and postmortem bundles under "
             "MPI4JAX_TPU_TELEMETRY_DIR.  ``off`` (default) keeps HLO "
             "and both program-cache tokens byte-identical to a build "
             "without the health plane — the layer is host-side only.",
             choices=("off", "on")),
        Flag("MPI4JAX_TPU_HEALTH_INTERVAL", "int", 1,
             "Boundary stride of the health detector's cross-rank digest "
             "exchange: every N-th megastep/commit boundary runs one "
             "tiny allgather of per-(op, comm) latency-digest summaries "
             "and the slowdown/skew checks.  Default 1 (every "
             "boundary)."),
        Flag("MPI4JAX_TPU_FLIGHT_RING", "int", 1024,
             "Capacity (records) of the flight-recorder ring: the most "
             "recent op begin/end/incident records kept in memory for "
             "``mpx.telemetry.flight_snapshot()`` and postmortem "
             "bundles.  Older records are overwritten; the ring's "
             "dropped count says how many.  Default 1024."),
        Flag("MPI4JAX_TPU_HEALTH_SUSPECTS", "bool", False,
             "Opt-in straggler handoff: let the health detector post "
             "persistent stragglers (and stalled in-flight collectives) "
             "as suspects into the elastic agreement machinery "
             "(resilience/elastic.py), so the elastic plane can act on "
             "slow-but-alive ranks.  Default off — detection only "
             "journals incidents and bumps meters."),
        Flag("MPI4JAX_TPU_HEALTH_PROM", "bool", False,
             "Write the Prometheus exposition rendering "
             "(``mpx.telemetry.prometheus_text()``) to "
             "``prom-p<process>.prom`` under MPI4JAX_TPU_TELEMETRY_DIR "
             "at every detector boundary, for file-based fleet "
             "scrapers.  Default off — the text surface is still "
             "available on demand."),
    )
}

# ---------------------------------------------------------------------------
# configuration epoch + environment fingerprint (the dispatch fast path)
# ---------------------------------------------------------------------------
#
# Every compiled-program cache key folds in ~10 dynamically-read flags so
# that toggling one retraces.  Re-parsing them on EVERY dispatch made the
# cache-hit path pay float/choice/fault-spec parsing per call
# (BENCH_r05.json: dispatch_overhead_s ~14% of wall).  Instead, the parsed
# token is memoized against a cheap *stamp*:
#
# - ``env_fingerprint()`` — the raw (unparsed) values of every declared
#   flag, one dict read each: catches environment mutation;
# - ``config_epoch()`` — a counter bumped by every programmatic override
#   (``set_watchdog_timeout``, ``set_analyze_mode``, ``set_logging``, ...):
#   catches non-environment configuration.
#
# The memoized consumer (ops/_base._dynamic_state, resilience plan_for)
# recomputes only when the stamp changes.

FLAG_NAMES = tuple(FLAGS)

_config_epoch = 0


def config_epoch() -> int:
    return _config_epoch


def bump_config_epoch() -> None:
    """Invalidate every stamp-memoized configuration consumer.  Called by
    each programmatic ``set_*`` override; environment mutation needs no
    bump (the fingerprint sees it)."""
    global _config_epoch
    _config_epoch += 1


def env_fingerprint() -> tuple:
    """Raw values of every declared flag — no parsing, one read each."""
    return tuple(map(os.environ.get, FLAG_NAMES))


def config_stamp() -> tuple:
    """Cheap change detector for the whole flag surface: memoize parsed
    configuration against this and the parsing cost leaves the per-call
    dispatch path."""
    return (_config_epoch, env_fingerprint())

# ---------------------------------------------------------------------------
# the tuning layer (feedback-directed configuration — docs/autotune.md)
# ---------------------------------------------------------------------------
#
# ``mpx.autotune()`` measures the perf knobs on the actual mesh and emits
# an ``mpx-tuning/1`` file (autotune/schema.py); this layer serves its
# values BETWEEN the static defaults and the environment:
#
#     default  <  tuning file  <  explicitly-set env flag
#
# so a fleet pre-tuned file never overrides an operator's deliberate
# override.  The active file resolves from ``load_tuning()`` (wins) or
# ``MPI4JAX_TPU_TUNING``; its content stamp folds into
# ``ops/_algos.algo_cache_token()`` — and through it into both
# compiled-program cache keys — so loading or changing a file retraces.
# With no file active every getter below returns exactly its pre-layer
# value and the stamp contributes nothing: cache keys and HLO stay
# byte-identical (pinned by tests/test_autotune.py).

_tuning_override = None  # autotune.schema.TuningFile set by load_tuning()


def load_tuning(spec=None):
    """Install a tuning layer programmatically: ``spec`` is a file path,
    a parsed ``mpx-tuning/1`` payload dict, or a ``TuningFile``.
    ``None`` clears the programmatic layer (an ``MPI4JAX_TPU_TUNING``
    env file, if set, becomes active again).  Returns the installed
    ``TuningFile`` (or ``None``).  Bumps the config epoch so every
    stamp-memoized consumer — and with it both program caches —
    retraces."""
    global _tuning_override
    if spec is None:
        _tuning_override = None
        bump_config_epoch()
        return None
    from ..autotune.schema import as_tuning

    # fresh=True: a path is RE-READ even if the env route memoized it —
    # this call is the documented way to pick up an edited file, and
    # the epoch bump below retraces every consumer consistently
    tf = as_tuning(spec, fresh=True)
    _tuning_override = tf
    bump_config_epoch()
    try:  # meter the load (no-op when telemetry is off)
        from ..telemetry.core import meter

        meter("autotune.loads")
    except ImportError:  # isolated loaders without the telemetry package
        pass
    return tf


def active_tuning():
    """The active ``TuningFile``, or ``None`` when no layer is loaded.
    Raises ``ValueError`` on a malformed ``MPI4JAX_TPU_TUNING`` file —
    a typo'd path must not silently run untuned."""
    if _tuning_override is not None:
        return _tuning_override
    path = (_getenv("MPI4JAX_TPU_TUNING") or "").strip()
    if not path:
        return None
    from ..autotune.schema import load_tuning_file_memo

    return load_tuning_file_memo(path)


def tuning_stamp() -> Optional[str]:
    """Content stamp of the active tuning layer (the ``tuned@<stamp>``
    provenance tag), or ``None`` when inactive — the cache-key
    contribution (ops/_algos.algo_cache_token)."""
    tf = active_tuning()
    return tf.stamp if tf is not None else None


def _tuned_knob(name: str, payload_bytes: Optional[int] = None):
    """The active layer's value for one knob (``None`` = untuned),
    resolved per the current topology override and payload bucket.
    Callers apply the env-wins precedence BEFORE consulting this."""
    tf = active_tuning()
    if tf is None:
        return None
    return tf.knob(name, topology=topology_spec() or None,
                   payload_bytes=payload_bytes)


def tuning_snapshot() -> Optional[dict]:
    """JSON-able view of the active layer for telemetry
    (telemetry/core.snapshot -> report's "tuning" section): stamp,
    source path, and per-knob tuned / default / effective values with
    an ``env_wins`` marker where an explicit flag overrides the file.
    ``None`` when the layer is inactive (the snapshot then carries no
    tuning payload at all)."""
    try:
        tf = active_tuning()
    except ValueError:
        return None
    if tf is None:
        return None
    from ..autotune.schema import KNOB_FLAGS

    defaults = {
        "ring_crossover_bytes": DEFAULT_RING_CROSSOVER_BYTES,
        "dcn_crossover_bytes": DEFAULT_DCN_CROSSOVER_BYTES,
        "alltoall_crossover_bytes": DEFAULT_ALLTOALL_CROSSOVER_BYTES,
        "fusion_bucket_bytes": DEFAULT_FUSION_BUCKET_BYTES,
        "overlap_chunks": DEFAULT_OVERLAP_CHUNKS,
        "compress": "off",
        "pipeline_microbatches": DEFAULT_PIPELINE_MICROBATCHES,
        "pipeline_virtual_stages": DEFAULT_PIPELINE_VIRTUAL_STAGES,
    }
    getters = {
        "ring_crossover_bytes": ring_crossover_bytes,
        "dcn_crossover_bytes": dcn_crossover_bytes,
        "alltoall_crossover_bytes": alltoall_crossover_bytes,
        "fusion_bucket_bytes": fusion_bucket_bytes,
        "overlap_chunks": overlap_chunks,
        "compress": compress_mode,
        "pipeline_microbatches": pipeline_microbatches,
        "pipeline_virtual_stages": pipeline_virtual_stages,
    }
    knobs = {}
    for name, flag in KNOB_FLAGS.items():
        raw = _getenv(flag)
        env_wins = raw is not None and bool(raw.strip())
        tuned = tf.knob(name, topology=topology_spec() or None)
        knobs[name] = {
            "tuned": tuned,
            "default": defaults[name],
            "effective": getters[name](),
            "env_wins": env_wins,
        }
    return {
        "stamp": tf.stamp,
        "path": tf.path,
        "knobs": knobs,
        "commit": dict(tf.payload.get("tuned", {}).get("commit", {})),
    }


TRUTHY = ("true", "1", "on", "yes")
FALSY = ("false", "0", "off", "no", "")


def _getenv(name: str) -> Optional[str]:
    """The single environment read point: the flag must be declared."""
    if name not in FLAGS:
        raise RuntimeError(
            f"environment flag {name} is not declared in "
            "mpi4jax_tpu.utils.config.FLAGS; declare it (name, type, "
            "default, docstring) before reading it"
        )
    return os.environ.get(name)


def parse_env_bool(name: str, default: bool = False) -> bool:
    """Parse a truthy/falsy environment variable.

    Raises ``ValueError`` on unrecognized values, like the reference's
    truthy/falsy parser (ref: mpi4jax/_src/decorators.py:29-34).
    """
    raw = _getenv(name)
    if raw is None:
        return default
    val = raw.lower().strip()
    if val in TRUTHY:
        return True
    if val in FALSY:
        return False
    raise ValueError(
        f"Environment variable {name}={raw!r} could not be parsed as a boolean "
        f"(truthy values: {TRUTHY}, falsy values: {FALSY})"
    )


def debug_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_DEBUG", False)


def trace_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_TRACE", False)


def parse_env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """Parse a non-negative finite float environment variable (empty/unset ->
    ``default``)."""
    raw = _getenv(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError as e:
        raise ValueError(
            f"Environment variable {name}={raw!r} could not be parsed as a "
            "number of seconds"
        ) from e
    # NaN would pass a plain `val < 0` check and then silently defeat every
    # comparison downstream (a NaN watchdog timeout never expires while
    # still instrumenting each op); Inf is equally meaningless as seconds
    if not math.isfinite(val) or val < 0:
        raise ValueError(
            f"Environment variable {name}={raw!r} must be a finite "
            "number >= 0"
        )
    return val


def _parse_env_choice(name: str) -> str:
    """Parse a declared choice-typed flag (empty/unset -> default)."""
    flag = FLAGS[name]
    raw = _getenv(name)
    if raw is None or not raw.strip():
        return flag.default
    val = raw.lower().strip()
    if val not in flag.choices:
        raise ValueError(
            f"Environment variable {name}={raw!r} must be one of "
            f"{flag.choices}"
        )
    return val


def watchdog_timeout() -> Optional[float]:
    """Collective watchdog timeout in seconds; ``None`` = disabled.

    ``MPI4JAX_TPU_WATCHDOG_TIMEOUT`` unset, empty, or ``0`` disables the
    watchdog (see mpi4jax_tpu/resilience/watchdog.py).
    """
    val = parse_env_float("MPI4JAX_TPU_WATCHDOG_TIMEOUT", None)
    if val is None or val == 0:
        return None
    return val


def fault_spec() -> str:
    """Raw ``MPI4JAX_TPU_FAULT_SPEC`` string ('' = no injection).

    Parsed by ``mpi4jax_tpu.resilience.parse_fault_spec`` (grammar in
    docs/resilience.md).
    """
    return (_getenv("MPI4JAX_TPU_FAULT_SPEC") or "").strip()


def bootstrap_deadline() -> float:
    """Total seconds the bootstrap rendezvous may retry
    (``MPI4JAX_TPU_BOOTSTRAP_DEADLINE``; default 300).  Shared by
    ``init_distributed`` and the elastic re-bootstrap."""
    val = parse_env_float("MPI4JAX_TPU_BOOTSTRAP_DEADLINE",
                          DEFAULT_BOOTSTRAP_DEADLINE)
    if val is None or val <= 0:
        raise ValueError(
            "MPI4JAX_TPU_BOOTSTRAP_DEADLINE must be a positive number of "
            f"seconds, got {val!r}"
        )
    return val


def bootstrap_max_attempts() -> int:
    """Attempt cap of the bootstrap retry policy
    (``MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS``; 0 = deadline-bounded
    only)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS", DEFAULT_BOOTSTRAP_MAX_ATTEMPTS
    )


def elastic_redundancy() -> int:
    """Shard replication budget of the elastic in-memory checkpoint
    (``MPI4JAX_TPU_ELASTIC_REDUNDANCY``; default 1 — each shard lives on
    its owner plus one neighbor, tolerating one simultaneous loss)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_ELASTIC_REDUNDANCY", DEFAULT_ELASTIC_REDUNDANCY
    )


def elastic_grow() -> bool:
    """Whether the elastic loop admits replacement ranks
    (``MPI4JAX_TPU_ELASTIC_GROW``; default off — see
    resilience/elastic.py and docs/resilience.md)."""
    return parse_env_bool("MPI4JAX_TPU_ELASTIC_GROW", False)


def drain_grace_s() -> float:
    """Graceful-drain notice window in seconds
    (``MPI4JAX_TPU_DRAIN_GRACE_S``; default 5)."""
    val = parse_env_float("MPI4JAX_TPU_DRAIN_GRACE_S",
                          DEFAULT_DRAIN_GRACE_S)
    if val is None or val <= 0:
        raise ValueError(
            "MPI4JAX_TPU_DRAIN_GRACE_S must be a positive number of "
            f"seconds, got {val!r}"
        )
    return val


def elastic_fail_unit() -> str:
    """Granularity of an elastic shrink
    (``MPI4JAX_TPU_ELASTIC_FAIL_UNIT``): ``rank`` (default) / ``row`` /
    ``col`` — see parallel/mesh.shrink_world_mesh."""
    return _parse_env_choice("MPI4JAX_TPU_ELASTIC_FAIL_UNIT")


def elastic_placement() -> str:
    """Shard-replica placement policy
    (``MPI4JAX_TPU_ELASTIC_PLACEMENT``): ``stripe`` (default) /
    ``neighbor`` — see resilience/elastic.py stripe_placement."""
    return _parse_env_choice("MPI4JAX_TPU_ELASTIC_PLACEMENT")


def elastic_agreement() -> str:
    """Failure-agreement transport
    (``MPI4JAX_TPU_ELASTIC_AGREEMENT``): ``coordinator`` (default) /
    ``gossip`` — see resilience/elastic.py negotiate_failed."""
    return _parse_env_choice("MPI4JAX_TPU_ELASTIC_AGREEMENT")


def elastic_port_span() -> int:
    """Width of the per-epoch elastic port window
    (``MPI4JAX_TPU_ELASTIC_PORT_SPAN``; default 64, minimum 1)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_ELASTIC_PORT_SPAN", DEFAULT_ELASTIC_PORT_SPAN,
        minimum=1,
    )


def check_numerics() -> bool:
    """Whether collectives guard their inputs/outputs against NaN/Inf
    (``MPI4JAX_TPU_CHECK_NUMERICS``; see mpi4jax_tpu/resilience/numerics.py)."""
    return parse_env_bool("MPI4JAX_TPU_CHECK_NUMERICS", False)


def collective_algo() -> str:
    """Reduction-family algorithm selection (``MPI4JAX_TPU_COLLECTIVE_ALGO``).

    ``auto`` (default): pick butterfly vs ring per call from static payload
    bytes and group size (ops/_algos.py).  ``butterfly`` / ``ring`` force
    one lowering everywhere it is expressible.
    """
    return _parse_env_choice("MPI4JAX_TPU_COLLECTIVE_ALGO")


def ring_crossover_bytes() -> int:
    """Payload bytes at which ``auto`` prefers the ring lowerings
    (``MPI4JAX_TPU_RING_CROSSOVER_BYTES``; default 1 MiB; a tuning
    layer's measured value applies when the flag is not explicitly
    set — docs/autotune.md)."""
    raw = _getenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES")
    if raw is None or not raw.strip():
        tuned = _tuned_knob("ring_crossover_bytes")
        if tuned is not None:
            return tuned
        return DEFAULT_RING_CROSSOVER_BYTES
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_RING_CROSSOVER_BYTES={raw!r} "
            "could not be parsed as an integer number of bytes"
        ) from e
    if val < 0:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_RING_CROSSOVER_BYTES={raw!r} "
            "must be >= 0"
        )
    return val


def _env_or_tuned(name: str, knob: str, static_default: int,
                  minimum: int = 0,
                  payload_bytes: Optional[int] = None) -> int:
    """One tuned int knob under the default < tuning < env precedence:
    an explicitly set (non-empty) env flag wins WITHOUT consulting the
    tuning layer at all — so a malformed tuning file can never mask a
    deliberate override, and the env fast path skips the knob lookup —
    else the active layer's value, else the static default."""
    raw = _getenv(name)
    if raw is not None and raw.strip():
        return _parse_env_positive_int(name, static_default, minimum)
    tuned = _tuned_knob(knob, payload_bytes=payload_bytes)
    return tuned if tuned is not None else static_default


def dcn_crossover_bytes() -> int:
    """Shard bytes at which the hierarchical lowerings' inter-host (DCN)
    phase prefers the ring (``MPI4JAX_TPU_DCN_CROSSOVER_BYTES``; default
    4 MiB — see docs/topology.md; a tuning layer's measured value
    applies when the flag is not explicitly set)."""
    return _env_or_tuned(
        "MPI4JAX_TPU_DCN_CROSSOVER_BYTES", "dcn_crossover_bytes",
        DEFAULT_DCN_CROSSOVER_BYTES,
    )


def alltoall_crossover_bytes() -> int:
    """Payload bytes at which a multi-host alltoall prefers the
    two-level hierarchical lowering
    (``MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES``; default 1 MiB — see
    docs/moe.md; a tuning layer's measured value applies when the flag
    is not explicitly set)."""
    return _env_or_tuned(
        "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "alltoall_crossover_bytes",
        DEFAULT_ALLTOALL_CROSSOVER_BYTES,
    )


def compress_mode(payload_bytes: Optional[int] = None) -> str:
    """Effective DCN-leg compression codec (``MPI4JAX_TPU_COMPRESS``):
    ``off`` (default) / ``bf16`` / ``fp8`` — the usual default < tuning
    < env precedence, payload-bucketed like :func:`overlap_chunks`.
    ``auto`` (env or tuned) resolves to the tuning layer's measured
    codec for this payload bucket, or ``bf16`` without one — callers
    always see a concrete codec, never ``auto``."""
    mode = _parse_env_choice("MPI4JAX_TPU_COMPRESS")
    raw = _getenv("MPI4JAX_TPU_COMPRESS")
    explicit = raw is not None and bool(raw.strip())
    if not explicit or mode == "auto":
        tuned = _tuned_knob("compress", payload_bytes=payload_bytes)
        if tuned is not None:
            tuned = str(tuned).lower()
            if tuned != "auto":
                return tuned
        if mode == "auto":
            return "bf16"
    return mode


def compress_error_budget() -> float:
    """Relative-error budget of the autotune codec sweep
    (``MPI4JAX_TPU_COMPRESS_ERROR_BUDGET``; default 1e-2)."""
    val = parse_env_float("MPI4JAX_TPU_COMPRESS_ERROR_BUDGET",
                          DEFAULT_COMPRESS_ERROR_BUDGET)
    if val is None or val <= 0:
        raise ValueError(
            "MPI4JAX_TPU_COMPRESS_ERROR_BUDGET must be a positive "
            f"relative error bound, got {val!r}"
        )
    return val


def moe_capacity_chunks() -> int:
    """Capacity-chunk count of the MoE combine/compute pipeline
    (``MPI4JAX_TPU_MOE_CAPACITY_CHUNKS``; default 2, minimum 1 — see
    parallel/moe.py and docs/moe.md)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_MOE_CAPACITY_CHUNKS", DEFAULT_MOE_CAPACITY_CHUNKS,
        minimum=1,
    )


def pipeline_microbatches(payload_bytes: Optional[int] = None) -> int:
    """Microbatch count of the pipeline schedule compiler
    (``MPI4JAX_TPU_PIPELINE_MICROBATCHES``; default 0 = unset — see
    parallel/pipeline.py and docs/pipeline.md; a tuning layer's measured
    value applies when the flag is not explicitly set)."""
    return _env_or_tuned(
        "MPI4JAX_TPU_PIPELINE_MICROBATCHES", "pipeline_microbatches",
        DEFAULT_PIPELINE_MICROBATCHES, payload_bytes=payload_bytes,
    )


def pipeline_virtual_stages(payload_bytes: Optional[int] = None) -> int:
    """Virtual stage-chunk count per rank of the interleaved pipeline
    schedule (``MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES``; default 0 = unset
    — see parallel/pipeline.py and docs/pipeline.md; a tuning layer's
    measured value applies when the flag is not explicitly set)."""
    return _env_or_tuned(
        "MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES", "pipeline_virtual_stages",
        DEFAULT_PIPELINE_VIRTUAL_STAGES, payload_bytes=payload_bytes,
    )


def topology_spec() -> str:
    """Raw ``MPI4JAX_TPU_TOPOLOGY`` string ('' = derive from the mesh's
    JAX process layout).  Parsed by :func:`parse_topology_spec`."""
    return (_getenv("MPI4JAX_TPU_TOPOLOGY") or "").strip()


def parse_topology_spec(raw: str) -> Optional[Tuple[int, ...]]:
    """Parse a topology spec into per-host rank counts.

    Grammar (docs/topology.md): ``<hosts>x<ranks_per_host>`` for uniform
    pods (``2x4`` -> ``(4, 4)``), or comma-separated per-host counts for
    heterogeneous clusters (``3,5`` -> ``(3, 5)``).  Empty/None ->
    ``None`` (no override).  Raises ``ValueError`` on malformed specs —
    a typo'd override must not silently disable the hierarchical layer.
    """
    if raw is None:
        return None
    raw = raw.strip().lower()
    if not raw:
        return None
    try:
        if "x" in raw:
            hosts_s, _, per_s = raw.partition("x")
            hosts, per = int(hosts_s), int(per_s)
            if hosts < 1 or per < 1:
                raise ValueError
            return (per,) * hosts
        counts = tuple(int(c) for c in raw.split(","))
        if not counts or any(c < 1 for c in counts):
            raise ValueError
        return counts
    except ValueError:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_TOPOLOGY={raw!r} could not "
            "be parsed: expected '<hosts>x<ranks_per_host>' (e.g. '2x4') "
            "or comma-separated per-host rank counts (e.g. '3,5'), all "
            "positive integers"
        ) from None


def analyze_mode() -> str:
    """Trace-time collective verifier mode (``MPI4JAX_TPU_ANALYZE``):
    ``off`` (default) / ``warn`` / ``error`` — see mpi4jax_tpu/analysis/."""
    return _parse_env_choice("MPI4JAX_TPU_ANALYZE")


def analyze_ranks():
    """Cross-rank pass setting (``MPI4JAX_TPU_ANALYZE_RANKS``):
    ``"auto"`` (default), ``"off"``, or a positive int cap on the comm
    sizes the ambient per-rank re-trace covers."""
    raw = (_getenv("MPI4JAX_TPU_ANALYZE_RANKS") or "").strip().lower()
    if not raw or raw == "auto":
        return "auto"
    if raw == "off":
        return "off"
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_ANALYZE_RANKS={raw!r} must "
            "be 'auto', 'off', or a positive integer rank cap"
        ) from None
    if val < 1:
        raise ValueError(
            f"Environment variable MPI4JAX_TPU_ANALYZE_RANKS={raw!r} must "
            "be 'auto', 'off', or a positive integer rank cap"
        )
    return val


def cost_model_path() -> str:
    """Path of the cost-model tuning file (``MPI4JAX_TPU_COST_MODEL``;
    '' = the documented analytic defaults — see analysis/costmodel.py
    and docs/analysis.md 'Cost model')."""
    return (_getenv("MPI4JAX_TPU_COST_MODEL") or "").strip()


def analyze_cost_enabled() -> bool:
    """Whether the ambient verifier's cross-rank pass also runs the
    critical-path cost simulation (``MPI4JAX_TPU_ANALYZE_COST``; default
    off — see analysis/cost.py)."""
    return _parse_env_choice("MPI4JAX_TPU_ANALYZE_COST") == "on"


def telemetry_mode() -> str:
    """Runtime telemetry tier (``MPI4JAX_TPU_TELEMETRY``): ``off``
    (default) / ``counters`` / ``events`` — see mpi4jax_tpu/telemetry/."""
    return _parse_env_choice("MPI4JAX_TPU_TELEMETRY")


def telemetry_dir() -> str:
    """Directory for the events-tier JSONL journals
    (``MPI4JAX_TPU_TELEMETRY_DIR``; '' = in-memory journal only)."""
    return (_getenv("MPI4JAX_TPU_TELEMETRY_DIR") or "").strip()


def health_mode() -> str:
    """Runtime health plane tier (``MPI4JAX_TPU_HEALTH``): ``off``
    (default) / ``on`` — see mpi4jax_tpu/telemetry/health.py and
    docs/observability.md 'Runtime health'."""
    return _parse_env_choice("MPI4JAX_TPU_HEALTH")


def health_interval() -> int:
    """Boundary stride of the health detector's digest exchange
    (``MPI4JAX_TPU_HEALTH_INTERVAL``; default 1 = every boundary)."""
    return _parse_env_positive_int("MPI4JAX_TPU_HEALTH_INTERVAL", 1,
                                   minimum=1)


def flight_ring_capacity() -> int:
    """Flight-recorder ring capacity in records
    (``MPI4JAX_TPU_FLIGHT_RING``; default 1024, minimum 1)."""
    return _parse_env_positive_int("MPI4JAX_TPU_FLIGHT_RING", 1024,
                                   minimum=1)


def health_suspects_enabled() -> bool:
    """Whether the health detector may post persistent stragglers as
    suspects into the elastic agreement machinery
    (``MPI4JAX_TPU_HEALTH_SUSPECTS``; default off)."""
    return parse_env_bool("MPI4JAX_TPU_HEALTH_SUSPECTS", False)


def health_prom_enabled() -> bool:
    """Whether detector boundaries also write the Prometheus exposition
    file under the telemetry dir (``MPI4JAX_TPU_HEALTH_PROM``; default
    off)."""
    return parse_env_bool("MPI4JAX_TPU_HEALTH_PROM", False)


def _parse_env_positive_int(name: str, default: int, minimum: int = 0) -> int:
    """Parse an integer flag with a lower bound (empty/unset -> default)."""
    raw = _getenv(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError as e:
        raise ValueError(
            f"Environment variable {name}={raw!r} could not be parsed as "
            "an integer"
        ) from e
    if val < minimum:
        raise ValueError(
            f"Environment variable {name}={raw!r} must be >= {minimum}"
        )
    return val


def fusion_mode() -> str:
    """Collective-fusion mode (``MPI4JAX_TPU_FUSION``): ``off`` (default)
    / ``auto`` / ``force`` — see mpi4jax_tpu/ops/_fusion.py and
    docs/overlap.md."""
    return _parse_env_choice("MPI4JAX_TPU_FUSION")


def fusion_bucket_bytes() -> int:
    """Byte cap per (dtype-segregated) fusion bucket
    (``MPI4JAX_TPU_FUSION_BUCKET_BYTES``; default 4 MiB; a tuning
    layer's measured value applies when the flag is not explicitly
    set)."""
    return _env_or_tuned(
        "MPI4JAX_TPU_FUSION_BUCKET_BYTES", "fusion_bucket_bytes",
        DEFAULT_FUSION_BUCKET_BYTES,
    )


def overlap_chunks(payload_bytes: Optional[int] = None) -> int:
    """Chunk count for the async start/wait collectives
    (``MPI4JAX_TPU_OVERLAP_CHUNKS``; default 2, minimum 1).  A tuning
    layer may bucket the value by payload: callers that know their
    payload pass it (ops/_async.py) and get the bucket's chunk count;
    the flag, when explicitly set, still wins everywhere."""
    return _env_or_tuned(
        "MPI4JAX_TPU_OVERLAP_CHUNKS", "overlap_chunks",
        DEFAULT_OVERLAP_CHUNKS, minimum=1, payload_bytes=payload_bytes,
    )


def compile_cache_dir() -> str:
    """Persistent compiled-program cache directory
    (``MPI4JAX_TPU_COMPILE_CACHE_DIR``; '' = the persistent tier is
    disabled — see mpi4jax_tpu/aot/diskcache.py and docs/aot.md)."""
    return (_getenv("MPI4JAX_TPU_COMPILE_CACHE_DIR") or "").strip()


def compile_cache_max_bytes() -> int:
    """Byte cap of the persistent compiled-program cache
    (``MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES``; default 1 GiB, 0 =
    unbounded)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_COMPILE_CACHE_MAX_BYTES",
        DEFAULT_COMPILE_CACHE_MAX_BYTES,
    )


def unroll_default() -> int:
    """Default megastep unroll factor (``MPI4JAX_TPU_UNROLL_DEFAULT``;
    default 1 = no device-resident loop — see parallel/megastep.py and
    docs/aot.md 'Megastep execution')."""
    return _parse_env_positive_int("MPI4JAX_TPU_UNROLL_DEFAULT", 1,
                                   minimum=1)


def cpp_dispatch() -> bool:
    """Whether pinned executables use jax's C++ fast-path dispatch where
    available (``MPI4JAX_TPU_CPP_DISPATCH``; default on — see
    mpi4jax_tpu/aot/fastpath.py)."""
    return parse_env_bool("MPI4JAX_TPU_CPP_DISPATCH", True)


def serving_max_batch() -> int:
    """Decode batch cap of the serving runtime
    (``MPI4JAX_TPU_SERVING_MAX_BATCH``; default 8, minimum 1 — see
    mpi4jax_tpu/serving/ and docs/serving.md)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_SERVING_MAX_BATCH", DEFAULT_SERVING_MAX_BATCH,
        minimum=1,
    )


def serving_buckets() -> str:
    """Raw ``MPI4JAX_TPU_SERVING_BUCKETS`` spec ('' = powers of two up
    to :func:`serving_max_batch`).  Parsed by
    ``mpi4jax_tpu.serving.buckets.BucketTable.from_spec``."""
    return (_getenv("MPI4JAX_TPU_SERVING_BUCKETS") or "").strip()


def serving_kv_slots() -> int:
    """KV slot budget of the serving runtime
    (``MPI4JAX_TPU_SERVING_KV_SLOTS``; 0 = twice the max batch)."""
    return _parse_env_positive_int("MPI4JAX_TPU_SERVING_KV_SLOTS", 0)


def serving_unroll() -> int:
    """Decode megastep trip count of the serving runtime
    (``MPI4JAX_TPU_SERVING_UNROLL``; default 4, minimum 1)."""
    return _parse_env_positive_int(
        "MPI4JAX_TPU_SERVING_UNROLL", DEFAULT_SERVING_UNROLL, minimum=1,
    )


def serving_slo_p99_ms() -> float:
    """The serving p99 latency objective in milliseconds
    (``MPI4JAX_TPU_SERVING_SLO_P99_MS``; default 1000)."""
    val = parse_env_float("MPI4JAX_TPU_SERVING_SLO_P99_MS",
                          DEFAULT_SERVING_SLO_P99_MS)
    if val is None or val <= 0:
        raise ValueError(
            "MPI4JAX_TPU_SERVING_SLO_P99_MS must be a positive number of "
            f"milliseconds, got {val!r}"
        )
    return val


def prefer_notoken() -> bool:
    """Whether the token API should delegate to implicit (notoken) ordering.

    Ref: mpi4jax/_src/utils.py:175-177 (``MPI4JAX_PREFER_NOTOKEN``).  In this
    framework the two paths share one lowering, so this only controls whether
    tokens are threaded through ``optimization_barrier`` chains.
    """
    return parse_env_bool("MPI4JAX_TPU_PREFER_NOTOKEN", False)
