"""Environment-variable flag system.

TPU-native analog of the reference's env-flag configuration
(ref: mpi4jax/_src/decorators.py:29-34 truthy parser; mpi4jax/_src/utils.py:175-177
``MPI4JAX_PREFER_NOTOKEN``; mpi4jax/_src/xla_bridge/__init__.py:24-28
``MPI4JAX_DEBUG``).

Recognized variables:

- ``MPI4JAX_TPU_DEBUG``     — per-op debug logging (``r{rank} | {id} | …`` format).
- ``MPI4JAX_TPU_TRACE``     — native runtime op tracing: host-side begin/end
  log lines with measured wall-clock latency per collective, via the C++
  host-hooks library (CPU backend; see mpi4jax_tpu/native.py).
- ``MPI4JAX_TPU_PREFER_NOTOKEN`` — make the token API delegate to the notoken
  (implicit-ordering) implementation.
- ``MPI4JAX_TPU_NO_WARN_JAX_VERSION`` — silence the JAX version advisory.
"""

import os

TRUTHY = ("true", "1", "on", "yes")
FALSY = ("false", "0", "off", "no", "")


def parse_env_bool(name: str, default: bool = False) -> bool:
    """Parse a truthy/falsy environment variable.

    Raises ``ValueError`` on unrecognized values, like the reference's
    truthy/falsy parser (ref: mpi4jax/_src/decorators.py:29-34).
    """
    raw = os.environ.get(name)
    if raw is None:
        return default
    val = raw.lower().strip()
    if val in TRUTHY:
        return True
    if val in FALSY:
        return False
    raise ValueError(
        f"Environment variable {name}={raw!r} could not be parsed as a boolean "
        f"(truthy values: {TRUTHY}, falsy values: {FALSY})"
    )


def debug_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_DEBUG", False)


def trace_enabled() -> bool:
    return parse_env_bool("MPI4JAX_TPU_TRACE", False)


def prefer_notoken() -> bool:
    """Whether the token API should delegate to implicit (notoken) ordering.

    Ref: mpi4jax/_src/utils.py:175-177 (``MPI4JAX_PREFER_NOTOKEN``).  In this
    framework the two paths share one lowering, so this only controls whether
    tokens are threaded through ``optimization_barrier`` chains.
    """
    return parse_env_bool("MPI4JAX_TPU_PREFER_NOTOKEN", False)
