"""Profiler integration: per-op device-time attribution on any backend.

SURVEY.md §5 "Tracing / profiling": the reference's measured per-op latency
lives in host-side ``perf_counter`` brackets inside libmpi calls (ref
mpi_xla_bridge.pyx:47-60, 100-112) — a structure TPU collectives don't
have (no host call per collective; XLA schedules them asynchronously on
the device stream).  The native host-hooks path (``MPI4JAX_TPU_TRACE``,
mpi4jax_tpu/native.py) reproduces the reference's measured brackets on the
CPU backend; on TPU the honest measured source is the device profiler,
and every op is already wrapped in ``jax.named_scope("mpi4jax_tpu.<op>")``
(utils/debug.py) so collectives are attributable there.

``profile_ops`` packages the correct capture protocol: the one pitfall is
async dispatch — a jitted call returns before the device work runs, so a
naive ``with jax.profiler.trace(...)`` can close the trace with nothing in
it.  The context manager blocks on every live array before closing, which
fences all outstanding device work into the captured window.
"""

import contextlib
import os

import jax

__all__ = ["profile_ops", "ProfileSummary"]


class ProfileSummary:
    """What a ``profile_ops`` capture did: the trace directory and how
    many live arrays the exit fence blocked on (``None`` until the
    context exits).  A zero ``fenced_arrays`` is the tell that the
    profiled block dropped its outputs on the floor — the work may have
    landed outside the capture window (see ``profile_ops``)."""

    __slots__ = ("trace_dir", "backend", "fenced_arrays")

    def __init__(self, trace_dir: str, backend: str):
        self.trace_dir = trace_dir
        self.backend = backend
        self.fenced_arrays = None

    def __repr__(self):
        return (
            f"ProfileSummary(trace_dir={self.trace_dir!r}, "
            f"backend={self.backend!r}, "
            f"fenced_arrays={self.fenced_arrays})"
        )


@contextlib.contextmanager
def profile_ops(logdir: str, *, create_perfetto_link: bool = False):
    """Capture a profiler trace of the enclosed ops, async-dispatch-safe.

    Usage::

        with mpx.profile_ops("/tmp/jax-trace") as prof:
            out = step(state)          # any program using mpi4jax_tpu ops
        # prof.fenced_arrays: how many live arrays the exit fence covered

    On exit, outstanding device work is fenced into the trace
    (``jax.block_until_ready`` over every live array on the DEFAULT
    backend — not every backend: a CPU-backed sidecar array, e.g. a
    host-staged checkpoint shard, must not stall the close of a TPU
    capture), then the trace is closed.  Yields a :class:`ProfileSummary`
    whose ``fenced_arrays`` count is filled in by the fence, so callers
    (and tests) can assert the fence actually ran.  The fence covers
    everything whose output is still referenced — BIND the results you
    are profiling (``out = step(state)``, as above); a call whose outputs
    you drop on the floor has nothing live to fence and may land outside
    the window (``jax.block_until_ready(step(state))`` inside the block
    is the explicit form).  Open the directory in TensorBoard/xprof and
    filter for ``mpi4jax_tpu.<op>`` to read each collective's device
    time, queue time, and overlap with compute — measured on the real
    stream, including any fusion/reordering XLA applied (docs/usage.md
    "Observability", docs/observability.md).
    """
    os.makedirs(logdir, exist_ok=True)
    backend = jax.default_backend()
    summary = ProfileSummary(logdir, backend)
    with jax.profiler.trace(logdir, create_perfetto_link=create_perfetto_link):
        try:
            yield summary
        finally:
            # fence: async dispatch means enclosed calls may not have
            # executed yet; blocking on live arrays lands their device work
            # inside the trace window.  In a finally so the fence also runs
            # when the profiled block raises — work dispatched before the
            # exception would otherwise land outside the window and the
            # partial trace would silently under-report.
            fenced = jax.live_arrays(backend)
            summary.fenced_arrays = len(fenced)
            jax.block_until_ready(fenced)
