"""Support utilities: dtype table, env flags, validation, debug logging.

TPU-native re-design of the reference's L3 support layer
(ref: mpi4jax/_src/{utils,decorators,validation,flush}.py).  What is *not*
here, and why:

- MPI handle marshalling (ref utils.py:80-96) — no MPI objects exist.
- ``HashableMPIType`` wrappers (ref utils.py:133-152) — comms/ops here are
  plain hashable Python objects already.
- platform-gated lowering decorators (ref decorators.py:94-149) — collectives
  lower through ``jax.lax`` on every platform; there are no per-platform
  custom-call bridges to gate.
"""

import jax

from .config import parse_env_bool, prefer_notoken  # noqa: F401
from .debug import (  # noqa: F401
    get_logging,
    get_runtime_tracing,
    set_logging,
    set_runtime_tracing,
)
from .dtypes import SUPPORTED_DTYPES, check_dtype  # noqa: F401
from .flush import flush  # noqa: F401
from .jax_compat import check_jax_version  # noqa: F401
from .validation import enforce_types  # noqa: F401


def has_tpu_support() -> bool:
    """True if a TPU backend is available.

    Capability probe in the spirit of ref ``has_cuda_support``
    (mpi4jax/_src/utils.py:158-165).
    """
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def has_cuda_support() -> bool:
    """True if a CUDA backend is available (ref: _src/utils.py:158-165).

    Collectives here lower to XLA HLO, so GPU works without any extension —
    this probe reports backend availability only.
    """
    try:
        return any(d.platform == "gpu" for d in jax.devices())
    except RuntimeError:
        return False


def has_sycl_support() -> bool:
    """Ref parity probe (mpi4jax/_src/utils.py:168-173). Always False: XLA has
    no SYCL plugin in this environment; the XPU platform was the reference
    fork's custom-call backend, which this framework replaces entirely."""
    return False
