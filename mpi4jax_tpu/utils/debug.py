"""Env-gated per-op debug logging + profiler annotations.

TPU-native analog of the reference's bridge logging
(ref: mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx:38-60): when enabled, every
collective emits a device-side print in the same format the reference used::

    r{rank} | {8-char id} | {OpName}: {details}

The id is random per *call site* (generated at trace time), matching the
reference's per-invocation 8-char hex id (ref: mpi_xla_bridge.pyx:47-52).
Logging is toggled by ``MPI4JAX_TPU_DEBUG`` (env, read at import like
ref xla_bridge/__init__.py:24-28) or programmatically via ``set_logging``.

Every collective is additionally wrapped in ``jax.named_scope`` so ops show up
named in XLA HLO and in ``jax.profiler`` traces (capability the reference
lacked).
"""

import secrets
from contextlib import contextmanager

import jax

from .config import bump_config_epoch, debug_enabled, trace_enabled

_logging_enabled = debug_enabled()
_tracing_enabled = trace_enabled()


def set_logging(enabled: bool) -> None:
    """Analog of ref mpi_xla_bridge.pyx:38-40 ``set_logging``."""
    global _logging_enabled
    _logging_enabled = bool(enabled)
    bump_config_epoch()


def get_logging() -> bool:
    """Analog of ref mpi_xla_bridge.pyx:43-44 ``get_logging``."""
    return _logging_enabled


def set_runtime_tracing(enabled: bool) -> None:
    """Toggle native runtime op tracing (host-side begin/end + latency via
    the C++ hooks library; see mpi4jax_tpu/native.py)."""
    global _tracing_enabled
    _tracing_enabled = bool(enabled)
    bump_config_epoch()


def get_runtime_tracing() -> bool:
    return _tracing_enabled


def log_op(opname: str, rank, detail: str = "") -> None:
    """Emit the per-op debug line (device-side, ordered with the computation).

    ``rank`` may be a traced value (``lax.axis_index``); formatting happens on
    the host via ``jax.debug.print`` when the op actually executes.
    """
    if not _logging_enabled:
        return
    call_id = secrets.token_hex(4)  # 8 hex chars, per trace site
    if detail:
        jax.debug.print(
            "r{rank} | " + call_id + " | " + opname + ": " + detail, rank=rank
        )
    else:
        jax.debug.print("r{rank} | " + call_id + " | " + opname, rank=rank)


@contextmanager
def op_scope(opname: str):
    """Named scope so collectives are attributable in profiles and HLO."""
    with jax.named_scope(f"mpi4jax_tpu.{opname}"):
        yield
