"""Native host-hooks: loading, registration, and jit-visible wrappers.

The reference's native layer (Cython XLA custom-call bridge,
ref mpi4jax/_src/xla_bridge/*.pyx) *is* the transport; here the transport is
XLA collective HLO, and the native library (csrc/host_hooks.cc) instead
provides the host-side runtime services around it:

- ``op_begin``/``op_end`` — per-op runtime logging and wall-clock latency in
  the reference's debug format (ref mpi_xla_bridge.pyx:47-60, 100-112),
  threaded into the program with data dependencies so the host timestamps
  bracket the collective's execution;
- ``abort_if`` — data-dependent fail-fast (MPI_Abort-on-error semantics,
  ref mpi_xla_bridge.pyx:67-91): if the predicate is true at run time the
  whole process dies, not just the computation;
- ``wallclock`` — host timestamp as an in-graph value;
- ``watchdog_arm``/``watchdog_disarm`` — the collective watchdog's in-graph
  bracket (resilience/watchdog.py): registry and monitor thread live in C++
  so the timeout fires even when every Python thread is wedged.

All hooks are CPU-backend custom calls (the test/dev backend).  On TPU the
compute path has no host hooks by design — ``runtime_tracing_supported()``
reports availability, and the pure-Python fallbacks (``jax.debug.callback``)
cover platforms without the native library.

Build the library with ``python -m mpi4jax_tpu.native build``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libmpx_hooks.so")

_lib: Optional[ctypes.CDLL] = None
_registered = False

_HANDLERS = ("MpxOpBegin", "MpxOpEnd", "MpxAbortIf", "MpxWallclock",
             "MpxWatchdogArm", "MpxWatchdogDisarm")
_TARGETS = ("mpx_op_begin", "mpx_op_end", "mpx_abort_if", "mpx_wallclock",
            "mpx_watchdog_arm", "mpx_watchdog_disarm")

# handlers actually present in the loaded .so (an older build may predate
# the watchdog hooks; feature probes below consult this set)
_loaded_handlers: set = set()


def build(verbose: bool = True) -> str:
    """Compile csrc/host_hooks.cc → mpi4jax_tpu/_lib/libmpx_hooks.so.

    Direct g++ invocation (no build system needed); csrc/CMakeLists.txt
    offers the same build for CMake users.
    """
    src = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "csrc", "host_hooks.cc"
    )
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
        f"-I{jax.ffi.include_dir()}",
        os.path.abspath(src), "-o", _LIB_PATH,
    ]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return _LIB_PATH


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _registered
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    _lib = ctypes.CDLL(_LIB_PATH)
    if not _registered:
        for handler, target in zip(_HANDLERS, _TARGETS):
            try:
                sym = getattr(_lib, handler)
            except AttributeError:
                continue  # stale .so from before this hook existed
            jax.ffi.register_ffi_target(
                target, jax.ffi.pycapsule(sym), platform="cpu",
            )
            _loaded_handlers.add(handler)
        _registered = True
    return _lib


def available() -> bool:
    """True if the native hooks library is built and loadable."""
    return _load() is not None


def runtime_tracing_supported() -> bool:
    """Native runtime op tracing runs on the CPU backend only (on TPU the
    compute path is pure HLO with no host hooks, by design)."""
    return available() and jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# jit-visible wrappers
# ---------------------------------------------------------------------------


def _tie(x, dep):
    """Make ``x`` depend on ``dep`` (ordering via OptimizationBarrier)."""
    x, _ = lax.optimization_barrier((x, dep))
    return x


def op_begin(opname: str, call_id: str, rank, detail: str = ""):
    """Log op entry on the host; returns a u32 the collective's inputs
    should be tied to (so the timestamp precedes the collective)."""
    call = jax.ffi.ffi_call(
        "mpx_op_begin",
        jax.ShapeDtypeStruct((), jnp.uint32),
        has_side_effect=True,
    )
    return call(
        jnp.asarray(rank, jnp.uint32), opname=opname, call_id=call_id, detail=detail
    )


def op_end(opname: str, call_id: str, rank, dep):
    """Log op completion + elapsed; ``dep`` ties the call after the
    collective's outputs."""
    call = jax.ffi.ffi_call(
        "mpx_op_end",
        jax.ShapeDtypeStruct((), jnp.uint32),
        has_side_effect=True,
    )
    return call(_tie(jnp.asarray(rank, jnp.uint32), dep),
                opname=opname, call_id=call_id)


def abort_if(pred, rank, message: str):
    """Kill the process if ``pred`` is true at run time (fail-fast,
    ref mpi_xla_bridge.pyx:67-91 ``abort_on_error``).

    Falls back to ``jax.debug.callback`` + ``os.abort`` off-CPU or without
    the native library.  Returns a u32 to thread into downstream values if
    the caller wants the check ordered before them.
    """
    pred = jnp.asarray(pred).astype(jnp.uint32).reshape(())
    rank = jnp.asarray(rank, jnp.uint32)
    if runtime_tracing_supported():
        call = jax.ffi.ffi_call(
            "mpx_abort_if",
            jax.ShapeDtypeStruct((), jnp.uint32),
            has_side_effect=True,
        )
        return call(pred, rank, message=message)

    def _cb(p, r):
        if p:
            # a tripped guard is about to kill the process: record it as
            # a telemetry incident first (meter + flushed events-tier
            # journal instant) so the post-mortem timeline shows WHERE
            # the job died, not just that it died
            try:
                from .telemetry import journal as _tjournal

                _tjournal.incident("numeric_guard.trips",
                                   "numeric_guard_trip", r, message)
            except Exception:
                pass
            host_fatal(r, message)

    jax.debug.callback(_cb, pred, rank, ordered=False)
    return pred


# ---------------------------------------------------------------------------
# collective watchdog hooks (resilience/watchdog.py)
# ---------------------------------------------------------------------------


def host_line(rank, text: str) -> None:
    """Host-side diagnostic line in the runtime-log format (``r{rank} | ...``).

    Plain Python (not in-graph): used by host-side monitors (the watchdog's
    Python-fallback thread) that speak outside any traced program.
    """
    print(f"r{int(rank)} | {text}", file=sys.stderr, flush=True)


def host_fatal(rank, text: str) -> None:
    """Host-side fail-fast: print in ``abort_if``'s FATAL format and kill the
    process (the watchdog's fallback death path — same loud exit as the
    native ``MpxAbortIf`` hook)."""
    print(f"r{int(rank)} | FATAL: {text}", file=sys.stderr, flush=True)
    os.abort()


def watchdog_supported() -> bool:
    """True when the C++ watchdog registry/monitor can back the collective
    watchdog (native library built with the watchdog hooks, CPU backend —
    same availability rule as the runtime trace hooks)."""
    return (
        runtime_tracing_supported() and "MpxWatchdogArm" in _loaded_handlers
    )


def watchdog_arm(opname: str, call_id: str, rank, axes: str, timeout: float):
    """Register one in-flight collective with the C++ watchdog; returns a u32
    the op's inputs must be tied to (so arming precedes the collective)."""
    call = jax.ffi.ffi_call(
        "mpx_watchdog_arm",
        jax.ShapeDtypeStruct((), jnp.uint32),
        has_side_effect=True,
    )
    import numpy as np

    return call(
        jnp.asarray(rank, jnp.uint32),
        opname=opname, call_id=call_id, axes=axes,
        timeout=np.float64(timeout),
    )


def watchdog_disarm(call_id: str, rank, dep):
    """Deregister after the collective: ``dep`` (the op's first output) ties
    the call after completion."""
    call = jax.ffi.ffi_call(
        "mpx_watchdog_disarm",
        jax.ShapeDtypeStruct((), jnp.uint32),
        has_side_effect=True,
    )
    return call(_tie(jnp.asarray(rank, jnp.uint32), dep), call_id=call_id)


# Base timestamp for the pure-Python fallback, captured at first use.  Raw
# clock values are seconds since boot/epoch, where f32 ULP is milliseconds
# (or worse); subtracting a process-local base before any f32 downcast
# keeps sub-microsecond resolution for hours of runtime.  The FFI path's
# base lives inside the C++ hook (host_hooks.cc WallclockImpl) for the
# same reason.
_py_wallclock_base: Optional[float] = None


def host_clock():
    """Host-side ``(mono, wall)`` clock pair for the telemetry journal
    (telemetry/journal.py): ``mono`` is monotonic seconds on the SAME
    process base as the pure-Python ``wallclock`` fallback, so journal
    timestamps are directly comparable with in-graph ``wallclock()``
    values; ``wall`` is ``time.time()``, the cross-process alignment
    clock the merge CLI lays timelines out on."""
    import time

    global _py_wallclock_base
    if _py_wallclock_base is None:
        _py_wallclock_base = time.perf_counter()
    return time.perf_counter() - _py_wallclock_base, time.time()


def wallclock(dep=None):
    """Host wall-clock timestamp as an in-graph value, ordered after
    ``dep``: seconds since the process's first ``wallclock`` use.

    Returns f64 when ``jax_enable_x64`` is on, else f32 — on both the FFI
    path and the pure-Python fallback, so the API is consistent across
    platforms (with x64 disabled, callback ``result_shape_dtypes`` reject
    64-bit types outright).  Only differences of ``wallclock`` values are
    meaningful."""
    tok = jnp.zeros((), jnp.uint32) if dep is None else _tie(
        jnp.zeros((), jnp.uint32), dep
    )
    out_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    if runtime_tracing_supported():
        call = jax.ffi.ffi_call(
            "mpx_wallclock",
            jax.ShapeDtypeStruct((), jnp.float64),
            has_side_effect=True,
        )
        return call(tok).astype(out_dtype)
    import time

    import numpy as np

    from jax.experimental import io_callback

    global _py_wallclock_base
    if _py_wallclock_base is None:
        _py_wallclock_base = time.perf_counter()
    base = _py_wallclock_base

    def _now(_):
        # io_callback (ordered) rather than pure_callback: two wallclock
        # reads in one jit are byte-identical subgraphs a pure callback
        # could legally dedupe into a single host call
        return np.asarray(time.perf_counter() - base, out_dtype)

    return io_callback(
        _now, jax.ShapeDtypeStruct((), out_dtype), tok, ordered=True
    )


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if argv[:1] == ["build"]:
        path = build()
        print(f"built {path}")
    else:
        print("usage: python -m mpi4jax_tpu.native build", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
