"""alltoall: transpose data across ranks.

TPU-native re-design of ref mpi4jax/_src/collective_ops/alltoall.py.  Shape
contract preserved: input ``(size, *s)`` -> output ``(size, *s)`` where
``out[i]`` is the slice rank ``i`` addressed to us; the leading-axis == size
requirement is checked like the reference (ref alltoall.py:71-73).

Lowerings, picked per call by ``_algos.resolve_alltoall_algo``:

- **flat** (``native``): one AllToAll HLO on a whole-axes comm — the
  building block for Ulysses-style sequence parallelism (head/sequence
  exchange) — or the allgather+select group form on color splits;
- **hierarchical** (``hier``, ops/_hierarchy.py): on a multi-host comm
  above ``MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES``, the two-level split —
  intra-host transpose over ICI, inter-host exchange of host-aggregated
  contiguous blocks over DCN (1/r the DCN message count), local
  de-interleave.  Bit-identical to flat by construction (pure routing);
  below the crossover / on single-host comms the flat path is emitted
  unchanged, so the lowered HLO is byte-identical to the pre-crossover
  build (pinned by tests/test_hier_traced.py).

Throughput layer (docs/overlap.md, docs/moe.md): inside ``mpx.overlap()``
the call auto-splits into ``alltoall_start``/``alltoall_wait``
(ops/_async.py) and the result is lazy until first use — the MoE
combine-exchange overlap rides exactly this path.
"""

from typing import Optional

from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from . import _async
from ._base import _permute_axis, dispatch, group_select_gather
from .token import Token, consume, produce


@enforce_types(comm=(Comm, None), token=(Token, None))
def alltoall(x, *, comm: Optional[Comm] = None, token: Optional[Token] = None):
    """Exchange slices: rank ``r`` sends ``x[i]`` to rank ``i`` and receives
    into ``out[i]`` from rank ``i``.

    Returns ``(result, token)`` (ref API: alltoall.py:39-77).
    """
    lazy = _async.maybe_lazy("alltoall", x, None, comm, token)
    if lazy is not None:
        return lazy

    def body(comm, arrays, token):
        from ..utils import config
        from . import _algos, _hierarchy

        (xl,) = arrays
        size = comm.Get_size()
        if xl.ndim == 0 or xl.shape[0] != size:
            raise ValueError(
                f"alltoall input must have leading axis == comm size "
                f"({size}), got shape {xl.shape} (ref alltoall.py:71-73)"
            )
        xl = consume(token, xl)
        log_op("MPI_Alltoall", comm.Get_rank(), f"sending {xl.size} items")
        nbytes = xl.size * xl.dtype.itemsize
        plan = _hierarchy.hier_plan(comm) if size > 1 else None
        algo = _algos.resolve_alltoall_algo(
            config.collective_algo(), nbytes, hier_ok=plan is not None
        )
        _hierarchy.annotate_selection("alltoall", algo, nbytes, size, plan,
                                      comm, dtype=xl.dtype.name)
        if algo == "hier":
            res = _hierarchy.apply_hier_alltoall(xl, comm, plan)
        elif comm.groups is not None:
            # color split (uniform): out[j] = group-member j's row
            # addressed to this rank's group-local index
            import jax.numpy as jnp

            sel = group_select_gather(comm, xl)
            res = jnp.take(sel, comm.Get_rank(), axis=1)
        else:
            # multi-axis comms exchange over the linearized row-major rank
            # order (XLA's AllToAll flattens the axis tuple the same way
            # Get_rank does)
            res = lax.all_to_all(xl, _permute_axis(comm), split_axis=0,
                                 concat_axis=0)
        return res, produce(token, res)

    return dispatch("alltoall", comm, body, (x,), token, static_key=())
