"""reduce_scatter: reduce, then scatter one block per rank.

The 13th op — BEYOND the reference's 12 (ref mpi4jax has no
reduce_scatter; its users pay a full allreduce for the reduce-scatter
half of data-parallel gradient exchange).  Semantics are
``MPI_Reduce_scatter_block``: every rank passes ``(size, *s)`` — block
``i`` addressed to rank ``i`` — and rank ``i`` receives the reduction of
every rank's block ``i``, shape ``s``.  Equivalent to
``allreduce(x)[rank]`` at half (or less) the byte volume, and the natural
first half of a bucketed data-parallel optimizer step (reduce_scatter →
local update → allgather).

Lowering (ops/_algos.apply_reduce_scatter): one native ``psum_scatter``
HLO for SUM on a whole single-axis comm; otherwise ring reduce-scatter
(O(size·(k-1)/k) bytes per rank) vs butterfly-allreduce + own-block select
(O(size·log k)) by the payload-aware selector
(``MPI4JAX_TPU_COLLECTIVE_ALGO``).  The combine runs on the user's own
blocks, so block-wise callables (e.g. ``jnp.matmul`` on ``(…, 2, 2)``
blocks) are valid with every algorithm.  Non-commutative associative
callables receive the ascending group-rank fold, the same deterministic
contract as ``allreduce``.

Differentiable: JVP reduce-scatters the tangents alongside the primals;
the transpose of SUM-reduce_scatter is ``all_gather`` (the psum_scatter /
all_gather adjoint pair), both inherited from JAX's rules for the
underlying collectives (pinned by tests/test_reduce_scatter.py).
"""

from typing import Optional

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from . import _async
from ._algos import apply_reduce_scatter
from ._base import SUM, Op, OpLike, dispatch, reduction_name
from .token import Token, consume, produce


@enforce_types(comm=(Comm, None), token=(Token, None))
def reduce_scatter(x, op: OpLike = SUM, *, comm: Optional[Comm] = None,
                   token: Optional[Token] = None):
    """Reduce ``x`` (shape ``(size, *s)``) with ``op`` across all ranks of
    ``comm`` and scatter the result: rank ``i`` receives the reduction of
    every rank's block ``x[i]``, shape ``s``.

    Returns ``(result, token)`` (MPI_Reduce_scatter_block semantics; on a
    color-split comm ``size`` is the uniform group size and blocks index
    group-local positions).

    Inside ``mpx.overlap()`` the call auto-splits into the async
    ``reduce_scatter_start``/``_wait`` pair (ops/_async.py,
    docs/overlap.md) and the result is lazy until first use.
    """
    lazy = _async.maybe_lazy("reduce_scatter", x, op, comm, token)
    if lazy is not None:
        return lazy

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        if xl.ndim == 0 or xl.shape[0] != size:
            raise ValueError(
                f"reduce_scatter input must have leading axis == comm size "
                f"({size}), got shape {xl.shape} (block i is addressed to "
                "rank i, MPI_Reduce_scatter_block)"
            )
        xl = consume(token, xl)
        log_op("MPI_Reduce_scatter", comm.Get_rank(),
               f"keeping {xl.size // size} of {xl.size} items")
        res = apply_reduce_scatter(xl, op, comm)
        return res, produce(token, res)

    # custom callable ops are uncacheable: their captured state can change
    # without changing identity (enum ops are pure values)
    return dispatch("reduce_scatter", comm, body, (x,), token,
                    static_key=(op,) if isinstance(op, Op) else None,
                    ana={"reduction": reduction_name(op)})
