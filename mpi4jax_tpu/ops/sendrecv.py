"""sendrecv: paired exchange — the halo-exchange workhorse.

TPU-native re-design of ref mpi4jax/_src/collective_ops/sendrecv.py (495 LoC).
One matched send+receive per rank, described collectively by a static routing
spec (``shift``/dict/pairs — see parallel/rankspec.py), lowering to a single
CollectivePermute HLO over ICI.

Autodiff parity (ref sendrecv.py:417-480) comes from JAX's ppermute rules:

- transpose swaps source and dest (ppermute transposes to the inverse
  permutation — exactly the reference's ``_must_transpose`` source/dest swap);
- reverse-mode through jit/grad works (matvec acceptance suite);
- forward-mode: the reference *raises* because a tangent traced on one
  process would land on the wrong rank (ref sendrecv.py:150-155).  Here the
  SPMD program traces all ranks at once, so the tangent is permuted alongside
  the primal and forward-mode is simply correct — a documented improvement.

Ranks without a source in the routing receive their ``recvbuf`` template back
(MPI_PROC_NULL semantics); ranks without a destination send nothing.
"""

from typing import Optional

import jax.numpy as jnp

from jax import lax

from ..parallel.comm import Comm
from ..parallel.rankspec import resolve_routing
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import _permute_axis, dispatch
from .status import Status
from .token import Token, consume, produce


def _apply_permute(xl, recvbuf, pairs, comm):
    """Run one CollectivePermute along GLOBAL pairs (routing specs are
    resolved through ``rankspec.resolve_routing`` before this).

    An identity routing — every pair ``(r, r)``, e.g. any wrapping
    ``shift`` on a size-1 axis — skips the collective entirely: the
    permutation is a per-rank no-op, and CollectivePermute is far from
    free on real interconnects (and costs ~100 us per MB on the
    single-chip attach platform, docs/shallow_water.md "Roofline").
    Empty pairs (a non-wrapping shift on a size-1 axis) elide the same
    way — the receiver mask below already hands every rank its recvbuf.
    Transpose/AD semantics are unchanged (the inverse of the identity is
    the identity, matching ppermute's transpose rule)."""
    if all(s == d for s, d in pairs):
        permuted = xl
    else:
        # multi-axis comms permute over the linearized row-major rank
        # order — the same order Get_rank defines (parallel/comm.py)
        permuted = lax.ppermute(xl, _permute_axis(comm), list(pairs))
    # the output is typed by the recv buffer (ref sendrecv.py:369-377
    # abstract eval): a message with a matching element count but different
    # shape — e.g. exchange-row-for-column — lands in recvbuf's shape
    permuted = permuted.reshape(recvbuf.shape)
    receivers = sorted(d for _, d in pairs)
    if len(receivers) == comm.world_size():
        return permuted
    rank = comm.global_rank()
    is_recv = jnp.isin(rank, jnp.asarray(receivers))
    return jnp.where(is_recv, permuted, recvbuf)


def _fill_status(status, pairs, comm, count, dtype, tag):
    """``pairs`` are GLOBAL; ``Status.source`` reports the comm-local rank
    of the sender (on a color-split comm the two differ, per MPI)."""
    if status is None:
        return
    rank = comm.global_rank()
    size = comm.world_size()
    src_table = [-1] * size  # MPI_PROC_NULL analog for no-source ranks
    for s, d in pairs:
        src_table[d] = comm.local_rank_of(s)
    status.source = jnp.asarray(src_table)[rank]
    # the tag the matched message was sent with (ref recv.py:43-48 fills the
    # full MPI.Status); matching is SPMD-uniform so this is static
    status.tag = tag
    status.count = count
    status.dtype = dtype


@enforce_types(sendtag=int, recvtag=int, comm=(Comm, None),
               status=(Status, None), token=(Token, None))
def sendrecv(
    sendbuf,
    recvbuf,
    source=None,
    dest=None,
    *,
    sendtag: int = 0,
    recvtag: int = 0,
    comm: Optional[Comm] = None,
    status: Optional[Status] = None,
    token: Optional[Token] = None,
):
    """Simultaneously send ``sendbuf`` and receive into ``recvbuf``'s shape
    along a static routing pattern.

    ``dest`` maps sender→receiver (e.g. ``shift(1)``); ``source`` is the
    receiver-centric view.  Give either (the other is inferred) or both
    (validated for consistency).  Returns ``(received, token)``
    (ref API: sendrecv.py:46-128).

    Tags are accepted for API parity but are *inert* for matching: a
    ``sendrecv`` is self-contained (one fused CollectivePermute), so the
    incoming message always comes from this same call and always carries
    ``sendtag``.  Ported MPI idioms with differing send/recv tags (e.g.
    swapped-tag bidirectional exchanges) therefore route correctly;
    ``Status.tag`` reports ``sendtag`` — the tag the message was actually
    sent with.
    """
    from ..analysis.report import mpx_error

    if sendbuf.dtype != recvbuf.dtype:
        raise mpx_error(
            ValueError, "MPX106",
            f"sendrecv requires matching send/recv dtypes (MPI type-signature "
            f"rule); got {sendbuf.dtype} vs {recvbuf.dtype}",
        )
    if sendbuf.shape != recvbuf.shape and sendbuf.size != recvbuf.size:
        raise mpx_error(
            ValueError, "MPX106",
            f"sendrecv: send/recv buffers may differ in shape only when their "
            f"element counts match (the output is typed by recvbuf, ref "
            f"sendrecv.py:369; under SPMD every rank's recv shape is the same "
            f"static recvbuf shape, so mismatched counts cannot be routed); "
            f"got {sendbuf.shape} vs {recvbuf.shape}. See docs/sharp_bits.md.",
        )

    # Eager-path caching: resolve the routing spec to concrete pairs ONCE,
    # up front, and close the body over the *resolved* pairs — the cached
    # program can then never re-read a mutated spec object, even on a
    # shape-triggered internal retrace.  The cache key uses the same pairs,
    # so callables/dicts with identical routing share an entry.  A Status
    # out-param must be filled at trace time, so those calls are
    # uncacheable.  Inside a region, pairs resolve at trace time instead
    # (comm size may only be known from the axis environment there).
    static_key = None
    resolved_pairs = None
    if status is None:
        from ..parallel.region import in_parallel_region, resolve_comm

        c = resolve_comm(comm)
        if c.mesh is not None and not in_parallel_region(c):
            resolved_pairs = resolve_routing(c, source, dest, what="sendrecv")
            static_key = (resolved_pairs, sendtag, recvtag)

    def body(comm, arrays, token):
        from ..analysis.hook import annotate

        xl, rbuf = arrays
        pairs = resolved_pairs
        if pairs is None:  # in-region: resolve at trace time, already GLOBAL
            pairs = resolve_routing(comm, source, dest, what="sendrecv")
        annotate(pairs=pairs)
        xl = consume(token, xl)
        log_op("MPI_Sendrecv", comm.Get_rank(),
               f"{xl.size} items along {list(pairs)}")
        res = _apply_permute(xl, rbuf, pairs, comm)
        _fill_status(status, pairs, comm, xl.size, xl.dtype, sendtag)
        return res, produce(token, res)

    return dispatch(
        "sendrecv", comm, body, (sendbuf, recvbuf), token,
        static_key=static_key, ana={"tag": sendtag},
    )
