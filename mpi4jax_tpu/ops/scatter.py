"""scatter: distribute slices of root's array to all ranks.

TPU-native re-design of ref mpi4jax/_src/collective_ops/scatter.py.  The
reference requires input shape ``(size, *s)`` on root only (ref
scatter.py:85-89); under SPMD every rank passes the same-shaped buffer (only
root's contents matter) and receives its slice ``s``.

Lowering: one AllToAll HLO, then a static index selecting the slices that
originated at ``root`` — each rank ends up with ``root_buffer[rank]``.
"""

from typing import Optional

from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import _permute_axis, dispatch, group_select_gather
from .token import Token, consume, produce


@enforce_types(root=int, comm=(Comm, None), token=(Token, None))
def scatter(x, root: int, *, comm: Optional[Comm] = None,
            token: Optional[Token] = None):
    """Scatter ``x`` (shape ``(size, *s)``, contents significant on root
    only) so rank ``r`` receives ``x[r]`` as sent by ``root``.

    Returns ``(result, token)`` (ref API: scatter.py:40-96).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        if not 0 <= root < size:
            from ..analysis.report import mpx_error

            raise mpx_error(
                ValueError, "MPX105",
                f"scatter root {root} out of range for size {size}",
            )
        if xl.ndim == 0 or xl.shape[0] != size:
            raise ValueError(
                f"scatter input must have leading axis == comm size ({size}), "
                f"got shape {xl.shape} (ref scatter.py:85-89)"
            )
        xl = consume(token, xl)
        log_op("MPI_Scatter", comm.Get_rank(),
               f"receiving {xl.size // size} items from root {root}")
        if comm.groups is not None:
            # color split (uniform): pick the group root's buffer, then
            # this rank's group-local row
            import jax.numpy as jnp

            sel = group_select_gather(comm, xl)
            res = jnp.take(jnp.take(sel, root, axis=0),
                           comm.Get_rank(), axis=0)
        else:
            # all_to_all: out[i] = rank i's slice addressed to us; keep
            # root's
            exchanged = lax.all_to_all(xl, _permute_axis(comm), split_axis=0,
                                       concat_axis=0)
            res = exchanged[root]
        return res, produce(token, res)

    return dispatch("scatter", comm, body, (x,), token, static_key=(root,),
                    ana={"root": root})
