"""gather: collect every rank's array at root.

TPU-native re-design of ref mpi4jax/_src/collective_ops/gather.py.  The
reference has a *rank-dependent output shape* — ``(size, *s)`` on root, the
input passed through on other ranks (ref gather.py:92-95, abstract
:270-284).  SPMD traces one program with one output type for all ranks, so
the shape is made uniform: **every rank receives the gathered ``(size, *s)``
array** (root's view is bit-identical to the reference's).  This is the
documented divergence for the gather family (see docs/sharp_bits.md); on ICI
the extra fan-out is handled by the AllGather HLO's bandwidth-optimal ring
schedule, so there is no latency cost over a rooted gather.
"""

from typing import Optional

from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import dispatch, group_select_gather
from .token import Token, consume, produce


@enforce_types(root=int, comm=(Comm, None), token=(Token, None))
def gather(x, root: int, *, comm: Optional[Comm] = None,
           token: Optional[Token] = None):
    """Gather ``x`` from every rank to ``root`` (all ranks receive a copy —
    see module docstring).

    Returns ``(result, token)`` (ref API: gather.py:40-96).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        if not 0 <= root < size:
            from ..analysis.report import mpx_error

            raise mpx_error(
                ValueError, "MPX105",
                f"gather root {root} out of range for size {size}",
            )
        xl = consume(token, xl)
        log_op("MPI_Gather", comm.Get_rank(),
               f"sending {xl.size} items to root {root}")
        if comm.groups is not None:
            # color split (uniform): same uniform-shape divergence as the
            # whole-axes form, selected per group
            res = group_select_gather(comm, xl)
        else:
            # multi-axis comms gather in row-major rank order (axis tuples
            # are supported natively by the AllGather lowering)
            res = lax.all_gather(xl, comm.axes, axis=0, tiled=False)
        return res, produce(token, res)

    return dispatch("gather", comm, body, (x,), token, static_key=(root,),
                    ana={"root": root})
