"""Collective fusion: Horovod-style bucketing of adjacent small collectives.

PR 2's algorithm layer (``_algos.py``) optimizes ONE large payload; real
training steps instead issue MANY small collectives (one per gradient
leaf), each paying full dispatch + per-collective latency.  Tensor fusion
(Sergeev & Del Balso, 2018; PyTorch DDP's bucketed allreduce, Li et al.,
VLDB 2020) coalesces them: adjacent same-(op, comm, reduction, root)
collectives pack into one flat-buffer collective per dtype bucket, cutting
per-call dispatch overhead and letting the bandwidth-optimal ring run once
over the packed payload instead of k times over slivers.

The reference executes ops asynchronously at run time, so Horovod fuses in
a background thread.  Here ops are *trace-time* — a collective is emitted
the moment the Python call runs — so fusion works by **deferral**: with
``MPI4JAX_TPU_FUSION=auto|force``, a fusable op inside a managed parallel
region does not emit its collective; it queues the payload and returns a
:class:`LazyResult`.  The queue drains ("flushes") into real fused
collectives at the first of:

- any use of a deferred result (``__jax_array__`` / operators / indexing),
- a dispatch that cannot join the queue (different op/comm/reduction/root,
  a non-fusable op, a barrier — program order is preserved),
- the end of the parallel region (``parallel/region.py`` flushes and
  materializes region outputs).

so the fusion-friendly idiom is "issue all collectives, then consume"::

    red = jax.tree.map(lambda g: mpx.allreduce(g, op=mpx.SUM)[0], grads)
    new = jax.tree.map(lambda p, g: p - lr * g / n, params, red)  # flushes

Packing is deterministic (queue = program order), dtype-segregated, and
capped per bucket by ``MPI4JAX_TPU_FUSION_BUCKET_BYTES``; unflattening is
exact (per-member offset slices + reshape), so fused and unfused results
are bit-identical for every enum reduction (pinned by the lockstep
simulator in tests/test_fusion.py).  Custom *callable* reductions never
fuse: concatenating payloads changes what a whole-array callable sees.

Ordering contract: a deferred op's token is a passthrough (the fused
collective is ordered by program position at the flush point), exactly the
``MPI4JAX_TPU_PREFER_NOTOKEN`` semantics.  ``off`` (the default) bypasses
every hook on this path — the lowered HLO is byte-identical to a build
without this module (pinned by tests/test_fusion.py).

The bucketing plan (``bucket_plan`` / ``pack_offsets``) is pure Python,
shared with the lockstep simulator so it runs under any JAX version.
"""

from __future__ import annotations

from typing import List, Optional

from ..utils import config

__all__ = [
    "bucket_plan",
    "pack_offsets",
    "set_fusion_mode",
    "effective_mode",
    "fusion_cache_token",
    "LazyResult",
    "maybe_defer",
    "flush_pending",
    "materialize_value",
    "materialize_tree",
]

# ops the deferral layer accepts (reduce_scatter is deliberately absent:
# its blocks are positional per rank, so concatenation would reroute them;
# the async start/wait pair in _async.py is its latency-hiding path)
FUSABLE_OPS = ("allreduce", "bcast")

_UNSET = object()
_mode_override = _UNSET

# non-zero while a flush is emitting its fused collectives: those inner
# dispatches must not re-enter the deferral layer
_inhibit = 0

# annotation handoff: the flush sets this right before emitting a fused
# collective; dispatch (ops/_base.py) merges it into that op's analysis
# ``ana`` dict so the event stream records the member count
_pending_ana: Optional[dict] = None


def set_fusion_mode(mode: Optional[str]) -> None:
    """Programmatic override of ``MPI4JAX_TPU_FUSION`` (``None`` returns
    control to the environment), mirroring ``set_telemetry_mode`` and the
    other ``set_*`` overrides."""
    global _mode_override
    if mode is None:
        _mode_override = _UNSET
        config.bump_config_epoch()
        return
    if mode not in config.FUSION_MODES:
        raise ValueError(
            f"fusion mode must be one of {config.FUSION_MODES}, got {mode!r}"
        )
    _mode_override = mode
    config.bump_config_epoch()


def effective_mode() -> str:
    if _mode_override is not _UNSET:
        return _mode_override
    return config.fusion_mode()


def fusion_cache_token() -> tuple:
    """Folded into both compiled-program cache keys (ops/_base.py eager
    cache, parallel/region.py spmd cache): flipping the fusion mode or the
    bucket cap changes the traced program, so it must retrace."""
    return (effective_mode(), config.fusion_bucket_bytes())


# ---------------------------------------------------------------------------
# the bucketing plan (pure — shared with the lockstep simulator)
# ---------------------------------------------------------------------------


def bucket_plan(entries, bucket_bytes: int, force: bool = False) -> List[list]:
    """Partition queued members into fusion buckets.

    ``entries`` is the queue in program order: one ``(dtype_str, nbytes)``
    per member.  Buckets are dtype-segregated (a flat buffer has one
    dtype), order-preserving within a dtype, and close when adding the
    next member would exceed ``bucket_bytes`` (a single oversized member
    still gets its own bucket; ``force`` ignores the cap).  Returned in
    deterministic order: buckets sorted by their first member's queue
    index, members ascending within each — so every rank packs
    identically, which the SPMD contract requires.
    """
    open_buckets: dict = {}   # dtype -> (member indices, cumulative bytes)
    buckets: List[list] = []
    for i, (dtype, nbytes) in enumerate(entries):
        cur = open_buckets.get(dtype)
        if cur is not None and not force and cur[1] + nbytes > bucket_bytes:
            buckets.append(cur[0])
            cur = None
        if cur is None:
            open_buckets[dtype] = ([i], nbytes)
        else:
            cur[0].append(i)
            open_buckets[dtype] = (cur[0], cur[1] + nbytes)
    buckets.extend(cur[0] for cur in open_buckets.values())
    buckets.sort(key=lambda members: members[0])
    return buckets


def pack_offsets(sizes) -> List[tuple]:
    """Exact unflattening plan: ``[(start, end)]`` per member of one
    bucket's flat buffer, in packing order."""
    out = []
    pos = 0
    for n in sizes:
        out.append((pos, pos + n))
        pos += n
    return out


# ---------------------------------------------------------------------------
# deferral
# ---------------------------------------------------------------------------


class LazyResult:
    """A deferred collective result.

    Behaves like the eventual array: ``shape``/``dtype``/``ndim``/``size``
    are known immediately; any *use* (arithmetic, indexing, ``jnp.*`` via
    ``__jax_array__``) forces the fusion queue to flush and returns the
    slice of the fused collective this member packed into.  Identity
    (``==`` on the wrapper, hashing) is NOT forwarded — force first if you
    need elementwise comparison.
    """

    __slots__ = ("_shape", "_dtype", "_value", "_ctx")

    def __init__(self, shape, dtype, ctx):
        self._shape = tuple(shape)
        self._dtype = dtype
        self._value = None
        self._ctx = ctx

    # -- forcing ------------------------------------------------------------

    def _force(self):
        if self._value is None:
            flush_pending(self._ctx)
            if self._value is None:  # pragma: no cover - defensive
                raise RuntimeError(
                    "deferred collective result used after its parallel "
                    "region ended without a flush; this is a bug in the "
                    "fusion layer (the region exit must flush)"
                )
        self._ctx = None
        return self._value

    def __jax_array__(self):
        return self._force()

    # -- static metadata ----------------------------------------------------

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        n = 1
        for d in self._shape:
            n *= d
        return n

    def __repr__(self):
        state = "pending" if self._value is None else "flushed"
        return (f"LazyResult(shape={self._shape}, dtype={self._dtype}, "
                f"{state})")

    # -- forwarding (every use forces) --------------------------------------

    def __getattr__(self, name):
        # array-method calls (.reshape, .sum, .astype, .at, ...) are uses:
        # force and delegate, so fusion stays a drop-in flag flip.  Dunder
        # probes (pickle/copy protocols, numpy interface sniffing) must
        # NOT force a flush mid-protocol — the explicit dunders below
        # cover the supported surface.
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return getattr(self._force(), name)

    def __array__(self, *args, **kwargs):
        import numpy as np

        return np.asarray(self._force(), *args, **kwargs)

    # elementwise comparison semantics, like the array this stands for
    # (and, like a traced array, therefore unhashable)
    __hash__ = None

    def __eq__(self, other):
        return self._force() == other

    def __ne__(self, other):
        return self._force() != other

    def __getitem__(self, idx):
        return self._force()[idx]

    def __add__(self, o):
        return self._force() + o

    def __radd__(self, o):
        return o + self._force()

    def __sub__(self, o):
        return self._force() - o

    def __rsub__(self, o):
        return o - self._force()

    def __mul__(self, o):
        return self._force() * o

    def __rmul__(self, o):
        return o * self._force()

    def __truediv__(self, o):
        return self._force() / o

    def __rtruediv__(self, o):
        return o / self._force()

    def __pow__(self, o):
        return self._force() ** o

    def __neg__(self):
        return -self._force()

    def __abs__(self):
        return abs(self._force())

    def __matmul__(self, o):
        return self._force() @ o

    def __rmatmul__(self, o):
        return o @ self._force()

    def __lt__(self, o):
        return self._force() < o

    def __le__(self, o):
        return self._force() <= o

    def __gt__(self, o):
        return self._force() > o

    def __ge__(self, o):
        return self._force() >= o


class _Entry:
    __slots__ = ("array", "cell")

    def __init__(self, array, cell):
        self.array = array
        self.cell = cell


class _Queue:
    """The pending adjacent run: members all share ``key`` =
    (opname, comm uid, reduction, root)."""

    __slots__ = ("key", "opname", "comm", "reduction", "root", "entries")

    def __init__(self, key, opname, comm, reduction, root):
        self.key = key
        self.opname = opname
        self.comm = comm
        self.reduction = reduction
        self.root = root
        self.entries: List[_Entry] = []


def _managed_ctx():
    from ..parallel.region import _region_stack

    return _region_stack[-1] if _region_stack else None


def maybe_defer(opname: str, x, comm, token, reduction=None, root=None):
    """Queue one fusable op; returns ``(LazyResult, Token)`` or ``None``
    when the deferral layer is inactive (mode off, outside a managed
    region, mid-flush, or a non-fusable argument)."""
    if _inhibit or opname not in FUSABLE_OPS:
        return None
    mode = effective_mode()
    if mode == "off":
        return None
    ctx = _managed_ctx()
    if ctx is None:
        return None
    from ..parallel.region import in_parallel_region, resolve_comm

    comm = resolve_comm(comm)
    if not in_parallel_region(comm):
        return None
    x = materialize_value(x)  # a deferred input joins via its flush
    key = (opname, comm.uid, reduction, root)
    q = getattr(ctx, "fusion_queue", None)
    if q is not None and q.key != key:
        flush_pending(ctx)
        q = None
    if q is None:
        q = _Queue(key, opname, comm, reduction, root)
        ctx.fusion_queue = q
    import jax

    aval = jax.typeof(x)
    cell = LazyResult(aval.shape, aval.dtype, ctx)
    q.entries.append(_Entry(x, cell))
    # passthrough token: the fused collective is ordered by program
    # position at the flush point (PREFER_NOTOKEN semantics; see module
    # docstring and docs/overlap.md)
    if token is None:
        from .token import create_token

        token = create_token()
    return cell, token


def flush_pending(ctx) -> None:
    """Drain ``ctx``'s fusion queue into real collectives (no-op when
    empty).  Called by every dispatch that does not join the queue and by
    the region exit, so program order is preserved."""
    if ctx is None:
        return
    q = getattr(ctx, "fusion_queue", None)
    if q is None:
        return
    ctx.fusion_queue = None
    _flush_queue(q)


def _flush_queue(q: _Queue) -> None:
    global _inhibit, _pending_ana
    import jax.numpy as jnp

    entries = q.entries
    mode = effective_mode()
    _inhibit += 1
    try:
        if len(entries) == 1 and mode != "force":
            # a lone member gains nothing from the flat-buffer round trip
            (e,) = entries
            e.cell._value = _run_member(q, e.array)
            return
        plan = bucket_plan(
            [(str(e.array.dtype), e.array.size * e.array.dtype.itemsize)
             for e in entries],
            config.fusion_bucket_bytes(),
            force=(mode == "force"),
        )
        for members in plan:
            sizes = [entries[i].array.size for i in members]
            flats = [entries[i].array.reshape(-1) for i in members]
            flat = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
            _meter_bucket(q, flat, len(members))
            # the layout (per-member dtype + element count, in pack
            # order) is what the cross-rank matcher compares: two ranks
            # packing different flat buffers is MPX124
            member_arrays = tuple(entries[i].array for i in members)
            _pending_ana = {"fused_members": len(members),
                            "fused_bytes": int(flat.size) * flat.dtype.itemsize,
                            "fused_layout": tuple(
                                (str(a.dtype), int(a.size))
                                for a in member_arrays),
                            # the dataflow hazard join key: the packed op
                            # charges the MEMBER buffers (not the flat
                            # concatenation), so a LazyResult aliasing a
                            # bucket member — or a donation of one — stays
                            # traceable (analysis/hazards.py MPX139/140)
                            "buffers": tuple(id(a) for a in member_arrays),
                            "buffer_carriers": member_arrays}
            try:
                fused = _run_member(q, flat)
            finally:
                _pending_ana = None
            for i, (start, end) in zip(members, pack_offsets(sizes)):
                e = entries[i]
                e.cell._value = fused[start:end].reshape(e.cell._shape)
    finally:
        _inhibit -= 1


def _run_member(q: _Queue, array):
    """Emit one real collective for a bucket (or a lone member) through
    the normal dispatch point, so analysis, telemetry, and resilience see
    it like any hand-written op."""
    if q.opname == "allreduce":
        from .allreduce import allreduce

        res, _ = allreduce(array, op=q.reduction, comm=q.comm)
    else:
        from .bcast import bcast

        res, _ = bcast(array, q.root, comm=q.comm)
    return res


def _meter_bucket(q: _Queue, flat, members: int) -> None:
    from ..telemetry import core as _telemetry

    if _telemetry.effective_mode() == "off":
        return
    from ._algos import chunk_layout, static_group_size

    nbytes = int(flat.size) * flat.dtype.itemsize
    k = static_group_size(q.comm)
    waste = 0
    if k and k > 1:
        chunk, padded = chunk_layout(int(flat.size), k)
        waste = (padded - int(flat.size)) * flat.dtype.itemsize
    prefix = f"fusion.{q.opname}.c{q.comm.uid}.{flat.dtype}"
    _telemetry.meter(f"{prefix}.buckets")
    _telemetry.meter(f"{prefix}.members", members)
    _telemetry.meter(f"{prefix}.bytes_packed", nbytes)
    _telemetry.meter(f"{prefix}.padding_waste", waste)


def take_pending_ana() -> Optional[dict]:
    """The fused-collective annotation for the dispatch in flight (member
    count + packed bytes), or ``None`` for every ordinary dispatch."""
    global _pending_ana
    ana, _pending_ana = _pending_ana, None
    return ana


# ---------------------------------------------------------------------------
# materialization
# ---------------------------------------------------------------------------


def materialize_value(x):
    """Force a deferred result to its array (no-op for everything else)."""
    if isinstance(x, LazyResult):
        return x._force()
    return x


def materialize_tree(tree):
    """Force every deferred result in a pytree (region outputs must be
    real arrays before they cross the shard_map boundary)."""
    import jax

    return jax.tree.map(materialize_value, tree)
