"""bcast: broadcast from root.

TPU-native re-design of ref mpi4jax/_src/collective_ops/bcast.py.  Contract
preserved: every rank receives root's value with the input's shape; the root
gets its own input back (ref bcast.py:76-81).

Lowering: masked AllReduce — ``psum(where(rank == root, x, 0))`` — one
O(n)-bandwidth collective on ICI (vs an AllGather-based broadcast which would
move ``size × n``).  ``where`` (not multiply-by-mask) so non-root NaN/Inf
payloads cannot poison the result.  Differentiable: the transpose of the
masked psum correctly routes cotangents back to the root.
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import apply_doubling_bcast, dispatch
from .token import Token, consume, produce


@enforce_types(root=int, comm=(Comm, None), token=(Token, None))
def bcast(x, root: int, *, comm: Optional[Comm] = None,
          token: Optional[Token] = None):
    """Broadcast ``x`` from rank ``root`` to all ranks.

    Returns ``(result, token)`` (ref API: bcast.py:40-84).  ``root`` must be
    a static Python int (SPMD traces one program for all ranks).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.min_size()  # on a color split, root must fit EVERY group
        if not 0 <= root < size:
            raise ValueError(f"bcast root {root} out of range for size {size}")
        xl = consume(token, xl)
        rank = comm.Get_rank()
        log_op("MPI_Bcast", rank, f"{xl.size} items from root {root}")
        if comm.groups is not None:
            # color split: log-depth doubling broadcast from each group's
            # root over ppermute rounds — O(log k) per-rank bandwidth, any
            # partition, no cross-group mixing (the r4 lowering was a full
            # AllGather + per-group take: O(world) bandwidth per call)
            res = apply_doubling_bcast(xl, comm, root)
        elif jnp.issubdtype(xl.dtype, jnp.bool_):
            masked = jnp.where(rank == root, xl.astype(jnp.uint8), 0)
            res = lax.psum(masked, comm.axes).astype(jnp.bool_)
        else:
            masked = jnp.where(rank == root, xl, jnp.zeros_like(xl))
            res = lax.psum(masked, comm.axes)
        return res, produce(token, res)

    return dispatch("bcast", comm, body, (x,), token, static_key=(root,))
