"""bcast: broadcast from root.

TPU-native re-design of ref mpi4jax/_src/collective_ops/bcast.py.  Contract
preserved: every rank receives root's value with the input's shape; the root
gets its own input back (ref bcast.py:76-81).

Lowering, picked per call by the payload-aware selector
(``MPI4JAX_TPU_COLLECTIVE_ALGO``, ops/_algos.py):

- whole-axes comm under ``auto``: masked AllReduce —
  ``psum(where(rank == root, x, 0))`` — one O(n)-bandwidth native
  collective on ICI.  ``where`` (not multiply-by-mask) so non-root
  NaN/Inf payloads cannot poison the result.  Differentiable: the
  transpose of the masked psum correctly routes cotangents back to root.
- color splits and forced algorithms, small payloads (**butterfly**):
  log-depth doubling broadcast over CollectivePermute
  (``apply_doubling_bcast``) — ``ceil(log2 k)`` full-payload rounds,
  latency-optimal, works on ANY partition (unequal groups included).
- color splits and forced algorithms, large payloads (**ring**):
  binomial-halving scatter + ring allgather
  (``_algos.apply_vdg_bcast``, van de Geijn) — ~2·size bytes per rank vs
  the doubling broadcast's size·ceil(log2 k), the bandwidth-optimal form
  for large frames.  Needs a uniform static group size; unequal splits
  keep the butterfly.
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from . import _fusion
from ._base import apply_doubling_bcast, dispatch
from .token import Token, consume, produce


@enforce_types(root=int, comm=(Comm, None), token=(Token, None))
def bcast(x, root: int, *, comm: Optional[Comm] = None,
          token: Optional[Token] = None):
    """Broadcast ``x`` from rank ``root`` to all ranks.

    Returns ``(result, token)`` (ref API: bcast.py:40-84).  ``root`` must be
    a static Python int (SPMD traces one program for all ranks).

    Under ``MPI4JAX_TPU_FUSION=auto|force`` adjacent same-root broadcasts
    coalesce into one flat-buffer bcast per dtype bucket (ops/_fusion.py,
    docs/overlap.md); the result materializes on first use.
    """
    deferred = _fusion.maybe_defer("bcast", x, comm, token, root=root)
    if deferred is not None:
        return deferred

    def body(comm, arrays, token):
        from . import _algos, _hierarchy
        from ..analysis.hook import annotate
        from ..utils.config import collective_algo

        (xl,) = arrays
        size = comm.min_size()  # on a color split, root must fit EVERY group
        if not 0 <= root < size:
            from ..analysis.report import mpx_error

            raise mpx_error(
                ValueError, "MPX105",
                f"bcast root {root} out of range for size {size}",
            )
        xl = consume(token, xl)
        rank = comm.Get_rank()
        log_op("MPI_Bcast", rank, f"{xl.size} items from root {root}")
        algo = collective_algo()
        if comm.groups is None and algo == "auto":
            annotate(algo="native")
            # whole-axes fast path: one native AllReduce HLO
            if jnp.issubdtype(xl.dtype, jnp.bool_):
                masked = jnp.where(rank == root, xl.astype(jnp.uint8), 0)
                res = lax.psum(masked, comm.axes).astype(jnp.bool_)
            else:
                masked = jnp.where(rank == root, xl, jnp.zeros_like(xl))
                res = lax.psum(masked, comm.axes)
        else:
            # color splits (XLA's axis_index_groups is unavailable under
            # shard_map — see Comm.Split) and forced algorithms: doubling
            # (butterfly) vs van de Geijn (ring) by static payload bytes,
            # vs the two-level scatter + inter-host bcast + allgather
            # (_hierarchy.apply_hier_bcast) on multi-host comms.  The vdg
            # scatter and the hierarchy need a uniform static group size;
            # unequal partitions keep the doubling broadcast, which works
            # on any partition (the r4 lowering was a full AllGather +
            # per-group take: O(world) bandwidth per call).
            k = _algos.static_group_size(comm)
            plan = (_hierarchy.hier_plan(comm)
                    if k is not None and k > 1 else None)
            nbytes = xl.size * xl.dtype.itemsize
            picked = _algos.resolve_algo(
                algo, nbytes, k or 1,
                ring_ok=k is not None and k > 1,
                hier_ok=plan is not None,
            )
            _hierarchy.annotate_selection("bcast", picked, nbytes, k or 1,
                                          plan, comm, dtype=xl.dtype.name)
            if picked == "hier":
                res = _hierarchy.apply_hier_bcast(xl, comm, root, plan)
            elif picked == "ring":
                res = _algos.apply_vdg_bcast(xl, comm, root, k)
            else:
                res = apply_doubling_bcast(xl, comm, root)
        return res, produce(token, res)

    return dispatch("bcast", comm, body, (x,), token, static_key=(root,),
                    ana={"root": root})
