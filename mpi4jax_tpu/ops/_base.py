"""Shared machinery for the 12 collective ops.

This is the TPU-native replacement for the reference's entire L2-L4 stack
(per-op primitives + abstract evals + per-platform lowerings + the Cython
custom-call bridge, ref: mpi4jax/_src/collective_ops/*.py and
_src/xla_bridge/*.pyx).  Here each op is a thin composition of ``jax.lax``
collectives, so:

- abstract eval, batching, and differentiation rules come from JAX itself
  (and were verified to match the reference's contracts — see tests);
- lowering emits native XLA collective HLO (AllReduce, AllGather, AllToAll,
  CollectivePermute) scheduled over ICI/DCN — no custom calls, no libmpi;
- "eager" execution outside a parallel region auto-wraps the op in a one-op
  ``shard_map`` over the comm's bound mesh — the analog of the reference's
  eager path through ``xla.apply_primitive`` (ref _src/utils.py:34-35), with
  the convention that a global array's leading axis indexes ranks.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.comm import Comm
from ..parallel.region import (
    RegionContext,
    _region_stack,
    in_parallel_region,
    resolve_comm,
)
from ..utils.debug import get_logging, get_runtime_tracing, op_scope
from ..utils.dtypes import check_dtype

# the trace-time collective verifier and the telemetry layer ride the same
# single dispatch point as resilience and the algorithm selector (imported
# last: analysis and telemetry.core only depend on utils.config, so the
# package import order stays acyclic); the fusion deferral layer hooks the
# same point (flush-on-dispatch preserves program order)
from ..analysis import hook as _analysis
from ..telemetry import core as _telemetry
from . import _fusion


class Op(enum.Enum):
    """Reduction operations (replaces MPI.Op handles, ref _src/utils.py:141-145).

    SUM/MIN/MAX lower to native ``psum``/``pmin``/``pmax`` HLO; the rest
    lower to a log-depth doubling butterfly over ``CollectivePermute``
    (O(log n) depth and per-rank bandwidth — see ``apply_allreduce``).  A
    Python callable ``f(a, b)`` is also accepted anywhere an ``Op`` is —
    the analog of user-defined MPI ops, which the reference could only
    pass through to libmpi.  Callables must be associative (MPI's
    contract); commutativity is not required.
    """

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"
    LAND = "land"
    LOR = "lor"
    LXOR = "lxor"
    BAND = "band"
    BOR = "bor"
    BXOR = "bxor"


SUM = Op.SUM
PROD = Op.PROD
MIN = Op.MIN
MAX = Op.MAX
LAND = Op.LAND
LOR = Op.LOR
LXOR = Op.LXOR
BAND = Op.BAND
BOR = Op.BOR
BXOR = Op.BXOR

OpLike = Union[Op, Callable]

# ops with a dedicated XLA collective
_NATIVE_COLLECTIVE = {
    Op.SUM: lax.psum,
    Op.MAX: lax.pmax,
    Op.MIN: lax.pmin,
}

_LOCAL_COMBINE = {
    Op.SUM: jnp.add,
    Op.PROD: jnp.multiply,
    Op.MIN: jnp.minimum,
    Op.MAX: jnp.maximum,
    Op.LAND: jnp.logical_and,
    Op.LOR: jnp.logical_or,
    Op.LXOR: jnp.logical_xor,
    Op.BAND: jnp.bitwise_and,
    Op.BOR: jnp.bitwise_or,
    Op.BXOR: jnp.bitwise_xor,
}


def reduction_name(op) -> str:
    """Static display name of a reduction for the trace-time verifier's
    event stream (``mpi4jax_tpu/analysis``)."""
    if isinstance(op, Op):
        return op.value
    return getattr(op, "__name__", "callable")


def combine_fn(op: OpLike) -> Callable:
    if isinstance(op, Op):
        return _LOCAL_COMBINE[op]
    if callable(op):
        return op
    raise TypeError(
        f"op must be an mpi4jax_tpu.Op or a binary callable, got {op!r}"
    )


def _comm_groups(comm: Comm):
    """Static group member lists (global ranks, group order): a whole-axes
    comm is one group of everyone."""
    if comm.groups is not None:
        return comm.groups
    return (tuple(range(comm.Get_size())),)


def _comm_pos_size(comm: Comm):
    """(group position, group size) of the calling rank — a traced pair on
    a color split (static table lookups, cached on the ``GroupComm`` at
    construction instead of rebuilt per collective trace), (traced, static
    int) otherwise."""
    if comm.groups is None:
        return comm.Get_rank(), comm.Get_size()
    table = comm.group_size_table()
    return comm.Get_rank(), jnp.asarray(table)[comm.global_rank()]


def _permute_axis(comm: Comm):
    """ppermute axis argument: linearized row-major over multi-axis comms
    (the same rank order ``Get_rank`` defines)."""
    axes = comm.axes
    return axes[0] if len(axes) == 1 else axes


def apply_doubling_bcast(xl, comm: Comm, root: int):
    """Log-depth broadcast from each group's ``root`` over ppermute rounds.

    Round ``t`` doubles the covered span: positions (relative to root,
    wrapped) ``[0, 2^t)`` hold the value and send to ``[2^t, 2^{t+1})``.
    ``ceil(log2 k)`` rounds, one message per rank per round — O(log k)
    per-rank bandwidth vs O(world) for an AllGather-based group broadcast.
    where-select (not multiply-by-mask) so non-participant payloads — the
    zeros ppermute delivers to pair-less ranks, or NaN/Inf garbage on
    non-root ranks — never poison the result.
    """
    groups = _comm_groups(comm)
    # ``members[(root + p) % kk]`` below would silently wrap an out-of-range
    # root into a *different* group position and misroute every round; fail
    # loudly here instead.  (bcast validates against ``comm.min_size()``
    # before dispatch, but this helper is callable on its own.)
    kmin = min(len(g) for g in groups)
    if not 0 <= root < kmin:
        from ..analysis.report import mpx_error

        raise mpx_error(
            ValueError, "MPX105",
            f"apply_doubling_bcast: root {root} out of range for the "
            f"smallest group (size {kmin}); root must be a valid group "
            "position in every group",
        )
    kmax = max(len(g) for g in groups)
    if kmax == 1:
        return xl
    pos, k = _comm_pos_size(comm)
    relpos = (pos - root) % k
    acc = xl
    axis = _permute_axis(comm)
    w = 1
    while w < kmax:
        perm = [
            (members[(root + p) % kk], members[(root + p + w) % kk])
            for members in groups
            if (kk := len(members)) > w
            for p in range(min(w, kk - w))
        ]
        recvd = lax.ppermute(acc, axis, perm)
        got = (relpos >= w) & (relpos < 2 * w)
        acc = jnp.where(got, recvd, acc)
        w *= 2
    return acc


def apply_allreduce(x, op: OpLike, comm: Comm):
    """All-reduce ``x`` over ``comm`` with reduction ``op``.

    Whole-axes comm, SUM/MIN/MAX: one native AllReduce HLO.  Every other
    case — PROD/logical/bitwise/callable ops, and ALL ops on a color-split
    comm (``axis_index_groups`` is unavailable under shard_map, see
    ``Comm.Split``) — picks per call between two CollectivePermute
    lowerings (``_algos.resolve_algo``, forced via
    ``MPI4JAX_TPU_COLLECTIVE_ALGO``):

    - the log-depth doubling **butterfly** (``apply_butterfly_allreduce``):
      ``2·ceil(log2 k)`` rounds shipping the FULL payload —
      latency-optimal, O(size·log k) bytes per rank;
    - the **ring** (``_algos.apply_ring_allreduce``): ``2·(k-1)`` rounds
      shipping one CHUNK (``size/k``) — bandwidth-optimal,
      ~``2·(k-1)/k·size`` bytes per rank, the win for large payloads
      (gradient buckets, halo frames).

    On a multi-host comm (derivable host topology spanning ``h > 1``
    hosts with uniform contiguous blocks — ``_hierarchy.hier_plan``),
    ``auto`` instead picks the two-level **hierarchical** lowering above
    the ring crossover: intra-host ring reduce-scatter over ICI →
    inter-host allreduce of the shards over DCN → intra-host allgather
    (``MPI4JAX_TPU_COLLECTIVE_ALGO=hier`` forces it; docs/topology.md).

    All three preserve the deterministic ascending group-rank fold for
    associative non-commutative callables; the ring and hierarchical
    paths additionally require an elementwise callable and a uniform
    static group size (see the ``_algos`` module docstring), so ``auto``
    only routes enum ``Op``s on uniform groups to them.
    """
    from . import _algos, _hierarchy
    from ..utils.config import collective_algo

    axes = comm.axes
    x = as_varying(x, axes)
    algo = collective_algo()
    if (algo == "auto" and comm.groups is None and isinstance(op, Op)
            and op in _NATIVE_COLLECTIVE):
        _analysis.annotate(algo="native")
        _telemetry.annotate(algo="native")
        return _NATIVE_COLLECTIVE[op](x, axes)
    k = _algos.static_group_size(comm)
    chunk_ok = isinstance(op, Op) or algo in ("ring", "hier")
    ring_ok = k is not None and k > 1 and (
        isinstance(op, Op) or algo == "ring"  # auto never chunks callables
    )
    plan = _hierarchy.hier_plan(comm) if k is not None and k > 1 else None
    nbytes = x.size * x.dtype.itemsize
    algo = _algos.resolve_algo(algo, nbytes, k or 1, ring_ok,
                               hier_ok=plan is not None and chunk_ok)
    # the annotation's plan is gated on chunk_ok too: a callable under
    # ``auto`` can never route to the hierarchy, so MPX113 must not
    # advise a choice that does not exist for this call
    _hierarchy.annotate_selection("allreduce", algo, nbytes, k or 1,
                                  plan if chunk_ok else None,
                                  comm, preserve=not isinstance(op, Op),
                                  op=op, dtype=x.dtype.name)
    if algo == "hier":
        return _hierarchy.apply_hier_allreduce(x, op, comm, plan)
    if algo == "ring":
        return _algos.apply_ring_allreduce(x, op, comm, k)
    return apply_butterfly_allreduce(x, op, comm)


def apply_butterfly_allreduce(x, op: OpLike, comm: Comm):
    """Log-depth doubling-butterfly allreduce: ``ceil(log2 k)`` suffix-fold
    rounds + a log-depth broadcast over CollectivePermute, O(log k) depth
    and O(size·log k) per-rank bytes (the round-3/4 lowering was AllGather
    + an O(world)-unrolled fold — O(world) bandwidth AND an O(world)
    serial dependency chain per call, which falls over at pod scale; see
    tests/test_scale.py's 64-device budget).  Works on ANY partition,
    unequal color-split groups included.

    The suffix fold combines in ascending group-rank order with plain
    associativity — no commutativity or identity element required, so
    arbitrary non-commutative callables keep MPI's deterministic
    same-result-everywhere contract (every rank receives group-position
    0's fold via the broadcast).
    """
    x = as_varying(x, comm.axes)
    fn = combine_fn(op)
    groups = _comm_groups(comm)
    kmax = max(len(g) for g in groups)
    if kmax == 1:
        return x
    pos, k = _comm_pos_size(comm)
    axis = _permute_axis(comm)
    # suffix-window doubling: after round t, acc at group position p folds
    # positions [p, min(p + 2^t, k)) in ascending order
    acc = x
    w = 1
    while w < kmax:
        perm = [
            (members[p + w], members[p])
            for members in groups
            for p in range(len(members) - w)
        ]
        recvd = lax.ppermute(acc, axis, perm)
        combine = pos + w < k
        acc = jnp.where(combine, fn(acc, recvd), acc)
        w *= 2
    # group position 0 now holds the full fold; distribute it
    return apply_doubling_bcast(acc, comm, 0)


def linear_rank(comm: Comm):
    return comm.Get_rank()


# ---------------------------------------------------------------------------
# eager wrapping
# ---------------------------------------------------------------------------


def varying(x, *, comm: Optional[Comm] = None):
    """Public helper: re-type a replicated value as rank-varying.

    Collective results (``allreduce``/``bcast``/…) are *replicated-typed* in
    JAX's collective type system — that typing is what gives the reference's
    transpose contract.  Structured control flow (``lax.while_loop`` /
    ``scan`` carries) requires stable types, so a carry that passes through a
    collective must be re-typed with this helper.  See docs/sharp_bits.md.
    """
    comm = resolve_comm(comm)
    # deferred fusion/overlap results materialize here: re-typing is a use
    return jax.tree.map(
        lambda v: as_varying(_fusion.materialize_value(v), comm.axes), x
    )


def as_varying(x, axes: Tuple[str, ...]):
    """Promote a replicated-typed value to varying over ``axes`` (VMA typing).

    JAX's variant/invariant collective typing requires ``psum`` inputs to be
    *varying* over the reduced axes; fresh trace constants (e.g. tangents of
    ``ones``) are replicated.  No-op for axes already varying.
    """
    vma = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(a for a in axes if a not in vma)
    if not missing:
        return x
    return lax.pcast(x, missing, to="varying")


def _mpi_opname(opname: str) -> str:
    return "MPI_" + opname.capitalize()


# Call ids pair begin/end hooks and watchdog arm/disarm across one dispatch.
# A module-level monotonic counter (hoisted out of ``_run_body``, which runs
# on EVERY traced collective when tracing or resilience is on) — unique per
# process, which is all the FIFO-aliasing registries require; 8 hex chars to
# match the historical ``secrets.token_hex(4)`` format in log lines.
import itertools

_call_id_counter = itertools.count()


def _next_call_id() -> str:
    return f"{next(_call_id_counter) & 0xFFFFFFFF:08x}"


def _run_body(opname: str, comm: Comm, body, arrays, token, bare=False):
    """Run an op body, bracketed by the instrumentation every op shares:

    - native runtime begin/end hooks when tracing is on (host-side log +
      measured per-op wall-clock latency; see mpi4jax_tpu/native.py);
    - the resilience plan when any resilience feature is on (fault
      injection, numeric guards, collective watchdog; see
      mpi4jax_tpu/resilience/runtime.py) — this is the single dispatch
      point that makes all 12 ops injectable/guardable without per-op code;
    - the telemetry record and, in the ``events`` tier, the journal
      begin/end bracket (mpi4jax_tpu/telemetry/) — counters are pure
      host-side bookkeeping (no graph change); the events bracket threads
      journal callbacks with the same data dependencies as the trace
      hooks.

    Data dependencies pin everything around the collective: inputs are tied
    after ``op_begin``/fault probe/watchdog arm/journal begin, and
    ``op_end``/watchdog disarm/output guards/journal end are tied to the
    first output.  The journal begin sits AFTER the resilience probe so an
    injected straggler delay shows up as late *arrival* — exactly what the
    cross-rank skew column attributes.  With tracing off, every resilience
    feature off, and telemetry off or counters-only (the default is off)
    the body's traced program is untouched — the lowered HLO is
    byte-identical to an uninstrumented build (pinned by
    tests/test_resilience.py and tests/test_telemetry.py).

    ``bare=True`` keeps only the telemetry counter record: the async
    ``*_start``/``*_wait`` ops (ops/_async.py) carry their own
    pair-SPANNING resilience/trace/journal instrumentation (watchdog armed
    at start, disarmed at wait), which per-phase bracketing here would
    double-instrument."""
    from .. import native
    from ..resilience import runtime as _resilience
    from ..telemetry import bracket as _tbracket

    plan = None if bare else _resilience.plan_for(opname)
    tracing = (not bare) and get_runtime_tracing() \
        and native.runtime_tracing_supported()
    rec = _telemetry.open_op(opname, comm, arrays)
    if plan is None and not tracing and rec is None:
        return body(comm, arrays, token)

    try:
        call_id = _next_call_id()
        name = _mpi_opname(opname)
        ebr = None if bare else _tbracket.bracket_for(rec)
        if plan is not None:
            arrays, token = plan.before(name, call_id, comm, arrays, token)
        if ebr is not None:
            arrays, token = ebr.begin(call_id, comm, arrays, token)
        if tracing:
            # computed only when consumed: a dangling axis_index equation
            # would break the counters-mode HLO byte-identity pin
            rank = comm.Get_rank()
            begin = native.op_begin(name, call_id, rank, "")
            arrays = tuple(native._tie(a, begin) for a in arrays)
        out = body(comm, arrays, token)
        results = [r for r in out if r is not None]
        dep = results[0]
        from .token import Token

        if isinstance(dep, Token):
            dep = dep.value
        if tracing:
            native.op_end(name, call_id, rank, dep)
        if ebr is not None:
            ebr.end(call_id, comm, dep)
        if plan is not None:
            plan.after(name, call_id, comm, dep, results)
    except BaseException:
        _telemetry.abort_op(rec)
        raise
    _telemetry.close_op(rec)
    return out


# eager-mode compiled programs, keyed by
# (opname, mesh, comm uid, op-specific statics, observability flags) — the
# analog of jax caching `xla.apply_primitive` per primitive+params (ref
# _src/utils.py:34-35).  jit itself handles shape/dtype/token-structure
# retraces within one entry.  LRU-bounded: callers may produce unbounded
# distinct keys (e.g. many routing patterns), and each entry pins a
# compiled executable plus its mesh.
from collections import OrderedDict

_eager_cache: "OrderedDict" = OrderedDict()
_EAGER_CACHE_MAX = 128

# hit/miss/eviction accounting: _EAGER_CACHE_MAX eviction used to be
# silent, making cache thrash (many distinct routing patterns cycling 128
# entries) invisible.  Mirrored into the telemetry meters when telemetry
# is on; always available via cache_stats().
_eager_cache_stats = {"hits": 0, "misses": 0, "evictions": 0}


# ---------------------------------------------------------------------------
# the dispatch fast path
# ---------------------------------------------------------------------------
#
# BENCH_r05.json measured dispatch_overhead_s at ~14% of the shallow-water
# wall: the cache-HIT path was re-parsing ~10 environment flags (float,
# choice, and fault-spec grammars) and re-hashing the full key tuple on
# every call.  Two memos remove that:
#
# - ``_dynamic_state()``: the flag-derived half of the cache key, parsed
#   once per configuration *stamp* (utils/config.config_stamp: programmatic
#   epoch + raw env fingerprint — one dict read per flag, no parsing);
# - ``_eager_prefix()``: the per-(op, comm, statics) half, interned with a
#   precomputed hash so a hit hashes two cached objects instead of
#   re-hashing mesh + statics.
#
# Toggling any flag (env or ``set_*``) changes the stamp, rebuilds the
# token, and misses the program cache — exactly the retrace-on-toggle
# contract the flat keys gave, at O(1) parse cost per toggle instead of
# per call.


class _Interned:
    """Hash-once wrapper for memoized cache-key halves.  Equality falls
    back to the wrapped key so logically-equal rebuilt wrappers (e.g.
    after ``clear_caches``) still match."""

    __slots__ = ("key", "_hash")

    def __init__(self, key):
        self.key = key
        self._hash = hash(key)

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return self is other or (
            isinstance(other, _Interned) and self.key == other.key
        )


_dyn_cell: list = [None, None, True, True]


def _dynamic_state():
    """``(interned flag token, analysis_off, telemetry_off)`` for the
    current configuration — every dynamically-read flag that shapes a
    trace, parsed only when the config stamp moves."""
    from ..utils import config as _config

    stamp = _config.config_stamp()
    if _dyn_cell[0] != stamp:
        from ..resilience.runtime import cache_token as resilience_token
        from ..utils.config import prefer_notoken
        from . import _async
        from ._algos import algo_cache_token

        tok = (get_runtime_tracing(), get_logging(), prefer_notoken(),
               resilience_token(), algo_cache_token(),
               _analysis.analysis_cache_token(),
               _telemetry.telemetry_cache_token(),
               _fusion.fusion_cache_token(),
               _async.overlap_cache_token())
        # publish the stamp LAST: a concurrent reader must never see the
        # new stamp paired with the previous token/gates
        _dyn_cell[1] = _Interned(tok)
        _dyn_cell[2] = _analysis.effective_mode() == "off"
        _dyn_cell[3] = _telemetry.effective_mode() == "off"
        _dyn_cell[0] = stamp
    return _dyn_cell[1], _dyn_cell[2], _dyn_cell[3]


def dynamic_cache_token() -> "_Interned":
    """The flag half of every compiled-program cache key (shared with the
    spmd program cache in parallel/region.py)."""
    return _dynamic_state()[0]


# LRU-bounded like the program cache it serves: callers may produce
# unbounded distinct static keys (many routing patterns), and each memo
# entry pins a mesh reference.  Sized above _EAGER_CACHE_MAX so every
# live program's prefix stays memoized.
_eager_prefix_memo: "OrderedDict" = OrderedDict()
_PREFIX_MEMO_MAX = 256


def _eager_prefix(opname: str, comm: Comm, static_key):
    """Interned ``(opname, mesh, comm uid, statics)`` key half + the
    comm's PartitionSpec, built once per (op, comm, statics).  The memo
    entry pins the mesh it was built against: re-binding a comm to a new
    mesh rebuilds (identity check, no hashing)."""
    k = (opname, comm.uid, static_key)
    ent = _eager_prefix_memo.get(k)
    if ent is not None and ent[0] is comm.mesh:
        _eager_prefix_memo.move_to_end(k)
        return ent[1], ent[2]
    axes_spec = P(comm.axes if len(comm.axes) > 1 else comm.axes[0])
    prefix = _Interned((opname, comm.mesh, comm.uid, static_key))
    _eager_prefix_memo[k] = (comm.mesh, prefix, axes_spec)
    if len(_eager_prefix_memo) > _PREFIX_MEMO_MAX:
        _eager_prefix_memo.popitem(last=False)
    return prefix, axes_spec


def cache_stats() -> dict:
    """Compiled-program cache accounting, all tiers in one call:

    - the eager one-op cache: ``{"hits", "misses", "evictions",
      "size"}`` — ``misses`` counts cacheable dispatches that compiled
      a new program (uncacheable dispatches — e.g. a Status out-param —
      count neither way); a high eviction rate means the working set
      exceeds the LRU bound and eager calls are recompiling in cycles;
    - ``"aot"``: the pinning layer (``mpx.compile`` — pins, pinned
      calls, MPX129 stale refusals, disk loads vs fresh compiles);
    - ``"disk_cache"``: the persistent tier
      (``MPI4JAX_TPU_COMPILE_CACHE_DIR`` — hits/misses/writes/
      evictions/bytes plus the on-disk entry count), the before/after
      evidence for cold-start behavior (docs/aot.md).

    Reset by ``clear_caches()`` (on-disk artifacts are untouched).
    """
    out = dict(_eager_cache_stats, size=len(_eager_cache))
    from ..aot import stats as _aot_stats

    out.update(_aot_stats())
    return out


def _bump_cache_stat(name: str, telemetry_off: bool = False) -> None:
    _eager_cache_stats[name] += 1
    if not telemetry_off:
        _telemetry.meter(f"eager_cache.{name}")


def clear_caches() -> None:
    """Drain the eager one-op compiled-program cache (resetting its
    hit/miss/eviction stats) and the memoized ``mpx.analyze`` reports.

    Each eager entry pins a compiled executable plus its mesh; call this
    after retiring a mesh, or when flipping a trace-shaping environment
    variable mid-process by hand (the knobs this library reads —
    ``MPI4JAX_TPU_COLLECTIVE_ALGO``, the resilience flags,
    ``MPI4JAX_TPU_ANALYZE``, ``MPI4JAX_TPU_TELEMETRY``, tracing/logging —
    are already folded into the cache key, so toggling them retraces
    without an explicit clear).  ``spmd``-decorated functions hold their
    own per-function program caches keyed the same way; they are dropped
    with the function object.
    """
    _eager_cache.clear()
    _eager_prefix_memo.clear()
    _dyn_cell[0] = None
    for k in _eager_cache_stats:
        _eager_cache_stats[k] = 0
    _analysis.clear_analysis_caches()
    from ..aot import reset_stats as _aot_reset

    _aot_reset()


def group_select_gather(comm: Comm, xl):
    """AllGather over the comm's FULL mesh axes, then select this rank's
    group members in group order: output ``(group_size, *xl.shape)``.

    The shared first step of every gather-family group lowering on a
    color-split comm (uniform group sizes only — ``my_group_members``
    raises the clear error otherwise)."""
    full = lax.all_gather(xl, comm.axes, axis=0, tiled=False)
    return jnp.take(full, comm.my_group_members(), axis=0)


def check_global_shape(opname: str, a, size: int) -> None:
    """Validate the eager global-array convention: leading axis = ranks."""
    if getattr(a, "ndim", 0) == 0 or a.shape[0] != size:
        raise ValueError(
            f"{opname} (eager): expected a global array with leading rank "
            f"axis of size {size} (global[r] = rank r's value); got shape "
            f"{getattr(a, 'shape', None)}. Inside a parallel region, pass "
            "rank-local arrays instead."
        )


def dispatch(opname: str, comm: Optional[Comm], body, arrays, token,
             static_key: Optional[tuple] = None,
             ana: Optional[dict] = None, bare: bool = False):
    """Run op ``body`` either inline (inside a parallel region) or eagerly.

    ``body(comm, arrays, token) -> (outputs..., token)`` operates on
    rank-local values.  In eager mode (outside any region), ``arrays`` are
    global arrays whose leading axis indexes ranks — ``global[r]`` is rank
    ``r``'s local value — and the op is wrapped in a one-op jitted
    ``shard_map`` over the comm's mesh: the analog of the reference's eager
    path through ``xla.apply_primitive`` (ref _src/utils.py:34-35).  Outputs
    use the same convention, so eager results have shape
    ``(size, *local_out_shape)``.

    ``ana`` is the op's static structure as the trace-time verifier sees
    it (root, tag, reduction, ... — mpi4jax_tpu/analysis/): every op that
    flows through this dispatch point is recorded when ``mpx.analyze`` or
    ``MPI4JAX_TPU_ANALYZE`` is active, and recording is pure host-side
    bookkeeping — the traced program (and thus the HLO) is untouched.
    """
    comm = resolve_comm(comm)
    # a dispatch that reaches this point does not join the fusion queue:
    # drain it first so the fused collectives keep their program position,
    # and force any deferred results used as inputs
    if _region_stack:
        _fusion.flush_pending(_region_stack[-1])
    arrays = tuple(_fusion.materialize_value(a) for a in arrays)
    for a in arrays:
        check_dtype(a, opname)
    fused_ana = _fusion.take_pending_ana()
    if fused_ana is not None:
        ana = {**(ana or {}), **fused_ana}
    if in_parallel_region(comm):
        # a pending tokenless barrier (see RegionContext.pending_sync) is
        # folded into this op's token so the op is ordered after it
        ctx = _region_stack[-1] if _region_stack else None
        if ctx is not None and ctx.pending_sync is not None:
            sync = ctx.pending_sync
            ctx.pending_sync = None
            from .token import Token, tie

            token = sync if token is None else Token(tie(sync, token.value))
            # tie the op inputs directly too: consume() may be disabled by
            # MPI4JAX_TPU_PREFER_NOTOKEN, but barrier ordering must hold
            arrays = tuple(tie(sync, a) for a in arrays)
        # promote replicated trace-constants to rank-varying once, centrally,
        # so every op accepts them (collectives are variant->invariant typed)
        arrays = tuple(as_varying(a, comm.axes) for a in arrays)
        with op_scope(opname):
            evt = _analysis.begin_event(opname, comm, arrays, token, ana, ctx)
            try:
                out = _run_body(opname, comm, body, arrays, token, bare=bare)
            except BaseException:
                if evt is not None:
                    _analysis.abort_event(evt)
                raise
            if evt is not None:
                _analysis.end_event(evt, out)
            return out

    if comm.mesh is None:
        raise RuntimeError(
            f"{opname}: called outside a parallel region with an unbound "
            "communicator. Either call inside mpi4jax_tpu.spmd / "
            "jax.shard_map, or bind the comm to a mesh (comm.bind(mesh))."
        )

    size = comm.world_size()
    for a in arrays:
        check_global_shape(opname, a, size)

    # ``static_key`` lists every closure value of ``body`` that shapes the
    # trace; ``None`` marks the call uncacheable (e.g. a Status out-param
    # that must be filled at trace time)
    cache_key = None
    dyn, analysis_off, telemetry_off = _dynamic_state()
    if static_key is not None and analysis_off and not _analysis.recording():
        # an active mpx.analyze recorder — or the ambient warn/error mode —
        # bypasses the cache entirely: a cache hit would skip tracing,
        # tracing is when events are recorded, and queue-state-dependent
        # findings (MPX110) can differ between calls that share a program.
        # Both key halves are memoized with precomputed hashes (see "the
        # dispatch fast path" above): a hit re-parses no flags and
        # re-hashes no mesh/statics.
        prefix, axes_spec = _eager_prefix(opname, comm, static_key)
        cache_key = (prefix, dyn)
        cached = _eager_cache.get(cache_key)
        if cached is not None:
            _eager_cache.move_to_end(cache_key)
            _bump_cache_stat("hits", telemetry_off)
            sm_hit, tele_cell = cached
            if telemetry_off:
                results, tok_out = sm_hit(tuple(arrays), token)
                return (*results, tok_out)
            # dispatch runs per call even on a hit, so the eager tier
            # counts per call — from the entry's stash for THIS call's
            # signature (jit retraces per signature; each retrace lands
            # its records under its own signature inside capture_eager)
            sig = _telemetry.call_signature(arrays)
            with _telemetry.capture_eager(tele_cell, sig):
                results, tok_out = sm_hit(tuple(arrays), token)
            _telemetry.count_eager_call(tele_cell, sig)
            return (*results, tok_out)
        _bump_cache_stat("misses", telemetry_off)
        if not telemetry_off:
            _telemetry.meter(f"recompiles.eager.{opname}")
    else:
        axes_spec = P(comm.axes if len(comm.axes) > 1 else comm.axes[0])

    def wrapped(arrs, tok):
        ctx = RegionContext(comm)
        _analysis.arm_context(ctx)
        _region_stack.append(ctx)
        try:
            with op_scope(opname):
                # shard_map hands us (1, *local); body wants (*local,)
                locals_ = tuple(a[0] for a in arrs)
                evt = _analysis.begin_event(opname, comm, locals_, tok, ana,
                                            ctx, eager=True)
                try:
                    out = _run_body(opname, comm, body, locals_, tok,
                                    bare=bare)
                except BaseException:
                    if evt is not None:
                        _analysis.abort_event(evt)
                    raise
                if evt is not None:
                    _analysis.end_event(evt, out)
            ctx.check_drained()
            _analysis.finish_context(ctx, f"eager {opname}")
        finally:
            _region_stack.pop()
        *results, tok_out = out
        if tok_out is not None:
            # make the global token replicated (and dependent on every
            # rank's completion) so it round-trips through out_specs=P()
            from .token import Token

            tok_out = Token(lax.psum(as_varying(tok_out.value, comm.axes), comm.axes))
        return tuple(r[None] for r in results), tok_out

    sm = jax.jit(jax.shard_map(
        wrapped,
        mesh=comm.mesh,
        in_specs=(tuple(axes_spec for _ in arrays), P()),
        out_specs=(axes_spec, P()),
    ))
    # insert into the cache only after the first call succeeds — a
    # trace/compile failure must not leave a broken entry to be replayed
    tele_cell = _telemetry.EagerCell()
    if telemetry_off:
        results, tok_out = sm(tuple(arrays), token)
    else:
        sig = _telemetry.call_signature(arrays)
        with _telemetry.capture_eager(tele_cell, sig):
            results, tok_out = sm(tuple(arrays), token)
        _telemetry.count_eager_call(tele_cell, sig)
    if cache_key is not None:
        _eager_cache[cache_key] = (sm, tele_cell)
        if len(_eager_cache) > _EAGER_CACHE_MAX:
            _eager_cache.popitem(last=False)
            _bump_cache_stat("evictions")
    return (*results, tok_out)
