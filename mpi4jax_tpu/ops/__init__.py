"""The 13 communication primitives.

TPU-native re-design of ref mpi4jax/_src/collective_ops/ — the reference's
12 ops with the same shape/autodiff contracts (divergences documented
per-module) plus ``reduce_scatter`` (MPI_Reduce_scatter_block, which the
reference lacks), and every op lowers to native XLA collective HLO over
ICI/DCN instead of custom-calling into libmpi.
"""

from ._base import (  # noqa: F401
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    PROD,
    SUM,
    Op,
    OpLike,
    cache_stats,
    clear_caches,
    varying,
)
from ._async import (  # noqa: F401
    AsyncHandle,
    P2PHandle,
    allreduce_start,
    allreduce_wait,
    alltoall_start,
    alltoall_wait,
    overlap,
    p2p_wait,
    recv_start,
    reduce_scatter_start,
    reduce_scatter_wait,
    send_start,
)
from ._fusion import set_fusion_mode  # noqa: F401
from .allgather import allgather  # noqa: F401
from .allreduce import allreduce  # noqa: F401
from .alltoall import alltoall  # noqa: F401
from .barrier import barrier  # noqa: F401
from .bcast import bcast  # noqa: F401
from .gather import gather  # noqa: F401
from .recv import recv  # noqa: F401
from .reduce import reduce  # noqa: F401
from .reduce_scatter import reduce_scatter  # noqa: F401
from .scan import scan  # noqa: F401
from .scatter import scatter  # noqa: F401
from .send import send  # noqa: F401
from .sendrecv import sendrecv  # noqa: F401
from .status import Status  # noqa: F401
from .token import Token, create_token  # noqa: F401
