"""send: point-to-point send half.

TPU-native re-design of ref mpi4jax/_src/collective_ops/send.py (blocking
send; returns token only, ref send.py:41, abstract :193-194).

Under SPMD there is no per-process program to block in — a matched
send/recv pair IS one CollectivePermute.  ``send`` therefore *records* the
payload and routing in the region's matching queue (keyed by (comm, tag),
FIFO per key — MPI's non-overtaking rule within a comm/tag channel); the
matching ``recv`` emits the fused CollectivePermute.  Ordering notes:

- matching is positional per (comm, tag) within one traced program, which is
  exactly MPI message ordering for deterministic programs;
- the returned token is tied to the payload, and the *recv side's* token is
  tied to the actual transfer;
- a send left unmatched at region end raises (see RegionContext.check_drained)
  — the SPMD analog of the reference's deadlock-on-unmatched-send, converted
  from a hang into a trace-time error.
"""

from typing import NamedTuple, Optional, Tuple

from ..parallel.comm import Comm
from ..parallel.rankspec import normalize_dest
from ..parallel.region import current_context
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import dispatch
from .token import Token, consume, produce


class PendingSend(NamedTuple):
    value: object
    pairs: Tuple[Tuple[int, int], ...]
    token: Optional[Token]


@enforce_types(tag=int, comm=(Comm, None), token=(Token, None))
def send(x, dest, tag: int = 0, *, comm: Optional[Comm] = None,
         token: Optional[Token] = None) -> Token:
    """Send ``x`` along routing ``dest`` (see parallel/rankspec.py).

    Must be matched by a ``recv`` on the same comm and tag later in the same
    parallel region.  Returns a token (ref API: send.py:41-79).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        pairs = normalize_dest(dest, size, what="send")
        xl = consume(token, xl)
        log_op("MPI_Send", comm.Get_rank(),
               f"{xl.size} items along {list(pairs)} (tag {tag})")
        ctx = current_context()
        ctx.queue(comm.uid, tag).append(PendingSend(xl, pairs, token))
        return (produce(token, xl),)

    # NOTE: send cannot run standalone in eager mode (the matching recv would
    # be in a different one-op program) — dispatch's drained-queue check
    # raises a clear error; use sendrecv or an spmd region for eager p2p.
    out = dispatch("send", comm, body, (x,), token)
    return out[0]
