"""send: point-to-point send half.

TPU-native re-design of ref mpi4jax/_src/collective_ops/send.py (blocking
send; returns token only, ref send.py:41, abstract :193-194).

Under SPMD there is no per-process program to block in — a matched
send/recv pair IS one CollectivePermute.  ``send`` therefore *records* the
payload and routing in the region's matching queue (keyed by (comm, tag),
FIFO per key — MPI's non-overtaking rule within a comm/tag channel); the
matching ``recv`` emits the fused CollectivePermute.  Ordering notes:

- matching is positional per (comm, tag) within one traced program, which is
  exactly MPI message ordering for deterministic programs;
- the returned token is tied to the payload, and the *recv side's* token is
  tied to the actual transfer;
- a send left unmatched at region end raises (see RegionContext.check_drained)
  — the SPMD analog of the reference's deadlock-on-unmatched-send, converted
  from a hang into a trace-time error.

Standalone *eager* use (outside any region) works by **deferred pairing**:
the send queues its (global) payload and routing host-side and returns
immediately — buffered-send (MPI_Bsend-like) semantics, where the
reference's eager send blocks until delivery (ref send.py:41-79) — and the
matching eager ``recv`` emits the fused one-CollectivePermute program.  A
send still queued at ``flush()``/exit raises a clear error (the analog of
the reference's deadlock-on-unmatched-send at MPI_Finalize).
"""

from collections import deque
from typing import Dict, NamedTuple, Optional, Tuple

from ..parallel.comm import Comm
from ..parallel.rankspec import resolve_routing
from ..parallel.region import current_context, in_parallel_region, resolve_comm
from ..utils.debug import log_op
from ..utils.dtypes import check_dtype
from ..utils.validation import enforce_types
from ._base import check_global_shape, dispatch
from .token import Token, consume, create_token, produce


class PendingSend(NamedTuple):
    value: object
    pairs: Tuple[Tuple[int, int], ...]
    token: Optional[Token]


# eager (outside-any-region) deferred sends: (comm_uid, tag) -> FIFO of
# PendingSend whose ``value`` is a GLOBAL array (leading axis = ranks, the
# eager convention) and whose token slot is unused (ordering is carried by
# the recv-side program)
_eager_sends: Dict[Tuple[int, int], deque] = {}


def _eager_queue(comm_uid: int, tag: int) -> deque:
    return _eager_sends.setdefault((comm_uid, tag), deque())


def check_eager_drained() -> None:
    """Raise if any standalone eager send is still unmatched — called by
    ``flush()`` (and thus at interpreter exit)."""
    leftover = {k: len(q) for k, q in _eager_sends.items() if q}
    if leftover:
        from ..analysis.report import mpx_error

        raise mpx_error(
            RuntimeError, "MPX101",
            f"unmatched eager send(s) at flush/exit: "
            f"{{(comm_uid, tag): count}} = {leftover}. Every standalone "
            "eager send must be matched by an eager recv on the same comm "
            "and tag before flush/exit (deferred pairing: the transfer only "
            "happens at the recv; the reference's blocking send would "
            "deadlock here instead).",
        )


@enforce_types(tag=int, comm=(Comm, None), token=(Token, None))
def send(x, dest, tag: int = 0, *, comm: Optional[Comm] = None,
         token: Optional[Token] = None) -> Token:
    """Send ``x`` along routing ``dest`` (see parallel/rankspec.py).

    Inside a parallel region: must be matched by a ``recv`` on the same comm
    and tag later in the same region.  Standalone eager use queues the
    (global) payload for the matching eager ``recv`` — deferred pairing, see
    module docstring.  Returns a token (ref API: send.py:41-79).
    """
    c = resolve_comm(comm)
    if c.mesh is not None and not in_parallel_region(c):
        # standalone eager: defer — queue payload + routing, transfer at
        # recv.  Inside an outer jit/grad trace the queued payload is a
        # tracer; that is fine as long as the matching recv happens in the
        # SAME trace (e.g. grad through a send->recv pair) — a recv in a
        # later trace/eager context gets a clear staleness error
        # (ops/recv.py) instead of a leaked-tracer failure.
        check_dtype(x, "send")
        # global arrays span ALL ranks (world) even on a color-split comm;
        # the routing spec is comm-local (per-group on a split) and
        # resolves to GLOBAL pairs
        check_global_shape("send", x, c.world_size())
        pairs = resolve_routing(c, None, dest, what="send")
        log_op("MPI_Send", 0,
               f"deferred: {x.size // c.world_size()} items/rank along "
               f"{list(pairs)} (tag {tag})")
        _eager_queue(c.uid, tag).append(PendingSend(x, pairs, None))
        # buffered-send semantics: nothing has moved yet, so the returned
        # token orders nothing beyond what the caller already had
        return token if token is not None else create_token()

    def body(comm, arrays, token):
        from ..analysis.hook import annotate
        from ..analysis.schedule import concretizing

        (xl,) = arrays
        pairs = resolve_routing(comm, None, dest, what="send")  # GLOBAL
        annotate(pairs=pairs)
        xl = consume(token, xl)
        log_op("MPI_Send", comm.Get_rank(),
               f"{xl.size} items along {list(pairs)} (tag {tag})")
        if concretizing():
            # per-rank schedule trace (analysis/crossrank.py): record the
            # send one-sided — the cross-rank matcher pairs it with the
            # peer rank's recv; the region queue must stay empty so a
            # rank whose schedule legitimately holds only this side does
            # not trip the single-trace MPX101 drain check
            return (produce(token, xl),)
        ctx = current_context()
        ctx.queue(comm.uid, tag).append(PendingSend(xl, pairs, token))
        return (produce(token, xl),)

    out = dispatch("send", comm, body, (x,), token, ana={"tag": tag})
    return out[0]
