"""Traced wire codecs + error feedback for the DCN leg (opt-in).

The appliers here are the compressed twins of the inter-host phases in
``ops/_hierarchy.py`` — they run ONLY when ``MPI4JAX_TPU_COMPRESS``
(resolved per payload bucket by ``ops/_codec.codec_for``) selects a
codec for a float32 payload, and only on the DCN (inter) leg; every ICI
phase and every non-f32 dtype stays exact.  With the knob off this
module is never imported by a trace: HLO and cache tokens are
byte-identical to a build without it (pinned by tests/test_compress.py).

Two codecs (byte math in ``ops/_codec.py``, table in
docs/compression.md):

- ``bf16`` — cast-through: the inter-phase value is cast to bfloat16,
  the EXACT exchange algorithms run on the bf16 array (ring or
  butterfly, unchanged), and the result is cast back.  2x fewer wire
  bytes; reduction arithmetic happens in bf16 (Horovod's fp16
  compression semantics).  Valid for every enum ``Op`` — bf16 keeps
  fp32's exponent, so MIN/MAX/PROD survive the cast.
- ``fp8`` — per-chunk max-abs-scaled quantization to float8_e4m3fn
  (int8 symmetric fallback when the installed jax lacks the dtype):
  1 byte/element + one fp32 scale per ``FP8_CHUNK`` elements, ~3.7x
  fewer wire bytes.  fp8 has no usable reduction arithmetic, so the
  allreduce/reduce_scatter form is a butterfly whose every stage
  encodes -> ppermutes the (q, scale) pair -> decodes -> accumulates in
  float32; it is therefore SUM-only — any other enum op silently
  degrades to the bf16 cast-through (the annotation layer mirrors this
  downgrade).  Pure-routing legs (alltoall, bcast) quantize once and
  ship the (q, scale) pair.

**Error feedback** (1-bit-Adam-style EF, docs/compression.md): the
compressed allreduce is biased per step; ``ef_allreduce`` carries the
quantization residual in program state and re-adds it before the next
quantize, making the bias telescope away across steps.  With the codec
off the roundtrip is the identity and the residual stays exactly zero —
the examples call it unconditionally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import _algos, _codec

__all__ = [
    "fp8_wire_dtype",
    "encode_fp8",
    "decode_fp8",
    "roundtrip",
    "inter_allreduce",
    "inter_reduce_scatter",
    "inter_alltoall",
    "inter_bcast",
    "ef_zeros_like",
    "ef_allreduce",
    "ef_reshard",
]

FP8_CHUNK = _codec.FP8_CHUNK

# the wire dtype of the fp8 codec: float8_e4m3fn where the installed
# jax has it (max normal 448), else symmetric int8 (no pip installs —
# the fallback keeps the same 1 byte/element wire width and the same
# per-chunk-scale math, with round-to-nearest instead of e4m3 rounding)
_F8 = getattr(jnp, "float8_e4m3fn", None)
_QMAX = 448.0 if _F8 is not None else 127.0


def fp8_wire_dtype():
    """The dtype fp8-quantized elements ship as (float8_e4m3fn, or int8
    on a jax without it) — 1 byte/element either way."""
    return _F8 if _F8 is not None else jnp.int8


def _encode_rows(x2d):
    """Quantize a (rows, cols) float32 array per FP8_CHUNK-element chunk:
    returns ``(q, scale)`` with ``q`` shape (rows, nchunks, FP8_CHUNK)
    in the wire dtype and ``scale`` shape (rows, nchunks, 1) float32."""
    rows, cols = x2d.shape
    padded = -(-max(cols, 1) // FP8_CHUNK) * FP8_CHUNK
    xp = jnp.pad(x2d, ((0, 0), (0, padded - cols)))
    ch = xp.reshape(rows, padded // FP8_CHUNK, FP8_CHUNK)
    maxabs = jnp.max(jnp.abs(ch), axis=-1, keepdims=True)
    scale = jnp.where(maxabs > 0, maxabs / _QMAX, 1.0)
    scaled = ch / scale
    if _F8 is not None:
        q = scaled.astype(_F8)
    else:
        q = jnp.round(scaled).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _decode_rows(q, scale, cols):
    """Inverse of :func:`_encode_rows`: (rows, cols) float32."""
    ch = q.astype(jnp.float32) * scale
    return ch.reshape(ch.shape[0], -1)[:, :cols]


def encode_fp8(x):
    """Whole-array fp8 encode: ``(q, scale)`` for any-shape float32
    ``x`` (treated as one row of elements)."""
    return _encode_rows(x.reshape(1, -1))


def decode_fp8(q, scale, shape, n):
    """Whole-array fp8 decode back to ``shape`` (``n`` = element
    count of the original array)."""
    return _decode_rows(q, scale, n).reshape(shape)


def roundtrip(x, codec):
    """Quantize-dequantize one array through ``codec`` (None/"off" =
    identity) — the error the wire introduces, used by the EF update
    and the autotune/benchmark relative-error measurement."""
    if not codec or codec == "off":
        return x
    if codec == "bf16":
        return x.astype(jnp.bfloat16).astype(x.dtype)
    if codec == "fp8":
        q, s = encode_fp8(x)
        return decode_fp8(q, s, x.shape, x.size).astype(x.dtype)
    raise ValueError(f"unknown wire codec {codec!r}")


# ---------------------------------------------------------------------------
# compressed DCN-phase appliers (the _hierarchy inter-phase twins)
# ---------------------------------------------------------------------------


def _fp8_butterfly_allreduce(x, comm):
    """SUM allreduce over ``comm`` shipping (q, scale) pairs: the
    recursive-fold butterfly of ``_base.apply_butterfly_allreduce``
    with every stage's wire traffic quantized — accumulation stays in
    float32 on the receiving side."""
    from ._base import (_comm_groups, _comm_pos_size, _permute_axis,
                        apply_doubling_bcast, as_varying)

    x = as_varying(x, comm.axes)
    groups = _comm_groups(comm)
    kmax = max(len(g) for g in groups)
    pos, k = _comm_pos_size(comm)
    axis = _permute_axis(comm)
    acc = x
    w = 1
    while w < kmax:
        perm = [(members[p + w], members[p])
                for members in groups for p in range(len(members) - w)]
        q, s = encode_fp8(acc)
        rq = lax.ppermute(q, axis, perm)
        rs = lax.ppermute(s, axis, perm)
        recvd = decode_fp8(rq, rs, acc.shape, acc.size)
        combine = pos + w < k
        acc = jnp.where(combine, acc + recvd, acc)
        w *= 2
    # rank 0 of each group holds the full fold; broadcast it back out,
    # quantized once (every receiver decodes the same root value)
    q, s = encode_fp8(acc)
    q = apply_doubling_bcast(q, comm, 0)
    s = apply_doubling_bcast(s, comm, 0)
    return decode_fp8(q, s, acc.shape, acc.size)


def _effective(codec, op):
    """fp8 reduction arithmetic exists only for SUM: every other enum
    op degrades to the bf16 cast-through (annotate_selection mirrors
    this so the recorded codec is the one that actually ran)."""
    from ._base import SUM

    if codec == "fp8" and op is not None and op != SUM:
        return "bf16"
    return codec


def inter_allreduce(v, op, plan, shard_bytes, codec):
    """Compressed DCN allreduce phase (``_hierarchy._inter_allreduce``
    twin): ring/butterfly on a bf16 cast, or the fp8 per-stage
    butterfly for SUM."""
    from ._base import Op, apply_butterfly_allreduce

    codec = _effective(codec, op)
    if codec == "fp8":
        return _fp8_butterfly_allreduce(v, plan.inter).astype(v.dtype)
    v16 = v.astype(jnp.bfloat16)
    ring_ok = isinstance(op, Op)
    if _algos.resolve_dcn_algo(shard_bytes, plan.h, ring_ok) == "ring":
        out = _algos.apply_ring_allreduce(v16, op, plan.inter, plan.h)
    else:
        out = apply_butterfly_allreduce(v16, op, plan.inter)
    return out.astype(v.dtype)


def inter_reduce_scatter(blocks, op, plan, codec):
    """Compressed DCN reduce-scatter phase
    (``_hierarchy._inter_reduce_scatter`` twin)."""
    from ._base import apply_butterfly_allreduce

    codec = _effective(codec, op)
    h = plan.h
    if codec == "fp8":
        full = _fp8_butterfly_allreduce(blocks, plan.inter)
        return jnp.take(full, plan.inter.Get_rank(),
                        axis=0).astype(blocks.dtype)
    b16 = blocks.astype(jnp.bfloat16)
    nbytes = int(blocks.size) * blocks.dtype.itemsize
    if _algos.resolve_dcn_algo(nbytes, h) == "ring":
        out = _algos.apply_ring_reduce_scatter(b16, op, plan.inter, h)
    else:
        full = apply_butterfly_allreduce(b16, op, plan.inter)
        out = jnp.take(full, plan.inter.Get_rank(), axis=0)
    return out.astype(blocks.dtype)


def inter_alltoall(z, plan, h, codec):
    """Compressed DCN alltoall exchange (the ``apply_pairwise_alltoall``
    calls over ``plan.inter`` in ``apply_hier_alltoall``): pure routing,
    so both codecs quantize once and ship — per destination block, so
    each receiver decodes exactly the blocks addressed to it."""
    if codec == "bf16":
        w = _algos.apply_pairwise_alltoall(z.astype(jnp.bfloat16),
                                           plan.inter, h)
        return w.astype(z.dtype)
    s = z.shape[1:]
    q, scale = _encode_rows(z.reshape(h, -1))
    wq = _algos.apply_pairwise_alltoall(q, plan.inter, h)
    ws = _algos.apply_pairwise_alltoall(scale, plan.inter, h)
    cols = int(z.size) // h
    return _decode_rows(wq, ws, cols).reshape((h,) + s).astype(z.dtype)


def inter_bcast(v, plan, b0, codec):
    """Compressed DCN broadcast phase (``_hierarchy._inter_bcast``
    twin): pure routing — quantize once at the root, ship (q, scale),
    decode on arrival.  fp8 always uses the doubling tree (the van de
    Geijn split would re-chunk the scale blocks)."""
    from ._base import apply_doubling_bcast

    if codec == "bf16":
        if _algos.resolve_dcn_algo(int(v.size) * v.dtype.itemsize,
                                   plan.h) == "ring":
            out = _algos.apply_vdg_bcast(v.astype(jnp.bfloat16),
                                         plan.inter, b0, plan.h)
        else:
            out = apply_doubling_bcast(v.astype(jnp.bfloat16),
                                       plan.inter, b0)
        return out.astype(v.dtype)
    q, s = encode_fp8(v)
    q = apply_doubling_bcast(q, plan.inter, b0)
    s = apply_doubling_bcast(s, plan.inter, b0)
    return decode_fp8(q, s, v.shape, v.size).astype(v.dtype)


def dcn_codec(v, nbytes, op=None):
    """The codec the DCN leg applies to traced value ``v`` (None =
    exact): float32 only, enum ``Op``s only where a reduction is
    involved (callables must see exact operands), resolved per payload
    bucket by ``_codec.codec_for``."""
    from ._base import Op

    if v.dtype != jnp.float32:
        return None
    if op is not None and not isinstance(op, Op):
        return None
    return _codec.codec_for(int(nbytes), "float32")


# ---------------------------------------------------------------------------
# error feedback (EF-SGD / 1-bit-Adam residual accumulation)
# ---------------------------------------------------------------------------


def ef_zeros_like(tree):
    """A zero residual matching ``tree`` — the EF state's initial value
    (and a cold joiner's mandatory reset, docs/compression.md)."""
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def ef_allreduce(grads, residual, op=None, *, comm=None):
    """Error-feedback allreduce of a gradient pytree.

    Per leaf: ``comp = g + residual``; ``q = roundtrip(comp, codec)``
    (the codec resolved for this leaf's payload bucket, identity when
    the knob is off); the new residual is ``comp - q``; ``q`` is
    allreduced exactly as any other payload (its DCN leg compresses
    again under the same knob — the residual already carries the
    quantization error, so training sees an unbiased telescoped sum).
    Returns ``(reduced_tree, new_residual_tree, token)``.

    With ``MPI4JAX_TPU_COMPRESS=off`` every roundtrip is the identity,
    the residual stays exactly zero, and the traced program is the
    plain tree-mapped allreduce — examples call this unconditionally.
    """
    from ._base import SUM
    from .allreduce import allreduce

    if op is None:
        op = SUM
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    res_leaves = jax.tree_util.tree_flatten(residual)[0]
    if len(res_leaves) != len(leaves):
        raise ValueError(
            "ef_allreduce: residual tree does not match the gradient "
            f"tree ({len(res_leaves)} vs {len(leaves)} leaves) — "
            "initialize it with ef_zeros_like(grads)"
        )
    from ..analysis import hook as _ana_hook
    from ..parallel.region import _region_stack

    outs, new_res, token = [], [], None
    for g, r in zip(leaves, res_leaves):
        codec = dcn_codec(g, int(g.size) * g.dtype.itemsize, op)
        comp = g + r
        q = roundtrip(comp, codec)
        new_res.append((comp - q).astype(g.dtype))
        out, token = allreduce(q, op=op, comm=comm, token=token)
        # mark the recorded reduction as an error-feedback step: arms the
        # approximate-lineage seeds of the dataflow taint pass
        # (analysis/dataflow.graph_arms_approx) — the residual and the
        # reduced value both carry codec error, and MPX141/MPX142 watch
        # where that lineage flows (docs/analysis.md "Dataflow hazards")
        _ana_hook.mark_last_event(
            "ef", True, ctx=_region_stack[-1] if _region_stack else None)
        outs.append(out)
    return (jax.tree_util.tree_unflatten(treedef, outs),
            jax.tree_util.tree_unflatten(treedef, new_res), token)


def ef_reshard(residual, rank_map, new_world):
    """Re-shard a replicated per-rank EF residual (leaves of leading
    dimension ``old_world``) across an elastic reconfiguration:
    survivors keep their row under the shrink's ``rank_map`` compaction
    and cold joiners get ZEROS — never a dead rank's stale error
    (plan math in ``_codec.ef_reshard_rows``; pinned by
    tests/test_compress*.py across shrink, grow, and commit/restore)."""
    def reshard_leaf(leaf):
        rows = _codec.ef_reshard_rows(int(leaf.shape[0]), rank_map,
                                      new_world)
        zero = jnp.zeros_like(leaf[0])
        return jnp.stack([leaf[o] if o is not None else zero
                          for o in rows])

    return jax.tree_util.tree_map(reshard_leaf, residual)
