"""reduce: reduction to root.

TPU-native re-design of ref mpi4jax/_src/collective_ops/reduce.py.  Contract
preserved exactly (possible here, unlike gather, because shapes match): root
receives the reduction, every other rank gets its own input back
(ref reduce.py:77-80, abstract :240-252).

Lowering: allreduce + per-rank select on the (traced) rank index.  The select
is free (fused); XLA's AllReduce is no slower than a rooted Reduce on ICI.
The allreduce itself goes through the payload-aware algorithm layer
(``apply_allreduce`` -> ops/_algos.py): native HLO where available, else
butterfly vs bandwidth-optimal ring by static payload bytes, forced via
``MPI4JAX_TPU_COLLECTIVE_ALGO`` — so large-payload rooted reductions get the
ring's O(size) byte volume automatically.
"""

from typing import Optional

import jax.numpy as jnp

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import Op, OpLike, apply_allreduce, dispatch, reduction_name
from .token import Token, consume, produce


@enforce_types(root=int, comm=(Comm, None), token=(Token, None))
def reduce(x, op: OpLike, root: int, *, comm: Optional[Comm] = None,
           token: Optional[Token] = None):
    """Reduce ``x`` with ``op`` to rank ``root``; non-root ranks receive
    their input unchanged.

    Returns ``(result, token)`` (ref API: reduce.py:41-96).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.min_size()  # on a color split, root must fit EVERY group
        if not 0 <= root < size:
            from ..analysis.report import mpx_error

            raise mpx_error(
                ValueError, "MPX105",
                f"reduce root {root} out of range for size {size}",
            )
        xl = consume(token, xl)
        rank = comm.Get_rank()  # group-local on a color split, like the root
        log_op("MPI_Reduce", rank, f"{xl.size} items to root {root}")
        reduced = apply_allreduce(xl, op, comm)
        res = jnp.where(rank == root, reduced, xl)
        return res, produce(token, res)

    return dispatch("reduce", comm, body, (x,), token,
                    static_key=(op, root) if isinstance(op, Op) else None,
                    ana={"root": root, "reduction": reduction_name(op)})
