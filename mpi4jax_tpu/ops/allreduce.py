"""allreduce: reduction across all ranks.

TPU-native re-design of ref mpi4jax/_src/collective_ops/allreduce.py (281 LoC
of primitive + per-platform custom-call lowerings).  Here the op IS
``lax.psum``/``pmax``/``pmin`` (one AllReduce HLO over ICI); JAX supplies the
batching rule and differentiation, whose semantics match the reference's
hand-written rules exactly (verified by tests/test_allreduce.py):

- JVP: tangents are allreduced alongside primals (ref allreduce.py:236-251);
- transpose of SUM-allreduce is the per-rank identity, and double transpose
  restores a true allreduce (ref allreduce.py:254-266 ``transpose`` flag +
  identity lowering :87-89) — here this falls out of JAX's varying/replicated
  collective typing (psum ↔ pbroadcast transposition).

Beyond the reference: MIN/MAX/PROD/logical/bitwise reductions are also
differentiable where mathematically defined (the reference raises
NotImplementedError for any op other than SUM, ref allreduce.py:240-243), and
user-defined reductions are accepted as Python callables.
"""

from typing import Optional

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from . import _async, _fusion
from ._base import SUM, Op, OpLike, apply_allreduce, dispatch, reduction_name
from .token import Token, consume, produce


@enforce_types(comm=(Comm, None), token=(Token, None))
def allreduce(x, op: OpLike = SUM, *, comm: Optional[Comm] = None,
              token: Optional[Token] = None):
    """Reduce ``x`` with ``op`` across all ranks of ``comm``; every rank
    receives the result.

    Returns ``(result, token)`` (ref API: allreduce.py:41-79).

    Throughput layers (docs/overlap.md): inside ``mpx.overlap()`` the call
    auto-splits into the async start/wait pair (ops/_async.py) and the
    returned result is lazy until first use; under
    ``MPI4JAX_TPU_FUSION=auto|force`` adjacent small allreduces coalesce
    into one flat-buffer collective (ops/_fusion.py) — both return a
    result that materializes on use, with passthrough token ordering.
    """
    # overlap takes precedence over fusion: a split collective already
    # hides latency, and re-bucketing its phases would serialize them
    lazy = _async.maybe_lazy("allreduce", x, op, comm, token)
    if lazy is not None:
        return lazy
    if isinstance(op, Op):  # callables never fuse (see _fusion docstring)
        deferred = _fusion.maybe_defer("allreduce", x, comm, token,
                                       reduction=op)
        if deferred is not None:
            return deferred

    def body(comm, arrays, token):
        (xl,) = arrays
        xl = consume(token, xl)
        log_op("MPI_Allreduce", comm.Get_rank(), f"with {xl.size} items")
        res = apply_allreduce(xl, op, comm)
        return res, produce(token, res)

    # custom callable ops are uncacheable: their captured state can change
    # without changing identity (enum ops are pure values)
    return dispatch("allreduce", comm, body, (x,), token,
                    static_key=(op,) if isinstance(op, Op) else None,
                    ana={"reduction": reduction_name(op)})
