"""Wire-compression codec bookkeeping: resolution, byte math, EF plans.

Stdlib-only on purpose (the isolated-loader pure tests import this next
to ``utils/config.py`` and ``autotune/schema.py`` without JAX): the
traced encode/decode appliers live in ``ops/_compress.py``; everything a
cost model, telemetry counter, benchmark sweep, or analyzer checker
needs to reason about compression — which codec applies, how many bytes
actually cross the DCN wire, how an error-feedback residual re-shards
across an elastic reconfiguration — lives here.

The codec model (docs/compression.md):

- ``bf16`` — float32 DCN payloads are cast to bfloat16 on the wire and
  back on arrival: 2 bytes/element, exactly half the wire bytes, a
  relative error of ~2^-8 per element (bf16 keeps fp32's exponent).
- ``fp8`` — per-chunk max-abs-scaled quantization to float8_e4m3fn
  (``FP8_CHUNK`` = 256 elements per scale): 1 byte/element + one fp32
  scale per chunk, ~0.27x the fp32 wire bytes (~3.7x reduction).
- ``off`` — no codec; wire bytes == logical bytes, HLO byte-identical
  to a build without the compression layer.

Compression applies to the INTER-HOST (DCN) leg of the hierarchical
lowerings only, and only to float32 payloads — ICI phases and every
non-f32 dtype stay exact in every mode.
"""

from typing import Dict, List, Optional

from ..utils import config

# elements per fp8 scale chunk: one fp32 max-abs scale amortized over
# this many quantized elements.  256 keeps the scale overhead at 1.6%
# of the quantized bytes while bounding each chunk's dynamic range
# tightly enough that e4m3's ~2 decimal digits hold per-element relative
# error near the format's 2^-3 mantissa step for gradient-shaped data.
FP8_CHUNK = 256

# wire bytes per element, by codec, for a float32 element (the only
# compressible dtype); fp8 adds the per-chunk scale separately
_F32_ITEMSIZE = 4

CODECS = ("off", "bf16", "fp8")


def wire_bytes(nbytes: int, codec: Optional[str]) -> int:
    """Bytes actually crossing the wire for a logical float32 payload of
    ``nbytes`` under ``codec`` (None/"off" = exact).  The single source
    of byte truth shared by the cost model, telemetry's wire counters,
    and the compression sweep."""
    if not codec or codec == "off":
        return nbytes
    if codec == "bf16":
        return nbytes // 2
    if codec == "fp8":
        elems = nbytes // _F32_ITEMSIZE
        nchunks = -(-elems // FP8_CHUNK) if elems else 0
        return elems + _F32_ITEMSIZE * nchunks
    raise ValueError(f"unknown wire codec {codec!r} "
                     f"(expected one of {CODECS})")


def codec_for(nbytes: int, dtype: str = "float32") -> Optional[str]:
    """The codec the DCN leg of a hierarchical lowering applies to a
    payload of ``nbytes`` logical bytes and ``dtype``, or ``None`` when
    the leg stays exact.  Resolution is ``config.compress_mode`` —
    default < tuning(payload-bucketed) < env — restricted to float32
    (the training-gradient dtype; everything else ships exact)."""
    if dtype != "float32":
        return None
    mode = config.compress_mode(payload_bytes=nbytes)
    return None if mode == "off" else mode


def compression_ratio(nbytes: int, codec: Optional[str]) -> float:
    """logical/wire — e.g. 2.0 for bf16; 1.0 when exact or empty."""
    wire = wire_bytes(nbytes, codec)
    return (nbytes / wire) if wire else 1.0


def ef_reshard_rows(old_k: int, rank_map: Dict[int, int],
                    new_world: int) -> List[Optional[int]]:
    """Row plan for re-sharding a per-rank error-feedback residual of
    leading dimension ``old_k`` across an elastic reconfiguration.

    ``rank_map`` is the shrink's ``{old_rank: new_rank}`` compaction
    (resilience/elastic.compact_rank_map, recorded on the ShardStore
    commit); ``new_world`` is the post-reconfig world size (> number of
    survivors when joiners grew the world back).  Returns one entry per
    NEW rank: the old residual row that rank carries forward, or
    ``None`` for a cold joiner — whose residual MUST be zeroed, not
    silently dropped or left holding a dead rank's stale error
    (docs/compression.md 'Error feedback under elasticity')."""
    if new_world < 1:
        raise ValueError(f"new_world must be >= 1 (got {new_world})")
    rows: List[Optional[int]] = [None] * new_world
    for old, new in rank_map.items():
        if not 0 <= old < old_k:
            raise ValueError(
                f"rank_map old rank {old} out of range for a residual "
                f"of leading dimension {old_k}"
            )
        if 0 <= new < new_world:
            rows[new] = old
    return rows
