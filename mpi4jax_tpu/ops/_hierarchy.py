"""Hierarchical topology-aware collectives: two-level ICI/DCN lowerings.

The flat algorithms in ``_algos.py`` treat the communicator as one ring —
on a multi-host pod that serializes every DCN (cross-host) hop behind the
slowest ICI step: a flat ring over ``h`` hosts × ``r`` local ranks pays
``2·(h·r - 1)`` rounds, ``h`` of them over DCN *per circulation*.  The
standard fix (Horovod's hierarchical allreduce; NCCL's intra/inter split)
is a **two-level decomposition** keyed on the host topology
(``parallel/topology.py``):

- ``apply_hier_allreduce`` — intra-host ring reduce-scatter over ICI
  (each local rank ends owning a ``1/r`` shard of its host's partial
  reduction) → inter-host allreduce over DCN among the ``r`` position
  groups (one leader shard per host per position; ring or butterfly by
  shard bytes vs ``MPI4JAX_TPU_DCN_CROSSOVER_BYTES``) → intra-host ring
  allgather.  Per-rank bytes: ``~2·(r-1)/r·size`` over ICI plus
  ``~2·(h-1)/h·size/r`` over DCN — vs the flat ring's ``2·(k-1)/k·size``
  with every round gated on DCN.
- ``apply_hier_reduce_scatter`` — the same split without the trailing
  allgather: intra-host reduce-scatter of position super-blocks, then an
  inter-host reduce-scatter of the per-host partials.
- ``apply_hier_bcast`` — binomial-halving **scatter** within the root's
  host (reusing ``vdg_scatter_pairs``), inter-host broadcast of each
  chunk from the root's host (doubling or van de Geijn by chunk bytes vs
  the DCN crossover), then an intra-host ring allgather: the root ships
  ``~size`` total, DCN carries ``~size/r`` per position instead of the
  full payload.

**Fold order.**  The two-level fold combines each host block in ascending
group order (the intra ring reduce-scatter reuses ``rs_update_pair``'s
order-preserving lo/hi accumulator for callables), then combines the
per-host partials in ascending host order.  Because a hierarchical plan
requires each group's host blocks to be CONTIGUOUS ascending runs of the
group order, the resulting operand sequence is exactly the flat ascending
group-rank fold — associativity alone (no commutativity) makes
hierarchical == flat, for enum ``Op``s and callables alike (pinned by the
lockstep simulator in tests/test_hierarchy.py).

**Expressibility and fallback.**  A plan exists only when every group of
the comm splits into ``h >= 2`` contiguous host blocks of one uniform
size ``r``, identical across groups (one SPMD program cannot express
per-group hierarchies).  Non-uniform partitions (e.g. a ``3,5`` host
split), single-host comms, round-robin rank placement, and comms with no
derivable topology all yield ``hier_plan(comm) is None`` and keep the
flat algorithms — topology support never turns a working program into an
error.

The plan geometry (``host_blocks`` / ``hier_split``) and the per-link-
class byte models (``hier_link_bytes`` / ``flat_link_bytes``) are plain
Python over ints and tuples, shared with the lockstep simulator in
tests/test_hierarchy.py — the bandwidth claim is a test, not a comment.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

import jax.numpy as jnp

from . import _algos

__all__ = [
    "host_blocks",
    "hier_split",
    "hier_plan",
    "comm_hosts",
    "hier_link_bytes",
    "flat_link_bytes",
    "alltoall_dcn_messages",
    "dcn_leg_bytes",
    "selected_codec",
    "annotate_selection",
    "apply_hier_allreduce",
    "apply_hier_reduce_scatter",
    "apply_hier_bcast",
    "apply_hier_alltoall",
]


# ---------------------------------------------------------------------------
# plan geometry (pure — shared with the lockstep simulator)
# ---------------------------------------------------------------------------


def host_blocks(members, host_of_rank) -> Optional[List[List[int]]]:
    """Split ``members`` (one group, in group order) into runs of
    same-host ranks.  Returns ``None`` when a host's members are not
    contiguous in group order (e.g. round-robin placement): the two-level
    fold would then permute operands relative to the flat ascending fold,
    breaking the hierarchical == flat contract for non-commutative
    reductions."""
    blocks: List[List[int]] = []
    seen = set()
    cur = None
    for m in members:
        h = host_of_rank[m]
        if h != cur:
            if h in seen:
                return None  # host reappears: non-contiguous
            seen.add(h)
            blocks.append([])
            cur = h
        blocks[-1].append(m)
    return blocks


def hier_split(groups, host_of_rank):
    """The two-level partition of ``groups`` under ``host_of_rank``, or
    ``None`` where no single SPMD program can express it.

    Returns ``(intra_groups, inter_groups, h, r)``: every group splits
    into ``h >= 2`` contiguous host blocks of uniform size ``r`` (the
    same ``(h, r)`` for every group); ``intra_groups`` are the host
    blocks, ``inter_groups`` collect the rank at intra position ``j`` of
    every host block of one group (the "leader shard" groups — ``r`` per
    group, ``h`` members each).
    """
    intra_groups: List[tuple] = []
    inter_groups: List[tuple] = []
    h = r = None
    for members in groups:
        blocks = host_blocks(members, host_of_rank)
        if blocks is None:
            return None
        sizes = {len(b) for b in blocks}
        if len(sizes) != 1:
            return None  # non-uniform ranks-per-host
        gh, gr = len(blocks), sizes.pop()
        if h is None:
            h, r = gh, gr
        elif (gh, gr) != (h, r):
            return None  # per-group hierarchies: inexpressible
        intra_groups.extend(tuple(b) for b in blocks)
        for j in range(gr):
            inter_groups.append(tuple(b[j] for b in blocks))
    if h is None or h < 2:
        return None  # single-host (or empty): nothing to hierarchize
    return tuple(intra_groups), tuple(inter_groups), h, r


# ---------------------------------------------------------------------------
# per-link-class byte models (pure — pinned by tests/test_hierarchy.py)
# ---------------------------------------------------------------------------


def hier_link_bytes(kind: str, nbytes: int, h: int, r: int,
                    preserve: bool = False) -> Tuple[int, int]:
    """Modeled per-rank wire bytes ``(intra_host, inter_host)`` for one
    hierarchical collective of ``nbytes`` payload over ``h`` hosts ×
    ``r`` ranks/host.

    The models mirror the lowerings below round for round (the inter
    algorithm is resolved exactly as the lowering resolves it):

    - ``allreduce``: intra ring reduce-scatter + allgather of ``r``
      chunks (``(r-1)·chunk·(pair+1)`` ≈ ``2·(r-1)/r·size``), inter
      allreduce of one chunk over ``h`` hosts (≈ ``2·(h-1)/h·size/r``);
    - ``reduce_scatter``: the same without the allgather — intra
      ``(r-1)·super·pair`` on ``size/r`` super-blocks, inter
      reduce-scatter of the per-host partial;
    - ``bcast``: intra binomial scatter (the root's host ships ``~size``
      down the halving tree; modeled per-rank as ``size``) + allgather,
      inter broadcast of one ``size/r`` chunk (doubling or vdg).

    ``pair`` is 2 for order-preserving callables (the lo/hi accumulator
    ships both halves), 1 for enum ``Op``s.
    """
    pair = 2 if preserve else 1
    chunk = -(-nbytes // r)
    if kind == "alltoall":
        # intra transpose ships (r-1) destination blocks of size/r each
        # over ICI; the inter exchange ships (h-1) host-aggregated
        # contiguous blocks of size/h each over DCN (the final intra
        # scatter degenerates to a local de-interleave — every rank is
        # its own position-group leader, see apply_hier_alltoall).
        # Total bytes match flat (alltoall is a fixed permutation); the
        # win is message granularity: alltoall_dcn_messages pins the
        # 1/r DCN message-count reduction.
        intra = (r - 1) * chunk
        inter = (h - 1) * (-(-nbytes // h))
        return intra, inter
    if kind == "allreduce":
        intra = (r - 1) * chunk * (pair + 1)
        dcn = _algos.resolve_dcn_algo(chunk, h, ring_ok=not preserve)
        inter = _algos.algorithm_bytes_per_rank(dcn, chunk, h, preserve)
        return intra, inter
    if kind == "reduce_scatter":
        super_b = chunk  # one position super-block = size/r bytes
        intra = (r - 1) * super_b * pair
        block = -(-super_b // h)
        if _algos.resolve_dcn_algo(super_b, h) == "ring":
            inter = (h - 1) * block * pair
        else:  # butterfly allreduce + own-block select
            inter = 2 * (h - 1).bit_length() * super_b if h > 1 else 0
        return intra, inter
    if kind == "bcast":
        intra = nbytes + (r - 1) * chunk  # halving scatter + ring allgather
        if _algos.resolve_dcn_algo(chunk, h) == "ring":
            inter = 2 * chunk  # van de Geijn: scatter + allgather
        else:
            inter = (h - 1).bit_length() * chunk  # doubling rounds
        return intra, inter
    raise ValueError(f"unknown hierarchical collective kind {kind!r}")


def flat_link_bytes(kind: str, algo: str, nbytes: int, k: int,
                    h: Optional[int],
                    preserve: bool = False) -> Tuple[int, int]:
    """Link-class attribution for a FLAT (single-level) algorithm,
    modeled per op kind round for round (mirroring the flat lowerings,
    so flat-vs-hier comparisons in the telemetry report are fair):

    - ``allreduce``: butterfly ``2·ceil(log2 k)·size`` (fold + doubling
      broadcast), ring ``(k-1)·chunk·(pair+1)``;
    - ``bcast``: doubling ``ceil(log2 k)·size`` (one full-payload send
      per round), van de Geijn ``~2·size`` (halving scatter + ring
      allgather);
    - ``reduce_scatter``: butterfly = allreduce-then-select
      (``2·ceil(log2 k)·size``), ring ``(k-1)·chunk·pair`` (no
      allgather phase).

    The volume lands entirely on the inter-host class when the comm
    spans ``h > 1`` hosts (every round of a flat algorithm over a
    multi-host comm is gated on its slowest — DCN — link; exactly the
    serialization MPX113 advises about), on the intra class otherwise.
    ``native`` HLO (and comms with no derivable topology) is attributed
    as payload bytes on the intra class — XLA schedules it, we don't
    model it."""
    pair = 2 if preserve else 1
    rounds = (k - 1).bit_length() if k > 1 else 0  # ceil(log2 k)
    chunk = -(-nbytes // k) if k else nbytes
    if kind == "alltoall":
        # a fixed permutation: every flat lowering — the native AllToAll
        # HLO, the pairwise ppermute rounds — moves the same (k-1) blocks
        # of size/k per rank, so (unlike the reduction family) the
        # ``native`` algorithm is honestly modeled rather than proxied
        total = (k - 1) * chunk
        if h is not None and h > 1:
            return 0, total
        return total, 0
    if algo == "butterfly":
        if kind == "bcast":
            total = rounds * nbytes
        else:  # allreduce; reduce_scatter = allreduce + own-block select
            total = 2 * rounds * nbytes
    elif algo == "ring":
        if kind == "bcast":  # van de Geijn: scatter + ring allgather
            total = nbytes + (k - 1) * chunk
        elif kind == "reduce_scatter":
            total = (k - 1) * chunk * pair
        else:  # allreduce: reduce-scatter + allgather
            total = (k - 1) * chunk * (pair + 1)
    else:
        return nbytes, 0
    if h is not None and h > 1:
        return 0, total
    return total, 0


def alltoall_dcn_messages(h: int, r: int) -> Tuple[int, int]:
    """DCN (cross-host) message counts ``(flat, hier)`` of one alltoall
    over ``h`` hosts × ``r`` ranks/host — the latency claim of the
    hierarchical split, pinned by tests/test_hierarchy.py:

    - flat: every rank addresses every remote rank directly —
      ``r² · h · (h−1)`` cross-host messages of ``size/(h·r)`` each;
    - hier: each rank exchanges one host-aggregated CONTIGUOUS block
      with each of its ``h−1`` position-group peers — ``r · h · (h−1)``
      messages of ``size/h`` each (``h·(h−1)`` per position group).

    Exactly ``1/r`` the flat message count at ``r×`` the message size;
    total DCN bytes are invariant (the permutation is fixed), so the
    whole win is per-message DCN latency and NIC message rate — the
    lever Tutel/FasterMoE pull for expert-parallel dispatch."""
    flat = r * r * h * (h - 1)
    hier = r * h * (h - 1)
    return flat, hier


# ---------------------------------------------------------------------------
# the plan: derived comms, memoized per (comm, topology)
# ---------------------------------------------------------------------------


class HierPlan:
    """One comm's two-level decomposition: the intra-host and inter-host
    derived communicators (color-split comms over the SAME mesh axes, so
    every phase is ordinary masked ``ppermute`` routing) plus the static
    geometry."""

    __slots__ = ("intra", "inter", "h", "r")

    def __init__(self, intra, inter, h: int, r: int):
        self.intra = intra
        self.inter = inter
        self.h = h
        self.r = r

    def __repr__(self):
        return f"HierPlan(h={self.h}, r={self.r})"


# LRU-bounded like the caches it feeds: each entry pins two GroupComms
# (and through them a mesh reference)
_plan_memo: "OrderedDict" = OrderedDict()
_PLAN_MEMO_MAX = 64
_NO_PLAN = object()


def hier_plan(comm) -> Optional[HierPlan]:
    """The two-level plan for ``comm``, or ``None`` when the hierarchy is
    not expressible (no derivable topology, single host, non-uniform or
    non-contiguous host partition) — callers then keep the flat
    algorithms.  Memoized per (comm, mesh, topology): plan construction
    walks the world once, which must not run per traced collective."""
    from ..parallel.topology import derive_world_topology

    topo = derive_world_topology(comm)
    if topo is None or topo.num_hosts < 2:
        return None
    groups = comm.groups
    if groups is None:
        try:
            world = comm.world_size()
        except RuntimeError:
            return None
        groups = (tuple(range(world)),)
    key = (comm.uid, comm.mesh, comm.axes, topo.fingerprint(), groups)
    cached = _plan_memo.get(key)
    if cached is not None:
        _plan_memo.move_to_end(key)
        return None if cached is _NO_PLAN else cached
    split = hier_split(groups, topo.host_of_rank)
    if split is None:
        plan = None
    else:
        from ..parallel.comm import GroupComm

        intra_groups, inter_groups, h, r = split
        plan = HierPlan(GroupComm(comm, intra_groups),
                        GroupComm(comm, inter_groups), h, r)
    _plan_memo[key] = _NO_PLAN if plan is None else plan
    if len(_plan_memo) > _PLAN_MEMO_MAX:
        _plan_memo.popitem(last=False)
    return plan


# memoized like the plan: the per-group span walk is O(world) and runs
# once per traced collective on comms without a plan (the common
# single-host case)
_hosts_memo: "OrderedDict" = OrderedDict()
_HOSTS_MEMO_MAX = 64


def comm_hosts(comm) -> Optional[int]:
    """How many hosts ``comm``'s widest group spans (``None`` when no
    topology is derivable) — the multi-host signal the telemetry link
    classes key on, available even where the full hierarchy is not
    expressible (non-uniform partitions still ship over DCN)."""
    from ..parallel.topology import derive_world_topology

    topo = derive_world_topology(comm)
    if topo is None:
        return None
    groups = comm.groups
    if groups is None:
        return topo.num_hosts
    key = (comm.uid, topo.fingerprint(), groups)
    cached = _hosts_memo.get(key)
    if cached is not None:
        _hosts_memo.move_to_end(key)
        return cached
    hosts = max(
        len({topo.host_of_rank[m] for m in members}) for members in groups
    )
    _hosts_memo[key] = hosts
    if len(_hosts_memo) > _HOSTS_MEMO_MAX:
        _hosts_memo.popitem(last=False)
    return hosts


def dcn_leg_bytes(kind: str, nbytes: int, r: int) -> int:
    """The payload the DCN phase of a hierarchical ``kind`` sees — the
    bucket the wire codec resolves against (``_codec.codec_for``): the
    full payload for the alltoall's host-aggregated exchange, one
    ``1/r`` position chunk for the reduction family's leader shards."""
    return nbytes if kind == "alltoall" else -(-nbytes // r)


def selected_codec(kind: str, nbytes: int, plan: Optional[HierPlan],
                   preserve: bool = False, op=None,
                   dtype: Optional[str] = None) -> Optional[str]:
    """The wire codec the hierarchical lowering's DCN leg applies for
    this call, or ``None`` when the leg ships exact: hier-only, float32
    only, never for order-preserving callables, and fp8 degrades to
    bf16 for non-SUM reductions (mirroring ``_compress._effective`` so
    the annotation records the codec that actually runs)."""
    if plan is None or preserve or dtype != "float32":
        return None
    from . import _codec

    codec = _codec.codec_for(dcn_leg_bytes(kind, nbytes, plan.r),
                             "float32")
    if codec == "fp8" and op is not None and \
            kind in ("allreduce", "reduce_scatter"):
        from ._base import SUM

        if op != SUM:
            codec = "bf16"
    return codec


def annotate_selection(kind: str, algo: str, nbytes: int, k: int,
                       plan: Optional[HierPlan], comm,
                       preserve: bool = False, op=None,
                       dtype: Optional[str] = None) -> None:
    """One-stop dispatch-point annotation for the reduction family: the
    selected algorithm (analysis + telemetry), the host span (MPX113),
    the modeled per-link-class wire bytes (telemetry's
    ``intra_host``/``inter_host`` counters), and — when the DCN-leg
    codec is active — the codec plus the COMPRESSED inter-host bytes
    (telemetry's wire-vs-logical split, MPX138).  Pure host-side
    bookkeeping: never adds an equation to the trace."""
    from ..analysis.hook import annotate as a_annotate
    from ..telemetry.core import annotate as t_annotate

    hosts = plan.h if plan is not None else comm_hosts(comm)
    if algo == "hier":
        link = hier_link_bytes(kind, nbytes, plan.h, plan.r, preserve)
    else:
        link = flat_link_bytes(kind, algo, nbytes, k, hosts, preserve)
    codec = None
    wire = link
    if algo == "hier":
        codec = selected_codec(kind, nbytes, plan, preserve, op, dtype)
        if codec is not None:
            from . import _codec

            wire = (link[0], _codec.wire_bytes(link[1], codec))
    # the analysis event carries ``hosts`` only when the hierarchy was
    # actually expressible (a plan existed): MPX113 advises on a CHOICE,
    # and where flat is the only option there is nothing to advise.  The
    # telemetry link classes keep the broader host signal — a flat
    # algorithm on a non-uniform multi-host comm still ships over DCN.
    # ``hier`` records the two-level decomposition this op actually
    # lowered with — the cross-rank matcher compares it across member
    # ranks (MPX125, analysis/matcher.py).
    a_annotate(algo=algo, hosts=plan.h if plan is not None else None,
               hier=(plan.h, plan.r) if (plan is not None
                                         and algo == "hier") else None,
               codec=codec)
    t_annotate(algo=algo, link_bytes=link, wire_bytes=wire)


# ---------------------------------------------------------------------------
# traced appliers
# ---------------------------------------------------------------------------


def _dcn_codec(v, nbytes: int, op=None):
    """The wire codec the DCN phase applies to traced value ``v``
    (``None`` = ship exact): float32 only, enum ``Op``s only when a
    reduction is involved, resolved per payload bucket
    (``_codec.codec_for`` — off by default, so this is a pure config
    read that changes nothing unless MPI4JAX_TPU_COMPRESS or a tuned
    codec is active; the mode folds into ``algo_cache_token`` so
    flipping it retraces)."""
    from ._base import Op

    if v.dtype != jnp.float32:
        return None
    if op is not None and not isinstance(op, Op):
        return None
    from . import _codec

    return _codec.codec_for(int(nbytes), "float32")


def apply_hier_allreduce(x, op, comm, plan: HierPlan):
    """Two-level allreduce: intra-host ring reduce-scatter (ICI) →
    inter-host allreduce of each rank's shard (DCN; ring or butterfly by
    ``resolve_dcn_algo``) → intra-host ring allgather (ICI).

    Same contract as the flat lowerings: all 10 ``Op``s plus associative
    callables folded in ascending group-rank order (callables must be
    ELEMENTWISE — the payload is chunked, the same caveat as the flat
    ring; ``auto`` never routes callables here, only a forced ``hier``
    does).  Bit-identical to the flat algorithms under exact arithmetic
    (tests/test_hierarchy.py pins all 10 ops across 4 topologies).
    """
    from ._base import as_varying

    x = as_varying(x, comm.axes)
    r, h = plan.r, plan.h
    if r == 1:
        # one rank per host: the inter phase IS the whole collective
        return _inter_allreduce(x, op, plan, x.size * x.dtype.itemsize)
    shape, n = x.shape, x.size
    chunk, padded = _algos.chunk_layout(n, r)
    blocks = _algos._pad_to(x.reshape(-1), padded).reshape(r, chunk)
    mine = _algos.apply_ring_reduce_scatter(blocks, op, plan.intra, r)
    reduced = _inter_allreduce(mine, op, plan, chunk * x.dtype.itemsize)
    pos = plan.intra.Get_rank()
    full = _algos.apply_ring_allgather(reduced, plan.intra, r, pos)
    return full.reshape(-1)[:n].reshape(shape)


def _inter_allreduce(v, op, plan: HierPlan, shard_bytes: int):
    """The DCN phase: allreduce ``v`` over the inter (leader-shard) comm,
    ring or butterfly by shard size vs the DCN crossover.  Callables keep
    the butterfly (the DCN ring would re-chunk the shard — the
    elementwise caveat squared)."""
    from ._base import Op, apply_butterfly_allreduce

    if plan.h == 1:
        return v
    codec = _dcn_codec(v, shard_bytes, op)
    if codec is not None:
        from . import _compress

        return _compress.inter_allreduce(v, op, plan, shard_bytes, codec)
    ring_ok = isinstance(op, Op)
    if _algos.resolve_dcn_algo(shard_bytes, plan.h, ring_ok) == "ring":
        return _algos.apply_ring_allreduce(v, op, plan.inter, plan.h)
    return apply_butterfly_allreduce(v, op, plan.inter)


def apply_hier_reduce_scatter(xl, op, comm, plan: HierPlan):
    """Two-level reduce-scatter of ``xl`` (shape ``(k, *s)``, block ``i``
    addressed to group position ``i``): intra-host ring reduce-scatter of
    the ``r`` position SUPER-blocks (super-block ``j`` stacks the ``h``
    blocks addressed to intra position ``j`` of each host) → inter-host
    reduce-scatter of the per-host partials.  No allgather phase — the
    result is each rank's own folded block, shape ``(*s,)``.

    Blocks are the user's own (never re-chunked), so block-wise callables
    remain valid — the combine sees ``(h, *s)`` stacks in the intra phase
    and must batch over the leading axis (e.g. ``jnp.matmul`` does).
    """
    from ._base import as_varying

    xl = as_varying(xl, comm.axes)
    r, h = plan.r, plan.h
    if r == 1:
        return _inter_reduce_scatter(xl, op, plan)
    s = xl.shape[1:]
    y = jnp.moveaxis(xl.reshape((h, r) + s), 1, 0)  # y[j, b] = block b·r+j
    partial = _algos.apply_ring_reduce_scatter(y, op, plan.intra, r)
    return _inter_reduce_scatter(partial, op, plan)


def _inter_reduce_scatter(blocks, op, plan: HierPlan):
    """DCN phase of the hierarchical reduce-scatter: ``blocks`` (shape
    ``(h, *s)``) holds this rank's per-host partials; host ``b``'s rank
    receives the ascending-host fold of every host's partial ``b``."""
    from ._base import apply_butterfly_allreduce

    h = plan.h
    if h == 1:
        return blocks[0]
    nbytes = int(blocks.size) * blocks.dtype.itemsize
    codec = _dcn_codec(blocks, nbytes, op)
    if codec is not None:
        from . import _compress

        return _compress.inter_reduce_scatter(blocks, op, plan, codec)
    if _algos.resolve_dcn_algo(nbytes, h) == "ring":
        return _algos.apply_ring_reduce_scatter(blocks, op, plan.inter, h)
    full = apply_butterfly_allreduce(blocks, op, plan.inter)
    return jnp.take(full, plan.inter.Get_rank(), axis=0)


def apply_hier_bcast(x, comm, root: int, plan: HierPlan):
    """Two-level broadcast from group position ``root``: binomial-halving
    scatter of the ``r`` payload chunks within the root's host block
    (reusing ``vdg_scatter_pairs`` over the intra groups) → inter-host
    broadcast of each chunk from the root's host (doubling or van de
    Geijn by chunk bytes vs the DCN crossover) → intra-host ring
    allgather.  DCN carries ``~size/r`` per position instead of the full
    payload.

    ``root`` is a group position (the same convention as the flat
    lowerings); with contiguous uniform host blocks its host index and
    intra position are the static pair ``divmod(root, r)``.
    """
    from ._base import _permute_axis, as_varying

    x = as_varying(x, comm.axes)
    r, h = plan.r, plan.h
    itemsize = x.dtype.itemsize
    if r == 1:
        return _inter_bcast(x, plan, root, x.size * itemsize)
    b0, j0 = divmod(root, r)
    shape, n = x.shape, x.size
    chunk, _ = _algos.chunk_layout(n, r)
    R = _algos.next_pow2(r)
    pos = plan.intra.Get_rank()
    relpos = (pos - j0) % r
    axis = _permute_axis(comm)
    buf = _algos._pad_to(x.reshape(-1), R * chunk).reshape(R, chunk)
    buf = _algos.apply_binomial_scatter(buf, plan.intra.groups, j0, axis,
                                        relpos, R)
    mine = jnp.take(buf, relpos, axis=0)  # this rank's chunk (relpos < r)
    mine = _inter_bcast(mine, plan, b0, chunk * itemsize)
    full = _algos.apply_ring_allgather(mine, plan.intra, r, relpos)
    return full.reshape(-1)[:n].reshape(shape)


def apply_hier_alltoall(xl, comm, plan: HierPlan):
    """Two-level alltoall of ``xl`` (shape ``(k, *s)``, block ``i``
    addressed to group position ``i``): intra-host transpose over ICI →
    inter-host exchange of host-aggregated contiguous blocks over DCN →
    local de-interleave.

    Writing position ``i = b·r + j`` (host block ``b``, intra position
    ``j`` — contiguous uniform blocks by plan construction):

    1. **intra transpose (ICI)** — member ``(b, i)`` ships host-mate
       ``(b, j)`` its ``h`` blocks addressed to position ``j`` of every
       host (one pairwise alltoall over ``plan.intra`` with
       ``size/r``-byte messages); afterwards ``(b, j)`` holds
       ``A[i, b'] = x_{(b,i)}[b'·r + j]`` — its host's ENTIRE traffic
       for the position-``j`` members, contiguous per destination host;
    2. **inter exchange (DCN)** — over the position-``j`` leader group
       (``plan.inter``), ``(b, j)`` ships ``(b', j)`` the aggregated
       block ``A[:, b']`` — ``h·(h−1)`` messages of ``size/h`` per
       group instead of flat's ``r²·h·(h−1)`` per-rank ones
       (``alltoall_dcn_messages``: exactly ``1/r`` the DCN message
       count);
    3. **intra scatter** — degenerates to a local de-interleave: every
       rank is its own position-group leader, so after the inter
       exchange it already holds every peer's block addressed to it,
       ordered ``(source host, source intra position)`` = ascending
       group order.

    Pure routing, no arithmetic — bit-identical to the flat lowering by
    construction (pinned across {2x4, 4x2, 8x1, 2x2} by the lockstep
    simulator in tests/test_hierarchy.py).
    """
    from ._base import as_varying

    xl = as_varying(xl, comm.axes)
    h, r = plan.h, plan.r
    s = xl.shape[1:]
    nbytes = int(xl.size) * xl.dtype.itemsize
    codec = _dcn_codec(xl, nbytes)
    if r == 1:
        # one rank per host: the inter exchange IS the whole alltoall
        if codec is not None:
            from . import _compress

            return _compress.inter_alltoall(xl, plan, h, codec)
        return _algos.apply_pairwise_alltoall(xl, plan.inter, h)
    y = jnp.moveaxis(xl.reshape((h, r) + s), 1, 0)  # y[j, b'] → (b'·r + j)
    a = _algos.apply_pairwise_alltoall(y, plan.intra, r)
    # a[i, b'] = host-mate i's block addressed to (b', my intra pos)
    z = jnp.moveaxis(a, 1, 0)  # z[b', i]: the host-aggregated block for b'
    if codec is not None:
        from . import _compress

        w = _compress.inter_alltoall(z, plan, h, codec)
    else:
        w = _algos.apply_pairwise_alltoall(z, plan.inter, h)
    # w[b'', i] = the block rank b''·r + i addressed to me
    return w.reshape((h * r,) + s)


def _inter_bcast(v, plan: HierPlan, b0: int, nbytes: int):
    """DCN phase of the hierarchical broadcast: every inter group
    broadcasts from group position ``b0`` (the root's host index —
    uniform across groups by plan construction)."""
    from ._base import apply_doubling_bcast

    if plan.h == 1:
        return v
    codec = _dcn_codec(v, nbytes)
    if codec is not None:
        from . import _compress

        return _compress.inter_bcast(v, plan, b0, codec)
    if _algos.resolve_dcn_algo(nbytes, plan.h) == "ring":
        return _algos.apply_vdg_bcast(v, plan.inter, b0, plan.h)
    return apply_doubling_bcast(v, plan.inter, b0)
