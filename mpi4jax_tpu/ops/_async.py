"""Async collectives: ``*_start``/``*_wait`` pairs + the ``mpx.overlap()``
region — communication/compute overlap for ``allreduce``,
``reduce_scatter``, and ``alltoall`` (the MoE dispatch/combine
primitive, docs/moe.md).

A monolithic collective is one HLO op: XLA schedules everything after it
behind it, so independent compute waits on the wire.  Splitting the
collective into explicit phases turns it into multiple smaller ops with a
data-dependency gap the scheduler can fill (the trace-time analog of
PyTorch DDP's overlap-scheduled bucket allreduce, Li et al., VLDB 2020):

- ``allreduce_start`` flattens the payload, splits it into
  ``MPI4JAX_TPU_OVERLAP_CHUNKS`` independent chunks (default 2 — classic
  double buffering), and emits each chunk's **ring reduce-scatter** phase;
- ``allreduce_wait`` emits each chunk's **ring allgather** phase and
  reassembles the exact original shape.

Between start and wait the program is free: independent compute issued
there has no data dependency on either phase, and chunk ``i``'s allgather
can run while chunk ``i+1``'s reduce-scatter is still on the wire.
``reduce_scatter_start/wait`` splits the same way (its blocks chunk over
the payload axis; the wait phase is pure reassembly).

Where the ring is not expressible (unequal color-split groups, callable
reductions, a forced butterfly, k <= 1) the start emits the whole
collective and the wait is reassembly only — always correct, no overlap.

Instrumentation spans the pair: the resilience plan's fault probe and
**watchdog arm** tie to the start's inputs and the **disarm** to the
wait's output (an unwaited collective is "in flight" and will trip the
watchdog); the telemetry events bracket opens at the start's input
readiness (arrival) and closes at the wait's output, so cross-rank skew
attributes stragglers exactly like the synchronous ops.  The analysis
layer records both ops with a shared span id — MPX112 flags a start whose
wait never appears (its phases would be dead-code-eliminated silently)
and a wait without a live start.

``mpx.overlap()`` is the implicit form: inside the region, plain
``allreduce``/``reduce_scatter`` calls auto-split — the start is emitted
at the call site and the wait is deferred until the result is first used
(or the region exits), so everything between the call and the use
overlaps with the wire phases.
"""

from __future__ import annotations

import itertools
from typing import List, Optional

from ..utils import config
from ..utils.validation import enforce_types
from ..parallel.comm import Comm
from . import _fusion
from .token import Token, consume, produce

__all__ = [
    "AsyncHandle",
    "P2PHandle",
    "allreduce_start",
    "allreduce_wait",
    "alltoall_start",
    "alltoall_wait",
    "reduce_scatter_start",
    "reduce_scatter_wait",
    "send_start",
    "recv_start",
    "p2p_wait",
    "overlap",
    "overlap_cache_token",
    "overlap_chunk_split",
]

_span_counter = itertools.count()


def overlap_cache_token() -> tuple:
    """Folded into both compiled-program cache keys: the chunk count
    shapes every start/wait trace."""
    return (config.overlap_chunks(),)


def overlap_chunk_split(n: int, chunks: int) -> List[int]:
    """Chunk element counts for an ``n``-element payload (pure — shared
    with tests/test_overlap.py's plan checks): at most ``chunks`` pieces,
    balanced to within one ``ceil(n/chunks)`` stride, none empty, summing
    to ``n``."""
    if n <= 0:
        return [n]
    c = max(1, min(int(chunks), n))
    stride = -(-n // c)
    sizes = []
    left = n
    while left > 0:
        take = min(stride, left)
        sizes.append(take)
        left -= take
    return sizes


class AsyncHandle:
    """In-flight state of one started collective: the phase-1 outputs plus
    the instrumentation stash the wait must close (watchdog disarm,
    telemetry bracket end, native op_end)."""

    __slots__ = ("kind", "comm", "reduction", "shape", "dtype", "sizes",
                 "k", "mode", "pieces", "span", "uid", "waited", "algo",
                 "plan")

    def __init__(self, kind, comm, reduction):
        self.kind = kind
        self.comm = comm
        self.reduction = reduction
        self.shape = None
        self.dtype = None
        self.sizes = None       # chunk element counts (ring mode)
        self.k = None
        self.mode = None        # "ring" | "hier" | "full"
        self.pieces = None
        self.span = None
        self.uid = next(_span_counter)
        self.waited = False
        self.algo = None
        self.plan = None        # HierPlan (hier mode only)

    def __repr__(self):
        state = "waited" if self.waited else "in-flight"
        return (f"AsyncHandle({self.kind}#{self.uid}, mode={self.mode}, "
                f"{state})")


class P2PHandle(AsyncHandle):
    """In-flight state of one async point-to-point half
    (``send_start``/``recv_start`` — the pipeline boundary transfers,
    docs/pipeline.md).  ``kind`` is ``"send"`` or ``"recv"``; the pair
    closes with :func:`p2p_wait`."""

    __slots__ = ("tag", "pairs")

    def __init__(self, kind, comm, tag):
        super().__init__(kind, comm, None)
        self.tag = tag
        self.pairs = None

    def __repr__(self):
        state = "waited" if self.waited else "in-flight"
        return (f"P2PHandle({self.kind}#{self.uid}, tag={self.tag}, "
                f"{state})")


# ---------------------------------------------------------------------------
# the instrumentation span (start -> wait)
# ---------------------------------------------------------------------------


def _span_open(base_op: str, comm, arrays, token, handle: AsyncHandle):
    """Open the pair-spanning instrumentation at the start op: resilience
    probe + watchdog arm and the events-tier journal begin tie to the
    start's inputs; the closers are stashed on the handle for the wait."""
    from .. import native
    from ..resilience import runtime as _resilience
    from ..telemetry import bracket as _tbracket
    from ..telemetry import core as _tcore
    from ..utils.debug import get_runtime_tracing
    from ._base import _mpi_opname, _next_call_id

    plan = _resilience.plan_for(base_op)
    tracing = get_runtime_tracing() and native.runtime_tracing_supported()
    rec = _tcore.current_open()  # the open start-op counter record
    ebr = _tbracket.bracket_for(rec)
    if plan is None and not tracing and ebr is None:
        handle.span = None
        return arrays, token
    call_id = _next_call_id()
    name = _mpi_opname(base_op)
    rank = None
    if plan is not None:
        arrays, token = plan.before(name, call_id, comm, arrays, token)
    if ebr is not None:
        arrays, token = ebr.begin(call_id, comm, arrays, token)
    if tracing:
        rank = comm.Get_rank()
        begin = native.op_begin(name, call_id, rank, "")
        arrays = tuple(native._tie(a, begin) for a in arrays)
    handle.span = (plan, call_id, name, ebr, tracing, rank)
    return arrays, token


def _span_close(handle: AsyncHandle, comm, dep, results) -> None:
    """Close the span at the wait op: native op_end, journal end, watchdog
    disarm + output guards — each tied to the wait's first output."""
    if handle.span is None:
        return
    from .. import native

    plan, call_id, name, ebr, tracing, rank = handle.span
    handle.span = None
    if tracing:
        native.op_end(name, call_id, rank, dep)
    if ebr is not None:
        ebr.end(call_id, comm, dep)
    if plan is not None:
        plan.after(name, call_id, comm, dep, results)


def _meter_chunks(opname: str, comm, dtype, n_chunks: int) -> None:
    from ..telemetry import core as _telemetry

    if _telemetry.effective_mode() == "off":
        return
    _telemetry.meter(f"overlap.{opname}.c{comm.uid}.{dtype}.chunks", n_chunks)


def _require_region(opname: str, comm):
    from ..parallel.region import in_parallel_region, resolve_comm

    comm = resolve_comm(comm)
    if not in_parallel_region(comm):
        raise RuntimeError(
            f"{opname}: the async start/wait collectives work inside a "
            "parallel region only (mpx.spmd / mpx.run / jax.shard_map); "
            "eager global-array calls have one compiled program per op, "
            "so there is no schedule to overlap into."
        )
    return comm


def _annotate_algo(algo: str, link=None) -> None:
    """Record the selected algorithm (analysis + telemetry) and, when
    given, the modeled per-link-class wire bytes.  The start op carries
    the FULL model for the exchange it initiates; the wait op annotates
    ``(0, 0)`` — its traffic is already accounted at the start, and the
    payload-on-intra default would double-count the pieces."""
    from ..analysis.hook import annotate
    from ..telemetry.core import annotate as t_annotate

    annotate(algo=algo)
    if link is None:
        t_annotate(algo=algo)
    else:
        t_annotate(algo=algo, link_bytes=link)


# ---------------------------------------------------------------------------
# allreduce start / wait
# ---------------------------------------------------------------------------


@enforce_types(comm=(Comm, None), token=(Token, None))
def allreduce_start(x, op=None, *, comm: Optional[Comm] = None,
                    token: Optional[Token] = None):
    """Begin an async allreduce: emits the chunked ring reduce-scatter
    phase and returns ``(handle, token)``.  Issue independent compute,
    then finish with :func:`allreduce_wait` (docs/overlap.md).
    """
    from . import _algos
    from ._base import (SUM, Op, apply_allreduce, as_varying, dispatch,
                        reduction_name)

    if op is None:
        op = SUM
    comm = _require_region("allreduce_start", comm)
    handle = AsyncHandle("allreduce", comm, op)

    def body(comm, arrays, token):
        arrays, token = _span_open("allreduce", comm, arrays, token, handle)
        (xl,) = arrays
        xl = consume(token, xl)
        handle.shape = xl.shape
        handle.dtype = xl.dtype
        k = _algos.static_group_size(comm)
        algo = config.collective_algo()
        ring_ok = (k is not None and k > 1 and isinstance(op, Op)
                   and algo != "butterfly")
        if not ring_ok:
            handle.mode = "full"
            handle.algo = "butterfly"
            full = apply_allreduce(xl, op, comm)
            return full, produce(token, full)
        # hierarchical composition (docs/topology.md): when the comm
        # spans multiple hosts and the selector would pick the two-level
        # lowering, each overlap chunk's start phase runs the intra-host
        # reduce-scatter AND the inter-host (DCN) exchange, and the wait
        # phase is the intra-host allgather — so independent compute
        # overlaps the expensive DCN rounds, not just the ICI ring.
        from . import _hierarchy

        plan = _hierarchy.hier_plan(comm)
        use_hier = (
            plan is not None and plan.r > 1
            and _algos.resolve_algo(
                algo, xl.size * xl.dtype.itemsize, k, ring_ok=True,
                hier_ok=True) == "hier"
        )
        handle.mode = "hier" if use_hier else "ring"
        handle.algo = "hier" if use_hier else "ring"
        handle.k = k
        handle.plan = plan if use_hier else None
        xl = as_varying(xl, comm.axes)
        flat = xl.reshape(-1)
        nbytes = flat.shape[0] * xl.dtype.itemsize
        # payload-aware chunk count: a tuning layer may bucket it by
        # payload bytes (docs/autotune.md); env flag still wins
        sizes = overlap_chunk_split(flat.shape[0],
                                    config.overlap_chunks(nbytes))
        handle.sizes = sizes
        if use_hier:
            link = _hierarchy.hier_link_bytes("allreduce", nbytes, plan.h,
                                              plan.r)
        else:
            link = _hierarchy.flat_link_bytes(
                "allreduce", "ring", nbytes, k, _hierarchy.comm_hosts(comm)
            )
        _annotate_algo(handle.algo, link)
        _meter_chunks("allreduce", comm, flat.dtype, len(sizes))
        pieces = []
        off = 0
        for csz in sizes:
            seg = flat[off:off + csz]
            off += csz
            if use_hier:
                chunk, padded = _algos.chunk_layout(csz, plan.r)
                blocks = _algos._pad_to(seg, padded).reshape(plan.r, chunk)
                piece = _algos.apply_ring_reduce_scatter(
                    blocks, op, plan.intra, plan.r
                )
                piece = _hierarchy._inter_allreduce(
                    piece, op, plan, chunk * xl.dtype.itemsize
                )
            else:
                chunk, padded = _algos.chunk_layout(csz, k)
                blocks = _algos._pad_to(seg, padded).reshape(k, chunk)
                piece = _algos.apply_ring_reduce_scatter(blocks, op, comm, k)
            pieces.append(piece)
        return (*pieces, produce(token, pieces[0]))

    out = dispatch("allreduce_start", comm, body, (x,), token,
                   ana={"reduction": reduction_name(op), "span": handle.uid},
                   bare=True)
    *pieces, tok = out
    handle.pieces = tuple(pieces)
    return handle, tok


@enforce_types(token=(Token, None))
def allreduce_wait(handle, *, token: Optional[Token] = None):
    """Finish an async allreduce: emits the chunked ring allgather phase,
    reassembles the exact input shape, and closes the start's
    instrumentation span.  Returns ``(result, token)``."""
    _check_handle("allreduce_wait", handle, "allreduce")
    from . import _algos
    from ._base import dispatch

    comm = handle.comm

    def body(comm, arrays, token):
        arrays = consume(token, *arrays)
        if len(handle.pieces) == 1:
            arrays = (arrays,)
        if handle.mode == "full":
            res = arrays[0]
        else:
            import jax.numpy as jnp

            if handle.mode == "hier":
                # the wait phase of the two-level split: the intra-host
                # (ICI) allgather — the DCN exchange already ran at start
                gather_comm = handle.plan.intra
                k = handle.plan.r
                pos = gather_comm.Get_rank()
            else:
                gather_comm, k, pos = comm, handle.k, comm.Get_rank()
            parts = []
            for piece, csz in zip(arrays, handle.sizes):
                full = _algos.apply_ring_allgather(piece, gather_comm, k, pos)
                parts.append(full.reshape(-1)[:csz])
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            res = flat.reshape(handle.shape)
        _annotate_algo(handle.algo, link=(0, 0))
        _span_close(handle, comm, res, [res])
        return res, produce(token, res)

    res, tok = dispatch("allreduce_wait", comm, body, handle.pieces, token,
                        ana={"span": handle.uid}, bare=True)
    handle.waited = True
    handle.pieces = None
    return res, tok


# ---------------------------------------------------------------------------
# alltoall start / wait
# ---------------------------------------------------------------------------


@enforce_types(comm=(Comm, None), token=(Token, None))
def alltoall_start(x, *, comm: Optional[Comm] = None,
                   token: Optional[Token] = None):
    """Begin an async alltoall of ``x`` (shape ``(size, *s)``, block
    ``i`` addressed to rank ``i``): splits the per-block payload into
    ``MPI4JAX_TPU_OVERLAP_CHUNKS`` independent double-buffered
    pairwise-exchange phases and emits them all, returning
    ``(handle, token)``.  Issue independent compute — the next capacity
    chunk's expert MLP, in the MoE recipe (docs/moe.md) — then finish
    with :func:`alltoall_wait`.

    On a multi-host comm above ``MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES``
    each chunk's exchange runs the two-level hierarchical split
    (ops/_hierarchy.py) — intra-host transpose AND the DCN exchange at
    start — so the compute in the gap overlaps the expensive inter-host
    messages, not just ICI.  The wait is pure reassembly either way.
    """
    from . import _algos, _hierarchy
    from ._base import as_varying, dispatch

    comm = _require_region("alltoall_start", comm)
    handle = AsyncHandle("alltoall", comm, None)

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        if xl.ndim == 0 or xl.shape[0] != size:
            raise ValueError(
                f"alltoall_start input must have leading axis == comm "
                f"size ({size}), got shape {xl.shape}"
            )
        arrays, token = _span_open("alltoall", comm, (xl,), token, handle)
        xl = consume(token, arrays[0])
        handle.shape = xl.shape
        handle.dtype = xl.dtype
        handle.k = size
        xl = as_varying(xl, comm.axes)
        if size == 1:
            handle.mode = "full"
            handle.algo = "native"
            res = xl  # one rank: the exchange is the identity
            return res, produce(token, res)
        nbytes = xl.size * xl.dtype.itemsize
        plan = _hierarchy.hier_plan(comm)
        # the flat form here is the pairwise ppermute exchange (not the
        # monolithic AllToAll HLO): each chunk must be an INDEPENDENT
        # op chain the scheduler can interleave compute between, and
        # pairwise is expressible on color splits too
        algo = _algos.resolve_alltoall_algo(
            config.collective_algo(), nbytes,
            hier_ok=plan is not None, flat="pairwise",
        )
        use_hier = algo == "hier"
        handle.mode = "hier" if use_hier else "flat"
        handle.algo = algo
        handle.plan = plan if use_hier else None
        blocks = xl.reshape(size, -1)
        sizes = overlap_chunk_split(blocks.shape[1],
                                    config.overlap_chunks(nbytes))
        handle.sizes = sizes
        _hierarchy.annotate_selection("alltoall", algo, nbytes, size,
                                      plan, comm, dtype=xl.dtype.name)
        _meter_chunks("alltoall", comm, blocks.dtype, len(sizes))
        pieces = []
        off = 0
        for csz in sizes:
            seg = blocks[:, off:off + csz]
            off += csz
            if use_hier:
                pieces.append(_hierarchy.apply_hier_alltoall(seg, comm,
                                                             plan))
            else:
                pieces.append(_algos.apply_pairwise_alltoall(seg, comm,
                                                             size))
        return (*pieces, produce(token, pieces[0]))

    out = dispatch("alltoall_start", comm, body, (x,), token,
                   ana={"span": handle.uid}, bare=True)
    *pieces, tok = out
    handle.pieces = tuple(pieces)
    return handle, tok


@enforce_types(token=(Token, None))
def alltoall_wait(handle, *, token: Optional[Token] = None):
    """Finish an async alltoall: reassembles the exact input shape from
    the chunk pieces (every exchange phase already ran at start — the
    wait is the reassembly barrier the results gate on) and closes the
    start's instrumentation span.  Returns ``(result, token)``."""
    _check_handle("alltoall_wait", handle, "alltoall")
    from ._base import dispatch

    comm = handle.comm

    def body(comm, arrays, token):
        arrays = consume(token, *arrays)
        if len(handle.pieces) == 1:
            arrays = (arrays,)
        if handle.mode == "full":
            res = arrays[0]
        else:
            import jax.numpy as jnp

            parts = [p.reshape(handle.k, -1) for p in arrays]
            flat = (jnp.concatenate(parts, axis=1) if len(parts) > 1
                    else parts[0])
            res = flat.reshape(handle.shape)
        _annotate_algo(handle.algo, link=(0, 0))
        _span_close(handle, comm, res, [res])
        return res, produce(token, res)

    res, tok = dispatch("alltoall_wait", comm, body, handle.pieces, token,
                        ana={"span": handle.uid}, bare=True)
    handle.waited = True
    handle.pieces = None
    return res, tok


# ---------------------------------------------------------------------------
# reduce_scatter start / wait
# ---------------------------------------------------------------------------


@enforce_types(comm=(Comm, None), token=(Token, None))
def reduce_scatter_start(x, op=None, *, comm: Optional[Comm] = None,
                         token: Optional[Token] = None):
    """Begin an async reduce_scatter of ``x`` (shape ``(size, *s)``, block
    ``i`` addressed to rank ``i``): emits the chunked ring reduce-scatter
    phase and returns ``(handle, token)``; finish with
    :func:`reduce_scatter_wait`."""
    from . import _algos
    from ._base import SUM, Op, as_varying, dispatch, reduction_name

    if op is None:
        op = SUM
    comm = _require_region("reduce_scatter_start", comm)
    handle = AsyncHandle("reduce_scatter", comm, op)

    def body(comm, arrays, token):
        (xl,) = arrays
        size = comm.Get_size()
        if xl.ndim == 0 or xl.shape[0] != size:
            raise ValueError(
                f"reduce_scatter_start input must have leading axis == "
                f"comm size ({size}), got shape {xl.shape}"
            )
        arrays, token = _span_open("reduce_scatter", comm, (xl,), token,
                                   handle)
        xl = consume(token, arrays[0])
        handle.shape = xl.shape[1:]
        handle.dtype = xl.dtype
        handle.k = size
        xl = as_varying(xl, comm.axes)
        if size == 1:
            handle.mode = "full"
            handle.algo = "butterfly"
            res = xl[0]
            return res, produce(token, res)
        algo = config.collective_algo()
        if not isinstance(op, Op) or algo == "butterfly":
            handle.mode = "full"
            handle.algo = "butterfly"
            res = _algos.apply_reduce_scatter(xl, op, comm)
            return res, produce(token, res)
        # hierarchical composition: each chunk's start runs the full
        # two-level exchange (intra super-block reduce-scatter over ICI,
        # then the inter-host reduce-scatter over DCN); there is no
        # second data-movement phase for reduce_scatter, so the wait
        # stays pure reassembly and everything in the gap overlaps both
        # levels (docs/topology.md)
        from . import _hierarchy

        plan = _hierarchy.hier_plan(comm)
        use_hier = (
            plan is not None
            and _algos.resolve_algo(
                algo, xl.size * xl.dtype.itemsize, size, ring_ok=True,
                hier_ok=True) == "hier"
        )
        handle.mode = "ring"
        handle.algo = "hier" if use_hier else "ring"
        blocks = xl.reshape(size, -1)
        nbytes = xl.size * xl.dtype.itemsize
        sizes = overlap_chunk_split(blocks.shape[1],
                                    config.overlap_chunks(nbytes))
        handle.sizes = sizes
        if use_hier:
            link = _hierarchy.hier_link_bytes("reduce_scatter", nbytes,
                                              plan.h, plan.r)
        else:
            link = _hierarchy.flat_link_bytes(
                "reduce_scatter", "ring", nbytes, size,
                _hierarchy.comm_hosts(comm)
            )
        _annotate_algo(handle.algo, link)
        _meter_chunks("reduce_scatter", comm, blocks.dtype, len(sizes))
        pieces = []
        off = 0
        for csz in sizes:
            sub = blocks[:, off:off + csz]
            off += csz
            if use_hier:
                pieces.append(
                    _hierarchy.apply_hier_reduce_scatter(sub, op, comm, plan)
                )
            else:
                pieces.append(_algos.apply_ring_reduce_scatter(sub, op, comm,
                                                               size))
        return (*pieces, produce(token, pieces[0]))

    out = dispatch("reduce_scatter_start", comm, body, (x,), token,
                   ana={"reduction": reduction_name(op), "span": handle.uid},
                   bare=True)
    *pieces, tok = out
    handle.pieces = tuple(pieces)
    return handle, tok


@enforce_types(token=(Token, None))
def reduce_scatter_wait(handle, *, token: Optional[Token] = None):
    """Finish an async reduce_scatter: reassembles this rank's block
    (shape ``s``) from the chunk pieces and closes the span.  Returns
    ``(result, token)``."""
    _check_handle("reduce_scatter_wait", handle, "reduce_scatter")
    from ._base import dispatch

    comm = handle.comm

    def body(comm, arrays, token):
        arrays = consume(token, *arrays)
        if len(handle.pieces) == 1:
            arrays = (arrays,)
        if handle.mode == "full":
            res = arrays[0]
        else:
            import jax.numpy as jnp

            flat = (jnp.concatenate(arrays) if len(arrays) > 1
                    else arrays[0])
            res = flat.reshape(handle.shape)
        _annotate_algo(handle.algo, link=(0, 0))
        _span_close(handle, comm, res, [res])
        return res, produce(token, res)

    res, tok = dispatch("reduce_scatter_wait", comm, body, handle.pieces,
                        token, ana={"span": handle.uid}, bare=True)
    handle.waited = True
    handle.pieces = None
    return res, tok


# ---------------------------------------------------------------------------
# async point-to-point: send_start / recv_start / p2p_wait
# ---------------------------------------------------------------------------
#
# The pipeline boundary transfers (parallel/pipeline.py, docs/pipeline.md).
# Semantics mirror the synchronous halves exactly — send_start queues the
# payload on the region's (comm, tag) FIFO, recv_start pops the match and
# emits the fused CollectivePermute — but the pair carries the span
# instrumentation of the collective starts: watchdog arm at the start,
# disarm at the wait, one events-tier bracket across the gap.  The
# transfer is EMITTED at recv_start and first USED at p2p_wait, so
# everything issued between the two (the next microbatch's stage compute)
# has no data dependency on the wire and overlaps it.  The op names end
# in ``_start``/``_wait`` deliberately: MPX112 (unpaired span) and MPX130
# (span straddling a megastep iteration) apply as-is.


@enforce_types(tag=int, comm=(Comm, None), token=(Token, None))
def send_start(x, dest, tag: int = 0, *, comm: Optional[Comm] = None,
               token: Optional[Token] = None):
    """Begin an async send of ``x`` along routing ``dest``: queues the
    payload for the matching ``recv_start`` on the same comm and tag
    (buffered — the transfer itself is emitted at the receive) and opens
    the instrumentation span.  Returns ``(handle, token)``; close the
    span with :func:`p2p_wait` (docs/pipeline.md)."""
    from ..parallel.rankspec import resolve_routing
    from ..parallel.region import current_context
    from ..utils.debug import log_op
    from ._base import dispatch
    from .send import PendingSend

    comm = _require_region("send_start", comm)
    handle = P2PHandle("send", comm, tag)

    def body(comm, arrays, token):
        from ..analysis.hook import annotate
        from ..analysis.schedule import concretizing

        arrays, token = _span_open("send", comm, arrays, token, handle)
        (xl,) = arrays
        xl = consume(token, xl)
        handle.shape = xl.shape
        handle.dtype = xl.dtype
        pairs = resolve_routing(comm, None, dest, what="send")  # GLOBAL
        handle.pairs = pairs
        annotate(pairs=pairs)
        log_op("MPI_Isend", comm.Get_rank(),
               f"{xl.size} items along {list(pairs)} (tag {tag})")
        if not concretizing():
            # per-rank re-traces record one-sided (the cross-rank
            # matcher pairs the halves); the real trace queues for the
            # matching recv_start, exactly like the blocking send
            ctx = current_context()
            ctx.queue(comm.uid, tag).append(PendingSend(xl, pairs, token))
        return xl, produce(token, xl)

    res, tok = dispatch("send_start", comm, body, (x,), token,
                        ana={"span": handle.uid, "tag": tag}, bare=True)
    handle.pieces = (res,)
    return handle, tok


@enforce_types(tag=int, comm=(Comm, None), token=(Token, None))
def recv_start(x, source=None, tag: int = 0, *, comm: Optional[Comm] = None,
               token: Optional[Token] = None):
    """Begin an async receive into ``x``'s shape/dtype: pops the matching
    queued ``send_start``/``send`` (FIFO per (comm, tag);
    ``source=None`` adopts the send's routing, like ``recv``) and emits
    the fused CollectivePermute HERE — the result is first *used* at
    :func:`p2p_wait`, so compute issued in the gap overlaps the wire.
    Returns ``(handle, token)``."""
    from ..parallel.rankspec import resolve_routing
    from ..parallel.region import current_context
    from ..utils.debug import log_op
    from ._base import as_varying, dispatch
    from .recv import _check_recv_match
    from .sendrecv import _apply_permute

    comm = _require_region("recv_start", comm)
    handle = P2PHandle("recv", comm, tag)

    def body(comm, arrays, token):
        from ..analysis.hook import annotate
        from ..analysis.report import mpx_error
        from ..analysis.schedule import concretizing

        arrays, token = _span_open("recv", comm, arrays, token, handle)
        (template,) = arrays
        handle.shape = template.shape
        handle.dtype = template.dtype
        if concretizing():
            # per-rank schedule trace: record one-sided; the matcher
            # pairs the transfer at the p2p_wait position (the blocking
            # point — analysis/schedule.py routes the span there)
            pairs = (resolve_routing(comm, source, None, what="recv")
                     if source is not None else None)
            handle.pairs = pairs
            annotate(pairs=pairs)
            res = as_varying(template, comm.axes)
            return res, produce(token, res)
        ctx = current_context()
        q = ctx.queue(comm.uid, tag)
        if not q:
            raise mpx_error(
                RuntimeError, "MPX102",
                f"recv_start(tag={tag}): no matching send queued on this "
                "comm. Under SPMD, the matching send/send_start must "
                "appear earlier in the same parallel region (the "
                "reference would deadlock here at run time; this "
                "framework turns it into a trace error).",
            )
        if len(q) >= 2:
            annotate(queue_depth=len(q))
        pending = q.popleft()
        _check_recv_match(pending, template, source, comm)
        annotate(pairs=pending.pairs)
        handle.pairs = pending.pairs
        payload = as_varying(consume(token, pending.value), comm.axes)
        log_op("MPI_Irecv", comm.Get_rank(),
               f"{payload.size} items along {list(pending.pairs)} "
               f"(tag {tag})")
        res = _apply_permute(payload, template, pending.pairs, comm)
        return res, produce(token, res)

    res, tok = dispatch("recv_start", comm, body, (x,), token,
                        ana={"span": handle.uid, "tag": tag}, bare=True)
    handle.pieces = (res,)
    return handle, tok


@enforce_types(token=(Token, None))
def p2p_wait(handle, *, token: Optional[Token] = None):
    """Finish an async p2p half: returns ``(value, token)`` — the
    received payload for a ``recv_start`` handle, the sent payload (a
    passthrough) for a ``send_start`` handle — and closes the span
    (watchdog disarm, events bracket end)."""
    from ..telemetry.core import annotate as t_annotate
    from ._base import dispatch

    _check_p2p_handle("p2p_wait", handle)
    comm = handle.comm

    def body(comm, arrays, token):
        res = consume(token, *arrays)
        # the payload bytes were accounted at the start half; zero the
        # wait's link attribution so the pair is not double-counted
        t_annotate(link_bytes=(0, 0))
        _span_close(handle, comm, res, [res])
        return res, produce(token, res)

    res, tok = dispatch("p2p_wait", comm, body, handle.pieces, token,
                        ana={"span": handle.uid, "tag": handle.tag},
                        bare=True)
    handle.waited = True
    handle.pieces = None
    return res, tok


def _check_p2p_handle(opname: str, handle) -> None:
    from ..analysis.report import mpx_error

    if not isinstance(handle, P2PHandle):
        raise TypeError(
            f"{opname} expects the P2PHandle returned by send_start/"
            f"recv_start, got {handle!r}"
        )
    if handle.waited:
        raise mpx_error(
            RuntimeError, "MPX112",
            f"{opname}: this handle was already waited — each "
            "send_start/recv_start pairs with exactly one p2p_wait",
        )


def _check_handle(opname: str, handle, kind: str) -> None:
    from ..analysis.report import mpx_error

    if not isinstance(handle, AsyncHandle) or handle.kind != kind:
        raise TypeError(
            f"{opname} expects the AsyncHandle returned by {kind}_start, "
            f"got {handle!r}"
        )
    if handle.waited:
        raise mpx_error(
            RuntimeError, "MPX112",
            f"{opname}: this handle was already waited — each "
            f"{kind}_start pairs with exactly one {kind}_wait",
        )


# ---------------------------------------------------------------------------
# the overlap() region: implicit start/wait
# ---------------------------------------------------------------------------


class _Scope:
    __slots__ = ("lazies",)

    def __init__(self):
        self.lazies: List["_LazyWait"] = []


_overlap_stack: List[_Scope] = []


class overlap:
    """``with mpx.overlap():`` — inside, ``allreduce``,
    ``reduce_scatter``, and ``alltoall`` auto-split into start/wait: the
    start phase is
    emitted at the call site and the wait is deferred until the result is
    first used (or the region exits), so the compute issued in between
    overlaps with the wire phases.  Requires a managed parallel region
    (``mpx.spmd`` / ``mpx.run``); see docs/overlap.md.

    While a start is in flight — including the implicit ones this region
    defers — its input buffer is live on the wire: donating it to a
    pinned executable (``mpx.compile(donate_argnums=...)``) before the
    wait is a write-after-start race, flagged MPX139 by the dataflow
    hazard verifier (docs/analysis.md "Dataflow hazards")."""

    def __enter__(self):
        from ..parallel.region import _region_stack

        if not _region_stack:
            raise RuntimeError(
                "mpx.overlap() requires a managed parallel region "
                "(mpx.spmd / mpx.run); use explicit allreduce_start/"
                "allreduce_wait inside a raw jax.shard_map body"
            )
        self._scope = _Scope()
        _overlap_stack.append(self._scope)
        return self

    def __exit__(self, exc_type, exc, tb):
        _overlap_stack.pop()
        if exc_type is None:
            for lw in self._scope.lazies:
                lw._force()
        return False


class _LazyWait(_fusion.LazyResult):
    """Deferred wait: forces ``*_wait`` on first use of the result."""

    __slots__ = ("_handle",)

    def __init__(self, handle, shape, dtype):
        super().__init__(shape, dtype, None)
        self._handle = handle

    def _force(self):
        if self._value is None:
            if self._handle.kind == "allreduce":
                res, _ = allreduce_wait(self._handle)
            elif self._handle.kind == "alltoall":
                res, _ = alltoall_wait(self._handle)
            else:
                res, _ = reduce_scatter_wait(self._handle)
            self._value = res
        return self._value


def overlap_active() -> bool:
    """True when ops should auto-split (inside ``mpx.overlap()``, not
    mid-flush of the fusion layer)."""
    return bool(_overlap_stack) and not _fusion._inhibit


def maybe_lazy(opname: str, x, op, comm, token):
    """Route one collective through start + deferred wait; ``None`` when
    the overlap region is inactive for this call."""
    if not overlap_active():
        return None
    from ..parallel.region import in_parallel_region, resolve_comm

    comm = resolve_comm(comm)
    if not in_parallel_region(comm):
        return None
    if opname == "allreduce":
        handle, tok = allreduce_start(x, op, comm=comm, token=token)
    elif opname == "alltoall":
        handle, tok = alltoall_start(x, comm=comm, token=token)
    else:
        handle, tok = reduce_scatter_start(x, op, comm=comm, token=token)
    lw = _LazyWait(handle, handle.shape, handle.dtype)
    _overlap_stack[-1].lazies.append(lw)
    return lw, tok
