"""Payload-aware collective algorithms: ring lowerings + the auto-selector.

The butterfly lowerings in ``_base.py`` ship the FULL payload every round —
O(size·log k) bytes per rank.  That is latency-optimal for small payloads
(``ceil(log2 k)`` neighbor hops) but a bandwidth disaster for large ones:
the well-known ring algorithms move only O(size) bytes per rank, the
bandwidth-optimal bound ``benchmarks/micro.py`` normalizes against
(``2·(n-1)/n·size`` for an allreduce).  This module provides:

- ``apply_ring_allreduce`` — ring reduce-scatter + ring allgather, for all
  10 ``Op``s and associative callables (ascending group-rank fold order is
  preserved for non-commutative callables via a lo/hi accumulator pair —
  see ``rs_update_pair``);
- ``apply_ring_reduce_scatter`` — the reduce-scatter building block, also
  the lowering of the public ``reduce_scatter`` op (ops/reduce_scatter.py);
- ``apply_ring_allgather`` — the allgather building block;
- ``apply_vdg_bcast`` — binomial-halving scatter + ring allgather broadcast
  (van de Geijn), ~2·size bytes per rank vs the doubling broadcast's
  size·log2(k);
- ``resolve_algo`` / ``algo_cache_token`` — per-call butterfly-vs-ring
  selection from STATIC payload bytes and group size, forced via
  ``MPI4JAX_TPU_COLLECTIVE_ALGO={auto,butterfly,ring}`` and folded into the
  compiled-program cache keys exactly like the resilience flags.

Ring lowerings need a static uniform group size (the chunk count); unequal
color-split groups keep the butterfly.  Chunks are padded to ``k·chunk``
elements so payloads not divisible by ``k`` lower cleanly; padding lanes
are discarded after the final reshape, so garbage combines never leak.

**Callable caveat**: ``apply_ring_allreduce`` splits the flattened payload
into chunks and applies the reduction per chunk, so a callable op must be
ELEMENTWISE (the ``MPI_User_function`` contract).  Whole-array callables
(e.g. ``jnp.matmul``) are only valid with the butterfly — ``auto`` never
routes callables to the ring; only an explicit ``ring`` override does.
``reduce_scatter`` has no such caveat: its chunks are the user's own
blocks, so block-wise callables (including ``jnp.matmul``) work there.

The index formulas and update rules below are polymorphic over Python ints
and traced values, so ``tests/test_algos.py`` drives the SAME functions
through a pure-Python lockstep simulator (symbolic string folds pin the
exact combine order; numpy folds pin all 10 ops) without needing a
multi-device mesh.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..utils import config

# ``auto`` never picks the ring below this group size: with k < 4 the ring's
# 2·(k-1) rounds don't beat the butterfly's 2·ceil(log2 k) and the byte
# volumes are comparable.
RING_MIN_GROUP = 4


# ---------------------------------------------------------------------------
# selection
# ---------------------------------------------------------------------------


def algo_cache_token() -> tuple:
    """Hashable fingerprint of the algorithm-selection configuration —
    folded into every compiled-program cache key that caches op lowerings
    (mirrors ``resilience.runtime.cache_token``), so toggling
    ``MPI4JAX_TPU_COLLECTIVE_ALGO`` — or the topology override / DCN
    crossover the hierarchical layer reads — retraces instead of silently
    serving the old program.  (The mesh-derived half of the topology is
    already in both cache keys via the mesh itself.)

    The tuning layer's content stamp (``config.tuning_stamp()``,
    docs/autotune.md) folds in whenever a layer is active — every
    ``mpx.load_tuning`` of new content retraces even where its values
    happen to match the defaults (the env route pins a file's content
    at first read per process, so an in-place edit needs the explicit
    ``load_tuning(path)`` refresh); with no layer the token is exactly
    the flat 5-tuple below (the alltoall crossover joined the base in
    PR 15, deliberately moving every cache key once) with no trailing
    stamp entry (pinned by tests/test_autotune_pure.py).

    The DCN wire codec (``MPI4JAX_TPU_COMPRESS``, docs/compression.md)
    folds the same conditional way: only when a codec is active — so
    ``off`` (the default) keeps the token EXACTLY the pre-compression
    value (byte-identical HLO and cache keys, pinned by
    tests/test_compress_pure.py), while flipping to bf16/fp8 (or
    loading a tuning file that tunes the knob — already covered by the
    stamp) retraces every program."""
    base = (config.collective_algo(), config.ring_crossover_bytes(),
            config.dcn_crossover_bytes(), config.topology_spec(),
            config.alltoall_crossover_bytes())
    compress = config.compress_mode()
    if compress != "off":
        base = base + (("compress", compress),)
    stamp = config.tuning_stamp()
    return base if stamp is None else base + (("tuning", stamp),)


def static_group_size(comm):
    """The comm's uniform static group size, or ``None`` when group sizes
    differ (unequal color splits cannot ring: the chunk count is the group
    size and one SPMD program cannot express per-rank chunk counts).
    Plain delegation to ``Comm.uniform_size`` — the explicit accessor that
    replaced catching ``Get_size``'s ``RuntimeError`` as control flow —
    with the one remaining exceptional case (an unbound whole-axes comm
    outside any trace has no size at all) still mapped to ``None``."""
    try:
        return comm.uniform_size()
    except RuntimeError:  # unbound comm outside any trace
        return None


def resolve_algo(algo: str, payload_bytes: int, k: int, ring_ok: bool,
                 hier_ok: bool = False) -> str:
    """Pick ``"butterfly"``, ``"ring"``, or ``"hier"`` for one call.

    ``algo`` is the configured value (``config.collective_algo()``); forced
    values win, except that a forced algorithm falls back where it is not
    expressible — a forced ring to the butterfly (``ring_ok=False``:
    unequal groups, k <= 1, or a callable op on the chunked-allreduce
    path), a forced hier to the ``auto`` rules (``hier_ok=False``: no
    derivable topology, single-host comm, or a non-uniform /
    non-contiguous host partition — see ``_hierarchy.hier_plan``); never
    an error.  ``auto`` picks the two-level hierarchical lowering when the
    comm spans more than one host (``hier_ok``) and the payload clears the
    ring crossover, the flat ring for single-host payloads at/above
    ``ring_crossover_bytes()`` on groups of at least ``RING_MIN_GROUP``,
    and the butterfly otherwise.
    """
    if algo == "hier":
        if hier_ok:
            return "hier"
        algo = "auto"  # inexpressible: fall back to the auto rules
    if not ring_ok or algo == "butterfly":
        return "butterfly"
    if algo == "ring":
        return "ring"
    if k >= RING_MIN_GROUP and payload_bytes >= config.ring_crossover_bytes():
        return "hier" if hier_ok else "ring"
    return "butterfly"


def resolve_dcn_algo(shard_bytes: int, h: int, ring_ok: bool = True) -> str:
    """Inter-host (DCN) phase selection for the hierarchical lowerings:
    ring when the per-host shard clears ``dcn_crossover_bytes()`` on at
    least ``RING_MIN_GROUP`` hosts (DCN rounds are expensive — see the
    flag's default rationale in utils/config.py), butterfly otherwise.
    ``ring_ok=False`` (callable reductions: the DCN ring would re-chunk
    the shard) keeps the butterfly."""
    if (ring_ok and h >= RING_MIN_GROUP
            and shard_bytes >= config.dcn_crossover_bytes()):
        return "ring"
    return "butterfly"


def resolve_alltoall_algo(algo: str, payload_bytes: int, hier_ok: bool,
                          flat: str = "native") -> str:
    """Lowering pick for one alltoall: ``"hier"`` (the two-level
    ICI/DCN split of ops/_hierarchy.py) or ``flat`` (the single-level
    exchange — ``"native"`` for the one-AllToAll-HLO whole-axes path,
    ``"pairwise"`` for the chunked ppermute rounds the async split
    uses on color-split comms).

    ``MPI4JAX_TPU_COLLECTIVE_ALGO=hier`` forces the hierarchy where
    expressible (``hier_ok``); the forced flat algorithms
    (``butterfly``/``ring``) force the flat lowering — alltoall is a
    fixed permutation, so "flat" is the only single-level shape and
    both spellings mean it.  ``auto`` picks the hierarchy on a
    multi-host comm when the payload clears
    ``MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES`` (below it, one monolithic
    exchange's latency wins; above it, the intra-host aggregation cuts
    the DCN message count to 1/r of flat — docs/moe.md).  Bit-identical
    either way: no arithmetic, only routing.
    """
    if algo == "hier":
        return "hier" if hier_ok else flat
    if algo in ("butterfly", "ring"):
        return flat
    if hier_ok and payload_bytes >= config.alltoall_crossover_bytes():
        return "hier"
    return flat


def algorithm_bytes_per_rank(algo: str, nbytes: int, k: int,
                             preserve_order: bool = False) -> int:
    """Algorithmic bytes one rank ships for an allreduce of ``nbytes``
    (the docs/microbenchmarks.md byte-volume table; also pinned by
    tests/test_algos.py against the simulated lowerings)."""
    if k <= 1:
        return 0
    if algo == "butterfly":
        rounds = (k - 1).bit_length()  # ceil(log2 k)
        return 2 * rounds * nbytes  # fold + doubling broadcast, full payload
    chunk = -(-nbytes // k)
    pair = 2 if preserve_order else 1
    # reduce-scatter ships the accumulator (pair or single chunk) k-1
    # times; the allgather ships one chunk k-1 times
    return (k - 1) * chunk * (pair + 1)


# ---------------------------------------------------------------------------
# static structure: chunk layout, ring routing, index formulas
# (polymorphic over Python ints and traced values — shared with the
# lockstep simulator in tests/test_algos.py)
# ---------------------------------------------------------------------------


def chunk_layout(n: int, k: int):
    """(elements per chunk, padded element count ``k·chunk``) for an
    ``n``-element payload split into ``k`` ring chunks."""
    chunk = -(-n // k)
    return chunk, chunk * k


def ring_pairs(groups):
    """Static ppermute pairs of the ring: every rank sends to its group
    ring-successor, every round (only the circulating chunk indices
    rotate).  Singleton groups need no edges."""
    return [
        (members[p], members[(p + 1) % len(members)])
        for members in groups
        if len(members) > 1
        for p in range(len(members))
    ]


def rs_send_chunk(pos, r, k):
    """Chunk index group-position ``pos`` sends in reduce-scatter round
    ``r`` (chunk ``c``'s journey starts at position ``(c+1) % k`` and walks
    the ring ascending, ending at position ``c`` after ``k-1`` hops)."""
    return (pos - r - 1) % k


def rs_recv_chunk(pos, r, k):
    """Chunk index group-position ``pos`` receives in reduce-scatter round
    ``r`` (= the predecessor's ``rs_send_chunk``)."""
    return (pos - r - 2) % k


def ag_recv_chunk(pos, r, k):
    """Chunk index received in allgather round ``r`` at position ``pos``
    (entering round ``r`` each position holds chunk ``(pos - r) % k``)."""
    return (pos - r - 1) % k


def rs_update_pair(where, fn, pos, c, k, lo_in, hi_in, mine):
    """Order-preserving reduce-scatter accumulator update at the receiving
    position ``pos`` for chunk ``c``.

    Chunk ``c``'s ring journey visits positions ``c+1 … k-1`` then (after
    wrapping past the ring seam) ``0 … c``.  Associativity alone cannot
    repair a cyclically rotated fold, so the accumulator is a pair:
    ``hi`` folds the pre-wrap segment ``x_{c+1} … x_{k-1}`` and ``lo`` the
    post-wrap segment ``x_0 … x_c``, each in ascending group order; the
    final value is ``lo ∘ hi`` (``rs_finish_pair``) — the exact ascending
    fold the butterfly produces, commutativity never required.

    ``where(cond, a, b)`` is supplied by the caller: ``jnp.where`` when
    traced, a plain Python select in the simulator tests.  Both branches
    are evaluated; discarded garbage (the ``lo`` placeholder before the
    wrap) never reaches a kept lane.
    """
    pre = (pos > c) | (c == k - 1)  # chunk k-1's journey never wraps
    lo = where(pre, lo_in, where(pos == 0, mine, fn(lo_in, mine)))
    hi = where(pre, fn(hi_in, mine), hi_in)
    return lo, hi


def rs_finish_pair(where, fn, pos, k, lo, hi):
    """Final order-preserving reduce-scatter value at position ``pos``
    (which owns chunk ``pos``): ``lo ∘ hi``, except chunk ``k-1`` whose
    journey never wrapped (``lo`` still holds its placeholder)."""
    return where(pos == k - 1, hi, fn(lo, hi))


def rotation_pairs(groups, t: int):
    """Static ppermute pairs of alltoall pairwise-exchange round ``t``:
    every group position ``p`` sends to position ``(p + t) % k`` — one
    rotation per round, ``k - 1`` rounds total (round 0 is the local
    own-block copy).  Singleton groups need no edges."""
    return [
        (members[p], members[(p + t) % len(members)])
        for members in groups
        if len(members) > 1
        for p in range(len(members))
    ]


def a2a_send_block(pos, t, k):
    """Block index group-position ``pos`` ships in pairwise-exchange
    round ``t``: the block addressed to its round-``t`` partner
    ``(pos + t) % k``."""
    return (pos + t) % k


def a2a_recv_slot(pos, t, k):
    """Source position whose block arrives at ``pos`` in round ``t`` (=
    the output slot it fills): the rotation's inverse, ``(pos - t) % k``."""
    return (pos - t) % k


def next_pow2(k: int) -> int:
    return 1 << max(0, (k - 1).bit_length())


def vdg_widths(K: int):
    """Binomial-scatter half-widths for ``K = next_pow2(k)`` virtual
    chunks: K/2, K/4, …, 1."""
    w = K >> 1
    out = []
    while w >= 1:
        out.append(w)
        w >>= 1
    return out


def vdg_scatter_pairs(groups, root, w, K):
    """Static ppermute pairs of one binomial-scatter round: every holder at
    relative position ``r0`` (``r0 % 2w == 0``) sends virtual chunks
    ``[r0+w, r0+2w)`` to relative position ``r0+w``; pairs whose receiver
    falls outside the (uniform) group carry only padding chunks and are
    dropped.  Relative positions are group positions rotated by ``root``
    (the same convention as ``apply_doubling_bcast``)."""
    pairs = []
    for members in groups:
        kk = len(members)
        for r0 in range(0, K, 2 * w):
            if r0 + w < kk:
                pairs.append((members[(root + r0) % kk],
                              members[(root + r0 + w) % kk]))
    return pairs


# ---------------------------------------------------------------------------
# traced appliers
# ---------------------------------------------------------------------------


def apply_ring_reduce_scatter(blocks, op, comm, k: int):
    """Ring reduce-scatter of ``blocks`` (shape ``(k, *s)``) over ``comm``:
    group position ``p`` receives ``fold_j blocks_j[p]`` in ascending group
    order (MPI_Reduce_scatter_block semantics), shape ``(*s,)``.

    ``k-1`` ppermute rounds, each carrying one block (two for
    order-preserving callables) — O(size·(k-1)/k) bytes per rank.  Enum
    ``Op``s are commutative, so they circulate a single accumulator in the
    ring's natural (cyclically rotated) fold order; callables get the
    lo/hi pair that preserves the ascending fold (``rs_update_pair``).
    """
    from ._base import Op, _comm_groups, _permute_axis, combine_fn

    if k == 1:
        return blocks[0]
    fn = combine_fn(op)
    pos = comm.Get_rank()
    axis = _permute_axis(comm)
    pairs = ring_pairs(_comm_groups(comm))
    preserve = not isinstance(op, Op)
    start = jnp.take(blocks, (pos - 1) % k, axis=0)
    if preserve:
        lo, hi = start, start  # lo is a placeholder until the wrap entry
        for r in range(k - 1):
            c = rs_recv_chunk(pos, r, k)
            mine = jnp.take(blocks, c, axis=0)
            recvd = lax.ppermute(jnp.stack([lo, hi]), axis, pairs)
            lo, hi = rs_update_pair(
                jnp.where, fn, pos, c, k, recvd[0], recvd[1], mine
            )
        return rs_finish_pair(jnp.where, fn, pos, k, lo, hi)
    acc = start
    for r in range(k - 1):
        c = rs_recv_chunk(pos, r, k)
        mine = jnp.take(blocks, c, axis=0)
        acc = fn(lax.ppermute(acc, axis, pairs), mine)
    return acc


def apply_ring_allgather(v, comm, k: int, pos):
    """Ring allgather: position ``pos`` contributes ``v`` (shape ``(*s,)``)
    as chunk ``pos``; every position receives ``(k, *s)`` in group order.
    ``k-1`` ppermute rounds of one chunk each."""
    from ._base import _comm_groups, _permute_axis

    out = jnp.zeros((k,) + v.shape, v.dtype).at[pos].set(v)
    if k == 1:
        return out
    axis = _permute_axis(comm)
    pairs = ring_pairs(_comm_groups(comm))
    cur = v
    for r in range(k - 1):
        cur = lax.ppermute(cur, axis, pairs)
        out = out.at[ag_recv_chunk(pos, r, k)].set(cur)
    return out


def _pad_to(flat, total):
    n = flat.shape[0]
    if total == n:
        return flat
    return jnp.concatenate([flat, jnp.zeros((total - n,), flat.dtype)])


def apply_ring_allreduce(x, op, comm, k=None):
    """Bandwidth-optimal allreduce: ring reduce-scatter + ring allgather.

    Moves ``~2·(k-1)/k·size`` bytes per rank (``3·(k-1)/k`` for
    order-preserving callables) over ``2·(k-1)`` chunk-sized ppermute
    rounds, vs the butterfly's ``2·ceil(log2 k)`` full-payload rounds —
    the asymptotic win for gradient buckets and halo frames.  Same
    contract as ``apply_butterfly_allreduce``: all 10 ``Op``s plus
    associative callables folded in ascending group-rank order (callables
    must be ELEMENTWISE here — the payload is chunked; see module
    docstring).  Requires a uniform static group size.
    """
    from ._base import as_varying

    if k is None:
        k = comm.Get_size()
    x = as_varying(x, comm.axes)
    if k == 1:
        return x
    shape, n = x.shape, x.size
    chunk, padded = chunk_layout(n, k)
    blocks = _pad_to(x.reshape(-1), padded).reshape(k, chunk)
    mine = apply_ring_reduce_scatter(blocks, op, comm, k)
    full = apply_ring_allgather(mine, comm, k, comm.Get_rank())
    return full.reshape(-1)[:n].reshape(shape)


def apply_pairwise_alltoall(blocks, comm, k: int):
    """Pairwise-exchange alltoall of ``blocks`` (shape ``(k, *s)``,
    block ``i`` addressed to group position ``i``) over ``comm``:
    position ``p`` receives ``(k, *s)`` where ``out[q]`` is position
    ``q``'s block addressed to ``p``.

    ``k - 1`` ppermute rounds, round ``t`` rotating every position's
    round-``t`` block one ``t``-step around the group
    (``rotation_pairs``) — one chunk-sized message per rank per round,
    the classic pairwise schedule.  This is the expressible-anywhere
    building block of the hierarchical alltoall (ops/_hierarchy.py) and
    the chunked async split (ops/_async.py): unlike the native AllToAll
    HLO it works on color-split comms, and unlike the allgather-based
    group lowering it ships each rank only its own O(size) bytes.
    Requires a uniform static group size.  Pure routing — bit-identical
    to any other alltoall lowering by construction.
    """
    from ._base import _comm_groups, _permute_axis, as_varying

    blocks = as_varying(blocks, comm.axes)
    if k == 1:
        return blocks
    pos = comm.Get_rank()
    axis = _permute_axis(comm)
    groups = _comm_groups(comm)
    out = jnp.zeros_like(blocks)
    out = out.at[pos].set(jnp.take(blocks, pos, axis=0))  # own block
    for t in range(1, k):
        pairs = rotation_pairs(groups, t)
        send = jnp.take(blocks, a2a_send_block(pos, t, k), axis=0)
        recvd = lax.ppermute(send, axis, pairs)
        out = out.at[a2a_recv_slot(pos, t, k)].set(recvd)
    return out


def apply_binomial_scatter(buf, groups, root: int, axis, relpos, K: int):
    """The binomial-halving scatter phase shared by ``apply_vdg_bcast``
    (over the whole comm) and the hierarchical broadcast (over the
    intra-host blocks — ops/_hierarchy.py): ``buf`` holds ``K`` virtual
    chunk rows addressed by ABSOLUTE chunk index, ``relpos`` is this
    rank's position in the root-rotated frame (= the chunk index it ends
    up owning).  Each round halves the in-flight span; pairs whose
    receiver falls outside a group carry only padding and are dropped by
    ``vdg_scatter_pairs``; non-participants' clamped slices are garbage
    no pair routes and the ``where`` discards."""
    for w in vdg_widths(K):
        pairs = vdg_scatter_pairs(groups, root, w, K)
        if not pairs:
            continue
        slab = lax.dynamic_slice_in_dim(buf, relpos + w, w, axis=0)
        recvd = lax.ppermute(slab, axis, pairs)
        is_recv = (relpos % (2 * w)) == w
        buf = jnp.where(
            is_recv,
            lax.dynamic_update_slice_in_dim(buf, recvd, relpos, axis=0),
            buf,
        )
    return buf


def apply_vdg_bcast(x, comm, root: int, k=None):
    """Large-payload broadcast: binomial-halving scatter from ``root`` +
    ring allgather (van de Geijn).

    The scatter tree halves the in-flight payload every round (root ships
    ``~size`` bytes total; ``ceil(log2 k)`` rounds), then the ring
    allgather circulates one chunk per round (``k-1`` rounds,
    ``(k-1)/k·size`` bytes per rank) — ~2·size bytes per rank end to end,
    vs ``size·ceil(log2 k)`` for ``apply_doubling_bcast``.  The chunk
    count is padded to the next power of two so any uniform group size
    lowers cleanly; padding chunks ride the scatter slabs but are dropped
    by the final reshape.  Requires a uniform static group size.
    """
    from ._base import _comm_groups, _permute_axis, as_varying

    if k is None:
        k = comm.Get_size()
    groups = _comm_groups(comm)
    kmin = min(len(g) for g in groups)
    if not 0 <= root < kmin:
        raise ValueError(
            f"apply_vdg_bcast: root {root} out of range for the smallest "
            f"group (size {kmin}); root must be a valid group position in "
            "every group"
        )
    x = as_varying(x, comm.axes)
    if k == 1:
        return x
    pos = comm.Get_rank()
    relpos = (pos - root) % k
    axis = _permute_axis(comm)
    shape, n = x.shape, x.size
    chunk, _ = chunk_layout(n, k)
    K = next_pow2(k)
    buf = _pad_to(x.reshape(-1), K * chunk).reshape(K, chunk)
    # senders (relpos % 2w == 0) hold virtual chunks [relpos, relpos+2w)
    # and ship the far half; the receiver at relpos+w writes it at its
    # OWN relpos (see apply_binomial_scatter)
    buf = apply_binomial_scatter(buf, groups, root, axis, relpos, K)
    mine = jnp.take(buf, relpos, axis=0)  # this rank's real chunk (relpos < k)
    full = apply_ring_allgather(mine, comm, k, relpos)
    return full.reshape(-1)[:n].reshape(shape)


def apply_reduce_scatter(xl, op, comm):
    """Lowering of the public ``reduce_scatter`` op: ``(k, *s)`` blocks in,
    ``(*s,)`` out — group position ``p`` receives the ascending-group-order
    fold of every member's block ``p``.

    Native path: one ``psum_scatter`` HLO for SUM on a whole single-axis
    comm under ``auto``.  Otherwise butterfly (allreduce the block stack,
    keep own block — O(size·log k) bytes) vs ring (O(size·(k-1)/k) bytes)
    vs the two-level hierarchical split (``_hierarchy``: intra-host
    reduce-scatter of position super-blocks over ICI, inter-host
    reduce-scatter of the per-host partials over DCN) by the selector.
    Blocks are the user's own, so block-wise callables (including
    whole-block ops like ``jnp.matmul``, which batch over the leading
    axis) are valid on EVERY algorithm — the chunked-allreduce
    elementwise caveat does not apply here.
    """
    from ._base import Op, apply_butterfly_allreduce, as_varying
    from ..analysis.hook import annotate
    from ..telemetry.core import annotate as t_annotate
    from . import _hierarchy

    k = comm.Get_size()  # static; raises the clear error on unequal splits
    xl = as_varying(xl, comm.axes)
    if k == 1:
        return xl[0]
    algo = config.collective_algo()
    if (algo == "auto" and op is Op.SUM and comm.groups is None
            and len(comm.axes) == 1):
        try:
            res = lax.psum_scatter(
                xl, comm.axes[0], scatter_dimension=0, tiled=False
            )
            annotate(algo="native")
            t_annotate(algo="native")
            return res
        except NotImplementedError:  # shard_map/backend gap: fall through
            pass
    plan = _hierarchy.hier_plan(comm)
    nbytes = xl.size * xl.dtype.itemsize
    algo = resolve_algo(algo, nbytes, k, ring_ok=True,
                        hier_ok=plan is not None)
    _hierarchy.annotate_selection("reduce_scatter", algo, nbytes, k, plan,
                                  comm, preserve=not isinstance(op, Op),
                                  op=op, dtype=xl.dtype.name)
    if algo == "hier":
        return _hierarchy.apply_hier_reduce_scatter(xl, op, comm, plan)
    if algo == "ring":
        return apply_ring_reduce_scatter(xl, op, comm, k)
    full = apply_butterfly_allreduce(xl, op, comm)
    return jnp.take(full, comm.Get_rank(), axis=0)
