"""Message status introspection.

Analog of the reference's optional ``MPI.Status`` out-parameter on ``recv``/
``sendrecv`` (ref mpi4jax/_src/collective_ops/recv.py:43-48).  On a
statically-routed interconnect everything a Status reports is known at trace
time, so fields are filled from the routing spec: ``source`` is a traced
per-rank value (-1 where the rank received nothing, the MPI_PROC_NULL
analog), ``tag``/``count``/``dtype`` are static (``tag`` is the tag the
matched message was *sent* with: the matched send's tag for ``recv``,
``sendtag`` for ``sendrecv`` — whose matching is internal to the call, so
its ``recvtag`` never participates).
"""


class Status:
    __slots__ = ("source", "tag", "count", "dtype")

    def __init__(self):
        self.source = None
        self.tag = None
        self.count = None
        self.dtype = None

    def Get_source(self):
        return self.source

    def Get_tag(self):
        return self.tag

    def Get_count(self):
        return self.count

    def __repr__(self):
        return (f"Status(source={self.source}, tag={self.tag}, "
                f"count={self.count}, dtype={self.dtype})")
