"""Message status introspection.

Analog of the reference's optional ``MPI.Status`` out-parameter on ``recv``/
``sendrecv`` (ref mpi4jax/_src/collective_ops/recv.py:43-48).  On a
statically-routed interconnect everything a Status reports is known at trace
time, so fields are filled from the routing spec: ``source`` is a traced
per-rank value (-1 where the rank received nothing, the MPI_PROC_NULL
analog), ``tag``/``count``/``dtype`` are static (``tag`` is the tag the
matched message was *sent* with: the matched send's tag for ``recv``,
``sendtag`` for ``sendrecv`` — whose matching is internal to the call, so
its ``recvtag`` never participates).

``Get_error`` always reports success: this framework keeps the reference's
fail-fast contract (any transport error aborts the whole job,
ref mpi_xla_bridge.pyx:67-91 → here ``native.abort_if``), so a Status that
exists at all describes a completed, successful receive — there is no
partially-failed state for MPI_ERROR to carry.
"""

import numpy as np

#: MPI_SUCCESS analog — the only error class a completed receive can have
#: under fail-fast semantics (see module docstring).
SUCCESS = 0


class Status:
    __slots__ = ("source", "tag", "count", "dtype", "error")

    def __init__(self):
        self.source = None
        self.tag = None
        self.count = None
        self.dtype = None
        self.error = SUCCESS

    def Get_source(self):
        return self.source

    def Get_tag(self):
        return self.tag

    def Get_count(self):
        return self.count

    def Get_error(self):
        """Always ``SUCCESS`` (0) — see module docstring for why."""
        return self.error

    def Get_elements(self, dtype=None):
        """Number of basic elements of ``dtype`` received.

        MPI's ``Get_elements(datatype)`` counts in units of the given basic
        datatype.  Messages here are never truncated or partially received,
        so this is the byte count divided by ``dtype``'s item size; it must
        divide evenly (MPI_UNDEFINED is represented by a ValueError, since a
        static framework can reject the query at call time).
        """
        if self.count is None:
            return None
        if dtype is None:
            dtype = self.dtype
        nbytes = self.count * np.dtype(self.dtype).itemsize
        itemsize = np.dtype(dtype).itemsize
        if nbytes % itemsize:
            raise ValueError(
                f"Get_elements: {nbytes} received bytes is not a whole "
                f"number of {np.dtype(dtype).name} elements"
            )
        return nbytes // itemsize

    def __repr__(self):
        return (f"Status(source={self.source}, tag={self.tag}, "
                f"count={self.count}, dtype={self.dtype}, "
                f"error={self.error})")
