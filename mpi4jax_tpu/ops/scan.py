"""scan: inclusive prefix reduction over rank order.

TPU-native re-design of ref mpi4jax/_src/collective_ops/scan.py (contract:
rank ``r`` receives ``reduce(op, [x_0 … x_r])``, ref scan.py:40-78).

Lowering: Hillis–Steele parallel prefix over ``log2(size)`` rounds of
CollectivePermute — rank ``r`` receives from ``r - d`` and accumulates for
doubling offsets ``d``.  This is the ICI-native prefix algorithm: log-depth,
each round one neighbor hop, O(n·log size) total traffic (vs the reference's
single MPI_Scan whose internals are the library's choice).  Non-participating
lanes in each round are masked with ``where`` (ppermute delivers zeros to
ranks with no source, which the mask discards).
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import (
    SUM,
    Op,
    OpLike,
    _permute_axis,
    combine_fn,
    dispatch,
    reduction_name,
)
from .token import Token, consume, produce


@enforce_types(comm=(Comm, None), token=(Token, None))
def scan(x, op: OpLike = SUM, *, comm: Optional[Comm] = None,
         token: Optional[Token] = None):
    """Inclusive prefix reduction: rank ``r`` gets ``x_0 op x_1 op … op x_r``.

    Returns ``(result, token)`` (ref API: scan.py:40-78).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        xl = consume(token, xl)
        rank = comm.Get_rank()
        log_op("MPI_Scan", rank, f"with {xl.size} items")
        fn = combine_fn(op)
        acc = xl
        groups = comm.groups
        if groups is None:
            groups = [tuple(range(comm.Get_size()))]
            expand = comm.expand_pairs
        else:
            # color split: group tables are static, so the per-group pairs
            # are built directly — UNEQUAL group sizes included (each
            # group runs its own prefix; rounds beyond a group's size
            # simply contribute no pairs for it).  ``rank`` is group-local
            # here, so the participation mask needs no change.
            expand = tuple
        d = 1
        while d < max(len(g) for g in groups):
            # rank r-d sends its accumulator to rank r (for r >= d), one
            # global permute per round across all groups
            perm = expand(
                (members[r - d], members[r])
                for members in groups
                for r in range(d, len(members))
            )
            recvd = lax.ppermute(acc, _permute_axis(comm), list(perm))
            acc = jnp.where(rank >= d, fn(acc, recvd), acc)
            d *= 2
        return acc, produce(token, acc)

    return dispatch("scan", comm, body, (x,), token,
                    static_key=(op,) if isinstance(op, Op) else None,
                    ana={"reduction": reduction_name(op)})
