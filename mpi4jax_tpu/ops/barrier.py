"""barrier: synchronization point.

TPU-native re-design of ref mpi4jax/_src/collective_ops/barrier.py (token-only
op, abstract :137).  Lowering: a scalar AllReduce tied into the token chain —
no rank can produce the output token before every rank has reached the
barrier.  On ICI this is a single-word collective (~µs), matching
MPI_Barrier's semantics without any host round-trip.
"""

from typing import Optional

import jax.numpy as jnp
from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import as_varying, dispatch
from .token import Token, tie


@enforce_types(comm=(Comm, None), token=(Token, None))
def barrier(*, comm: Optional[Comm] = None, token: Optional[Token] = None):
    """Synchronize all ranks of ``comm``.  Returns a token
    (ref API: barrier.py:38-66)."""

    def body(comm, arrays, token):
        z = jnp.zeros((), jnp.uint32)
        if token is not None:
            # tie, not consume: ordering IS the barrier's semantics, so the
            # incoming dependency must hold even under PREFER_NOTOKEN (which
            # disables consume) — same reasoning as the pending-sync ties in
            # ops/_base.py dispatch.  This is also what anchors the
            # resilience probe/arm for a bare barrier() (the synthesized
            # token in resilience/runtime.py Plan.before).
            z = tie(token, z)
        log_op("MPI_Barrier", comm.Get_rank())
        s = lax.psum(as_varying(z, comm.axes), comm.axes)
        # the output token IS the collective result, so consuming the token
        # both orders work after the barrier and keeps the AllReduce alive
        return (Token(s),)

    out = dispatch("barrier", comm, body, (), token, static_key=())
    tok = out[0]
    from ..parallel.region import in_parallel_region, resolve_comm
    from .token import deposit_sync

    if in_parallel_region(resolve_comm(comm)):
        # MPI_Barrier always executes, even if the caller drops the returned
        # token (and consume() may be disabled by PREFER_NOTOKEN): anchor the
        # collective through the implicit-sync mechanism.  A consumed token
        # just adds a second, harmless data dependency.
        deposit_sync(tok)
    return tok
