"""allgather: gather every rank's array to all ranks.

TPU-native re-design of ref mpi4jax/_src/collective_ops/allgather.py.  Shape
contract preserved exactly: input ``s`` -> output ``(size, *s)`` on every rank
(ref allgather.py:229-236 abstract eval).  Lowering: one AllGather HLO.
"""

from typing import Optional

from jax import lax

from ..parallel.comm import Comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import dispatch, group_select_gather
from .token import Token, consume, produce


@enforce_types(comm=(Comm, None), token=(Token, None))
def allgather(x, *, comm: Optional[Comm] = None, token: Optional[Token] = None):
    """Gather ``x`` from every rank; all ranks receive ``(size, *x.shape)``.

    Returns ``(result, token)`` (ref API: allgather.py:38-76).
    """

    def body(comm, arrays, token):
        (xl,) = arrays
        xl = consume(token, xl)
        log_op("MPI_Allgather", comm.Get_rank(), f"sending {xl.size} items")
        if comm.groups is not None:
            # color split (uniform group sizes): output (group_size, *s)
            res = group_select_gather(comm, xl)
        else:
            res = lax.all_gather(xl, comm.axes, axis=0, tiled=False)
        return res, produce(token, res)

    return dispatch("allgather", comm, body, (x,), token, static_key=())
