"""Tokens: explicit ordering handles.

The reference threads opaque XLA tokens through every op to force a total
order on MPI calls — without them, ranks could compile different schedules and
deadlock (ref: mpi4jax/_src/collective_ops/allreduce.py:63-64 ``create_token``;
docs/sharp-bits.rst).  Under the SPMD model every rank runs the *same*
compiled program, so cross-rank schedule divergence is impossible and tokens
are no longer needed for deadlock-freedom.  They are kept because:

1. API parity — reference code threads ``(result, token)`` pairs;
2. they still pin the *relative execution order* of collectives inside one
   program (useful for deterministic overlap/scheduling), implemented as data
   dependencies through ``lax.optimization_barrier`` — the XLA-native ordering
   mechanism, replacing the reference's side-effecting custom calls.

A ``Token`` is a pytree wrapping a scalar ``uint32``; ops *consume* a token
(tying their inputs to it) and *produce* a new one (tied to their outputs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


@jax.tree_util.register_pytree_node_class
@dataclass
class Token:
    value: jax.Array

    def tree_flatten(self):
        return (self.value,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])


def create_token(_arg=None) -> Token:
    """Create a fresh ordering token (ref: jax.lax.create_token usage at
    mpi4jax/_src/collective_ops/allreduce.py:63-64).  The optional argument is
    accepted for drop-in compatibility and ignored."""
    return Token(jnp.zeros((), jnp.uint32))


def _barrier_pair(a, b):
    """Tie ``a`` and ``b`` together: returned values each depend on both
    inputs (XLA OptimizationBarrier semantics)."""
    return lax.optimization_barrier((a, b))


def consume(token: Optional[Token], *arrays):
    """Make ``arrays`` depend on ``token`` (op inputs wait for the token).

    Returns the (possibly rewrapped) arrays.  ``None`` token is a no-op, and
    with ``MPI4JAX_TPU_PREFER_NOTOKEN=1`` the token API stops threading
    ``optimization_barrier`` chains entirely — the delegation the reference
    implements by re-binding through the notoken primitives
    (ref _src/collective_ops/allreduce.py:66-69, _src/utils.py:175-177).
    """
    if token is None or _prefer_notoken():
        return arrays if len(arrays) != 1 else arrays[0]
    tied = []
    tval = token.value
    for x in arrays:
        x, tval = _barrier_pair(x, tval)
        tied.append(x)
    return tuple(tied) if len(tied) != 1 else tied[0]


def produce(token: Optional[Token], *arrays) -> Token:
    """Produce the op's output token: depends on every output array, so the
    next token-consuming op is scheduled after this op completes."""
    if _prefer_notoken():
        return token if token is not None else Token(jnp.zeros((), jnp.uint32))
    tval = token.value if token is not None else jnp.zeros((), jnp.uint32)
    for x in arrays:
        _, tval = _barrier_pair(x, tval)
    return Token(tval)


def _prefer_notoken() -> bool:
    from ..utils.config import prefer_notoken

    return prefer_notoken()


def tie(token: Token, x):
    """Unconditionally make ``x`` depend on ``token`` — unlike ``consume``,
    never skipped by MPI4JAX_TPU_PREFER_NOTOKEN.  Used for synchronization
    that must survive DCE (RegionContext.pending_sync)."""
    x, _ = _barrier_pair(x, token.value)
    return x


def deposit_sync(token: Token) -> None:
    """Record ``token`` as implicit pending synchronization.

    Inside an spmd-managed region, the token lands in
    ``RegionContext.pending_sync`` where the next op (or the region outputs)
    ties it in.  Inside a *user's own* ``shard_map`` (the global fallback
    context) there is no drain point and a stored tracer would leak across
    traces — instead the token is anchored with an effectful no-op host
    callback, which DCE cannot remove."""
    from ..parallel.region import _region_stack

    if _region_stack:
        ctx = _region_stack[-1]
        if ctx.pending_sync is not None:
            # merge consecutive deposits
            token = Token(tie(ctx.pending_sync, token.value))
        ctx.pending_sync = token
    else:
        jax.debug.callback(lambda _: None, token.value)
