"""recv: point-to-point receive half.

TPU-native re-design of ref mpi4jax/_src/collective_ops/recv.py (input array
is a shape/dtype template only, ref recv.py:43; abstract :246).  Pops the
matching ``send`` from the region's (comm, tag) queue and emits the fused
CollectivePermute (see ops/send.py for the matching model).

Wildcard semantics: the reference defaults to ``ANY_SOURCE``/``ANY_TAG``
(ref recv.py:44-48).  A statically-routed interconnect has no wildcards;
``recv(source=None)`` instead adopts the queued send's routing — which covers
the reference's default-argument uses — and an explicit ``source`` spec is
validated against it.  A ``recv`` with no queued send is a trace-time error
(the reference would deadlock at run time).
"""

from typing import Optional

from ..parallel.comm import Comm
from ..parallel.rankspec import normalize_source
from ..parallel.region import current_context
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import as_varying, dispatch
from .sendrecv import _apply_permute, _fill_status
from .status import Status
from .token import Token, consume, produce


@enforce_types(tag=int, comm=(Comm, None), status=(Status, None),
               token=(Token, None))
def recv(x, source=None, tag: int = 0, *, comm: Optional[Comm] = None,
         status: Optional[Status] = None, token: Optional[Token] = None):
    """Receive into ``x``'s shape/dtype from the matching ``send``.

    Returns ``(received, token)`` (ref API: recv.py:43-87).  Ranks outside
    the routing receive ``x`` back unchanged (MPI_PROC_NULL semantics).
    """

    def body(comm, arrays, token):
        (template,) = arrays
        size = comm.Get_size()
        ctx = current_context()
        q = ctx.queue(comm.uid, tag)
        if not q:
            raise RuntimeError(
                f"recv(tag={tag}): no matching send queued on this comm. "
                "Under SPMD, the matching send must appear earlier in the "
                "same parallel region (the reference would deadlock here at "
                "run time; this framework turns it into a trace error)."
            )
        pending = q.popleft()
        if source is not None:
            pairs_s = normalize_source(source, size, what="recv")
            if pairs_s != pending.pairs:
                raise ValueError(
                    f"recv: source spec implies routing {pairs_s} but the "
                    f"matching send declared {pending.pairs}"
                )
        if pending.value.dtype != template.dtype or (
                pending.value.size != template.size):
            raise ValueError(
                f"recv: template shape/dtype {template.shape}/{template.dtype} "
                f"does not match sent {pending.value.shape}/"
                f"{pending.value.dtype} (shapes may differ only at equal "
                "element count; the output is typed by the template, ref "
                "recv.py:246)"
            )
        payload = as_varying(consume(token, pending.value), comm.axes)
        log_op("MPI_Recv", comm.Get_rank(),
               f"{payload.size} items along {list(pending.pairs)} (tag {tag})")
        res = _apply_permute(payload, template, pending.pairs, comm)
        _fill_status(status, pending.pairs, comm, payload.size,
                     payload.dtype, tag)
        return res, produce(token, res)

    return dispatch("recv", comm, body, (x,), token)
