"""recv: point-to-point receive half.

TPU-native re-design of ref mpi4jax/_src/collective_ops/recv.py (input array
is a shape/dtype template only, ref recv.py:43; abstract :246).  Pops the
matching ``send`` from the region's (comm, tag) queue and emits the fused
CollectivePermute (see ops/send.py for the matching model).

Wildcard semantics: the reference defaults to ``ANY_SOURCE``/``ANY_TAG``
(ref recv.py:44-48).  A statically-routed interconnect has no wildcards;
``recv(source=None)`` instead adopts the queued send's routing — which covers
the reference's default-argument uses — and an explicit ``source`` spec is
validated against it.  A ``recv`` with no queued send is a trace-time error
(the reference would deadlock at run time).

Standalone *eager* use pops the matching deferred eager ``send`` (see
ops/send.py) and emits the fused one-CollectivePermute program right here —
the transfer happens at the recv.
"""

from typing import Optional

import jax

from ..parallel.comm import Comm
from ..parallel.rankspec import resolve_routing
from ..parallel.region import current_context, in_parallel_region, resolve_comm
from ..utils.debug import log_op
from ..utils.validation import enforce_types
from ._base import as_varying, dispatch
from .send import _eager_queue
from .sendrecv import _apply_permute, _fill_status
from .status import Status
from .token import Token, consume, produce


def _check_recv_match(pending, template, source, comm):
    """Shared send↔recv compatibility checks (routing + type signature).
    ``pending.pairs`` are GLOBAL (resolved by the send side)."""
    if source is not None:
        pairs_s = resolve_routing(comm, source, None, what="recv")
        if pairs_s != pending.pairs:
            raise ValueError(
                f"recv: source spec implies routing {pairs_s} but the "
                f"matching send declared {pending.pairs}"
            )
    if pending.value.dtype != template.dtype or (
            pending.value.size != template.size):
        from ..analysis.report import mpx_error

        raise mpx_error(
            ValueError, "MPX106",
            f"recv: template shape/dtype {template.shape}/{template.dtype} "
            f"does not match sent {pending.value.shape}/"
            f"{pending.value.dtype} (shapes may differ only at equal "
            "element count; the output is typed by the template, ref "
            "recv.py:246)",
        )


@enforce_types(tag=int, comm=(Comm, None), status=(Status, None),
               token=(Token, None))
def recv(x, source=None, tag: int = 0, *, comm: Optional[Comm] = None,
         status: Optional[Status] = None, token: Optional[Token] = None):
    """Receive into ``x``'s shape/dtype from the matching ``send``.

    Returns ``(received, token)`` (ref API: recv.py:43-87).  Ranks outside
    the routing receive ``x`` back unchanged (MPI_PROC_NULL semantics).
    """
    c = resolve_comm(comm)
    if c.mesh is not None and not in_parallel_region(c):
        return _eager_recv(x, source, tag, c, status, token)

    def body(comm, arrays, token):
        from ..analysis.hook import annotate
        from ..analysis.report import mpx_error
        from ..analysis.schedule import concretizing

        (template,) = arrays
        if concretizing():
            # per-rank schedule trace: the matching send may live on a
            # DIFFERENT rank's schedule, so the region queue cannot pair
            # it — record the recv one-sided (explicit source resolves
            # the routing; source=None is a wildcard for the matcher)
            # and type the result by the template, like the reference
            pairs = (resolve_routing(comm, source, None, what="recv")
                     if source is not None else None)
            annotate(pairs=pairs)
            res = as_varying(template, comm.axes)
            if status is not None and pairs:
                _fill_status(status, pairs, comm, res.size, res.dtype, tag)
            return res, produce(token, res)
        ctx = current_context()
        q = ctx.queue(comm.uid, tag)
        if not q:
            raise mpx_error(
                RuntimeError, "MPX102",
                f"recv(tag={tag}): no matching send queued on this comm. "
                "Under SPMD, the matching send must appear earlier in the "
                "same parallel region (the reference would deadlock here at "
                "run time; this framework turns it into a trace error).",
            )
        if len(q) >= 2:
            # FIFO will pick the oldest of several pending sends — the
            # trace-time verifier surfaces this as an MPX110 advisory
            annotate(queue_depth=len(q))
        pending = q.popleft()
        _check_recv_match(pending, template, source, comm)
        annotate(pairs=pending.pairs)
        payload = as_varying(consume(token, pending.value), comm.axes)
        log_op("MPI_Recv", comm.Get_rank(),
               f"{payload.size} items along {list(pending.pairs)} (tag {tag})")
        pairs = pending.pairs  # GLOBAL (resolved by the send side)
        res = _apply_permute(payload, template, pairs, comm)
        _fill_status(status, pairs, comm, payload.size, payload.dtype, tag)
        return res, produce(token, res)

    return dispatch("recv", comm, body, (x,), token, ana={"tag": tag})


def _eager_recv(x, source, tag, comm, status, token):
    """Standalone eager recv: pop the matching deferred eager send and run
    the fused send+recv as one one-op program (the transfer happens here).

    ``x`` and the queued payload are GLOBAL arrays (leading axis = ranks,
    the eager convention); matching/validation mirrors the in-region path.
    """
    from ..analysis.report import mpx_error

    q = _eager_queue(comm.uid, tag)
    if not q:
        raise mpx_error(
            RuntimeError, "MPX102",
            f"recv(tag={tag}): no matching eager send queued on this comm. "
            "Standalone eager recv pairs with a prior standalone eager send "
            "on the same comm and tag (the reference would block here until "
            "one arrived; this framework turns the missing-send case into "
            "an immediate error).",
        )
    # peek, don't pop: a recv that fails ANY argument check must not
    # consume the message (MPI semantics — the send stays matchable by a
    # corrected retry); the entry is popped only after the transfer program
    # runs, or when it is provably unreceivable (dead tracer, below)
    pending = q[0]
    import jax.core

    from ..utils.jax_compat import tracer_is_live

    if (isinstance(pending.value, jax.core.Tracer)
            and not tracer_is_live(pending.value)):
        q.popleft()  # can never be received — drop with a clear error
        raise RuntimeError(_STALE_SEND_MSG.format(tag=tag))
    _check_recv_match(pending, x, source, comm)
    pairs = pending.pairs  # GLOBAL (resolved by the send side)

    def body(comm, arrays, token):
        from ..analysis.hook import annotate

        if len(q) >= 2:
            annotate(queue_depth=len(q))
        xl, template = arrays
        payload = consume(token, xl)
        log_op("MPI_Recv", comm.Get_rank(),
               f"{payload.size} items along {list(pairs)} (tag {tag})")
        res = _apply_permute(payload, template, pairs, comm)
        _fill_status(status, pairs, comm, payload.size, payload.dtype, tag)
        return res, produce(token, res)

    static_key = None if status is not None else (pairs, tag, "eager_pair")
    try:
        out = dispatch("recv", comm, body, (pending.value, x), token,
                       static_key=static_key,
                       ana={"tag": tag, "pairs": pairs})
    except jax.errors.UnexpectedTracerError as e:
        # backstop for liveness cases the proactive probe cannot see
        q.popleft()
        raise RuntimeError(_STALE_SEND_MSG.format(tag=tag)) from e
    q.popleft()
    return out


_STALE_SEND_MSG = (
    "recv(tag={tag}): the matching eager send was traced inside a jit/grad "
    "function whose trace has ended, so its payload no longer exists. Pair "
    "traced sends with a recv in the SAME trace, or use sendrecv / an "
    "mpi4jax_tpu.spmd region."
)
