"""``mpx.compress`` — the public wire-compression + error-feedback API.

Thin re-export surface over the codec layer (docs/compression.md):

- byte math + resolution (stdlib, ``ops/_codec.py``): ``wire_bytes``,
  ``codec_for``, ``compression_ratio``, ``ef_reshard_rows``;
- traced appliers + EF (``ops/_compress.py``): ``ef_allreduce``,
  ``ef_zeros_like``, ``ef_reshard``, ``roundtrip``, the fp8
  encode/decode pair;
- the effective mode (``utils/config.compress_mode`` — default <
  tuning < env, payload-bucketed).

The whole layer is opt-in and OFF by default: with
``MPI4JAX_TPU_COMPRESS=off`` cache tokens and lowered HLO are
byte-identical to a build without it, ``ef_allreduce`` degenerates to
the plain tree-mapped allreduce, and the residual stays exactly zero.
Compressed results are NOT bit-identical to the exact run — the
convergence harness (benchmarks/compress_replay.py, BENCH_compress.json)
is the parity contract.
"""

from .ops._codec import (  # noqa: F401
    CODECS,
    FP8_CHUNK,
    codec_for,
    compression_ratio,
    ef_reshard_rows,
    wire_bytes,
)
from .ops._compress import (  # noqa: F401
    decode_fp8,
    ef_allreduce,
    ef_reshard,
    ef_zeros_like,
    encode_fp8,
    fp8_wire_dtype,
    roundtrip,
)
from .utils.config import compress_mode  # noqa: F401

__all__ = [
    "CODECS",
    "FP8_CHUNK",
    "codec_for",
    "compression_ratio",
    "compress_mode",
    "decode_fp8",
    "ef_allreduce",
    "ef_reshard",
    "ef_reshard_rows",
    "ef_zeros_like",
    "encode_fp8",
    "fp8_wire_dtype",
    "roundtrip",
    "wire_bytes",
]
