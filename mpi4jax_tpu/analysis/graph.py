"""The collective graph: what the verifier sees of a traced program.

One :class:`CollectiveEvent` is recorded per op at the shared dispatch
point (ops/_base.py) — op kind, communicator identity, static structure
(root, routing pairs, tag), payload size/dtype, the token edges, and the
algorithm the payload-aware selector picked.  A :class:`CollectiveGraph`
is the ordered stream of one trace plus the configuration snapshot the
checkers need (algo mode, crossover bytes).

Token edges are recorded as opaque ids (``id()`` of the token's carrier
value at trace time; the recorder pins the carriers so ids cannot be
reused within one recording).  Checkers treat ids purely as equality
handles.

Dependency-free (no jax) so hand-built graphs drive the checkers in
tests/test_analysis_pure.py under any JAX version.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class CollectiveEvent:
    """One collective as seen at dispatch.  Mutable: ops annotate fields
    that only become known inside their body (routing pairs, match depth,
    selected algorithm) via ``analysis.hook.annotate``."""

    index: int
    op: str
    comm_uid: int = 0
    comm_axes: Tuple[str, ...] = ()
    comm_size: Optional[int] = None     # static group size, if it has one
    min_size: Optional[int] = None      # smallest group (root bound)
    split: bool = False                 # color-split comm?
    payload_bytes: int = 0
    dtype: str = ""
    shape: Tuple[int, ...] = ()
    root: Optional[int] = None
    pairs: Optional[Tuple[Tuple[int, int], ...]] = None
    tag: Optional[int] = None
    reduction: Optional[str] = None
    algo: Optional[str] = None    # "native" | "butterfly" | "ring" | "hier"
    hosts: Optional[int] = None   # hosts the comm's widest group spans
    token_in: Optional[int] = None
    token_out: Optional[int] = None
    eager: bool = False
    span: Optional[int] = None          # async start/wait pairing handle id
    # megastep loop scope (parallel/megastep.py): the loop id of the
    # device-resident fori_loop body this op was traced inside, and its
    # trip count.  None outside any megastep.  MPX130 errors on async
    # spans straddling a loop boundary; MPX128 skips loop-body events
    # (the body traces ONCE — it is not an unrolled Python loop).
    loop: Optional[int] = None
    unroll: Optional[int] = None
    fused_members: Optional[int] = None  # member ops packed into this op
    fused_bytes: Optional[int] = None   # flat-buffer payload bytes
    # per-member (dtype, nelems) composition of a fused flat buffer — the
    # cross-rank matcher compares it across ranks (MPX124)
    fused_layout: Optional[Tuple] = None
    # (hosts, ranks_per_host) of the two-level plan this op lowered with
    # (ops/_hierarchy.annotate_selection), compared across ranks (MPX125)
    hier: Optional[Tuple[int, int]] = None
    # DCN-leg wire codec the hierarchy applied ("bf16" | "fp8"), None on
    # exact lowerings (docs/compression.md) — prices the inter-host leg
    # at wire bytes in the cost model and gates the MPX138 advisory
    codec: Optional[str] = None
    # communication epoch the comm was built in (parallel/comm.py stamp;
    # resilience/elastic.py revocation) — compared against the CURRENT
    # epoch in graph.meta by the MPX126 checker
    epoch: Optional[int] = None
    # True when the comm's world executed a planned drain and this
    # collective was issued AFTER the leave boundary (resilience/
    # elastic.py drained-comm registry) — flagged MPX127.  A comm merely
    # *scheduled* to drain (boundary not yet reached) records False:
    # collectives remain legal through the boundary.
    drained: bool = False
    # buffer identities (``id()`` of the traced array carriers, pinned by
    # the recorder like the token carriers) of this op's array inputs —
    # fusion flushes overwrite them with the MEMBER buffers of the packed
    # flat buffer, so a LazyResult aliasing a bucket member stays
    # traceable.  The dataflow hazard checkers (analysis/hazards.py)
    # intersect these with the donation records in
    # ``CollectiveGraph.meta["donations"]`` (MPX139/MPX140); they are
    # equality handles only and never rendered.
    buffers: Tuple[int, ...] = ()
    # static member groups (global ranks, group order) of this op's comm
    # when derivable — comm.groups on a split, or the rank-concretization
    # scope's sub-axes partition during a per-rank schedule trace.  The
    # cross-rank schedule builder reads participants from here.
    groups: Optional[Tuple[Tuple[int, ...], ...]] = None
    extra: Dict = field(default_factory=dict)

    def where(self) -> str:
        return f"{self.op}#{self.index}"


@dataclass
class CollectiveGraph:
    """Ordered event stream of one trace + the config snapshot."""

    events: List[CollectiveEvent] = field(default_factory=list)
    # {"collective_algo": ..., "ring_crossover_bytes": ...}; when the
    # recording saw pinned calls that donate buffers (aot/pinning.py),
    # also "donations": tuple of (event-stream position, frozenset of
    # donated buffer ids, human-readable call site) — present only when
    # nonempty so pre-hazard snapshots stay byte-identical
    meta: Dict = field(default_factory=dict)

    def by_channel(self) -> Dict[Tuple[int, Optional[int]], List[CollectiveEvent]]:
        """Point-to-point events grouped by (comm_uid, tag) channel, in
        stream order — the FIFO matching domains."""
        out: Dict[Tuple[int, Optional[int]], List[CollectiveEvent]] = {}
        for e in self.events:
            if e.op in ("send", "recv"):
                out.setdefault((e.comm_uid, e.tag), []).append(e)
        return out

    def by_comm(self) -> Dict[int, List[CollectiveEvent]]:
        out: Dict[int, List[CollectiveEvent]] = {}
        for e in self.events:
            out.setdefault(e.comm_uid, []).append(e)
        return out
