"""Cross-rank verification front-end: re-trace once per rank, then match.

``mpx.analyze(fn, *args, ranks='all')`` and the ambient
``MPI4JAX_TPU_ANALYZE`` mode share this machinery:

1. the target is re-traced abstractly once **per rank** under a
   :class:`~.schedule.ConcreteScope` — ``comm.Get_rank`` returns that
   rank's concrete coordinates, and concrete-predicate ``lax.cond`` /
   ``lax.switch`` take only the branch the rank would take — so
   rank-divergent programs yield their real per-rank op streams;
2. each stream becomes a :class:`~.schedule.SchedOp` schedule
   (analysis/schedule.py);
3. the global matcher pairs collectives by (comm, seq), point-to-point
   by (src, dst, tag) FIFO, and start/wait by span (analysis/matcher.py);
4. the progress checker simulates the matched program and reports
   deadlock cycles (analysis/progress.py).

While a per-rank trace runs, in-region send/recv matching relaxes to
one-sided recording (ops/send.py, ops/recv.py): the whole point is that
each rank's schedule may legitimately contain only one side of an
exchange — cross-rank pairing is the matcher's job, not the region
queue's.  Re-tracing is pure host-side work (``jax.make_jaxpr``: nothing
compiles or executes), so the ambient pass leaves the lowered HLO
byte-identical in every mode.
"""

from __future__ import annotations

import math
import warnings
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import config
from . import hook as _hook
from . import schedule as _schedule
from .checkers import run_checkers
from .matcher import match_schedules
from .progress import check_progress
from .report import Finding, Report, finding_from_exception

# the per-trace p2p FIFO replay (MPX101/102/106/110) is skipped on
# per-rank graphs: a rank's schedule legitimately holds one side of an
# exchange.  The matcher re-reports MPX101/102/106 with whole-program
# context, and the progress simulation replays MPX110 (pending-send
# depth at simulated match time).
_PER_RANK_SKIP = ("MPX101", "MPX102", "MPX106", "MPX110")


@contextmanager
def _concrete_control_flow():
    """Patch ``jax.lax.cond``/``switch`` so a concrete (non-tracer)
    predicate evaluates only the taken branch — rank-dependent structure
    concretizes; data-dependent control flow traces exactly as before."""
    import jax
    from jax import core

    orig_cond = jax.lax.cond
    orig_switch = jax.lax.switch

    def _is_concrete(x) -> bool:
        if isinstance(x, core.Tracer):
            return False
        try:
            bool(x == x)  # 0-d arrays and scalars are fine
        except Exception:
            return False
        return True

    def cond(pred, true_fun, false_fun=None, *operands, **kwargs):
        if false_fun is not None and not kwargs and _is_concrete(pred):
            return (true_fun if bool(pred) else false_fun)(*operands)
        if false_fun is None:
            return orig_cond(pred, true_fun, **kwargs)
        return orig_cond(pred, true_fun, false_fun, *operands, **kwargs)

    def switch(index, branches, *operands, **kwargs):
        if not kwargs and branches and _is_concrete(index):
            i = min(max(int(index), 0), len(branches) - 1)
            return branches[i](*operands)
        return orig_switch(index, branches, *operands, **kwargs)

    jax.lax.cond = cond
    jax.lax.switch = switch
    try:
        yield
    finally:
        jax.lax.cond = orig_cond
        jax.lax.switch = orig_switch


class _EventStream(list):
    """A rank's recorded events plus the recording's donation records
    (``hook.Recorder.donations``) riding along as an attribute —
    list-shaped so every existing consumer (schedule builder, matcher,
    report events) is untouched."""

    donations: tuple = ()


def trace_rank_schedules(target, args, kwargs, static_argnums,
                         axis_names: Sequence[str],
                         axis_sizes: Sequence[int],
                         rank_list: Sequence[int]):
    """Re-trace ``target(*args, **kwargs)`` once per rank in
    ``rank_list``.  Returns ``(per_rank_events, fatal_findings,
    closed_jaxprs)``; a rank whose trace aborts on an MPX-tagged raise
    contributes a finding instead of an event stream (untagged
    exceptions propagate)."""
    import jax
    from dataclasses import replace

    per_rank_events: Dict[int, list] = {}
    closed: Dict[int, object] = {}
    fatal: List[Finding] = []
    for r in rank_list:
        rec = _hook.Recorder("collect")
        _hook.push_recorder(rec)
        try:
            with _schedule.scope(axis_names, axis_sizes, r), \
                    _concrete_control_flow():
                closed[r] = jax.make_jaxpr(
                    target, static_argnums=static_argnums)(*args, **kwargs)
        except Exception as e:
            f = finding_from_exception(e)
            if f is None:
                raise
            fatal.append(replace(f, rank=r))
        finally:
            _hook.pop_recorder()
        events = _EventStream(rec.events)
        events.donations = tuple(rec.donations)
        per_rank_events[r] = events
    return per_rank_events, fatal, closed


def uid_watermark() -> int:
    """Snapshot the comm-uid counter BEFORE the per-rank re-traces: uids
    below it belong to comms shared across the traces (stable identity);
    uids above it are per-trace creations, aligned by creation order
    (see ``schedule.build_schedule``).  Consumes one uid — uids only
    need uniqueness."""
    from ..parallel import comm as _comm

    return next(_comm._uid_counter)


def match_rank_schedules(per_rank_events: Dict[int, list], world: int,
                         watermark: Optional[int] = None):
    """Per-rank event streams -> schedules -> the matched whole-program
    view (the cost pass in analysis/cost.py consumes the same
    :class:`~.matcher.MatchedProgram` the progress checker does)."""
    schedules = {
        r: _schedule.build_schedule(events, rank=r, world=world,
                                    uid_watermark=watermark)
        for r, events in per_rank_events.items()
    }
    return match_schedules(schedules)


def cross_rank_findings(per_rank_events: Dict[int, list], world: int,
                        watermark: Optional[int] = None,
                        matched=None) -> List[Finding]:
    """Schedules -> matcher -> progress, over per-rank event streams."""
    if matched is None:
        matched = match_rank_schedules(per_rank_events, world, watermark)
    findings = list(matched.findings)
    findings.extend(check_progress(matched))
    return findings


def per_rank_graph_findings(per_rank_events: Dict[int, list]) -> List[Finding]:
    """The single-trace checkers over each rank's stream (minus the p2p
    FIFO replay — see ``_PER_RANK_SKIP``), deduplicated across ranks."""
    findings: List[Finding] = []
    seen = set()
    for r in sorted(per_rank_events):
        meta = _hook.config_snapshot()
        donations = getattr(per_rank_events[r], "donations", ())
        if donations:
            # pinned-call donations recorded during this rank's re-trace
            # (hook.record_donation) — the MPX139/MPX140 join input
            meta["donations"] = donations
        graph = _hook.CollectiveGraph(events=per_rank_events[r], meta=meta)
        for f in run_checkers(graph, skip=_PER_RANK_SKIP):
            key = (f.code, f.op, f.index, f.message)
            if key in seen:
                continue
            seen.add(key)
            findings.append(f)
    return findings


def per_rank_hazard_findings(closed: Dict[int, object],
                             per_rank_events: Dict[int, list],
                             ) -> List[Finding]:
    """The dataflow taint pass (analysis/dataflow.py, MPX141/MPX142) over
    each rank's re-trace, deduplicated by message like the per-rank
    cond-divergence walk.  A deduplicated MPX141 names the would-diverge
    rank pair: the first two analyzed ranks that surfaced it (or the sole
    surfacing rank and its successor, when concretization hid the hazard
    from every other re-trace)."""
    from dataclasses import replace

    from .dataflow import graph_arms_approx, hazard_jaxpr_findings

    order: List[tuple] = []
    hit_ranks: Dict[tuple, List[int]] = {}
    base: Dict[tuple, Finding] = {}
    for r in sorted(closed):
        graph = _hook.CollectiveGraph(events=per_rank_events.get(r, []),
                                      meta=_hook.config_snapshot())
        for f in hazard_jaxpr_findings(
                closed[r], approx_armed=graph_arms_approx(graph), rank=r):
            key = (f.code, f.op, f.message)
            if key not in base:
                base[key] = f
                order.append(key)
                hit_ranks[key] = []
            hit_ranks[key].append(r)
    findings: List[Finding] = []
    for key in order:
        f = base[key]
        ranks_hit = hit_ranks[key]
        a = ranks_hit[0]
        b = ranks_hit[1] if len(ranks_hit) > 1 else a + 1
        if f.code == "MPX141":
            f = replace(f, message=(
                f"{f.message} (ranks {a} and {b} would diverge here)"))
        findings.append(f)
    return findings


def resolve_rank_list(ranks, world: int) -> Tuple[int, ...]:
    """Normalize the ``ranks`` argument: ``'all'`` -> every rank, an int
    ``n`` -> ranks ``0..n-1``, any iterable -> its sorted unique ints;
    every entry must exist on the comm."""
    if ranks == "all":
        return tuple(range(world))
    if isinstance(ranks, bool):
        raise ValueError("ranks must be 'all', an int, or an iterable "
                         "of ranks")
    if isinstance(ranks, int):
        if not 0 < ranks <= world:
            raise ValueError(
                f"ranks={ranks} out of range for a {world}-rank comm")
        return tuple(range(ranks))
    out = tuple(sorted({int(r) for r in ranks}))
    if not out:
        raise ValueError("ranks must name at least one rank")
    if out[0] < 0 or out[-1] >= world:
        raise ValueError(
            f"ranks {out} out of range for a {world}-rank comm")
    return out


# ---------------------------------------------------------------------------
# the ambient (env-mode) pass, hooked from parallel/region.py
# ---------------------------------------------------------------------------


def _ambient_enabled(world: int) -> bool:
    setting = config.analyze_ranks()
    if setting == "off":
        return False
    if setting == "auto":
        return True
    return world <= setting  # int: cost cap on the per-rank re-traces


def verify_region_crossrank(fn, *, comm, in_specs, out_specs,
                            static_argnums, c, args, kwargs) -> None:
    """Run the cross-rank pass for an spmd region about to trace
    (called on a program-cache miss, before the program is built, so
    ``error`` mode raises before anything compiles or runs).

    No-op when the verifier is off, an explicit recorder is already
    capturing (``mpx.analyze`` drives its own pass), the cross-rank pass
    is disabled or capped (``MPI4JAX_TPU_ANALYZE_RANKS``), or the comm's
    size is not statically known.  Results are memoized alongside the
    ``mpx.analyze`` reports (same cache, dropped by
    ``mpx.clear_caches``), keyed by the same config tokens the program
    caches fold in.
    """
    mode = _hook.effective_mode()
    if mode == "off" or _hook.recording():
        return
    mesh = c.mesh
    if mesh is None:
        return
    axis_sizes = [mesh.shape[a] for a in c.axes]
    world = math.prod(axis_sizes)
    if world < 2 or not _ambient_enabled(world):
        return

    import jax

    from ..ops._algos import algo_cache_token

    # kwargs flatten by sorted key with values as leaves, so both the
    # keyword names (treedef) and their avals key the memo
    leaves, treedef = jax.tree.flatten((args, kwargs))
    avals = tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
        else repr(leaf)
        for leaf in leaves
    )
    key = ("crossrank", fn, c.uid, treedef, avals,
           tuple(static_argnums or ()), mode, config.analyze_ranks(),
           algo_cache_token())
    cost_model = None
    if config.analyze_cost_enabled():
        from . import cost as _cost

        try:
            cost_model = _cost.resolve_model(None)
        except ValueError as e:
            warnings.warn(
                f"MPI4JAX_TPU_ANALYZE_COST: cost pass skipped "
                f"(tuning file rejected: {e})", stacklevel=3)
        else:
            # folded in ONLY when the cost pass is armed: cost=off memo
            # keys stay byte-identical to a build without the model
            key = key + ("cost", cost_model.stamp())
    try:
        hash(key)
    except TypeError:
        key = None
    cache = _hook.analyze_cache()
    fresh = False
    if key is not None and key in cache:
        report = cache[key]
    else:
        report = _run_region_pass(fn, comm, in_specs, out_specs,
                                  static_argnums, c, args, kwargs,
                                  axis_sizes, world, cost_model)
        if report is None:
            return
        fresh = True
        if key is not None:
            cache[key] = report
    if report.ok and report.cost is None:
        return
    if fresh:
        # sink/warn once per verified program, not once per call — a
        # host loop over a dirty region must not inflate the CLI's
        # finding counts with duplicates of the same report.  A CLEAN
        # report is sunk too when the cost pass ran: the CLI's --cost
        # breakdown artifacts cover clean programs as well.
        _hook.sink_report(f"cross-rank pass over spmd region "
                          f"{getattr(fn, '__name__', fn)!s}", report)
    if report.ok:
        return
    if mode == "error":
        # every call refuses: the program must not run
        report.raise_if_findings()
    if fresh:
        warnings.warn(
            "MPI4JAX_TPU_ANALYZE: cross-rank findings in spmd region "
            f"{getattr(fn, '__name__', fn)!s}:\n{report.render()}",
            stacklevel=3,
        )


def _run_region_pass(fn, comm, in_specs, out_specs, static_argnums,
                     c, args, kwargs, axis_sizes, world,
                     cost_model=None) -> Optional[Report]:
    from ..parallel.region import spmd

    from . import _normalize_statics

    target = spmd(fn, comm=comm, in_specs=in_specs, out_specs=out_specs,
                  static_argnums=static_argnums, jit=False)
    statics = _normalize_statics(static_argnums, len(args))
    watermark = uid_watermark()
    try:
        per_rank, fatal, closed = trace_rank_schedules(
            target, args, kwargs, statics, c.axes, axis_sizes,
            range(world))
    except Exception as e:  # pragma: no cover - defensive
        # a re-trace failure must never break the user's real trace; the
        # normal trace path surfaces genuine errors itself
        warnings.warn(
            f"MPI4JAX_TPU_ANALYZE: cross-rank pass skipped (per-rank "
            f"re-trace failed: {type(e).__name__}: {e})", stacklevel=3)
        return None
    if fatal:
        # the normal trace will raise the same tagged error with a full
        # traceback — do not pre-empt it with a partial cross-rank view
        return None
    matched = match_rank_schedules(per_rank, world, watermark)
    findings = cross_rank_findings(per_rank, world, matched=matched)
    # value-level lineage over the same per-rank re-traces: this is how
    # the env mode surfaces MPX141/MPX142 (the single-trace region
    # recorder only runs the graph-side checkers)
    findings.extend(per_rank_hazard_findings(closed, per_rank))
    cost_report = None
    if cost_model is not None:
        from . import cost as _cost

        meta = _hook.config_snapshot()
        cost_report, cost_findings = _cost.run_cost_pass(
            matched, model=cost_model,
            host_of_rank=_cost.host_map_for(c), closed=closed, meta=meta)
        findings.extend(cost_findings)
    first = per_rank.get(0, ())
    return Report(findings=tuple(findings), events=tuple(first),
                  meta=dict(_hook.config_snapshot(),
                            ranks=list(range(world))), cost=cost_report)
