"""Dispatch-point recording + the ``MPI4JAX_TPU_ANALYZE`` env mode.

Two front-ends share this machinery:

- ``mpx.analyze(fn, *args)`` pushes an explicit :class:`Recorder` and
  re-traces ``fn``; every op flowing through the shared dispatch point
  (ops/_base.py) records a :class:`~.graph.CollectiveEvent`;
- the env mode (``MPI4JAX_TPU_ANALYZE={off,warn,error}``) arms the
  region context instead: events accumulate per spmd region (or per
  eager one-op program) and the checkers run when the region's trace
  completes — ``warn`` emits a warning, ``error`` raises
  :class:`~.report.AnalysisError` at trace time.

Recording is pure host-side bookkeeping: it never adds an equation to the
trace, so the lowered HLO is byte-identical whether the verifier is off,
warning, or erroring (pinned by tests/test_analysis.py).  The mode is
still folded into every compiled-program cache key
(``analysis_cache_token``): a cached program skips tracing, and trace
time is when the verifier looks.
"""

from __future__ import annotations

import warnings
from typing import List, Optional

from ..utils import config
from .checkers import run_checkers
from .graph import CollectiveEvent, CollectiveGraph
from .report import Report

_UNSET = object()
_mode_override = _UNSET


def set_analyze_mode(mode: Optional[str]) -> None:
    """Programmatic override of ``MPI4JAX_TPU_ANALYZE`` (``None`` returns
    control to the environment), mirroring the resilience ``set_*``
    overrides."""
    global _mode_override
    if mode is None:
        _mode_override = _UNSET
        config.bump_config_epoch()
        return
    if mode not in config.ANALYZE_MODES:
        raise ValueError(
            f"analyze mode must be one of {config.ANALYZE_MODES}, got {mode!r}"
        )
    _mode_override = mode
    config.bump_config_epoch()


def effective_mode() -> str:
    if _mode_override is not _UNSET:
        return _mode_override
    return config.analyze_mode()


def analysis_cache_token() -> tuple:
    """Folded into the compiled-program cache keys (ops/_base.py eager
    cache, parallel/region.py spmd cache): flipping the mode — or the
    cross-rank pass setting — must retrace; the verifier only sees
    programs as they trace.  The ambient cost pass folds in ONLY when
    armed, so cost=off cache keys stay byte-identical to a build
    without the cost model (pinned by tests/test_cost.py)."""
    tok = (effective_mode(), config.analyze_ranks())
    if config.analyze_cost_enabled():
        tok = tok + ("cost", config.cost_model_path())
    return tok


class Recorder:
    """Event sink for one recording scope (an ``analyze`` call or one
    armed region)."""

    def __init__(self, mode: str):
        self.mode = mode
        self.events: List[CollectiveEvent] = []
        # live token/buffer carriers: events store id()s, so carriers must
        # stay alive for the recording or a GC'd carrier's id could be
        # reused
        self.pins: List = []
        # (event-stream position, frozenset of buffer ids, call site) per
        # recorded pinned-call donation (record_donation) — the MPX139/
        # MPX140 checkers' join key against per-event buffer ids
        self.donations: List[tuple] = []

    def graph(self) -> CollectiveGraph:
        meta = config_snapshot()
        if self.donations:
            # present only when nonempty: pre-hazard snapshots (and every
            # donation-free recording) stay byte-identical
            meta["donations"] = tuple(self.donations)
        return CollectiveGraph(events=self.events, meta=meta)


def config_snapshot() -> dict:
    from ..ops._fusion import effective_mode as fusion_mode
    from ..resilience.elastic import current_epoch

    # is this trace being pinned by mpx.compile right now?  Gates the
    # MPX128 advisory: a program under the pinner must not be advised to
    # pin itself.  Guarded — the aot package needs jax, and hand-built
    # graphs (pure test half) never pass through here anyway.
    try:
        from ..aot.pinning import tracing_pinned

        pinned = tracing_pinned()
    except ImportError:
        pinned = False
    # is this trace inside a megastep loop body right now?  Meta-level
    # twin of the per-event ``loop`` stamp (guarded for the same
    # isolated-loader reason as the aot import above).
    try:
        from ..parallel.megastep import tracing_megastep

        megastep = tracing_megastep()
    except ImportError:
        megastep = False
    # the declared serving bucket table (serving/buckets.py), the MPX136
    # gate: key present only when a serving runtime declared one, so
    # every non-serving snapshot stays byte-identical to a build without
    # the serving package (guarded like the aot import above).
    serving_buckets = None
    try:
        from ..serving.buckets import declared_buckets

        table = declared_buckets()
        if table is not None:
            serving_buckets = tuple(table.buckets)
    except ImportError:
        pass
    snap = {
        "collective_algo": config.collective_algo(),
        "ring_crossover_bytes": config.ring_crossover_bytes(),
        "dcn_crossover_bytes": config.dcn_crossover_bytes(),
        "alltoall_crossover_bytes": config.alltoall_crossover_bytes(),
        "topology": config.topology_spec(),
        "fusion": fusion_mode(),
        "fusion_bucket_bytes": config.fusion_bucket_bytes(),
        "epoch": current_epoch(),
        "pinned": pinned,
        "megastep": megastep,
        # the DCN wire codec mode (docs/compression.md) — the MPX138
        # gate reads it to tell "compression declined" from "never
        # offered"; payload-bucketed tuned codecs resolve per event, so
        # the snapshot records the unbucketed mode
        "compress": config.compress_mode(),
    }
    if serving_buckets is not None:
        snap["serving_buckets"] = serving_buckets
    # the flight-recorder capacity (telemetry/health.py), the MPX143
    # gate: key present only when the health plane is armed, so every
    # health-off snapshot stays byte-identical (the serving_buckets
    # pattern above; guarded the same way)
    try:
        from ..telemetry.health import armed as _health_armed

        if _health_armed():
            snap["flight_ring"] = config.flight_ring_capacity()
    except ImportError:
        pass
    # measured crossovers from the cost-model tuning file (empty when
    # MPI4JAX_TPU_COST_MODEL is unset, keeping the snapshot — and with
    # it the MPX111/MPX113 advisory texts — byte-identical to a build
    # without the cost model)
    from .costmodel import measured_meta

    snap.update(measured_meta())
    return snap


# explicit-analyze recorders (mpx.analyze); innermost wins
_recorder_stack: List[Recorder] = []

# (event, recorder) currently between begin/end (annotate targets the
# innermost event; end_event pins the produced token on its recorder)
_open_events: List[tuple] = []


def recording() -> bool:
    """True while ``mpx.analyze`` is re-tracing: dispatch must bypass its
    compiled-program caches (a cache hit skips tracing, and tracing is
    what records events)."""
    return bool(_recorder_stack)


def push_recorder(rec: Recorder) -> None:
    _recorder_stack.append(rec)


def pop_recorder() -> Recorder:
    rec = _recorder_stack.pop()
    # drop any events left open by an exception mid-op (a later annotate
    # must never target a stale event from an aborted trace)
    while _open_events and _open_events[-1][1] is rec:
        _open_events.pop()
    return rec


def arm_context(ctx) -> None:
    """Attach an env-mode recorder to a fresh region context (spmd body or
    eager one-op program).  No-op when the verifier is off or an explicit
    ``analyze`` recorder is already capturing."""
    if _recorder_stack:
        return
    mode = effective_mode()
    if mode != "off":
        rec = Recorder(mode)
        # donations that landed OUTSIDE any recording scope (a top-level
        # pinned call between regions) pre-seed every fresh env-mode
        # recorder at stream position 0: a later collective consuming the
        # donated storage is still MPX140.  Position 0 precedes every
        # span start, so a pre-seeded donation can never fake an MPX139
        # race — correct, since no span was open when it landed.
        for ids, where in _ambient_donations:
            rec.donations.append((0, ids, where))
        ctx.analysis_recorder = rec


def _target(ctx) -> Optional[Recorder]:
    if _recorder_stack:
        return _recorder_stack[-1]
    return getattr(ctx, "analysis_recorder", None) if ctx is not None else None


def begin_event(opname: str, comm, arrays, token, ana: Optional[dict],
                ctx, eager: bool = False) -> Optional[CollectiveEvent]:
    """Record the dispatch of one op.  Returns None (fast path) unless a
    recorder is active; otherwise the open event, to be closed with
    ``end_event`` after the op body ran."""
    rec = _target(ctx)
    if rec is None:
        return None
    try:
        size = comm.Get_size()
    except RuntimeError:
        size = None
    try:
        min_size = comm.min_size()
    except RuntimeError:
        min_size = None
    a0 = arrays[0] if arrays else None
    from .schedule import static_groups_for

    evt = CollectiveEvent(
        index=len(rec.events),
        op=opname,
        comm_uid=comm.uid,
        comm_axes=tuple(comm.axes),
        comm_size=size,
        min_size=min_size,
        split=comm.groups is not None,
        payload_bytes=(int(a0.size) * a0.dtype.itemsize) if a0 is not None else 0,
        dtype=str(a0.dtype) if a0 is not None else "",
        shape=tuple(a0.shape) if a0 is not None else (),
        eager=eager,
        epoch=getattr(comm, "epoch", None),
        drained=bool(getattr(comm, "drained", False)),
        groups=static_groups_for(comm),
    )
    # megastep loop scope (parallel/megastep.py _loop_trace_scope): ops
    # traced inside a device-resident loop body carry their loop id and
    # trip count, the MPX130/MPX128 discriminator
    ms = getattr(ctx, "megastep", None) if ctx is not None else None
    if ms is not None:
        evt.loop, evt.unroll = ms
    # buffer identity of the array inputs (dataflow hazard join key,
    # analysis/hazards.py) — recorded BEFORE ana so a fusion flush can
    # overwrite it with the packed bucket's member buffers
    live = [a for a in arrays if a is not None]
    if live:
        evt.buffers = tuple(id(a) for a in live)
        rec.pins.extend(live)
    if ana:
        carriers = ana.pop("buffer_carriers", None)
        if carriers:
            # a fusion flush hands the member arrays alongside their ids
            # so they stay pinned like every other id carrier (graph.py)
            rec.pins.extend(carriers)
        for k, v in ana.items():
            setattr(evt, k, v)
    if token is not None:
        evt.token_in = id(token.value)
        rec.pins.append(token.value)
    rec.events.append(evt)
    _open_events.append((evt, rec))
    return evt


def end_event(evt: CollectiveEvent, out) -> None:
    """Close an open event: record the produced token edge."""
    assert _open_events and _open_events[-1][0] is evt
    _, rec = _open_events.pop()
    from ..ops.token import Token

    if out and isinstance(out[-1], Token):
        evt.token_out = id(out[-1].value)
        rec.pins.append(out[-1].value)


def abort_event(evt: CollectiveEvent) -> None:
    """Unwind an open event whose op body raised (the raise itself is the
    diagnostic — tagged at the raise site)."""
    if _open_events and _open_events[-1][0] is evt:
        _open_events.pop()


# donations recorded outside any recording scope under the env mode:
# (frozenset of buffer ids, call site), carriers pinned alongside.
# Seeded into every fresh env-mode recorder at position 0 (arm_context);
# capped so a long-running donating loop cannot grow host state
# unboundedly, and cleared with the analysis caches.
_AMBIENT_DONATION_CAP = 32
_ambient_donations: List[tuple] = []
_ambient_donation_pins: List = []


def record_donation(arrays, where: str, ctx=None) -> None:
    """Record that a pinned call (aot/pinning.py, ``donate_argnums``) just
    handed the storage of ``arrays`` to its executable.  Pure host-side
    bookkeeping like ``begin_event`` — never touches the trace.  With a
    recorder active (explicit ``mpx.analyze``, or the caller passes the
    armed region context for the env mode) the donation lands at the
    current event-stream position; under the env mode with no recorder in
    scope it joins the ambient log that pre-seeds the next armed region.
    The MPX139/MPX140 checkers intersect the recorded ids with span holds
    and later consumers."""
    live = [a for a in arrays if a is not None]
    if not live:
        return
    rec = _target(ctx)
    if rec is not None:
        rec.pins.extend(live)
        rec.donations.append(
            (len(rec.events), frozenset(id(a) for a in live), where))
        return
    if effective_mode() != "off" and \
            len(_ambient_donations) < _AMBIENT_DONATION_CAP:
        _ambient_donation_pins.extend(live)
        _ambient_donations.append(
            (frozenset(id(a) for a in live), where))


def mark_last_event(key: str, value, ctx=None) -> None:
    """Stamp an ``extra`` annotation on the most recently recorded event
    — for op wrappers that only learn a fact AFTER their inner dispatch
    returned (ops/_compress.ef_allreduce marks its reductions ``ef``,
    arming the approximate-lineage seeds).  No-op when nothing records."""
    rec = _target(ctx)
    if rec is not None and rec.events:
        rec.events[-1].extra[key] = value


def annotate(**fields) -> None:
    """Fill event fields only the op body knows (resolved routing pairs,
    FIFO queue depth at match time, the selected algorithm).  No-op when
    nothing records — safe to call unconditionally from op bodies and
    ``_algos`` appliers."""
    if not _open_events:
        return
    evt = _open_events[-1][0]
    for k, v in fields.items():
        if k in ("queue_depth", "bare_int_routing", "traced_structure",
                 "pipeline"):
            evt.extra[k] = v
        else:
            setattr(evt, k, v)


def finish_context(ctx, where: str) -> None:
    """Run the checkers over a region's recorded stream (env mode only) and
    surface findings per the mode."""
    rec = getattr(ctx, "analysis_recorder", None)
    if rec is None or not rec.events:
        return
    ctx.analysis_recorder = None
    graph = rec.graph()
    findings = run_checkers(graph)
    if not findings:
        return
    report = Report(findings=tuple(findings), events=tuple(rec.events),
                    meta=dict(graph.meta))
    sink_report(where, report)
    if rec.mode == "error":
        report.raise_if_findings()
    warnings.warn(
        f"MPI4JAX_TPU_ANALYZE: findings in {where}:\n{report.render()}",
        stacklevel=2,
    )


# ---------------------------------------------------------------------------
# report sink (the CLI's aggregation channel)
# ---------------------------------------------------------------------------
#
# ``python -m mpi4jax_tpu.analysis`` installs a sink so the exit-code
# contract (1 on any error-severity finding) and the ``--json`` payload
# can aggregate findings across every region of every script without
# aborting at the first one.

_report_sink: Optional[list] = None


def set_report_sink(sink: Optional[list]) -> None:
    """Install (or clear, with ``None``) the ambient report sink: every
    env-mode report — single-trace and cross-rank — is appended to it as
    ``(where, Report)`` before the mode's warn/raise action runs."""
    global _report_sink
    _report_sink = sink


def sink_report(where: str, report) -> None:
    if _report_sink is not None:
        _report_sink.append((where, report))


# ---------------------------------------------------------------------------
# analyze() memoization (cleared by mpx.clear_caches)
# ---------------------------------------------------------------------------

_analyze_cache: dict = {}


def analyze_cache() -> dict:
    return _analyze_cache


def clear_analysis_caches() -> None:
    _analyze_cache.clear()
    del _ambient_donations[:]
    del _ambient_donation_pins[:]
